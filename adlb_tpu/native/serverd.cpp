// Native server daemon: the C++ twin of the Python server reactor
// (adlb_tpu/runtime/server.py), covering the reference's full steal-mode
// protocol — the equivalent of ADLBP_Server's ~2,100-line event loop
// (reference src/adlb.c:382-2506): Put admission + immediate rq match,
// Reserve with targeted-first indexed matching, Get/common fetch, qmstat
// state broadcast (reference src/adlb.c:806-822), RFR pull stealing with
// stale-state patching and UNRESERVE compensation (reference
// src/adlb.c:1802-2070), memory-pressure push with PUSH_DEL cancellation
// (reference src/adlb.c:509-556,2109-2362), the double-pass exhaustion vote
// (reference src/adlb.c:754-785,1575-1650), held two-phase shutdown ring
// (reference src/adlb.c:1493-1574), abort fan-out, and the Info stats
// surface (reference src/adlb.c:3072-3141).
//
// Runs one process per server rank. Clients may be Python (binary-codec
// frames; spawn_world declares native servers as binary peers) or native C
// (libadlb.cpp). Server<->server frames reuse the same TLV form with
// field ids >= 27, which exist only here: worlds never mix native and
// Python servers, so those ids never reach the Python decoder.
//
// Bootstrap protocol with the Python wrapper (transport_tcp._child_main):
//   stdin:  config lines ... "endconfig"
//   stdout: "PORT <n>"
//   stdin:  "addr <rank> <host> <port>" lines ... "endaddrs"
//   ... runs ...
//   stdout: "STATS {json}"   (finalize_stats), or "ABORT <code>"
//
// The balancer brain stays in Python/JAX (SURVEY §7's language split);
// balancer="tpu" worlds use the Python server.

#include <arpa/inet.h>
#include <dirent.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cmath>
#include <condition_variable>
#include <cstdarg>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <deque>
#include <iostream>
#include <array>
#include <map>
#include <mutex>
#include <set>
#include <sstream>
#include <stdexcept>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "wqcore.hpp"

namespace {

// ---- constants (adlb_tpu/types.py) ----------------------------------------
constexpr int ADLB_SUCCESS = 1;
constexpr int ADLB_NO_MORE_WORK = -999999999;
constexpr int ADLB_DONE_BY_EXHAUSTION = -999999998;
constexpr int ADLB_NO_CURRENT_WORK = -999999997;
constexpr int ADLB_PUT_REJECTED = -999999996;
// Python-plane extension rcs (this daemon never issues them — no lease
// table, no watermark backpressure — but the constants are registered so
// the rc space stays in sync with adlb.h / adlb_tpu/types.py)
constexpr int ADLB_RETRY = -999999995;
constexpr int ADLB_FENCED = -999999994;
constexpr int ADLB_BACKOFF = -999999993;
constexpr int ADLB_LOWEST_PRIO = -999999999;

// InfoKey (adlb_tpu/types.py InfoKey)
enum InfoKey {
  K_MALLOC_HWM = 1,
  K_AVG_TIME_ON_RQ = 2,
  K_NPUSHED_FROM_HERE = 3,
  K_NPUSHED_TO_HERE = 4,
  K_NREJECTED_PUTS = 5,
  K_LOOP_TOP_TIME = 6,
  K_MAX_QMSTAT_TRIP_TIME = 7,
  K_AVG_QMSTAT_TRIP_TIME = 8,
  K_NUM_QMS_EXCEED_INT = 9,
  K_NUM_RESERVES = 10,
  K_NUM_RESERVES_PUT_ON_RQ = 11,
  K_MAX_WQ_COUNT = 12,
  K_LAST = 13,  // bound of the stats_[] table; keys below are NOT stats slots
  // introspection keys answered from live probes, not the stats_[] table
  // (must match ADLB_INFO_RSS_KB / ADLB_INFO_TRANSPORT_BACKLOG in
  // include/adlb/adlb.h and types.py InfoKey)
  K_RSS_KB = 13,
  K_TRANSPORT_BACKLOG = 14,
};

// ---- wire tags (codec.py WIRE_TAG) ----------------------------------------
enum WireTag : uint16_t {
  T_FA_PUT = 1001,
  T_FA_PUT_COMMON = 1003,
  T_FA_BATCH_DONE = 1005,
  T_FA_DID_PUT_AT_REMOTE = 1006,
  T_FA_RESERVE = 1007,
  T_TA_RESERVE_RESP = 1008,
  T_FA_GET_RESERVED = 1009,
  T_TA_GET_RESERVED_RESP = 1010,
  T_FA_NO_MORE_WORK = 1011,
  T_FA_LOCAL_APP_DONE = 1012,
  T_TA_PUT_RESP = 1020,
  T_FA_ABORT = 1027,
  T_FA_INFO_NUM_WORK_UNITS = 1037,
  T_FA_GET_COMMON = 1038,
  T_TA_GET_COMMON_RESP = 1039,
  T_FA_INFO_GET = 1041,
  T_TA_PUT_COMMON_RESP = 1042,
  T_TA_INFO_NUM_RESP = 1043,
  T_TA_INFO_GET_RESP = 1044,
  T_TA_ABORT = 1046,
  // server <-> server (codec.py 11xx block)
  T_SS_QMSTAT = 1101,
  T_SS_RFR = 1102,
  T_SS_RFR_RESP = 1103,
  T_SS_UNRESERVE = 1104,
  T_SS_PUSH_QUERY = 1105,
  T_SS_PUSH_QUERY_RESP = 1106,
  T_SS_PUSH_WORK = 1107,
  T_SS_PUSH_DEL = 1108,
  T_SS_MOVING_TARGETED_WORK = 1109,
  T_SS_NO_MORE_WORK = 1110,
  T_SS_EXHAUST_CHK_1 = 1111,
  T_SS_EXHAUST_CHK_2 = 1112,
  T_SS_DONE_BY_EXHAUSTION = 1113,
  T_SS_END_1 = 1114,
  T_SS_END_2 = 1115,
  T_SS_ABORT = 1116,
  T_SS_PERIODIC_STATS = 1122,
  T_SS_STATE = 1117,
  T_SS_STATE_DELTA = 1125,
  T_SS_HUNGRY = 1124,
  T_SS_PLAN_MATCH = 1118,
  T_SS_PLAN_MIGRATE = 1119,
  T_SS_MIGRATE_WORK = 1120,
  T_SS_MIGRATE_ACK = 1121,
  T_DS_LOG = 1131,
  T_DS_END = 1132,
  // checkpoint/resume (runtime/checkpoint.py; no reference analogue)
  T_FA_CHECKPOINT = 1048,
  T_TA_CHECKPOINT_RESP = 1049,
  T_SS_CHECKPOINT = 1123,
  // gray-failure surface (Python servers only): a liveness beacon this
  // daemon parses-and-ignores — it keeps no lease table, so a client
  // heartbeating across a mixed-version world must not be fatal
  T_FA_HEARTBEAT = 1054,
  T_PEER_EOF = 1999,  // transport-internal synthetic signal (never on wire)
};

// ---- field ids ------------------------------------------------------------
// 1..26 mirror codec.py FIELDS (shared with Python/native clients);
// >= 27 are native-server-only (server<->server frames).
enum FieldId : uint8_t {
  F_PAYLOAD = 1,       // bytes
  F_WORK_TYPE = 2,     // i64
  F_PRIO = 3,          // i64
  F_TARGET_RANK = 4,   // i64
  F_ANSWER_RANK = 5,   // i64
  F_COMMON_LEN = 6,    // i64
  F_COMMON_SERVER = 7, // i64
  F_COMMON_SEQNO = 8,  // i64
  F_RC = 9,            // i64
  F_HINT = 10,         // i64
  F_REQ_TYPES = 11,    // list
  F_HANG = 12,         // i64
  F_RQSEQNO = 13,      // i64
  F_HANDLE = 14,       // list
  F_WORK_LEN = 15,     // i64
  F_TIME_ON_Q = 16,    // f64
  F_COUNT = 17,        // i64
  F_NBYTES = 18,       // i64
  F_MAX_WQ = 19,       // i64
  F_CODE = 20,         // i64
  F_SEQNO = 21,        // i64
  F_REFCNT = 22,       // i64
  F_SERVER_RANK = 23,  // i64
  F_KEY = 24,          // i64
  F_VALUE = 25,        // f64
  // -- native-only --
  F_QLEN = 27,            // i64
  F_HI_PRIO = 28,         // list: prios in world-types order
  F_FOR_RANK = 29,        // i64
  F_TARGETED_LOOKUP = 30, // i64
  F_LOOKUP_TYPE = 31,     // i64
  F_FOUND = 32,           // i64
  F_QUERY_ID = 33,        // i64
  F_ACCEPT = 34,          // i64
  F_HOME_SERVER = 35,     // i64
  F_TIME_STAMP = 36,      // f64
  F_APP_RANK = 37,        // i64
  F_FROM_SERVER = 38,     // i64
  F_TO_SERVER = 39,       // i64
  F_ORIGIN = 40,          // i64
  F_VOTE_OK = 41,              // i64
  F_COMPLETE = 42,        // i64
  F_NPARKED = 43,         // i64
  F_ACT = 44,             // list: alternating (rank, activity)
  F_PARKED = 45,          // list: flattened (rank, ntypes, t0..tn)*
  F_TOKEN_ID = 62,        // i64: exhaustion-token id (lost-token recovery)
  F_EVENTS = 63,          // i64 (DS_LOG: msgs handled since last log)
  F_WQ_TARGETED = 64,     // i64 (DS_LOG)
  F_RESERVES = 65,        // i64 (DS_LOG, since last log)
  F_RESERVES_IMMED = 66,  // i64 (DS_LOG, since last log)
  F_RESERVES_PARKED = 67, // i64 (DS_LOG, since last log)
  F_RFR_FAILED = 68,      // i64 (DS_LOG, since last log)
  F_SS_MSGS = 69,         // i64 (DS_LOG, since last log)
  F_BACKLOG = 70,         // i64 (DS_LOG: unhandled inbox frames)
  F_RSS_KB = 71,          // i64 (DS_LOG: /proc/self/status VmRSS)
  // checkpoint ring token (shared with codec.py: the requesting client
  // may be a Python rank)
  F_PATH = 72,            // bytes: shard path prefix
  F_CLIENT = 73,          // i64: requesting client's world rank
  F_STARTED = 74,         // i64: 0 = fresh request at master, 1 = ring token
  F_CK_COUNTS = 76,       // list: units captured, one entry per ring hop
  // -- balancer sidecar (shared with codec.py: the sidecar is Python) --
  F_REQ_HOME = 46,        // i64
  F_DEST = 47,            // i64
  F_SEQNOS = 48,          // list
  F_TASKS_FLAT = 49,      // list: (seqno, type, prio, len)*
  F_REQS_FLAT = 50,       // list: (rank, rqseqno, ntypes, t0..tn)*
  F_CONSUMERS = 51,       // i64
  F_BOUNCED = 52,         // i64
  F_UNITS_BLOB = 53,      // bytes: packed migrate batch
  F_WQ_COUNT = 54,        // i64 (DS_LOG heartbeat)
  F_RQ_COUNT = 55,        // i64 (DS_LOG heartbeat)
  F_QM_TABLE = 56,        // list: (rank, nbytes, qlen, prio[T])* ring token
  F_PUT_ID = 58,          // i64: pipelined-put id echoed in TA_PUT_RESP
  F_FETCH = 59,           // i64: fused reserve+get request (get_work)
  F_HUNGRY = 60,          // i64: balancer -> servers, parked reqs exist
  F_GREW = 61,            // i64: the hungry wanted-set grew
  F_PSTATS_BLOB = 57,     // bytes: packed periodic-stats ring token entries
  // migration-batch ack: planner batch id on SS_PLAN_MIGRATE /
  // SS_MIGRATE_WORK; highest id received PER SOURCE reported in
  // snapshots (flattened (src, id) pairs) so the planner's in-flight
  // credits clear exactly when the batch lands
  F_MIG_ID = 77,          // i64
  F_MIG_ACKS = 78,        // list
  // batched fused fetch (get_work_batch): request cap + the batch
  // response's parallel per-unit fields (codec.py ids 79-84)
  F_FETCH_MAX = 79,       // i64
  F_PAYLOADS = 80,        // blist
  F_WORK_TYPES = 81,      // list
  F_PRIOS = 82,           // list
  F_ANSWER_RANKS = 83,    // list
  F_TIMES_ON_Q = 84,      // flist
  // batched SS_STATE_DELTA (round 4): parallel per-unit lists so a
  // streaming producer's inventory reaches the balancer within one
  // rate-limit gap instead of one unit per gap (codec.py id 85;
  // F_SEQNOS/F_WORK_TYPES/F_PRIOS are shared with other messages)
  F_WORK_LENS = 85,       // list
};

enum Kind : uint8_t {
  KIND_I64 = 0, KIND_BYTES = 1, KIND_LIST = 2, KIND_F64 = 3,
  KIND_BLIST = 4,  // list of byte strings: u16 count, (u32 len + bytes)*
  KIND_FLIST = 5,  // list of f64: u16 count, f64*
};

struct FieldVal {
  uint8_t kind = KIND_I64;
  int64_t i = 0;
  double d = 0.0;
  std::string b;
  std::vector<int64_t> l;
  std::vector<std::string> bl;
  std::vector<double> fl;
};

struct NMsg {
  uint16_t tag = 0;
  int32_t src = -1;
  std::map<uint8_t, FieldVal> f;

  bool has(uint8_t id) const { return f.count(id) != 0; }
  int64_t geti(uint8_t id, int64_t dflt = 0) const {
    auto it = f.find(id);
    return it == f.end() ? dflt : it->second.i;
  }
  double getd(uint8_t id, double dflt = 0.0) const {
    auto it = f.find(id);
    return it == f.end() ? dflt : it->second.d;
  }
  const std::string* getb(uint8_t id) const {
    auto it = f.find(id);
    return it == f.end() ? nullptr : &it->second.b;
  }
  const std::vector<int64_t>* getl(uint8_t id) const {
    auto it = f.find(id);
    return it == f.end() ? nullptr : &it->second.l;
  }
  NMsg& seti(uint8_t id, int64_t v) {
    FieldVal& fv = f[id];
    fv.kind = KIND_I64;
    fv.i = v;
    return *this;
  }
  NMsg& setd(uint8_t id, double v) {
    FieldVal& fv = f[id];
    fv.kind = KIND_F64;
    fv.d = v;
    return *this;
  }
  NMsg& setb(uint8_t id, std::string v) {
    FieldVal& fv = f[id];
    fv.kind = KIND_BYTES;
    fv.b = std::move(v);
    return *this;
  }
  NMsg& setl(uint8_t id, std::vector<int64_t> v) {
    FieldVal& fv = f[id];
    fv.kind = KIND_LIST;
    fv.l = std::move(v);
    return *this;
  }
  NMsg& setbl(uint8_t id, std::vector<std::string> v) {
    FieldVal& fv = f[id];
    fv.kind = KIND_BLIST;
    fv.bl = std::move(v);
    return *this;
  }
  NMsg& setfl(uint8_t id, std::vector<double> v) {
    FieldVal& fv = f[id];
    fv.kind = KIND_FLIST;
    fv.fl = std::move(v);
    return *this;
  }
};

[[noreturn]] void die(const char* fmt, ...) {
  va_list ap;
  va_start(ap, fmt);
  std::fprintf(stderr, "[adlb_serverd] fatal: ");
  std::vfprintf(stderr, fmt, ap);
  std::fprintf(stderr, "\n");
  va_end(ap);
  std::exit(1);
}

double monotonic() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return double(ts.tv_sec) + double(ts.tv_nsec) * 1e-9;
}

// ---- TLV codec (codec.py encode_binary/decode_binary) ---------------------

void put_u16(std::string& out, uint16_t v) { out.append((const char*)&v, 2); }
void put_u32(std::string& out, uint32_t v) { out.append((const char*)&v, 4); }
void put_i32(std::string& out, int32_t v) { out.append((const char*)&v, 4); }
void put_i64(std::string& out, int64_t v) { out.append((const char*)&v, 8); }
void put_f64(std::string& out, double v) { out.append((const char*)&v, 8); }

std::string encode(const NMsg& m) {
  std::string out;
  out.push_back(char(0x01));  // BINARY_MAGIC
  put_u16(out, m.tag);
  put_i32(out, m.src);
  put_u16(out, uint16_t(m.f.size()));
  for (const auto& kv : m.f) {
    out.push_back(char(kv.first));
    out.push_back(char(kv.second.kind));
    switch (kv.second.kind) {
      case KIND_I64: put_i64(out, kv.second.i); break;
      case KIND_F64: put_f64(out, kv.second.d); break;
      case KIND_BYTES:
        put_u32(out, uint32_t(kv.second.b.size()));
        out.append(kv.second.b);
        break;
      case KIND_LIST:
        // the codec's element count is a u16; silent wrap-around would
        // make the frame undecodable at the receiver — fail fast instead
        if (kv.second.l.size() > 65535)
          die("list field %u overflows the u16 codec bound (%zu elements)",
              kv.first, kv.second.l.size());
        put_u16(out, uint16_t(kv.second.l.size()));
        for (int64_t x : kv.second.l) put_i64(out, x);
        break;
      case KIND_BLIST:
        if (kv.second.bl.size() > 65535)
          die("blist field %u overflows the u16 codec bound", kv.first);
        put_u16(out, uint16_t(kv.second.bl.size()));
        for (const std::string& b : kv.second.bl) {
          put_u32(out, uint32_t(b.size()));
          out.append(b);
        }
        break;
      case KIND_FLIST:
        if (kv.second.fl.size() > 65535)
          die("flist field %u overflows the u16 codec bound", kv.first);
        put_u16(out, uint16_t(kv.second.fl.size()));
        for (double x : kv.second.fl) put_f64(out, x);
        break;
    }
  }
  return out;
}

// Malformed frames throw (the reader drops them and keeps serving, like
// the Python TcpEndpoint) rather than die(): one garbage connection must
// not take down a server that other ranks depend on.
struct FrameError : std::runtime_error {
  using std::runtime_error::runtime_error;
};

NMsg decode(const std::string& body) {
  if (body.size() < 9 || body[0] != 0x01) throw FrameError("bad frame magic");
  NMsg m;
  size_t off = 1;
  std::memcpy(&m.tag, body.data() + off, 2); off += 2;
  std::memcpy(&m.src, body.data() + off, 4); off += 4;
  uint16_t nfields;
  std::memcpy(&nfields, body.data() + off, 2); off += 2;
  auto need = [&](size_t n) {
    if (off + n > body.size())
      throw FrameError("truncated frame (tag " + std::to_string(m.tag) + ")");
  };
  for (uint16_t i = 0; i < nfields; ++i) {
    need(2);
    uint8_t fid = uint8_t(body[off]);
    uint8_t kind = uint8_t(body[off + 1]);
    off += 2;
    FieldVal fv;
    fv.kind = kind;
    switch (kind) {
      case KIND_I64:
        need(8);
        std::memcpy(&fv.i, body.data() + off, 8); off += 8;
        break;
      case KIND_F64:
        need(8);
        std::memcpy(&fv.d, body.data() + off, 8); off += 8;
        break;
      case KIND_BYTES: {
        need(4);
        uint32_t n;
        std::memcpy(&n, body.data() + off, 4); off += 4;
        need(n);
        fv.b.assign(body.data() + off, n); off += n;
        break;
      }
      case KIND_LIST: {
        need(2);
        uint16_t cnt;
        std::memcpy(&cnt, body.data() + off, 2); off += 2;
        need(size_t(cnt) * 8);
        fv.l.resize(cnt);
        for (uint16_t j = 0; j < cnt; ++j) {
          std::memcpy(&fv.l[j], body.data() + off, 8); off += 8;
        }
        break;
      }
      case KIND_BLIST: {
        need(2);
        uint16_t cnt;
        std::memcpy(&cnt, body.data() + off, 2); off += 2;
        fv.bl.reserve(cnt);
        for (uint16_t j = 0; j < cnt; ++j) {
          need(4);
          uint32_t n;
          std::memcpy(&n, body.data() + off, 4); off += 4;
          need(n);
          fv.bl.emplace_back(body.data() + off, n); off += n;
        }
        break;
      }
      case KIND_FLIST: {
        need(2);
        uint16_t cnt;
        std::memcpy(&cnt, body.data() + off, 2); off += 2;
        need(size_t(cnt) * 8);
        fv.fl.resize(cnt);
        for (uint16_t j = 0; j < cnt; ++j) {
          std::memcpy(&fv.fl[j], body.data() + off, 8); off += 8;
        }
        break;
      }
      default:
        throw FrameError("bad field kind " + std::to_string(kind));
    }
    m.f.emplace(fid, std::move(fv));
  }
  // every legitimate encoder (codec.py, libadlb, this file) emits exact
  // frames; trailing bytes mean garbage that decoded by luck
  if (off != body.size())
    throw FrameError("trailing bytes after field " +
                     std::to_string(nfields));
  // tag outside the wire ranges (client block 1001-1049 plus the
  // heartbeat beacon 1054, server/debug block 1101-1132): a crafted or
  // version-skewed frame — it must not reach the dispatch switch, whose
  // unhandled-tag arm is fatal
  if (!((m.tag >= 1001 && m.tag <= 1049) || m.tag == T_FA_HEARTBEAT ||
        (m.tag >= 1101 && m.tag <= 1132)))
    throw FrameError("unknown wire tag " + std::to_string(m.tag));
  return m;
}

// ---- endpoint: acceptor + readers -> inbox, lazy outbound -----------------
// Same shape as the native client's transport (libadlb.cpp) and the Python
// TcpEndpoint: one listener, one reader thread per inbound connection,
// persistent outbound sockets, 4-byte LE length prefix per frame.

class Endpoint {
 public:
  Endpoint() = default;

  int listen_any() {
    lsock_ = socket(AF_INET, SOCK_STREAM, 0);
    if (lsock_ < 0) die("socket: %s", strerror(errno));
    int one = 1;
    setsockopt(lsock_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    addr.sin_port = 0;
    if (bind(lsock_, (sockaddr*)&addr, sizeof(addr)) < 0)
      die("bind: %s", strerror(errno));
    if (listen(lsock_, 64) < 0) die("listen: %s", strerror(errno));
    socklen_t len = sizeof(addr);
    getsockname(lsock_, (sockaddr*)&addr, &len);
    port_ = ntohs(addr.sin_port);
    acceptor_ = std::thread([this] { accept_loop(); });
    return port_;
  }

  void set_addr(int rank, std::string host, int port) {
    addr_map_[rank] = {std::move(host), port};
  }

  void send(int dest, const NMsg& m) {
    std::string body = encode(m);
    std::string frame;
    put_u32(frame, uint32_t(body.size()));
    frame += body;
    std::unique_lock<std::mutex> lk(out_mu_);
    int& sock = out_socks_[dest];
    if (sock == 0) sock = connect_to(dest);
    if (sock < 0) {
      // peer unreachable after the retry window (shutdown races): drop this
      // frame loudly, but leave the slot retryable so a recovered peer is
      // reconnected on the next send instead of being black-holed forever
      sock = 0;
      std::fprintf(stderr,
                   "[adlb_serverd] dropping frame tag %u to unreachable "
                   "rank %d\n", m.tag, dest);
      return;
    }
    const char* p = frame.data();
    size_t left = frame.size();
    while (left > 0) {
      ssize_t n = ::send(sock, p, left, MSG_NOSIGNAL);
      if (n <= 0) {
        close(sock);
        sock = connect_to(dest);  // one reconnect attempt
        if (sock < 0) return;
        p = frame.data();
        left = frame.size();
        continue;
      }
      p += n;
      left -= size_t(n);
    }
  }

  // blocking receive with timeout (seconds); false on timeout
  bool recv(NMsg* out, double timeout) {
    std::unique_lock<std::mutex> lk(in_mu_);
    if (inbox_.empty()) {
      in_cv_.wait_for(lk, std::chrono::duration<double>(timeout),
                      [this] { return !inbox_.empty(); });
    }
    if (inbox_.empty()) return false;
    *out = std::move(inbox_.front());
    inbox_.pop_front();
    return true;
  }

  bool recv_now(NMsg* out) {
    std::unique_lock<std::mutex> lk(in_mu_);
    if (inbox_.empty()) return false;
    *out = std::move(inbox_.front());
    inbox_.pop_front();
    return true;
  }

  // received-but-unhandled frames: the TCP analogue of the reference's
  // MPI unexpected-message-queue probe (src/adlb.c:3645-3719)
  size_t backlog() {
    std::unique_lock<std::mutex> lk(in_mu_);
    return inbox_.size();
  }

  void close_all() {
    closed_ = true;
    if (lsock_ >= 0) { shutdown(lsock_, SHUT_RDWR); close(lsock_); }
    std::unique_lock<std::mutex> lk(out_mu_);
    for (auto& kv : out_socks_)
      if (kv.second > 0) { shutdown(kv.second, SHUT_WR); close(kv.second); }
  }

 private:
  void accept_loop() {
    while (!closed_) {
      int conn = accept(lsock_, nullptr, nullptr);
      if (conn < 0) return;
      std::thread([this, conn] { reader(conn); }).detach();
    }
  }

  void reader(int conn) {
    // Robustness policy (mirrors libadlb.cpp's reader): garbage on a
    // connection that has never delivered a decodable frame closes that
    // connection and nothing else — a stray scanner must not kill a
    // server other ranks depend on. Corruption on an ESTABLISHED stream
    // is a protocol error between real ranks and fails fast: silently
    // dropping a request would leave its sender parked forever.
    // The length cap guards resize(): a hostile 4 GB prefix must not
    // become the allocation that kills the daemon.
    static constexpr uint32_t kMaxFrame = 1u << 28;  // 256 MB
    int32_t last_src = -1;
    bool established = false;
    for (;;) {
      uint32_t n;
      if (!read_exact(conn, (char*)&n, 4)) break;
      if (n > kMaxFrame) {
        if (established)
          die("frame length %u from rank %d exceeds %u cap", n, last_src,
              kMaxFrame);
        std::fprintf(stderr,
                     "[adlb_serverd] frame length %u exceeds %u cap; "
                     "closing connection\n", n, kMaxFrame);
        break;
      }
      std::string body;
      if (!read_body(conn, n, &body)) break;
      if (n == 0 || body[0] != 0x01) {
        if (established)
          // never legitimate: Python peers raise rather than pickle to a
          // declared-binary destination, so mid-stream non-TLV is
          // corruption (or a misconfigured peer), and dropping it could
          // park its sender forever
          die("non-binary frame (%u bytes) from rank %d", n, last_src);
        std::fprintf(stderr,
                     "[adlb_serverd] closing connection after non-binary "
                     "frame (%u B)\n", n);
        break;
      }
      NMsg m;
      try {
        m = decode(body);
      } catch (const FrameError& e) {
        if (!established) {
          std::fprintf(stderr,
                       "[adlb_serverd] closing connection after "
                       "undecodable first frame (%u B): %s — stray "
                       "connection, or a version-skewed peer (if a rank "
                       "now hangs, rebuild both sides from one tree)\n",
                       n, e.what());
          break;
        }
        die("undecodable frame (%u bytes) from rank %d: %s", n, last_src,
            e.what());
      }
      established = true;
      last_src = m.src;
      {
        std::lock_guard<std::mutex> lk(in_mu_);
        inbox_.push_back(std::move(m));
      }
      in_cv_.notify_one();
    }
    // EOF after the peer's frames: synthetic in-order signal so the
    // reactor can tell a finalized peer from a dead one (the reference's
    // failure model is rank-death-kills-job, src/adlb.c:2508-2526)
    if (last_src >= 0 && !closed_) {
      NMsg eof;
      eof.tag = T_PEER_EOF;
      eof.src = last_src;
      {
        std::lock_guard<std::mutex> lk(in_mu_);
        inbox_.push_back(std::move(eof));
      }
      in_cv_.notify_one();
    }
    close(conn);
  }

  static bool read_exact(int fd, char* buf, size_t n) {
    size_t got = 0;
    while (got < n) {
      ssize_t r = ::recv(fd, buf + got, n - got, 0);
      if (r <= 0) return false;
      got += size_t(r);
    }
    return true;
  }

  // Body reads grow with the bytes actually received instead of
  // pre-allocating the advertised length: a connection that sends only a
  // large length prefix (and then stalls) must not pin the whole frame's
  // memory while blocked in recv.
  static bool read_body(int fd, uint32_t n, std::string* body) {
    body->clear();
    char chunk[65536];
    while (body->size() < n) {
      size_t want = std::min(sizeof chunk, size_t(n) - body->size());
      ssize_t r = ::recv(fd, chunk, want, 0);
      if (r <= 0) return false;
      body->append(chunk, size_t(r));
    }
    return true;
  }

  int connect_to(int dest) {
    auto it = addr_map_.find(dest);
    if (it == addr_map_.end()) die("no address for rank %d", dest);
    double deadline = monotonic() + 15.0;
    for (;;) {
      int sock = socket(AF_INET, SOCK_STREAM, 0);
      sockaddr_in addr{};
      addr.sin_family = AF_INET;
      inet_pton(AF_INET, it->second.first.c_str(), &addr.sin_addr);
      addr.sin_port = htons(uint16_t(it->second.second));
      if (connect(sock, (sockaddr*)&addr, sizeof(addr)) == 0) {
        int one = 1;
        setsockopt(sock, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        return sock;
      }
      close(sock);
      if (monotonic() >= deadline || closed_) return -1;
      usleep(50000);
    }
  }

  int lsock_ = -1;
  int port_ = 0;
  bool closed_ = false;
  std::thread acceptor_;
  std::map<int, std::pair<std::string, int>> addr_map_;
  std::map<int, int> out_socks_;
  std::mutex out_mu_;
  std::deque<NMsg> inbox_;
  std::mutex in_mu_;
  std::condition_variable in_cv_;
};

// ---- world / config -------------------------------------------------------

struct World {
  int nranks = 0;
  int nservers = 0;
  bool use_debug_server = false;
  std::vector<int> types;

  int num_app_ranks() const {
    return nranks - nservers - (use_debug_server ? 1 : 0);
  }
  int master_server_rank() const { return num_app_ranks(); }
  bool is_server(int r) const {
    return r >= num_app_ranks() && r < num_app_ranks() + nservers;
  }
  bool is_app(int r) const { return r < num_app_ranks(); }
  int home_server(int app) const {
    return num_app_ranks() + (app % nservers);
  }
  int ring_next(int s) const {
    int i = s - num_app_ranks();
    return num_app_ranks() + (i + 1) % nservers;
  }
};

struct Cfg {
  double qmstat_interval = 0.05;
  bool qmstat_ring = false;  // reference-faithful ring token gossip
  double exhaust_check_interval = 0.25;
  double max_malloc = 0.0;
  // tpu mode: stream snapshots to a Python/JAX balancer sidecar and enact
  // its plan (SURVEY §7 language split: C++ data plane, JAX brain)
  bool tpu_mode = false;
  double periodic_log_interval = 0.0;  // 0 = off (reference src/adlb.c:712)
  double debug_log_interval = 1.0;
  int balancer_rank = -1;
  double balancer_interval = 0.02;
  double balancer_min_gap = 0.002;
  int64_t balancer_max_tasks = 256;
  int64_t balancer_max_requesters = 64;
  // reload this rank's <prefix>.<rank>.ckpt shard at startup (same shard
  // bytes as the Python servers: runtime/checkpoint.py ACK1 format)
  std::string restore_path;
};

// ---- server state ---------------------------------------------------------

struct Meta {  // per-unit fields beyond the matching index
  std::string payload;
  int32_t answer_rank = -1;
  int32_t home_server = -1;
  int64_t common_len = 0, common_server = -1, common_seqno = -1;
  double time_stamp = 0.0;
};

struct RqEntry {
  int world_rank;
  int64_t rqseqno;
  bool any_type;
  std::vector<int32_t> req_types;  // sorted when !any_type
  double time_stamp;
  bool fetch = false;  // fused reserve+get (this framework's extension)

  bool wants(int32_t t) const {
    if (any_type) return true;
    for (int32_t x : req_types)
      if (x == t) return true;
    return false;
  }
};

struct PeerState {  // reference qmstat entry (src/adlb.c:151-159)
  int64_t nbytes = 0;
  int64_t qlen = 0;
  std::unordered_map<int32_t, int32_t> hi_prio;
};

struct CommonEntry {
  std::string buf;
  int64_t refcnt = -1;
  int64_t ngets = 0;
};

class Server {
 public:
  Server(World w, Cfg cfg, int rank, Endpoint* ep)
      : w_(w), cfg_(cfg), rank_(rank), ep_(ep) {
    master_ = (rank_ == w_.master_server_rank());
    for (int r = 0; r < w_.num_app_ranks(); ++r)
      if (w_.home_server(r) == rank_) local_apps_.insert(r);
    for (int s = w_.num_app_ranks(); s < w_.num_app_ranks() + w_.nservers; ++s)
      peers_[s];  // default entries
    stats_.assign(K_LAST, 0.0);
    if (!cfg_.restore_path.empty()) restore_from(cfg_.restore_path);
  }

  void run() {
    double now = monotonic();
    next_qmstat_ = now;
    next_exhaust_ = now + cfg_.exhaust_check_interval;
    next_pstats_ = now + cfg_.periodic_log_interval;
    while (!done_) {
      now = monotonic();
      periodic(now);
      double deadline = next_qmstat_;
      if (master_ && next_exhaust_ < deadline) deadline = next_exhaust_;
      if (!pend_seqnos_.empty()) {
        double d = last_event_snap_ + cfg_.balancer_min_gap;
        if (d < deadline) deadline = d;  // pending delta flush is due
      }
      NMsg m;
      bool got = ep_->recv(&m, std::max(deadline - monotonic(), 0.0));
      double t0 = monotonic();
      if (got) {
        dispatch(m);
        // bounded drain before paying the poll timeout again
        for (int i = 0; i < 128 && !done_; ++i) {
          if (monotonic() >= deadline) break;
          NMsg m2;
          if (!ep_->recv_now(&m2)) break;
          dispatch(m2);
        }
      }
      stats_[K_LOOP_TOP_TIME] += monotonic() - t0;
    }
  }

  void print_stats() {
    stats_[K_MALLOC_HWM] = double(mem_hwm_);
    stats_[K_AVG_TIME_ON_RQ] =
        rq_wait_n_ ? rq_wait_sum_ / double(rq_wait_n_) : 0.0;
    stats_[K_MAX_WQ_COUNT] = double(wq_.max_count);
    std::ostringstream os;
    os << "STATS {";
    char num[64];
    for (int k = 1; k < K_LAST; ++k) {
      if (k > 1) os << ", ";
      // full precision: default ostream formatting rounds to 6 significant
      // digits, corrupting large counters and MALLOC_HWM
      std::snprintf(num, sizeof(num), "%.17g", stats_[k]);
      os << "\"" << k << "\": " << num;
    }
    os << "}";
    std::printf("%s\n", os.str().c_str());
    std::fflush(stdout);
  }

  bool aborted() const { return aborted_; }
  int abort_code() const { return abort_code_; }

  void notify_balancer_end() {
    if (cfg_.tpu_mode && cfg_.balancer_rank >= 0)
      ep_->send(cfg_.balancer_rank, mk(T_DS_END));
    if (w_.use_debug_server)
      ep_->send(w_.nranks - 1, mk(T_DS_END));
  }

 private:
  // ---- memory accounting (reference src/adlb.c:3419-3474) -----------------
  bool mem_try_alloc(int64_t n) {
    if (cfg_.max_malloc > 0 && double(mem_curr_ + n) > cfg_.max_malloc)
      return false;
    mem_alloc(n);
    return true;
  }
  void mem_alloc(int64_t n) {
    mem_curr_ += n;
    if (mem_curr_ > mem_hwm_) mem_hwm_ = mem_curr_;
  }
  void mem_free(int64_t n) { mem_curr_ -= n; }
  bool mem_under_pressure() const {
    return cfg_.max_malloc > 0 && double(mem_curr_) > 0.95 * cfg_.max_malloc;
  }
  bool mem_has_room(int64_t n) const {
    return cfg_.max_malloc <= 0 ||
           double(mem_curr_ + n) <= 0.95 * cfg_.max_malloc;
  }

  // ---- small helpers ------------------------------------------------------
  const adlbwq::Unit* wq_find_match(int rank, const RqEntry& e) {
    const int32_t* tp = e.any_type ? nullptr : e.req_types.data();
    int32_t nt = e.any_type ? 0 : int32_t(e.req_types.size());
    const adlbwq::Unit* u = wq_.find_targeted(rank, tp, nt);
    if (u == nullptr) u = wq_.find_untargeted(tp, nt);
    return u;
  }

  int64_t wq_num_unpinned() const {
    int64_t n = 0;
    for (const auto& kv : wq_.units)
      if (kv.second.pin_rank < 0) n += 1;
    return n;
  }

  int64_t wq_num_unpinned_untargeted() const {
    int64_t n = 0;
    for (const auto& kv : wq_.units)
      if (kv.second.pin_rank < 0 && kv.second.target_rank < 0) n += 1;
    return n;
  }

  // remove a unit and its metadata from the queue, returning the Meta
  // (payload + bookkeeping); shared by Get_reserved and the fused path
  Meta consume_unit(int64_t seqno) {
    Meta meta = std::move(meta_[seqno]);
    meta_.erase(seqno);
    auto it = wq_.units.find(seqno);
    wq_.total_bytes -= it->second.payload_len;
    wq_.units.erase(it);
    wq_.count -= 1;
    mem_free(int64_t(meta.payload.size()));
    return meta;
  }

  RqEntry* rq_find_rank(int world_rank) {
    for (auto& e : rq_)
      if (e.world_rank == world_rank) return &e;
    return nullptr;
  }

  void rq_remove(int world_rank) {
    for (auto it = rq_.begin(); it != rq_.end(); ++it)
      if (it->world_rank == world_rank) { rq_.erase(it); return; }
  }

  // parked requester matching a freshly available (type, target) — the
  // reference's rq_find_rank_queued_for_type (src/adlb.c:988-1042)
  RqEntry* rq_find_for_type(int32_t work_type, int32_t target_rank) {
    if (target_rank >= 0) {
      RqEntry* e = rq_find_rank(target_rank);
      return (e != nullptr && e->wants(work_type)) ? e : nullptr;
    }
    for (auto& e : rq_)
      if (e.wants(work_type)) return &e;
    return nullptr;
  }

  NMsg mk(uint16_t tag) {
    NMsg m;
    m.tag = tag;
    m.src = rank_;
    return m;
  }

  void reserve_resp_fail(int app, int rc) {
    NMsg r = mk(T_TA_RESERVE_RESP);
    r.seti(F_RC, rc);
    ep_->send(app, r);
  }

  void reserve_resp_ok(int app, const adlbwq::Unit& u, const Meta& meta,
                       int holder, bool fetch = false) {
    resolved_ctr_ += 1;
    if (fetch && holder == rank_ && meta.common_len == 0) {
      // fused reserve+get (no reference analogue): local prefix-free unit,
      // consume now and inline the payload in the reservation response
      NMsg r = mk(T_TA_RESERVE_RESP);
      r.seti(F_RC, ADLB_SUCCESS);
      r.seti(F_WORK_TYPE, u.work_type);
      r.seti(F_PRIO, u.prio);
      r.seti(F_WORK_LEN, u.payload_len);
      r.seti(F_ANSWER_RANK, meta.answer_rank);
      Meta m2 = consume_unit(u.seqno);
      r.setd(F_TIME_ON_Q, monotonic() - m2.time_stamp);
      r.setb(F_PAYLOAD, std::move(m2.payload));
      ep_->send(app, r);
      return;
    }
    NMsg r = mk(T_TA_RESERVE_RESP);
    r.seti(F_RC, ADLB_SUCCESS);
    r.seti(F_WORK_TYPE, u.work_type);
    r.seti(F_PRIO, u.prio);
    r.setl(F_HANDLE, {u.seqno, holder, meta.common_len, meta.common_server,
                      meta.common_seqno});
    r.seti(F_WORK_LEN, u.payload_len + meta.common_len);
    r.seti(F_ANSWER_RANK, meta.answer_rank);
    ep_->send(app, r);
  }

  void reserve_resp_batch(int app, const std::vector<int64_t>& seqnos) {
    resolved_ctr_ += int64_t(seqnos.size());
    double now = monotonic();
    std::vector<std::string> payloads;
    std::vector<int64_t> wtypes, prios, answers;
    std::vector<double> times;
    payloads.reserve(seqnos.size());
    for (int64_t sq : seqnos) {
      const adlbwq::Unit& u = wq_.units.at(sq);
      wtypes.push_back(u.work_type);
      prios.push_back(u.prio);
      Meta m2 = consume_unit(sq);
      answers.push_back(m2.answer_rank);
      times.push_back(now - m2.time_stamp);
      payloads.push_back(std::move(m2.payload));
    }
    NMsg r = mk(T_TA_RESERVE_RESP);
    r.seti(F_RC, ADLB_SUCCESS);
    r.setbl(F_PAYLOADS, std::move(payloads));
    r.setl(F_WORK_TYPES, std::move(wtypes));
    r.setl(F_PRIOS, std::move(prios));
    r.setl(F_ANSWER_RANKS, std::move(answers));
    r.setfl(F_TIMES_ON_Q, std::move(times));
    ep_->send(app, r);
  }

  void satisfy_parked(const RqEntry& e, const adlbwq::Unit& u,
                      const Meta& meta) {
    int app = e.world_rank;
    bool fetch = e.fetch;
    double wait = monotonic() - e.time_stamp;
    rq_remove(app);
    rfr_excluded_.erase(app);
    rq_wait_sum_ += wait;
    rq_wait_n_ += 1;
    activity_ += 1;
    reserve_resp_ok(app, u, meta, rank_, fetch);
  }

  void match_rq() {
    // local analogue of check_remote_work_for_queued_apps
    // (reference src/adlb.c:3536-3579)
    bool progressed = true;
    while (progressed) {
      progressed = false;
      for (auto& e : rq_) {
        const adlbwq::Unit* u = wq_find_match(e.world_rank, e);
        if (u != nullptr) {
          int64_t seqno = u->seqno;
          wq_.units[seqno].pin_rank = e.world_rank;
          RqEntry copy = e;
          satisfy_parked(copy, wq_.units[seqno], meta_[seqno]);
          progressed = true;
          break;
        }
      }
    }
  }

  int least_loaded_peer(int64_t nbytes_needed) {
    int best = -1, fallback = -1;
    int64_t best_bytes = 0, fallback_bytes = 0;
    for (const auto& kv : peers_) {
      if (kv.first == rank_) continue;
      if (fallback < 0 || kv.second.nbytes < fallback_bytes) {
        fallback = kv.first;
        fallback_bytes = kv.second.nbytes;
      }
      if (cfg_.max_malloc > 0 &&
          double(kv.second.nbytes + nbytes_needed) > cfg_.max_malloc)
        continue;
      if (best < 0 || kv.second.nbytes < best_bytes) {
        best = kv.first;
        best_bytes = kv.second.nbytes;
      }
    }
    return best >= 0 ? best : fallback;
  }

  // ---- dispatch -----------------------------------------------------------
  void dispatch(const NMsg& m) {
    events_ctr_ += 1;
    if (m.tag >= 1101 && m.tag <= 1125) ss_msgs_ctr_ += 1;
    switch (m.tag) {
      case T_FA_HEARTBEAT: break;  // liveness beacon: parse-and-ignore
      case T_FA_PUT: on_put(m); break;
      case T_FA_PUT_COMMON: on_put_common(m); break;
      case T_FA_BATCH_DONE: on_batch_done(m); break;
      case T_FA_DID_PUT_AT_REMOTE: on_did_put_at_remote(m); break;
      case T_FA_RESERVE: on_reserve(m); break;
      case T_FA_GET_RESERVED: on_get_reserved(m); break;
      case T_FA_GET_COMMON: on_get_common(m); break;
      case T_FA_NO_MORE_WORK: on_fa_no_more_work(m); break;
      case T_FA_LOCAL_APP_DONE: on_local_app_done(m); break;
      case T_FA_ABORT: do_abort(int(m.geti(F_CODE, -1)), true); break;
      case T_FA_INFO_NUM_WORK_UNITS: on_info_num(m); break;
      case T_FA_INFO_GET: on_info_get(m); break;
      case T_FA_CHECKPOINT: on_fa_checkpoint(m); break;
      case T_SS_CHECKPOINT: on_ss_checkpoint(m); break;
      case T_SS_QMSTAT: on_qmstat(m); break;
      case T_SS_RFR: on_rfr(m); break;
      case T_SS_RFR_RESP: on_rfr_resp(m); break;
      case T_SS_UNRESERVE: on_unreserve(m); break;
      case T_SS_PUSH_QUERY: on_push_query(m); break;
      case T_SS_PUSH_QUERY_RESP: on_push_query_resp(m); break;
      case T_SS_PUSH_WORK: on_push_work(m); break;
      case T_SS_PUSH_DEL: on_push_del(m); break;
      case T_SS_MOVING_TARGETED_WORK: on_moving_targeted(m); break;
      case T_SS_NO_MORE_WORK: on_ss_no_more_work(); break;
      case T_SS_EXHAUST_CHK_1: on_exhaust_chk(m, true); break;
      case T_SS_EXHAUST_CHK_2: on_exhaust_chk(m, false); break;
      case T_SS_DONE_BY_EXHAUSTION: on_done_by_exhaustion(); break;
      case T_SS_END_1: on_end_1(m); break;
      case T_SS_END_2: on_end_2(m); break;
      case T_SS_ABORT: do_abort(int(m.geti(F_CODE, -1)), false); break;
      // a client-directed abort frame reaching a server means the world
      // is already in an abort storm (misdirected fan-out / rank reuse);
      // treat it as the abort it is rather than dying on "no handler"
      // and cascading connection-loss aborts through every peer
      case T_TA_ABORT: do_abort(int(m.geti(F_CODE, -1)), false); break;
      case T_PEER_EOF: on_peer_eof(m); break;
      case T_SS_PERIODIC_STATS: on_periodic_stats(m); break;
      case T_SS_HUNGRY: {
        hungry_ = m.geti(F_HUNGRY, 0) != 0;
        const std::vector<int64_t>* ts = m.getl(F_REQ_TYPES);
        hungry_any_ = hungry_ && ts == nullptr;
        hungry_types_.clear();
        if (ts != nullptr)
          for (int64_t t : *ts) hungry_types_.insert(int32_t(t));
        // when the wanted-set grows our inventory of those types may be
        // heartbeat-stale at the sidecar: refresh so the solve sees it
        if (hungry_ && m.geti(F_GREW, 0) != 0) send_snapshot();
        break;
      }
      case T_SS_PLAN_MATCH: on_plan_match(m); break;
      case T_SS_PLAN_MIGRATE: on_plan_migrate(m); break;
      case T_SS_MIGRATE_WORK: on_migrate_work(m); break;
      case T_SS_MIGRATE_ACK:
        migrate_unacked_ -= 1;
        if (migrate_unacked_ == 0 && !held_ckpts_.empty()) {
          std::vector<NMsg> held;
          held.swap(held_ckpts_);
          for (const NMsg& h : held) process_checkpoint(h);
        }
        break;
      default: die("no handler for tag %u", m.tag);
    }
  }

  void periodic(double now) {
    if (!pend_seqnos_.empty() &&
        now - last_event_snap_ >= cfg_.balancer_min_gap)
      flush_event_deltas(now);
    if (now >= next_qmstat_) {
      next_qmstat_ = cfg_.tpu_mode ? now + cfg_.balancer_interval
                                   : now + cfg_.qmstat_interval;
      if (cfg_.tpu_mode) {
        // O(wq) walk: fast cadence only while someone is parked AND this
        // server could contribute (inventory for the solve, or its own
        // parked requesters whose fresh stamps keep them re-plannable),
        // or under memory pressure; slow heartbeat otherwise (parks send
        // event snapshots themselves)
        bool relevant = hungry_ && (!rq_.empty() || wq_has_untargeted());
        if (relevant || mem_under_pressure() || now >= next_idle_snap_) {
          next_idle_snap_ = now + 0.25;
          send_snapshot();
        }
      } else {
        broadcast_qmstat();
      }
      if (mem_under_pressure()) try_push();
    }
    if (master_ && now >= next_exhaust_) {
      next_exhaust_ = now + cfg_.exhaust_check_interval;
      check_exhaustion(now);
    }
    if (master_ && cfg_.periodic_log_interval > 0 && now >= next_pstats_) {
      next_pstats_ = now + cfg_.periodic_log_interval;
      kick_periodic_stats(now);
    }
    if (w_.use_debug_server && now >= next_ds_log_) {
      next_ds_log_ = now + cfg_.debug_log_interval;
      // the reference's 11-counter heartbeat (src/adlb.c:3222-3259); the
      // iq / unexpected-queue fields map to the inbox backlog
      int64_t wq_targeted = 0;
      for (const auto& kv : wq_.units)
        if (kv.second.target_rank >= 0) wq_targeted += 1;
      int64_t reserves = int64_t(stats_[K_NUM_RESERVES]);
      int64_t parked = int64_t(stats_[K_NUM_RESERVES_PUT_ON_RQ]);
      NMsg m = mk(T_DS_LOG);
      m.seti(F_EVENTS, events_ctr_ - ds_last_.events);
      m.seti(F_WQ_TARGETED, wq_targeted);
      m.seti(F_WQ_COUNT, wq_.count);
      m.seti(F_RQ_COUNT, int64_t(rq_.size()));
      m.seti(F_BACKLOG, int64_t(ep_->backlog()));
      m.seti(F_RESERVES, reserves - ds_last_.reserves);
      m.seti(F_RESERVES_IMMED, reserve_immed_ctr_ - ds_last_.immed);
      m.seti(F_RESERVES_PARKED, parked - ds_last_.parked);
      m.seti(F_RFR_FAILED, rfr_failed_ctr_ - ds_last_.rfr_failed);
      m.seti(F_SS_MSGS, ss_msgs_ctr_ - ds_last_.ss);
      m.seti(F_RSS_KB, rss_kb());
      m.seti(F_NBYTES, mem_curr_);
      ep_->send(w_.nranks - 1, m);  // debug server is the last world rank
      ds_last_.events = events_ctr_;
      ds_last_.ss = ss_msgs_ctr_;
      ds_last_.reserves = reserves;
      ds_last_.immed = reserve_immed_ctr_;
      ds_last_.parked = parked;
      ds_last_.rfr_failed = rfr_failed_ctr_;
    }
  }

  static int64_t rss_kb() {
    // the reference's /proc/self/status probe (src/adlb.c:3347-3369)
    FILE* f = fopen("/proc/self/status", "r");
    if (f == nullptr) return 0;
    char line[256];
    int64_t kb = 0;
    while (fgets(line, sizeof line, f) != nullptr)
      if (sscanf(line, "VmRSS: %lld", (long long*)&kb) == 1) break;
    fclose(f);
    return kb;
  }

  // ---- app handlers (reference src/adlb.c:889-1383) -----------------------
  void on_put(const NMsg& m) {
    puts_ctr_ += 1;
    bool has_pid = m.has(F_PUT_ID);
    int64_t pid = m.geti(F_PUT_ID);
    auto echo_pid = [&](NMsg& r) {
      if (has_pid) r.seti(F_PUT_ID, pid);
    };
    if (no_more_work_ || done_by_exhaustion_) {
      NMsg r = mk(T_TA_PUT_RESP);
      r.seti(F_RC, ADLB_NO_MORE_WORK);
      echo_pid(r);
      ep_->send(m.src, r);
      return;
    }
    const std::string* payload = m.getb(F_PAYLOAD);
    static const std::string kEmpty;
    if (payload == nullptr) payload = &kEmpty;
    if (!mem_try_alloc(int64_t(payload->size()))) {
      stats_[K_NREJECTED_PUTS] += 1;
      NMsg r = mk(T_TA_PUT_RESP);
      r.seti(F_RC, ADLB_PUT_REJECTED);
      r.seti(F_HINT, least_loaded_peer(int64_t(payload->size())));
      echo_pid(r);
      ep_->send(m.src, r);
      return;
    }
    int64_t seqno = next_seqno_++;
    adlbwq::Unit u{seqno, int32_t(m.geti(F_WORK_TYPE)),
                   int32_t(m.geti(F_PRIO)), int32_t(m.geti(F_TARGET_RANK, -1)),
                   -1, int64_t(payload->size())};
    wq_.units.emplace(seqno, u);
    wq_.count += 1;
    if (wq_.count > wq_.max_count) wq_.max_count = wq_.count;
    wq_.total_bytes += u.payload_len;
    wq_.index(u);
    Meta& meta = meta_[seqno];
    meta.payload = *payload;
    meta.answer_rank = int32_t(m.geti(F_ANSWER_RANK, -1));
    meta.home_server = rank_;
    meta.common_len = m.geti(F_COMMON_LEN, 0);
    meta.common_server = m.geti(F_COMMON_SERVER, -1);
    meta.common_seqno = m.geti(F_COMMON_SEQNO, -1);
    meta.time_stamp = monotonic();
    activity_ += 1;
    exhaust_held_ = false;
    RqEntry* e = rq_find_for_type(u.work_type, u.target_rank);
    if (e != nullptr) {
      wq_.units[seqno].pin_rank = e->world_rank;
      RqEntry copy = *e;
      satisfy_parked(copy, wq_.units[seqno], meta);
    }
    NMsg r = mk(T_TA_PUT_RESP);
    r.seti(F_RC, ADLB_SUCCESS);
    echo_pid(r);
    ep_->send(m.src, r);
    // event path for an untargeted put of a type some parked requester
    // wants (SS_HUNGRY): an O(1) DELTA carrying just this unit, not the
    // O(wq) snapshot walk; targeted puts match at the target's home
    // server and never enter snapshots, and the periodic heartbeat
    // covers everything else
    if (e == nullptr && u.target_rank < 0 && hungry_ &&
        (hungry_any_ || hungry_types_.count(u.work_type)))
      maybe_event_delta(seqno, u.work_type, u.prio, int64_t(u.payload_len));
  }

  void on_put_common(const NMsg& m) {
    const std::string* payload = m.getb(F_PAYLOAD);
    static const std::string kEmpty;
    if (payload == nullptr) payload = &kEmpty;
    NMsg r = mk(T_TA_PUT_COMMON_RESP);
    if (!mem_try_alloc(int64_t(payload->size()))) {
      r.seti(F_RC, ADLB_PUT_REJECTED);
      r.seti(F_COMMON_SEQNO, -1);
    } else {
      int64_t seqno = next_common_seqno_++;
      cq_[seqno].buf = *payload;
      r.seti(F_RC, ADLB_SUCCESS);
      r.seti(F_COMMON_SEQNO, seqno);
    }
    ep_->send(m.src, r);
  }

  void cq_maybe_gc(int64_t seqno) {
    auto it = cq_.find(seqno);
    if (it == cq_.end()) return;
    if (it->second.refcnt >= 0 && it->second.ngets >= it->second.refcnt) {
      mem_free(int64_t(it->second.buf.size()));
      cq_.erase(it);
    }
  }

  void on_batch_done(const NMsg& m) {
    int64_t seqno = m.geti(F_COMMON_SEQNO);
    auto it = cq_.find(seqno);
    if (it == cq_.end()) return;
    it->second.refcnt = m.geti(F_REFCNT);
    cq_maybe_gc(seqno);
  }

  // ---- checkpoint / resume (runtime/checkpoint.py ACK1 shard format) ------
  // No reference analogue (SURVEY §5: pool serialization absent upstream).
  // Same ring protocol and shard bytes as the Python servers, so a shard
  // written by either plane restores into the other.

  int64_t write_ckpt_shard(const std::string& prefix) {
    std::string body;
    int64_t n = 0;
    auto u32 = [](std::string& out, uint32_t v) {
      out.append((const char*)&v, 4);
    };
    auto i32 = [](std::string& out, int32_t v) {
      out.append((const char*)&v, 4);
    };
    auto i64 = [](std::string& out, int64_t v) {
      out.append((const char*)&v, 8);
    };
    // serialize in seqno order: restore assigns fresh seqnos in shard order,
    // so hash-map order would scramble FIFO-among-equal-priority dispatch
    // (the "FIFO by seqno among equals" contract in wqcore.hpp) that the
    // Python plane's insertion-ordered dict preserves
    std::vector<int64_t> seqnos;
    seqnos.reserve(wq_.units.size());
    for (const auto& kv : wq_.units) seqnos.push_back(kv.first);
    std::sort(seqnos.begin(), seqnos.end());
    for (int64_t sq : seqnos) {
      const adlbwq::Unit& u = wq_.units.at(sq);
      const Meta& meta = meta_.at(u.seqno);
      i32(body, u.work_type);
      i32(body, u.target_rank);
      i32(body, meta.answer_rank);
      i64(body, int64_t(u.prio));
      i64(body, meta.common_server);
      i64(body, meta.common_seqno);
      u32(body, uint32_t(meta.common_len));
      u32(body, uint32_t(meta.payload.size()));
      body.append(meta.payload);
      n += 1;
    }
    // ACK2 header: format version + world shape (nranks/nservers) so a
    // restore into a different shape fails loudly instead of silently
    // misrouting targeted units (ACK1 stays read-compatible below)
    std::string out("ACK2");
    u32(out, 2u);
    u32(out, uint32_t(w_.nranks));
    u32(out, uint32_t(w_.nservers));
    u32(out, uint32_t(n));
    out += body;
    u32(out, uint32_t(cq_.size()));
    for (const auto& kv : cq_) {
      i64(out, kv.first);
      i64(out, kv.second.refcnt);
      i64(out, kv.second.ngets);
      u32(out, uint32_t(kv.second.buf.size()));
      out += kv.second.buf;
    }
    std::string path = prefix + "." + std::to_string(rank_) + ".ckpt";
    std::string tmp = path + "." + std::to_string(getpid()) + ".tmp";
    FILE* f = std::fopen(tmp.c_str(), "wb");
    if (f == nullptr) die("checkpoint: cannot open %s", tmp.c_str());
    if (std::fwrite(out.data(), 1, out.size(), f) != out.size())
      die("checkpoint: short write to %s", tmp.c_str());
    std::fclose(f);
    if (std::rename(tmp.c_str(), path.c_str()) != 0)
      die("checkpoint: rename to %s failed", path.c_str());
    return n;
  }

  void restore_from(const std::string& prefix) {
    // stray-shard guard (mirrors runtime/server.py): shards for server
    // ranks outside this world mean the checkpoint came from a different
    // world shape — silently loading only our own shard would lose every
    // unit the extra shards hold
    // plain directory scan + prefix/suffix comparison rather than glob():
    // a restore_path containing glob metacharacters (*, ?, [) would make
    // the pattern match nothing (silently skipping this check) or match
    // unrelated files — the Python plane avoids the same trap with
    // re.escape in existing_shard_ranks
    std::string dir = ".", base = prefix;
    size_t slash = prefix.find_last_of('/');
    if (slash != std::string::npos) {
      // a root-anchored prefix ("/pool") must scan "/", not ""
      dir = slash == 0 ? "/" : prefix.substr(0, slash);
      base = prefix.substr(slash + 1);
    }
    if (DIR* d = opendir(dir.c_str())) {
      while (struct dirent* ent = readdir(d)) {
        std::string name = ent->d_name;
        if (name.size() <= base.size() + 6) continue;  // ".<r>.ckpt" min 7
        if (name.compare(0, base.size(), base) != 0 ||
            name[base.size()] != '.')
          continue;
        if (name.compare(name.size() - 5, 5, ".ckpt") != 0) continue;
        std::string mid = name.substr(base.size() + 1,
                                      name.size() - base.size() - 6);
        if (mid.empty() ||
            mid.find_first_not_of("0123456789") != std::string::npos)
          continue;
        long r = std::strtol(mid.c_str(), nullptr, 10);
        if (!w_.is_server(int(r)))
          die("checkpoint %s has a shard for rank %ld outside this world's "
              "servers; restore with the same world shape", prefix.c_str(),
              r);
      }
      closedir(d);
    }
    std::string path = prefix + "." + std::to_string(rank_) + ".ckpt";
    FILE* f = std::fopen(path.c_str(), "rb");
    if (f == nullptr)
      die("checkpoint shard missing: %s (was the checkpoint taken with the "
          "same world shape?)", path.c_str());
    std::string data;
    char buf[1 << 16];
    size_t got;
    while ((got = std::fread(buf, 1, sizeof buf, f)) > 0) data.append(buf, got);
    std::fclose(f);
    size_t off = 0;
    auto need = [&](size_t n) {
      if (off + n > data.size()) die("truncated shard %s", path.c_str());
    };
    auto rd_u32 = [&]() {
      need(4);
      uint32_t v;
      std::memcpy(&v, data.data() + off, 4);
      off += 4;
      return v;
    };
    auto rd_i32 = [&]() {
      need(4);
      int32_t v;
      std::memcpy(&v, data.data() + off, 4);
      off += 4;
      return v;
    };
    auto rd_i64 = [&]() {
      need(8);
      int64_t v;
      std::memcpy(&v, data.data() + off, 8);
      off += 8;
      return v;
    };
    need(4);
    bool v2 = data.compare(0, 4, "ACK2") == 0;
    if (!v2 && data.compare(0, 4, "ACK1") != 0)
      die("bad shard magic in %s", path.c_str());
    off = 4;
    if (v2) {
      uint32_t ver = rd_u32(), nranks = rd_u32(), nservers = rd_u32();
      if (ver > 2)
        die("shard %s: format version %u is newer than this build (2)",
            path.c_str(), ver);
      if (nranks != 0 && (int(nranks) != w_.nranks ||
                          int(nservers) != w_.nservers))
        die("shard %s: checkpoint world shape nranks=%u/nservers=%u does "
            "not match this world (%d/%d); restore with the same shape",
            path.c_str(), nranks, nservers, w_.nranks, w_.nservers);
    }
    uint32_t n = rd_u32();
    for (uint32_t i = 0; i < n; ++i) {
      int32_t wt = rd_i32(), tgt = rd_i32(), ans = rd_i32();
      int64_t prio = rd_i64(), cserver = rd_i64(), cseqno = rd_i64();
      uint32_t clen = rd_u32(), plen = rd_u32();
      need(plen);
      // the shard stores 64-bit priorities (the Python plane accepts
      // arbitrary ints); silently truncating would invert the dispatch
      // order of exactly the units marked most/least urgent
      if (prio > INT32_MAX || prio < INT32_MIN)
        die("shard %s: unit priority %lld does not fit this plane's "
            "int32 priorities; restore under Python servers",
            path.c_str(), (long long)prio);
      int64_t seqno = next_seqno_++;
      adlbwq::Unit u{seqno, wt, int32_t(prio), tgt, -1, int64_t(plen)};
      wq_.units.emplace(seqno, u);
      wq_.count += 1;
      if (wq_.count > wq_.max_count) wq_.max_count = wq_.count;
      wq_.total_bytes += u.payload_len;
      wq_.index(u);
      Meta& meta = meta_[seqno];
      meta.payload.assign(data.data() + off, plen);
      off += plen;
      meta.answer_rank = ans;
      meta.home_server = rank_;
      meta.common_len = clen;
      meta.common_server = cserver;
      meta.common_seqno = cseqno;
      meta.time_stamp = monotonic();
      mem_curr_ += plen;
      if (mem_curr_ > mem_hwm_) mem_hwm_ = mem_curr_;
    }
    uint32_t nc = rd_u32();
    for (uint32_t i = 0; i < nc; ++i) {
      int64_t seqno = rd_i64(), refcnt = rd_i64(), ngets = rd_i64();
      uint32_t blen = rd_u32();
      need(blen);
      CommonEntry& e = cq_[seqno];
      e.buf.assign(data.data() + off, blen);
      off += blen;
      e.refcnt = refcnt;
      e.ngets = ngets;
      mem_curr_ += blen;
      if (seqno >= next_common_seqno_) next_common_seqno_ = seqno + 1;
    }
    if (mem_curr_ > mem_hwm_) mem_hwm_ = mem_curr_;
    std::fprintf(stderr,
                 "[adlb_serverd %d] restored %u units, %u common entries "
                 "from %s\n", rank_, n, nc, path.c_str());
  }

  void on_fa_checkpoint(const NMsg& m) {
    const std::string* p = m.getb(F_PATH);
    if (p == nullptr) die("FA_CHECKPOINT without path");
    NMsg fwd = mk(T_SS_CHECKPOINT);
    fwd.setb(F_PATH, *p);
    fwd.seti(F_CLIENT, m.src);
    fwd.seti(F_STARTED, 0);
    if (master_) on_ss_checkpoint(fwd);
    else ep_->send(w_.master_server_rank(), fwd);
  }

  void on_ss_checkpoint(const NMsg& m) {
    // units inside an unacked SS_MIGRATE_WORK live in no wq anywhere;
    // holding the token until the ack lands keeps them out of the
    // lost-update window (runtime/server.py does the same). A queue, not
    // a slot: concurrent checkpoints from different clients must all
    // complete (each client blocks on its own TA_CHECKPOINT_RESP)
    if (migrate_unacked_ != 0) {
      held_ckpts_.push_back(m);
      return;
    }
    process_checkpoint(m);
  }

  void process_checkpoint(const NMsg& m) {
    const std::string* p = m.getb(F_PATH);
    if (p == nullptr) die("SS_CHECKPOINT without path");
    std::vector<int64_t> counts;
    if (m.getl(F_CK_COUNTS) != nullptr) counts = *m.getl(F_CK_COUNTS);
    if (master_ && m.geti(F_STARTED, 0) != 0) {  // token came back around
      ack_checkpoint(m.geti(F_CLIENT), counts);
      return;
    }
    int64_t nn = write_ckpt_shard(*p);
    counts.push_back(nn);
    if (master_ && w_.nservers == 1) {
      ack_checkpoint(m.geti(F_CLIENT), counts);
      return;
    }
    NMsg fwd = mk(T_SS_CHECKPOINT);
    fwd.setb(F_PATH, *p);
    fwd.seti(F_CLIENT, m.geti(F_CLIENT));
    fwd.seti(F_STARTED, 1);
    fwd.setl(F_CK_COUNTS, std::move(counts));
    ep_->send(w_.ring_next(rank_), fwd);
  }

  void ack_checkpoint(int64_t client, const std::vector<int64_t>& counts) {
    int64_t total = 0;
    for (int64_t c : counts) total += c;
    NMsg r = mk(T_TA_CHECKPOINT_RESP);
    r.seti(F_RC, ADLB_SUCCESS);
    r.seti(F_COUNT, total);
    ep_->send(int(client), r);
  }

  void on_did_put_at_remote(const NMsg& m) {
    // reference src/adlb.c:2845-2852 + tq (src/xq.h:73-79)
    int app = int(m.geti(F_TARGET_RANK));
    int32_t wt = int32_t(m.geti(F_WORK_TYPE));
    int server = int(m.geti(F_SERVER_RANK));
    tq_[app][wt][server] += 1;
    RqEntry* e = rq_find_rank(app);
    if (e != nullptr && e->wants(wt)) try_rfr(*e);
  }

  void on_reserve(const NMsg& m) {
    stats_[K_NUM_RESERVES] += 1;
    int app = m.src;
    RqEntry e;
    e.world_rank = app;
    e.rqseqno = m.geti(F_RQSEQNO);
    const std::vector<int64_t>* types = m.getl(F_REQ_TYPES);
    e.any_type = (types == nullptr);
    if (types != nullptr)
      for (int64_t t : *types) e.req_types.push_back(int32_t(t));
    e.time_stamp = monotonic();
    e.fetch = m.geti(F_FETCH, 0) != 0;
    if (no_more_work_) { reserve_resp_fail(app, ADLB_NO_MORE_WORK); return; }
    if (done_by_exhaustion_) {
      reserve_resp_fail(app, ADLB_DONE_BY_EXHAUSTION);
      return;
    }
    const adlbwq::Unit* u = wq_find_match(app, e);
    if (u != nullptr) {
      int64_t seqno = u->seqno;
      wq_.units[seqno].pin_rank = app;
      activity_ += 1;
      reserve_immed_ctr_ += 1;
      // clamp: a batch is bounded by the u16 element counts of the
      // codec's list kinds — an unclamped client value could push
      // encode() into its overflow guard and abort the daemon
      int64_t fetch_max = m.geti(F_FETCH_MAX, 1);
      if (fetch_max > 4096) fetch_max = 4096;
      if (e.fetch && fetch_max > 1 && meta_[seqno].common_len == 0) {
        // batched fused fetch: pop up to fetch_max local prefix-free
        // matches into ONE response (mirrors the Python server's
        // _reserve_resp_batch) — only locally pre-positioned inventory
        // can batch, so the balancer's locality is what amortizes the
        // consumer's round trips
        std::vector<int64_t> seqnos{seqno};
        while (int64_t(seqnos.size()) < fetch_max) {
          const adlbwq::Unit* extra = wq_find_match(app, e);
          if (extra == nullptr || meta_[extra->seqno].common_len != 0) break;
          wq_.units[extra->seqno].pin_rank = app;
          seqnos.push_back(extra->seqno);
        }
        reserve_resp_batch(app, seqnos);
        return;
      }
      reserve_resp_ok(app, wq_.units[seqno], meta_[seqno], rank_, e.fetch);
      return;
    }
    if (m.geti(F_HANG, 0) == 0) {
      reserve_resp_fail(app, ADLB_NO_CURRENT_WORK);
      return;
    }
    stats_[K_NUM_RESERVES_PUT_ON_RQ] += 1;
    rq_remove(app);  // re-park replaces (one entry per rank)
    rq_.push_back(e);
    rfr_excluded_.erase(app);
    try_rfr(rq_.back());
    maybe_event_snapshot();
  }

  void on_get_reserved(const NMsg& m) {
    int64_t seqno = m.geti(F_SEQNO);
    auto it = wq_.units.find(seqno);
    if (it == wq_.units.end() || it->second.pin_rank != m.src)
      die("invalid GET_RESERVED seqno %lld from rank %d",
          (long long)seqno, m.src);  // reference aborts too (src/adlb.c:1349)
    Meta meta = consume_unit(seqno);
    NMsg r = mk(T_TA_GET_RESERVED_RESP);
    r.seti(F_RC, ADLB_SUCCESS);
    r.setb(F_PAYLOAD, std::move(meta.payload));
    r.setd(F_TIME_ON_Q, monotonic() - meta.time_stamp);
    ep_->send(m.src, r);
  }

  void on_get_common(const NMsg& m) {
    int64_t seqno = m.geti(F_COMMON_SEQNO);
    auto it = cq_.find(seqno);
    if (it == cq_.end())
      die("invalid GET_COMMON seqno %lld", (long long)seqno);
    NMsg r = mk(T_TA_GET_COMMON_RESP);
    r.seti(F_RC, ADLB_SUCCESS);
    r.setb(F_PAYLOAD, it->second.buf);
    ep_->send(m.src, r);
    it->second.ngets += 1;
    cq_maybe_gc(seqno);
  }

  void on_info_num(const NMsg& m) {
    int32_t wt = int32_t(m.geti(F_WORK_TYPE));
    int64_t n = 0, nbytes = 0;
    for (const auto& kv : wq_.units)
      if (kv.second.work_type == wt) {
        n += 1;
        nbytes += kv.second.payload_len;
      }
    NMsg r = mk(T_TA_INFO_NUM_RESP);
    r.seti(F_RC, ADLB_SUCCESS);
    r.seti(F_COUNT, n);
    r.seti(F_NBYTES, nbytes);
    r.seti(F_MAX_WQ, wq_.max_count);
    ep_->send(m.src, r);
  }

  void on_info_get(const NMsg& m) {
    int key = int(m.geti(F_KEY));
    NMsg r = mk(T_TA_INFO_GET_RESP);
    if (key == K_RSS_KB) {
      r.seti(F_RC, ADLB_SUCCESS);
      r.setd(F_VALUE, double(rss_kb()));
      ep_->send(m.src, r);
      return;
    }
    if (key == K_TRANSPORT_BACKLOG) {
      r.seti(F_RC, ADLB_SUCCESS);
      r.setd(F_VALUE, double(ep_->backlog()));
      ep_->send(m.src, r);
      return;
    }
    if (key < 1 || key >= K_LAST) {
      r.seti(F_RC, -1);
      r.setd(F_VALUE, 0.0);
    } else {
      double v;
      if (key == K_MALLOC_HWM) v = double(mem_hwm_);
      else if (key == K_AVG_TIME_ON_RQ)
        v = rq_wait_n_ ? rq_wait_sum_ / double(rq_wait_n_) : 0.0;
      else if (key == K_MAX_WQ_COUNT) v = double(wq_.max_count);
      else v = stats_[key];
      r.seti(F_RC, ADLB_SUCCESS);
      r.setd(F_VALUE, v);
    }
    ep_->send(m.src, r);
  }

  // ---- stealing: RFR (reference src/adlb.c:1802-2070,3487-3579) -----------
  void try_rfr(const RqEntry& e) {
    int app = e.world_rank;
    if (rfr_out_.count(app)) return;
    auto& excluded = rfr_excluded_[app];
    // 1) targeted-directory hit
    auto tit = tq_.find(app);
    if (tit != tq_.end()) {
      for (const auto& by_type : tit->second) {
        if (!e.wants(by_type.first)) continue;
        for (const auto& by_server : by_type.second) {
          if (by_server.second <= 0) continue;
          int server = by_server.first;
          if (server == rank_ || excluded.count(server)) continue;
          send_rfr(e, server, true, by_type.first);
          return;
        }
      }
    }
    if (cfg_.tpu_mode) return;  // untargeted stealing is the planner's job
    // 2) best advertised untargeted priority among peers
    int best_server = -1;
    int32_t best_prio = ADLB_LOWEST_PRIO;
    for (const auto& kv : peers_) {
      if (kv.first == rank_ || excluded.count(kv.first)) continue;
      if (e.any_type) {
        for (const auto& tp : kv.second.hi_prio)
          if (tp.second > best_prio) {
            best_server = kv.first;
            best_prio = tp.second;
          }
      } else {
        for (int32_t t : e.req_types) {
          auto hit = kv.second.hi_prio.find(t);
          if (hit != kv.second.hi_prio.end() && hit->second > best_prio) {
            best_server = kv.first;
            best_prio = hit->second;
          }
        }
      }
    }
    if (best_server >= 0) send_rfr(e, best_server, false, -1);
  }

  void send_rfr(const RqEntry& e, int server, bool targeted, int32_t ltype) {
    rfr_out_.insert(e.world_rank);
    NMsg m = mk(T_SS_RFR);
    m.seti(F_FOR_RANK, e.world_rank);
    m.seti(F_RQSEQNO, e.rqseqno);
    if (!e.any_type) {
      std::vector<int64_t> ts(e.req_types.begin(), e.req_types.end());
      m.setl(F_REQ_TYPES, ts);
    }
    m.seti(F_TARGETED_LOOKUP, targeted ? 1 : 0);
    m.seti(F_LOOKUP_TYPE, ltype);
    ep_->send(server, m);
  }

  void on_rfr(const NMsg& m) {
    RqEntry probe;
    probe.world_rank = int(m.geti(F_FOR_RANK));
    probe.rqseqno = m.geti(F_RQSEQNO);
    const std::vector<int64_t>* types = m.getl(F_REQ_TYPES);
    probe.any_type = (types == nullptr);
    if (types != nullptr)
      for (int64_t t : *types) probe.req_types.push_back(int32_t(t));
    const adlbwq::Unit* u = wq_find_match(probe.world_rank, probe);
    if (u != nullptr) {
      int64_t seqno = u->seqno;
      adlbwq::Unit& unit = wq_.units[seqno];
      unit.pin_rank = probe.world_rank;
      activity_ += 1;
      exhaust_held_ = false;
      const Meta& meta = meta_[seqno];
      NMsg r = mk(T_SS_RFR_RESP);
      r.seti(F_FOUND, 1);
      r.seti(F_FOR_RANK, probe.world_rank);
      r.seti(F_RQSEQNO, probe.rqseqno);
      r.seti(F_SEQNO, seqno);
      r.seti(F_WORK_TYPE, unit.work_type);
      r.seti(F_PRIO, unit.prio);
      r.seti(F_TARGET_RANK, unit.target_rank);
      r.seti(F_WORK_LEN, unit.payload_len + meta.common_len);
      r.seti(F_ANSWER_RANK, meta.answer_rank);
      r.seti(F_COMMON_LEN, meta.common_len);
      r.seti(F_COMMON_SERVER, meta.common_server);
      r.seti(F_COMMON_SEQNO, meta.common_seqno);
      ep_->send(m.src, r);
    } else {
      NMsg r = mk(T_SS_RFR_RESP);
      r.seti(F_FOUND, 0);
      r.seti(F_FOR_RANK, probe.world_rank);
      r.seti(F_RQSEQNO, probe.rqseqno);
      if (types != nullptr) r.setl(F_REQ_TYPES, *types);
      r.seti(F_TARGETED_LOOKUP, m.geti(F_TARGETED_LOOKUP));
      r.seti(F_LOOKUP_TYPE, m.geti(F_LOOKUP_TYPE));
      ep_->send(m.src, r);
    }
  }

  void tq_remove(int app, int32_t wt, int server) {
    auto ait = tq_.find(app);
    if (ait == tq_.end()) return;
    auto tit = ait->second.find(wt);
    if (tit == ait->second.end()) return;
    auto sit = tit->second.find(server);
    if (sit == tit->second.end()) return;
    if (--sit->second <= 0) tit->second.erase(sit);
    if (tit->second.empty()) ait->second.erase(tit);
    if (ait->second.empty()) tq_.erase(ait);
  }

  void on_rfr_resp(const NMsg& m) {
    int app = int(m.geti(F_FOR_RANK));
    rfr_out_.erase(app);
    if (!m.geti(F_FOUND)) rfr_failed_ctr_ += 1;
    if (m.geti(F_FOUND)) {
      RqEntry* e = rq_find_rank(app);
      int32_t wt = int32_t(m.geti(F_WORK_TYPE));
      if (e == nullptr || e->rqseqno != m.geti(F_RQSEQNO) || !e->wants(wt)) {
        // satisfied while the RFR flew — compensate (reference SS_UNRESERVE,
        // src/adlb.c:1949-1963)
        NMsg u = mk(T_SS_UNRESERVE);
        u.seti(F_SEQNO, m.geti(F_SEQNO));
        ep_->send(m.src, u);
        return;
      }
      int64_t target = m.geti(F_TARGET_RANK, -1);
      if (target >= 0 && app == int(target)) tq_remove(app, wt, m.src);
      double wait = monotonic() - e->time_stamp;
      rq_remove(app);
      rfr_excluded_.erase(app);
      rq_wait_sum_ += wait;
      rq_wait_n_ += 1;
      activity_ += 1;
      resolved_ctr_ += 1;
      NMsg r = mk(T_TA_RESERVE_RESP);
      r.seti(F_RC, ADLB_SUCCESS);
      r.seti(F_WORK_TYPE, wt);
      r.seti(F_PRIO, m.geti(F_PRIO));
      r.setl(F_HANDLE, {m.geti(F_SEQNO), m.src, m.geti(F_COMMON_LEN),
                        m.geti(F_COMMON_SERVER), m.geti(F_COMMON_SEQNO)});
      r.seti(F_WORK_LEN, m.geti(F_WORK_LEN));
      r.seti(F_ANSWER_RANK, m.geti(F_ANSWER_RANK, -1));
      ep_->send(app, r);
    } else {
      // stale belief: patch it (reference src/adlb.c:1979-2005)
      if (m.geti(F_TARGETED_LOOKUP)) {
        tq_remove(app, int32_t(m.geti(F_LOOKUP_TYPE)), m.src);
      } else {
        auto pit = peers_.find(m.src);
        if (pit != peers_.end()) {
          const std::vector<int64_t>* types = m.getl(F_REQ_TYPES);
          if (types != nullptr) {
            for (int64_t t : *types)
              pit->second.hi_prio[int32_t(t)] = ADLB_LOWEST_PRIO;
          } else {
            for (auto& tp : pit->second.hi_prio) tp.second = ADLB_LOWEST_PRIO;
          }
        }
      }
      rfr_excluded_[app].insert(m.src);
      RqEntry* e = rq_find_rank(app);
      if (e != nullptr) try_rfr(*e);
    }
  }

  void on_unreserve(const NMsg& m) {
    int64_t seqno = m.geti(F_SEQNO);
    auto it = wq_.units.find(seqno);
    if (it != wq_.units.end() && it->second.pin_rank >= 0) {
      it->second.pin_rank = -1;
      wq_.index(it->second);
      match_rq();
    }
  }

  // ---- push (memory pressure; reference src/adlb.c:509-556,2109-2362) -----
  const adlbwq::Unit* find_unpinned_for_push() {
    // prefer untargeted lowest priority; else any unpinned
    const adlbwq::Unit* worst = nullptr;
    for (const auto& kv : wq_.units) {
      const adlbwq::Unit& u = kv.second;
      if (u.pin_rank >= 0) continue;
      if (u.target_rank < 0 && (worst == nullptr || u.prio < worst->prio))
        worst = &u;
    }
    if (worst != nullptr) return worst;
    for (const auto& kv : wq_.units)
      if (kv.second.pin_rank < 0) return &kv.second;
    return nullptr;
  }

  void try_push() {
    if (!push_offered_.empty()) return;  // one outstanding push at a time
    const adlbwq::Unit* u = find_unpinned_for_push();
    if (u == nullptr) return;
    int target = -1;
    for (const auto& kv : peers_) {
      if (kv.first == rank_) continue;
      if (cfg_.max_malloc <= 0 ||
          double(kv.second.nbytes + u->payload_len) <= 0.9 * cfg_.max_malloc) {
        if (target < 0 || kv.second.nbytes < peers_[target].nbytes)
          target = kv.first;
      }
    }
    if (target < 0) return;
    int64_t qid = (int64_t(rank_) << 20) | (++push_seq_);
    push_offered_[qid] = u->seqno;
    NMsg m = mk(T_SS_PUSH_QUERY);
    m.seti(F_QUERY_ID, qid);
    m.seti(F_NBYTES, u->payload_len);
    ep_->send(target, m);
  }

  void on_push_query(const NMsg& m) {
    int64_t nbytes = m.geti(F_NBYTES);
    bool ok = mem_has_room(nbytes);
    if (ok) {
      mem_alloc(nbytes);  // reserved until WORK or DEL
      push_reserved_[m.geti(F_QUERY_ID)] = nbytes;
    }
    NMsg r = mk(T_SS_PUSH_QUERY_RESP);
    r.seti(F_QUERY_ID, m.geti(F_QUERY_ID));
    r.seti(F_ACCEPT, ok ? 1 : 0);
    ep_->send(m.src, r);
  }

  void on_push_query_resp(const NMsg& m) {
    int64_t qid = m.geti(F_QUERY_ID);
    auto oit = push_offered_.find(qid);
    if (oit == push_offered_.end()) return;
    int64_t seqno = oit->second;
    push_offered_.erase(oit);
    if (!m.geti(F_ACCEPT)) return;
    auto uit = wq_.units.find(seqno);
    if (uit == wq_.units.end() || uit->second.pin_rank >= 0) {
      // reserved while the query flew — cancel (reference SS_PUSH_DEL,
      // src/adlb.c:2182-2192)
      NMsg d = mk(T_SS_PUSH_DEL);
      d.seti(F_QUERY_ID, qid);
      ep_->send(m.src, d);
      return;
    }
    adlbwq::Unit unit = uit->second;
    Meta meta = std::move(meta_[seqno]);
    meta_.erase(seqno);
    wq_.total_bytes -= unit.payload_len;
    wq_.units.erase(uit);
    wq_.count -= 1;
    mem_free(int64_t(meta.payload.size()));
    stats_[K_NPUSHED_FROM_HERE] += 1;
    if (unit.target_rank >= 0) {
      int home = w_.home_server(unit.target_rank);
      NMsg mv = mk(T_SS_MOVING_TARGETED_WORK);
      mv.seti(F_APP_RANK, unit.target_rank);
      mv.seti(F_WORK_TYPE, unit.work_type);
      mv.seti(F_FROM_SERVER, rank_);
      mv.seti(F_TO_SERVER, m.src);
      ep_->send(home, mv);
    }
    NMsg wk = mk(T_SS_PUSH_WORK);
    wk.seti(F_QUERY_ID, qid);
    wk.setb(F_PAYLOAD, std::move(meta.payload));
    wk.seti(F_WORK_TYPE, unit.work_type);
    wk.seti(F_PRIO, unit.prio);
    wk.seti(F_TARGET_RANK, unit.target_rank);
    wk.seti(F_ANSWER_RANK, meta.answer_rank);
    wk.seti(F_HOME_SERVER, meta.home_server);
    wk.seti(F_COMMON_LEN, meta.common_len);
    wk.seti(F_COMMON_SERVER, meta.common_server);
    wk.seti(F_COMMON_SEQNO, meta.common_seqno);
    wk.setd(F_TIME_STAMP, meta.time_stamp);
    ep_->send(m.src, wk);
  }

  void on_push_work(const NMsg& m) {
    push_reserved_.erase(m.geti(F_QUERY_ID));  // budget now owned by the unit
    const std::string* payload = m.getb(F_PAYLOAD);
    static const std::string kEmpty;
    if (payload == nullptr) payload = &kEmpty;
    int64_t seqno = next_seqno_++;
    adlbwq::Unit u{seqno, int32_t(m.geti(F_WORK_TYPE)),
                   int32_t(m.geti(F_PRIO)), int32_t(m.geti(F_TARGET_RANK, -1)),
                   -1, int64_t(payload->size())};
    wq_.units.emplace(seqno, u);
    wq_.count += 1;
    if (wq_.count > wq_.max_count) wq_.max_count = wq_.count;
    wq_.total_bytes += u.payload_len;
    wq_.index(u);
    Meta& meta = meta_[seqno];
    meta.payload = *payload;
    meta.answer_rank = int32_t(m.geti(F_ANSWER_RANK, -1));
    meta.home_server = int32_t(m.geti(F_HOME_SERVER, -1));
    meta.common_len = m.geti(F_COMMON_LEN, 0);
    meta.common_server = m.geti(F_COMMON_SERVER, -1);
    meta.common_seqno = m.geti(F_COMMON_SEQNO, -1);
    meta.time_stamp = m.getd(F_TIME_STAMP, monotonic());
    stats_[K_NPUSHED_TO_HERE] += 1;
    match_rq();
  }

  void on_push_del(const NMsg& m) {
    auto it = push_reserved_.find(m.geti(F_QUERY_ID));
    if (it != push_reserved_.end()) {
      mem_free(it->second);
      push_reserved_.erase(it);
    }
  }

  void on_moving_targeted(const NMsg& m) {
    // home-server directory fixup (reference src/adlb.c:2071-2108)
    int app = int(m.geti(F_APP_RANK));
    int32_t wt = int32_t(m.geti(F_WORK_TYPE));
    int from = int(m.geti(F_FROM_SERVER));
    int to = int(m.geti(F_TO_SERVER));
    if (from != rank_) tq_remove(app, wt, from);
    if (to != rank_) tq_[app][wt][to] += 1;
    RqEntry* e = rq_find_rank(app);
    if (e != nullptr && e->wants(wt)) try_rfr(*e);
  }

  // ---- qmstat state broadcast (reference src/adlb.c:806-822) --------------
  std::vector<int64_t> refresh_self_entry() {
    PeerState& self = peers_[rank_];
    self.nbytes = mem_curr_;
    self.qlen = wq_num_unpinned_untargeted();
    std::vector<int64_t> prios;
    prios.reserve(w_.types.size());
    for (int32_t t : w_.types) {
      auto it = wq_.untargeted.find(t);
      const adlbwq::Unit* u =
          (it == wq_.untargeted.end()) ? nullptr : wq_.peek_best(&it->second, -1);
      int32_t p = (u == nullptr) ? ADLB_LOWEST_PRIO : u->prio;
      self.hi_prio[t] = p;
      prios.push_back(p);
    }
    return prios;
  }

  // flattened ring-token entry layout: (rank, nbytes, qlen, prio[T])*
  void token_set_entry(std::vector<int64_t>& tbl, int rank,
                       const PeerState& st,
                       const std::vector<int64_t>* prios) {
    size_t stride = 3 + w_.types.size();
    for (size_t i = 0; i + stride <= tbl.size(); i += stride) {
      if (tbl[i] == rank) {
        tbl[i + 1] = st.nbytes;
        tbl[i + 2] = st.qlen;
        for (size_t j = 0; j < w_.types.size(); ++j)
          tbl[i + 3 + j] = prios != nullptr
                               ? (*prios)[j]
                               : st.hi_prio.count(w_.types[j])
                                     ? st.hi_prio.at(w_.types[j])
                                     : ADLB_LOWEST_PRIO;
        return;
      }
    }
    tbl.push_back(rank);
    tbl.push_back(st.nbytes);
    tbl.push_back(st.qlen);
    for (size_t j = 0; j < w_.types.size(); ++j)
      tbl.push_back(prios != nullptr
                        ? (*prios)[j]
                        : st.hi_prio.count(w_.types[j])
                              ? st.hi_prio.at(w_.types[j])
                              : ADLB_LOWEST_PRIO);
  }

  void broadcast_qmstat() {
    std::vector<int64_t> prios = refresh_self_entry();
    PeerState& self = peers_[rank_];
    if (cfg_.qmstat_ring) {
      // reference-faithful store-and-forward ring token: master-kicked,
      // full table, per-hop staleness (reference src/adlb.c:806-822,
      // 1705-1757)
      if (master_ && w_.nservers > 1) {
        std::vector<int64_t> tbl;
        for (const auto& kv : peers_)
          token_set_entry(tbl, kv.first, kv.second,
                          kv.first == rank_ ? &prios : nullptr);
        NMsg m = mk(T_SS_QMSTAT);
        m.setl(F_QM_TABLE, tbl);
        m.seti(F_ORIGIN, rank_);
        m.setd(F_TIME_STAMP, monotonic());
        ep_->send(w_.ring_next(rank_), m);
      }
      return;
    }
    for (int s = w_.num_app_ranks(); s < w_.num_app_ranks() + w_.nservers;
         ++s) {
      if (s == rank_) continue;
      NMsg m = mk(T_SS_QMSTAT);
      m.seti(F_NBYTES, self.nbytes);
      m.seti(F_QLEN, self.qlen);
      m.setl(F_HI_PRIO, prios);
      ep_->send(s, m);
    }
  }

  void apply_peer_entry(int src, int64_t nbytes, int64_t qlen,
                        const int64_t* prios, size_t nprios) {
    PeerState& st = peers_[src];
    st.nbytes = nbytes;
    st.qlen = qlen;
    bool any_work = false;
    for (size_t i = 0; i < w_.types.size() && i < nprios; ++i) {
      st.hi_prio[w_.types[i]] = int32_t(prios[i]);
      if (prios[i] > ADLB_LOWEST_PRIO) any_work = true;
    }
    if (any_work)
      for (auto& kv : rfr_excluded_) kv.second.erase(src);
  }

  void on_qmstat(const NMsg& m) {
    const std::vector<int64_t>* tbl = m.getl(F_QM_TABLE);
    if (tbl != nullptr) {
      // ring token: install every entry except our own, then either record
      // the trip (back at origin, reference src/adlb.c:1731-1743) or
      // refresh our entry and forward
      size_t stride = 3 + w_.types.size();
      for (size_t i = 0; i + stride <= tbl->size(); i += stride) {
        int src = int((*tbl)[i]);
        if (src != rank_)
          apply_peer_entry(src, (*tbl)[i + 1], (*tbl)[i + 2],
                           tbl->data() + i + 3, w_.types.size());
      }
      if (int(m.geti(F_ORIGIN)) == rank_) {
        double trip = monotonic() - m.getd(F_TIME_STAMP);
        if (trip > stats_[K_MAX_QMSTAT_TRIP_TIME])
          stats_[K_MAX_QMSTAT_TRIP_TIME] = trip;
        qm_trips_ += 1;
        stats_[K_AVG_QMSTAT_TRIP_TIME] +=
            (trip - stats_[K_AVG_QMSTAT_TRIP_TIME]) / double(qm_trips_);
        if (trip > cfg_.qmstat_interval) stats_[K_NUM_QMS_EXCEED_INT] += 1;
      } else {
        std::vector<int64_t> out = *tbl;
        std::vector<int64_t> prios = refresh_self_entry();
        token_set_entry(out, rank_, peers_[rank_], &prios);
        NMsg fwd = mk(T_SS_QMSTAT);
        fwd.setl(F_QM_TABLE, out);
        fwd.seti(F_ORIGIN, m.geti(F_ORIGIN));
        fwd.setd(F_TIME_STAMP, m.getd(F_TIME_STAMP));
        ep_->send(w_.ring_next(rank_), fwd);
      }
    } else {
      apply_peer_entry(m.src, m.geti(F_NBYTES), m.geti(F_QLEN),
                       m.getl(F_HI_PRIO) ? m.getl(F_HI_PRIO)->data() : nullptr,
                       m.getl(F_HI_PRIO) ? m.getl(F_HI_PRIO)->size() : 0);
    }
    for (auto& e : rq_)
      if (!rfr_out_.count(e.world_rank)) try_rfr(e);
  }

  // ---- termination (reference src/adlb.c:754-785,1385-1801) ---------------
  void flush_rq(int rc) {
    std::vector<RqEntry> entries = rq_;
    rq_.clear();
    for (const auto& e : entries) reserve_resp_fail(e.world_rank, rc);
  }

  void on_fa_no_more_work(const NMsg& m) {
    if (no_more_work_) return;
    if (master_) {
      on_ss_no_more_work();
    } else {
      ep_->send(w_.master_server_rank(), mk(T_SS_NO_MORE_WORK));
    }
  }

  void on_ss_no_more_work() {
    if (no_more_work_) return;
    no_more_work_ = true;
    if (master_) {
      for (int s = w_.num_app_ranks(); s < w_.num_app_ranks() + w_.nservers;
           ++s)
        if (s != rank_) ep_->send(s, mk(T_SS_NO_MORE_WORK));
    }
    flush_rq(ADLB_NO_MORE_WORK);
  }

  bool all_local_apps_parked() {
    for (int app : local_apps_) {
      if (finalized_.count(app)) continue;
      if (rq_find_rank(app) == nullptr) return false;
    }
    return true;
  }

  bool exhaust_vote(const std::vector<int64_t>* parked) {
    if (!all_local_apps_parked()) return false;
    if (migrate_unacked_ != 0) return false;  // units inside a message
    if (wq_.count != wq_num_unpinned()) return false;  // handoff in flight
    if (parked != nullptr) {
      // flattened (rank, ntypes, t0..tn)*
      size_t i = 0;
      while (i < parked->size()) {
        RqEntry probe;
        probe.world_rank = int((*parked)[i++]);
        int64_t nt = (*parked)[i++];
        probe.any_type = (nt < 0);
        for (int64_t j = 0; j < nt; ++j)
          probe.req_types.push_back(int32_t((*parked)[i++]));
        if (wq_find_match(probe.world_rank, probe) != nullptr) return false;
      }
    }
    return true;
  }

  std::vector<int64_t> parked_list() {
    std::vector<int64_t> out;
    for (const auto& e : rq_) {
      out.push_back(e.world_rank);
      if (e.any_type) {
        out.push_back(-1);
      } else {
        out.push_back(int64_t(e.req_types.size()));
        for (int32_t t : e.req_types) out.push_back(t);
      }
    }
    return out;
  }

  void forward_exhaust(uint16_t tag, NMsg token) {
    int nxt = w_.ring_next(rank_);
    token.tag = tag;
    token.src = rank_;
    token.seti(F_COMPLETE, nxt == int(token.geti(F_ORIGIN)) ? 1 : 0);
    ep_->send(nxt, token);
  }

  void check_exhaustion(double now) {
    if (no_more_work_ || done_by_exhaustion_) return;
    if (exhaust_inflight_) {
      // lost-token recovery: a ring pass over S servers takes well under
      // a second; if the token has not come home in 10 intervals, assume
      // it died (a peer dropped it mid-restart / message lost) and allow
      // a fresh vote. The token id makes any late straggler harmless.
      if (now - exhaust_sent_at_ < 10 * cfg_.exhaust_check_interval) return;
      exhaust_inflight_ = false;
    }
    if (!exhaust_vote(nullptr)) { exhaust_held_ = false; return; }
    if (!exhaust_held_) {
      exhaust_held_ = true;
      exhaust_held_since_ = now;
      return;
    }
    if (now - exhaust_held_since_ < cfg_.exhaust_check_interval) return;
    exhaust_inflight_ = true;
    exhaust_sent_at_ = now;
    exhaust_token_id_ += 1;
    NMsg token = mk(T_SS_EXHAUST_CHK_1);
    token.seti(F_ORIGIN, rank_);
    token.seti(F_TOKEN_ID, exhaust_token_id_);
    token.seti(F_VOTE_OK, 1);
    token.setl(F_ACT, {rank_, activity_});
    token.seti(F_NPARKED, int64_t(rq_.size()));
    token.setl(F_PARKED, parked_list());
    forward_exhaust(T_SS_EXHAUST_CHK_1, token);
  }

  int64_t act_for_self(const std::vector<int64_t>* act) {
    if (act == nullptr) return -1;
    for (size_t i = 0; i + 1 < act->size(); i += 2)
      if ((*act)[i] == rank_) return (*act)[i + 1];
    return -1;
  }

  void on_exhaust_chk(const NMsg& m, bool phase1) {
    NMsg token = m;  // copy; we mutate fields then forward
    if (m.geti(F_COMPLETE) && int(m.geti(F_ORIGIN)) == rank_) {
      if (m.geti(F_TOKEN_ID) != exhaust_token_id_)
        return;  // straggler from a token we already gave up on
      const std::vector<int64_t>* parked = m.getl(F_PARKED);
      bool ok = m.geti(F_VOTE_OK) != 0 && m.geti(F_NPARKED) > 0 &&
                exhaust_vote(parked) &&
                activity_ == act_for_self(m.getl(F_ACT));
      if (!ok) {
        exhaust_held_ = false;
        exhaust_inflight_ = false;
        return;
      }
      if (phase1) {
        token.f.erase(F_COMPLETE);
        forward_exhaust(T_SS_EXHAUST_CHK_2, token);
      } else {
        exhaust_inflight_ = false;
        declare_exhaustion();
      }
      return;
    }
    if (phase1) {
      bool vote = exhaust_vote(nullptr);
      token.seti(F_VOTE_OK, (m.geti(F_VOTE_OK) != 0 && vote) ? 1 : 0);
      std::vector<int64_t> act =
          m.getl(F_ACT) ? *m.getl(F_ACT) : std::vector<int64_t>{};
      act.push_back(rank_);
      act.push_back(activity_);
      token.setl(F_ACT, act);
      token.seti(F_NPARKED, m.geti(F_NPARKED) + int64_t(rq_.size()));
      std::vector<int64_t> parked =
          m.getl(F_PARKED) ? *m.getl(F_PARKED) : std::vector<int64_t>{};
      std::vector<int64_t> mine = parked_list();
      parked.insert(parked.end(), mine.begin(), mine.end());
      token.setl(F_PARKED, parked);
      forward_exhaust(uint16_t(m.tag), token);
    } else {
      bool ok = m.geti(F_VOTE_OK) != 0 && exhaust_vote(m.getl(F_PARKED)) &&
                activity_ == act_for_self(m.getl(F_ACT));
      token.seti(F_VOTE_OK, ok ? 1 : 0);
      forward_exhaust(uint16_t(m.tag), token);
    }
  }

  void declare_exhaustion() {
    for (int s = w_.num_app_ranks(); s < w_.num_app_ranks() + w_.nservers; ++s)
      if (s != rank_) ep_->send(s, mk(T_SS_DONE_BY_EXHAUSTION));
    on_done_by_exhaustion();
  }

  void on_done_by_exhaustion() {
    if (done_by_exhaustion_) return;
    done_by_exhaustion_ = true;
    flush_rq(ADLB_DONE_BY_EXHAUSTION);
  }

  void on_local_app_done(const NMsg& m) {
    finalized_.insert(m.src);
    bool all_done = true;
    for (int app : local_apps_)
      if (!finalized_.count(app)) { all_done = false; break; }
    if (all_done) {
      if (master_ && !end1_pending_) {
        end1_pending_ = true;
        NMsg token = mk(T_SS_END_1);
        token.seti(F_ORIGIN, rank_);
        forward_end1(token);
      } else if (end1_pending_) {
        end1_pending_ = false;
        forward_end1(held_end1_);
      }
    }
  }

  void forward_end1(NMsg token) {
    int nxt = w_.ring_next(rank_);
    token.tag = T_SS_END_1;
    token.src = rank_;
    token.seti(F_COMPLETE, nxt == int(token.geti(F_ORIGIN)) ? 1 : 0);
    ep_->send(nxt, token);
  }

  void on_end_1(const NMsg& m) {
    ending_ = true;
    if (m.geti(F_COMPLETE) && int(m.geti(F_ORIGIN)) == rank_) {
      int nxt = w_.ring_next(rank_);
      NMsg token = mk(T_SS_END_2);
      token.seti(F_ORIGIN, m.geti(F_ORIGIN));
      token.seti(F_COMPLETE, nxt == int(m.geti(F_ORIGIN)) ? 1 : 0);
      ep_->send(nxt, token);
      if (w_.nservers == 1) done_ = true;
      return;
    }
    bool all_done = true;
    for (int app : local_apps_)
      if (!finalized_.count(app)) { all_done = false; break; }
    if (all_done) {
      NMsg token = m;
      forward_end1(token);
    } else {
      // hold until our apps finish (reference held END_LOOP_1,
      // src/adlb.c:1790-1798)
      end1_pending_ = true;
      held_end1_ = m;
    }
  }

  void on_end_2(const NMsg& m) {
    ending_ = true;
    done_ = true;
    if (!m.geti(F_COMPLETE)) {
      int nxt = w_.ring_next(rank_);
      NMsg token = mk(T_SS_END_2);
      token.seti(F_ORIGIN, m.geti(F_ORIGIN));
      token.seti(F_COMPLETE, nxt == int(m.geti(F_ORIGIN)) ? 1 : 0);
      ep_->send(nxt, token);
    }
  }

  // ---- periodic cluster-wide stats ring (reference src/adlb.c:712-753,
  // 2391-2465): master kicks a token; each server appends its packed
  // contribution; back at the master the sum is printed as <=500-byte
  // STAT_APS chunks, same format as the Python side (stats.py), parsed by
  // scripts/get_stats.py. Entry layout:
  //   i32 rank, i64 wq_count, i64 rq, i64 puts, i64 resolved, i64 nbytes,
  //   u32 nhist, (i32 type, i32 tgt, i64 n)*

  void append_pstats_entry(std::string& blob) {
    blob_i32(blob, rank_);
    blob_i64(blob, wq_.count);
    blob_i64(blob, int64_t(rq_.size()));
    blob_i64(blob, puts_ctr_);
    blob_i64(blob, resolved_ctr_);
    blob_i64(blob, mem_curr_);
    std::map<std::pair<int32_t, int32_t>, int64_t> hist;
    for (const auto& kv : wq_.units) {
      int32_t tgt = kv.second.target_rank < 0 ? -1 : kv.second.target_rank;
      hist[{kv.second.work_type, tgt}] += 1;
    }
    blob_u32(blob, uint32_t(hist.size()));
    for (const auto& h : hist) {
      blob_i32(blob, h.first.first);
      blob_i32(blob, h.first.second);
      blob_i64(blob, h.second);
    }
  }

  void kick_periodic_stats(double now) {
    if (no_more_work_ || done_by_exhaustion_) return;  // peers may be gone
    pstats_seq_ += 1;
    std::string blob;
    append_pstats_entry(blob);
    if (w_.nservers == 1) {
      emit_stat_aps(blob, pstats_seq_, now);
      return;
    }
    NMsg m = mk(T_SS_PERIODIC_STATS);
    m.setb(F_PSTATS_BLOB, std::move(blob));
    m.seti(F_SEQNO, pstats_seq_);
    m.seti(F_ORIGIN, rank_);
    m.setd(F_TIME_STAMP, now);
    ep_->send(w_.ring_next(rank_), m);
  }

  void on_periodic_stats(const NMsg& m) {
    const std::string* blob = m.getb(F_PSTATS_BLOB);
    if (blob == nullptr) return;
    if (int(m.geti(F_ORIGIN)) == rank_) {
      emit_stat_aps(*blob, m.geti(F_SEQNO), m.getd(F_TIME_STAMP));
      return;
    }
    std::string out = *blob;
    append_pstats_entry(out);
    NMsg fwd = mk(T_SS_PERIODIC_STATS);
    fwd.setb(F_PSTATS_BLOB, std::move(out));
    fwd.seti(F_SEQNO, m.geti(F_SEQNO));
    fwd.seti(F_ORIGIN, m.geti(F_ORIGIN));
    fwd.setd(F_TIME_STAMP, m.getd(F_TIME_STAMP));
    ep_->send(w_.ring_next(rank_), fwd);
  }

  void emit_stat_aps(const std::string& blob, int64_t seq, double t0) {
    // aggregate the packed entries into the JSON record stats.py emits
    struct Cell { int64_t targeted = 0, untargeted = 0; };
    std::map<int32_t, Cell> by_type;
    int64_t twq = 0, trq = 0, tputs = 0, tres = 0, tnb = 0;
    std::map<int32_t, std::array<int64_t, 3>> per_server;  // wq, rq, nbytes
    size_t off = 0;
    auto rd_i32 = [&](int32_t* v) {
      std::memcpy(v, blob.data() + off, 4); off += 4;
    };
    auto rd_i64 = [&](int64_t* v) {
      std::memcpy(v, blob.data() + off, 8); off += 8;
    };
    while (off + 4 + 5 * 8 + 4 <= blob.size()) {
      int32_t rank; int64_t wq, rq, puts, res, nb; uint32_t nhist;
      rd_i32(&rank); rd_i64(&wq); rd_i64(&rq); rd_i64(&puts);
      rd_i64(&res); rd_i64(&nb);
      std::memcpy(&nhist, blob.data() + off, 4); off += 4;
      for (uint32_t i = 0; i < nhist && off + 16 <= blob.size(); ++i) {
        int32_t t, tgt; int64_t n;
        rd_i32(&t); rd_i32(&tgt); rd_i64(&n);
        if (tgt >= 0) by_type[t].targeted += n;
        else by_type[t].untargeted += n;
      }
      twq += wq; trq += rq; tputs += puts; tres += res; tnb += nb;
      per_server[rank] = {wq, rq, nb};
    }
    double now = monotonic();
    std::ostringstream js;
    char num[64];
    std::snprintf(num, sizeof(num), "%.6f", now);
    js << "{\"seq\":" << seq << ",\"t\":" << num;
    std::snprintf(num, sizeof(num), "%.6f", now - t0);
    js << ",\"trip_s\":" << num
       << ",\"nservers\":" << per_server.size() << ",\"by_type\":{";
    bool first = true;
    for (const auto& kv : by_type) {
      if (!first) js << ",";
      first = false;
      js << "\"" << kv.first << "\":{\"targeted\":" << kv.second.targeted
         << ",\"untargeted\":" << kv.second.untargeted << "}";
    }
    js << "},\"total\":{\"wq\":" << twq << ",\"rq\":" << trq
       << ",\"puts\":" << tputs << ",\"resolved\":" << tres
       << ",\"nbytes\":" << tnb << "},\"per_server\":{";
    first = true;
    for (const auto& kv : per_server) {
      if (!first) js << ",";
      first = false;
      js << "\"" << kv.first << "\":{\"wq\":" << kv.second[0]
         << ",\"rq\":" << kv.second[1] << ",\"nbytes\":" << kv.second[2]
         << "}";
    }
    js << "}}";
    std::string payload = js.str();
    size_t nparts = (payload.size() + 499) / 500;
    if (nparts == 0) nparts = 1;
    for (size_t i = 0; i < nparts; ++i) {
      std::printf("STAT_APS: seq=%lld part=%zu/%zu %s\n",
                  (long long)seq, i + 1, nparts,
                  payload.substr(i * 500, 500).c_str());
    }
    std::fflush(stdout);
  }

  // ---- balancer sidecar (tpu mode) ----------------------------------------
  // The JAX brain runs in a Python sidecar process; this server streams
  // fixed-shape queue-state snapshots to it and enacts SS_PLAN_MATCH /
  // SS_PLAN_MIGRATE exactly like the Python server does (plan entries are
  // hints validated against live state; staleness is harmless).

  // Any available (unpinned, untargeted) unit? Amortized-cheap: peek_best
  // pops stale lazy-heap tops, each popped at most once over its lifetime.
  // A server holding only targeted work (gfmc's answer collectors) must
  // not count as snapshot-relevant — its walk would ship nothing.
  bool wq_has_untargeted() {
    for (auto& kv : wq_.untargeted)
      if (wq_.peek_best(&kv.second, -1) != nullptr) return true;
    return false;
  }

  void maybe_event_snapshot() {
    if (!cfg_.tpu_mode) return;
    double now = monotonic();
    if (now - last_event_snap_ < cfg_.balancer_min_gap) return;
    last_event_snap_ = now;
    send_snapshot();
  }

  void maybe_event_delta(int64_t seqno, int32_t wtype, int32_t prio,
                         int64_t len) {
    if (!cfg_.tpu_mode || cfg_.balancer_rank < 0) return;
    // accumulate; flush as ONE batched delta when the rate-limit gap
    // elapses (round 4): without batching a producer streaming puts was
    // visible to the balancer at one unit per gap — a lagging inventory
    // view that kept the fair-share pump's scarcity gate closed while
    // worker pools idled
    pend_seqnos_.push_back(seqno);
    pend_wtypes_.push_back(wtype);
    pend_prios_.push_back(prio);
    pend_lens_.push_back(len);
    double now = monotonic();
    if (now - last_event_snap_ >= cfg_.balancer_min_gap)
      flush_event_deltas(now);
  }

  void flush_event_deltas(double now) {
    if (pend_seqnos_.empty()) return;
    last_event_snap_ = now;
    NMsg m = mk(T_SS_STATE_DELTA);
    m.setl(F_SEQNOS, std::move(pend_seqnos_));
    m.setl(F_WORK_TYPES, std::move(pend_wtypes_));
    m.setl(F_PRIOS, std::move(pend_prios_));
    m.setl(F_WORK_LENS, std::move(pend_lens_));
    m.seti(F_NBYTES, mem_curr_);
    ep_->send(cfg_.balancer_rank, m);
    pend_seqnos_.clear();
    pend_wtypes_.clear();
    pend_prios_.clear();
    pend_lens_.clear();
  }

  void send_snapshot() {
    if (cfg_.balancer_rank < 0) return;
    // the full walk supersedes pending put deltas (units are in the wq)
    pend_seqnos_.clear();
    pend_wtypes_.clear();
    pend_prios_.clear();
    pend_lens_.clear();
    // top-K unpinned untargeted by (prio desc, seqno asc)
    std::vector<const adlbwq::Unit*> avail;
    avail.reserve(wq_.units.size());
    for (const auto& kv : wq_.units)
      if (kv.second.pin_rank < 0 && kv.second.target_rank < 0)
        avail.push_back(&kv.second);
    std::sort(avail.begin(), avail.end(),
              [](const adlbwq::Unit* a, const adlbwq::Unit* b) {
                if (a->prio != b->prio) return a->prio > b->prio;
                return a->seqno < b->seqno;
              });
    size_t k = std::min<size_t>(avail.size(), size_t(cfg_.balancer_max_tasks));
    std::vector<int64_t> tasks;
    tasks.reserve(4 * k);
    for (size_t i = 0; i < k; ++i) {
      tasks.push_back(avail[i]->seqno);
      tasks.push_back(avail[i]->work_type);
      tasks.push_back(avail[i]->prio);
      tasks.push_back(avail[i]->payload_len);
    }
    std::vector<int64_t> reqs;
    int64_t nreqs = 0;
    for (const auto& e : rq_) {
      if (nreqs >= cfg_.balancer_max_requesters) break;
      if (reqs.size() + 3 + e.req_types.size() > 60000) break;  // u16 codec
      if (rfr_out_.count(e.world_rank)) continue;  // RFR handoff pending
      reqs.push_back(e.world_rank);
      reqs.push_back(e.rqseqno);
      if (e.any_type) {
        reqs.push_back(-1);
      } else {
        reqs.push_back(int64_t(e.req_types.size()));
        for (int32_t t : e.req_types) reqs.push_back(t);
      }
      nreqs += 1;
    }
    // suppress repeat empty snapshots (an idle server must not wake the
    // sidecar every tick for nothing) — but an unreported mig_acks
    // change is NOT empty: the ack clears the planner's in-flight
    // credit, and swallowing it here would re-open the phantom-credit
    // stall the empty-batch ack exists to close
    bool empty = tasks.empty() && reqs.empty() &&
                 mig_acks_ == last_snap_acks_;
    if (empty && last_snap_empty_) return;
    last_snap_empty_ = empty;
    last_snap_acks_ = mig_acks_;
    int64_t consumers = 0;
    for (int app : local_apps_)
      if (!finalized_.count(app)) consumers += 1;
    NMsg m = mk(T_SS_STATE);
    m.setl(F_TASKS_FLAT, tasks);
    m.setl(F_REQS_FLAT, reqs);
    m.seti(F_NBYTES, mem_curr_);
    m.seti(F_CONSUMERS, consumers);
    std::vector<int64_t> acks;
    acks.reserve(2 * mig_acks_.size());
    for (const auto& kv : mig_acks_) {
      acks.push_back(kv.first);
      acks.push_back(kv.second);
    }
    m.setl(F_MIG_ACKS, std::move(acks));
    ep_->send(cfg_.balancer_rank, m);
  }

  void on_plan_match(const NMsg& m) {
    // enact one plan entry through the RFR response path (mirrors the
    // Python server's _on_plan_match)
    int64_t seqno = m.geti(F_SEQNO);
    auto it = wq_.units.find(seqno);
    if (it == wq_.units.end() || it->second.pin_rank >= 0 ||
        it->second.target_rank >= 0)
      return;  // stale plan entry; next round re-plans
    int for_rank = int(m.geti(F_FOR_RANK));
    it->second.pin_rank = for_rank;
    activity_ += 1;
    exhaust_held_ = false;
    const Meta& meta = meta_[seqno];
    NMsg r = mk(T_SS_RFR_RESP);
    r.seti(F_FOUND, 1);
    r.seti(F_FOR_RANK, for_rank);
    r.seti(F_RQSEQNO, m.geti(F_RQSEQNO));
    r.seti(F_SEQNO, seqno);
    r.seti(F_WORK_TYPE, it->second.work_type);
    r.seti(F_PRIO, it->second.prio);
    r.seti(F_TARGET_RANK, it->second.target_rank);
    r.seti(F_WORK_LEN, it->second.payload_len + meta.common_len);
    r.seti(F_ANSWER_RANK, meta.answer_rank);
    r.seti(F_COMMON_LEN, meta.common_len);
    r.seti(F_COMMON_SERVER, meta.common_server);
    r.seti(F_COMMON_SEQNO, meta.common_seqno);
    ep_->send(int(m.geti(F_REQ_HOME)), r);
  }

  static void blob_u32(std::string& b, uint32_t v) { b.append((const char*)&v, 4); }
  static void blob_i32(std::string& b, int32_t v) { b.append((const char*)&v, 4); }
  static void blob_i64(std::string& b, int64_t v) { b.append((const char*)&v, 8); }
  static void blob_f64(std::string& b, double v) { b.append((const char*)&v, 8); }

  void on_plan_migrate(const NMsg& m) {
    const std::vector<int64_t>* seqnos = m.getl(F_SEQNOS);
    if (seqnos == nullptr) return;
    // batch blob: [u32 n] then per unit
    // u32 plen, i32 type, i32 prio, i32 answer, i32 home,
    // i64 clen, i64 cserver, i64 cseqno, f64 ts, payload bytes
    std::string blob;
    uint32_t n = 0;
    blob_u32(blob, 0);  // patched below
    for (int64_t seqno : *seqnos) {
      auto it = wq_.units.find(seqno);
      if (it == wq_.units.end() || it->second.pin_rank >= 0 ||
          it->second.target_rank >= 0)
        continue;  // stale plan entry
      adlbwq::Unit unit = it->second;
      Meta meta = std::move(meta_[seqno]);
      meta_.erase(seqno);
      wq_.total_bytes -= unit.payload_len;
      wq_.units.erase(it);
      wq_.count -= 1;
      mem_free(int64_t(meta.payload.size()));
      stats_[K_NPUSHED_FROM_HERE] += 1;
      blob_u32(blob, uint32_t(meta.payload.size()));
      blob_i32(blob, unit.work_type);
      blob_i32(blob, unit.prio);
      blob_i32(blob, meta.answer_rank);
      blob_i32(blob, meta.home_server);
      blob_i64(blob, meta.common_len);
      blob_i64(blob, meta.common_server);
      blob_i64(blob, meta.common_seqno);
      blob_f64(blob, meta.time_stamp);
      blob.append(meta.payload);
      n += 1;
    }
    // a fully-stale batch is STILL sent, empty, carrying the planner's
    // batch id: the destination's ack clears the planner's in-flight
    // credit; silently dropping it left a phantom credit suppressing
    // solve+pump for that destination until the TTLs expired
    std::memcpy(blob.data(), &n, 4);
    if (n > 0) {
      activity_ += 1;
      exhaust_held_ = false;
    }
    migrate_unacked_ += 1;
    NMsg wk = mk(T_SS_MIGRATE_WORK);
    wk.setb(F_UNITS_BLOB, std::move(blob));
    wk.seti(F_BOUNCED, 0);
    wk.seti(F_MIG_ID, m.geti(F_MIG_ID));
    ep_->send(int(m.geti(F_DEST)), wk);
  }

  void on_migrate_work(const NMsg& m) {
    // ack the planner's batch id via the next snapshot, per source —
    // transport ordering only holds per sender pair (bounced resends
    // carry id 0: the original sighting already acked it)
    int64_t mid = m.geti(F_MIG_ID);
    if (mid > 0) {
      int64_t& slot = mig_acks_[m.src];
      slot = std::max(slot, mid);
    }
    const std::string* blob = m.getb(F_UNITS_BLOB);
    if (blob == nullptr || blob->size() < 4) return;
    bool bounced = m.geti(F_BOUNCED) != 0;
    size_t off = 0;
    uint32_t n;
    std::memcpy(&n, blob->data(), 4); off = 4;
    std::string bounce_blob;
    uint32_t n_bounced = 0;
    blob_u32(bounce_blob, 0);
    bool any_added = false;
    for (uint32_t i = 0; i < n; ++i) {
      if (off + 4 > blob->size()) die("truncated migrate blob");
      uint32_t plen;
      std::memcpy(&plen, blob->data() + off, 4);
      size_t rec = 4 + 4 * 4 + 3 * 8 + 8;
      if (off + rec + plen > blob->size()) die("truncated migrate blob");
      // admission control like every other ingress; an admitted unit is
      // never dropped — on a full server it bounces back ONCE, and the
      // sender must then keep it (overcommit beats losing work)
      if (!bounced && !mem_try_alloc(int64_t(plen))) {
        bounce_blob.append(*blob, off, rec + plen);
        n_bounced += 1;
        off += rec + plen;
        continue;
      }
      if (bounced) mem_alloc(int64_t(plen));
      int32_t wtype, prio, answer, home;
      int64_t clen, cserver, cseqno;
      double ts;
      size_t o = off + 4;
      std::memcpy(&wtype, blob->data() + o, 4); o += 4;
      std::memcpy(&prio, blob->data() + o, 4); o += 4;
      std::memcpy(&answer, blob->data() + o, 4); o += 4;
      std::memcpy(&home, blob->data() + o, 4); o += 4;
      std::memcpy(&clen, blob->data() + o, 8); o += 8;
      std::memcpy(&cserver, blob->data() + o, 8); o += 8;
      std::memcpy(&cseqno, blob->data() + o, 8); o += 8;
      std::memcpy(&ts, blob->data() + o, 8); o += 8;
      int64_t seqno = next_seqno_++;
      adlbwq::Unit u{seqno, wtype, prio, -1, -1, int64_t(plen)};
      wq_.units.emplace(seqno, u);
      wq_.count += 1;
      if (wq_.count > wq_.max_count) wq_.max_count = wq_.count;
      wq_.total_bytes += u.payload_len;
      wq_.index(u);
      Meta& meta = meta_[seqno];
      meta.payload.assign(blob->data() + o, plen);
      meta.answer_rank = answer;
      meta.home_server = home;
      meta.common_len = clen;
      meta.common_server = cserver;
      meta.common_seqno = cseqno;
      meta.time_stamp = ts;
      stats_[K_NPUSHED_TO_HERE] += 1;
      any_added = true;
      off += rec + plen;
    }
    ep_->send(m.src, mk(T_SS_MIGRATE_ACK));
    if (n_bounced > 0) {
      std::memcpy(bounce_blob.data(), &n_bounced, 4);
      migrate_unacked_ += 1;
      NMsg wk = mk(T_SS_MIGRATE_WORK);
      wk.setb(F_UNITS_BLOB, std::move(bounce_blob));
      wk.seti(F_BOUNCED, 1);
      ep_->send(m.src, wk);
    }
    if (any_added) match_rq();
    // immediate full snapshot: the batch ack and the post-batch
    // inventory reach the planner now, not a heartbeat later — the
    // follow-up top-up cadence rides on this. Sent for empty id-bearing
    // batches too: the ack clearing the phantom credit must not wait
    // for the next heartbeat.
    if (cfg_.tpu_mode && (any_added || mid > 0)) send_snapshot();
  }

  void on_peer_eof(const NMsg& m) {
    // benign during termination; before it, a rank died without finalizing
    // (connection-based: a rank that never sent a frame is invisible here).
    // Only the HOME server judges an app EOF — finalize knowledge is
    // home-local, and finished apps legitimately EOF at other servers.
    if (done_ || no_more_work_ || done_by_exhaustion_ || aborted_ || ending_)
      return;
    if (w_.is_app(m.src) && w_.home_server(m.src) == rank_ &&
        !finalized_.count(m.src)) {
      std::fprintf(stderr,
                   "[adlb_serverd %d] app rank %d connection lost before "
                   "finalize; aborting the world\n", rank_, m.src);
      do_abort(-3, true);
    } else if (w_.is_server(m.src)) {
      std::fprintf(stderr,
                   "[adlb_serverd %d] server rank %d connection lost "
                   "mid-run; aborting\n", rank_, m.src);
      do_abort(-3, true);
    }
  }

  // ---- abort --------------------------------------------------------------
  void do_abort(int code, bool broadcast) {
    if (aborted_) return;
    aborted_ = true;
    abort_code_ = code;
    if (broadcast) {
      for (int s = w_.num_app_ranks(); s < w_.num_app_ranks() + w_.nservers;
           ++s) {
        if (s == rank_) continue;
        NMsg a = mk(T_SS_ABORT);
        a.seti(F_CODE, code);
        ep_->send(s, a);
      }
    }
    for (int app : local_apps_) {
      NMsg a = mk(T_TA_ABORT);
      a.seti(F_CODE, code);
      ep_->send(app, a);
    }
    std::printf("ABORT %d\n", code);
    std::fflush(stdout);
    done_ = true;
  }

  World w_;
  Cfg cfg_;
  int rank_;
  Endpoint* ep_;
  bool master_ = false;
  std::set<int> local_apps_;

  adlbwq::WorkQueue wq_;
  std::unordered_map<int64_t, Meta> meta_;
  std::vector<RqEntry> rq_;  // insert-ordered, one per rank
  // tq: app -> type -> server -> count (reference src/xq.h:73-79)
  std::unordered_map<int, std::unordered_map<int32_t, std::map<int, int>>> tq_;
  std::unordered_map<int64_t, CommonEntry> cq_;
  std::map<int, PeerState> peers_;

  int64_t next_seqno_ = 1;
  int64_t next_common_seqno_ = 1;
  int64_t mem_curr_ = 0, mem_hwm_ = 0;

  std::unordered_set<int> rfr_out_;
  std::unordered_map<int, std::unordered_set<int>> rfr_excluded_;
  int64_t push_seq_ = 0;
  std::unordered_map<int64_t, int64_t> push_offered_;   // qid -> seqno
  std::unordered_map<int64_t, int64_t> push_reserved_;  // qid -> bytes
  int64_t migrate_unacked_ = 0;
  std::vector<NMsg> held_ckpts_;  // tokens parked on in-flight migrations
  double last_event_snap_ = 0.0;
  // put-event deltas pending behind the rate-limit gap (batched flush)
  std::vector<int64_t> pend_seqnos_, pend_wtypes_, pend_prios_, pend_lens_;
  bool hungry_ = false;  // sidecar says: parked requesters exist somewhere
  bool hungry_any_ = false;  // ... and one of them accepts any type
  std::set<int32_t> hungry_types_;  // the types parked requesters want
  double next_idle_snap_ = 0.0;  // slow snapshot heartbeat when not hungry
  bool last_snap_empty_ = false;
  // src server -> highest planner migration-batch id received from it
  std::map<int, int64_t> mig_acks_;
  std::map<int, int64_t> last_snap_acks_;  // acks as of last sent snapshot

  bool no_more_work_ = false;
  bool done_by_exhaustion_ = false;
  bool done_ = false;
  bool aborted_ = false;
  int abort_code_ = 0;
  std::set<int> finalized_;
  bool end1_pending_ = false;
  bool ending_ = false;  // shutdown ring underway: peer EOFs are benign
  NMsg held_end1_;
  bool exhaust_held_ = false;
  double exhaust_held_since_ = 0.0;
  bool exhaust_inflight_ = false;
  double exhaust_sent_at_ = 0.0;
  int64_t exhaust_token_id_ = 0;
  int64_t activity_ = 0;

  std::vector<double> stats_;
  double rq_wait_sum_ = 0.0;
  int64_t rq_wait_n_ = 0;
  double next_qmstat_ = 0.0, next_exhaust_ = 0.0, next_ds_log_ = 0.0;
  int64_t qm_trips_ = 0;
  int64_t puts_ctr_ = 0, resolved_ctr_ = 0, pstats_seq_ = 0;
  // since-last-DS_LOG counters (reference src/adlb.c:3222-3259)
  int64_t events_ctr_ = 0, ss_msgs_ctr_ = 0, reserve_immed_ctr_ = 0,
          rfr_failed_ctr_ = 0;
  struct { int64_t events = 0, ss = 0, reserves = 0, immed = 0, parked = 0,
                   rfr_failed = 0; } ds_last_;
  double next_pstats_ = 0.0;
};

}  // namespace

int main() {
  World w;
  Cfg cfg;
  int rank = -1;
  std::string line;
  // phase 1: config
  while (std::getline(std::cin, line)) {
    std::istringstream is(line);
    std::string key;
    is >> key;
    if (key == "endconfig") break;
    if (key == "nranks") is >> w.nranks;
    else if (key == "nservers") is >> w.nservers;
    else if (key == "use_debug_server") { int v; is >> v; w.use_debug_server = v != 0; }
    else if (key == "types") { int t; while (is >> t) w.types.push_back(t); }
    else if (key == "rank") is >> rank;
    else if (key == "qmstat_interval") is >> cfg.qmstat_interval;
    else if (key == "exhaust_check_interval") is >> cfg.exhaust_check_interval;
    else if (key == "max_malloc") is >> cfg.max_malloc;
    else if (key == "balancer") {
      std::string v; is >> v;
      cfg.tpu_mode = (v == "tpu");
    }
    else if (key == "balancer_rank") is >> cfg.balancer_rank;
    else if (key == "debug_log_interval") is >> cfg.debug_log_interval;
    else if (key == "periodic_log_interval") is >> cfg.periodic_log_interval;
    else if (key == "qmstat_mode") {
      std::string v; is >> v;
      cfg.qmstat_ring = (v == "ring");
    }
    else if (key == "balancer_interval") is >> cfg.balancer_interval;
    else if (key == "balancer_min_gap") is >> cfg.balancer_min_gap;
    else if (key == "balancer_max_tasks") is >> cfg.balancer_max_tasks;
    else if (key == "balancer_max_requesters") is >> cfg.balancer_max_requesters;
    else if (key == "restore_path") {
      is >> std::ws;
      std::getline(is, cfg.restore_path);  // rest of line: paths may have spaces
    }
    else if (!key.empty()) die("unknown config key '%s'", key.c_str());
  }
  if (rank < 0 || !w.is_server(rank)) die("bad or missing rank");
  Endpoint ep;
  int port = ep.listen_any();
  std::printf("PORT %d\n", port);
  std::fflush(stdout);
  // phase 2: address map
  while (std::getline(std::cin, line)) {
    std::istringstream is(line);
    std::string key;
    is >> key;
    if (key == "endaddrs") break;
    if (key == "addr") {
      int r, p;
      std::string host;
      is >> r >> host >> p;
      ep.set_addr(r, host, p);
    }
  }
  Server server(w, cfg, rank, &ep);
  server.run();
  server.notify_balancer_end();
  server.print_stats();
  ep.close_all();
  // readers may still be blocked in recv; exit hard after stats are out
  std::_Exit(server.aborted() ? 2 : 0);
}
