"""Native core: C++ implementations of the hot server paths.

Built on demand with the system toolchain (g++ only; no pip/pybind11) and
loaded via ctypes. Everything here has a pure-Python fallback — the native
path is a drop-in accelerator, never a requirement.
"""

from adlb_tpu.native.build import ensure_built, native_available  # noqa: F401
from adlb_tpu.native.wq import NativeWorkQueue  # noqa: F401
