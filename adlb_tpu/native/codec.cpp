// Compiled TLV wire codec — the C twin of adlb_tpu/runtime/codec.py's
// encode_binary_iov / decode_binary (which remain the authoritative
// fallback twin; the parity fuzz in tests/test_codec_fuzz.py holds the
// two byte-identical).
//
// Loaded with ctypes.PyDLL — the wqcore O(1)-getter discipline from the
// PR 7 pop-latency fix, extended to a whole hot path: the GIL stays held
// (these functions manipulate PyObjects and never block or do I/O), so a
// call costs a plain C call instead of a GIL bounce, and the CPython API
// is usable directly. Python header/ABI only; no pip, no setuptools —
// built by adlb_tpu/native/build.py::ensure_codec with the system g++,
// exactly like wqcore.
//
// Layout contract (keep in sync with codec.py, the module docstring
// there is the registry of record):
//
//   u8  magic 0x01 | u16 tag | i32 src | u16 nfields
//   per field: u8 fid | u8 kind | value
//   kinds: 0=i64, 1=bytes(u32 len+data), 2=i64 list(u16 cnt+i64*),
//          3=f64, 4=bytes list(u16 cnt,(u32 len+data)*), 5=f64 list
//
// All integers little-endian; this file memcpy's scalars directly and is
// gated to little-endian hosts at build time (the same x86-64 assumption
// the shm ring's TSO publish discipline already bakes in).

#include <Python.h>

#include <cstdint>
#include <cstring>

#if defined(__BYTE_ORDER__) && __BYTE_ORDER__ != __ORDER_LITTLE_ENDIAN__
#error "compiled TLV codec requires a little-endian host"
#endif

namespace {

enum Kind {
    K_I64 = 0,
    K_BYTES = 1,
    K_LIST = 2,
    K_F64 = 3,
    K_BLIST = 4,
    K_FLIST = 5,
};

// bytes fields at least this large ride the iovec as zero-copy parts
// (codec.py IOV_INLINE_MAX — set from Python at setup so the twins can
// never drift)
Py_ssize_t g_inline_max = 512;

// encode table: field-name str -> PyLong((fid << 8) | kind); the key
// objects are the very strings codec.py's FIELDS holds, so lookups hit
// the interned-pointer fast path inside PyDict_GetItem
PyObject* g_field_ids = nullptr;
// decode table: fid -> (owned name str, kind); absent = unknown field
// (parsed and skipped, not fatal — codec.py semantics)
struct FieldInfo {
    PyObject* name;
    int kind;
};
FieldInfo g_by_fid[256];

// ---------------------------------------------------------------- writer

struct Enc {
    char* buf;
    Py_ssize_t len, cap;
    PyObject* parts;  // list[bytes | original big-payload object]

    bool reserve(Py_ssize_t n) {
        if (len + n <= cap) return true;
        Py_ssize_t want = cap ? cap * 2 : 1024;
        while (want < len + n) want *= 2;
        char* nb = static_cast<char*>(PyMem_Realloc(buf, want));
        if (!nb) {
            PyErr_NoMemory();
            return false;
        }
        buf = nb;
        cap = want;
        return true;
    }
    bool put(const void* p, Py_ssize_t n) {
        if (!reserve(n)) return false;
        memcpy(buf + len, p, n);
        len += n;
        return true;
    }
    bool u8(uint8_t v) { return put(&v, 1); }
    bool u16(uint16_t v) { return put(&v, 2); }
    bool u32(uint32_t v) { return put(&v, 4); }
    bool i32(int32_t v) { return put(&v, 4); }
    bool i64(int64_t v) { return put(&v, 8); }
    bool f64(double v) { return put(&v, 8); }

    // seal the accumulated segment into parts (no-op when empty)
    bool flush() {
        if (!len) return true;
        PyObject* b = PyBytes_FromStringAndSize(buf, len);
        if (!b) return false;
        int rc = PyList_Append(parts, b);
        Py_DECREF(b);
        len = 0;
        return rc == 0;
    }
};

// int(value) as the Python twin does: fast path for real ints, nb_int
// coercion otherwise
bool as_i64(PyObject* v, int64_t* out) {
    if (PyLong_Check(v)) {
        long long x = PyLong_AsLongLong(v);
        if (x == -1 && PyErr_Occurred()) return false;
        *out = x;
        return true;
    }
    PyObject* n = PyNumber_Long(v);
    if (!n) return false;
    long long x = PyLong_AsLongLong(n);
    Py_DECREF(n);
    if (x == -1 && PyErr_Occurred()) return false;
    *out = x;
    return true;
}

// _bytes_view twin: a flat byte view of a bytes-ish value, plus which
// object to append to parts for the zero-copy path (the original when
// it is itself a flat byte buffer, a flattened copy otherwise).
struct BytesView {
    Py_buffer view{};
    PyObject* flat = nullptr;  // owned flattened copy, when needed
    bool have_view = false;

    ~BytesView() {
        if (have_view) PyBuffer_Release(&view);
        Py_XDECREF(flat);
    }
    bool acquire(PyObject* v) {
        if (PyObject_GetBuffer(v, &view, PyBUF_SIMPLE) == 0) {
            have_view = true;
            return true;
        }
        // non-contiguous exporter: flatten, as bytes(value) would
        PyErr_Clear();
        flat = PyBytes_FromObject(v);
        if (!flat) return false;
        if (PyObject_GetBuffer(flat, &view, PyBUF_SIMPLE) != 0) return false;
        have_view = true;
        return true;
    }
    // the object whose bytes equal the view, safe to hand to sendmsg /
    // ring writers as its own iovec part
    PyObject* part_obj(PyObject* v) const {
        if (flat) return flat;
        if (PyBytes_Check(v) || PyByteArray_Check(v)) return v;
        if (PyMemoryView_Check(v)) {
            const Py_buffer* b = PyMemoryView_GET_BUFFER(v);
            if (b->itemsize == 1 && b->ndim == 1) return v;
        }
        return nullptr;  // exotic exporter: caller copies
    }
};

bool write_bytes_field(Enc* e, PyObject* v) {
    BytesView bv;
    if (!bv.acquire(v)) return false;
    Py_ssize_t n = bv.view.len;
    if (!e->u32(static_cast<uint32_t>(n))) return false;
    if (n >= g_inline_max) {
        if (!e->flush()) return false;
        PyObject* part = bv.part_obj(v);
        if (part != nullptr) {
            if (PyList_Append(e->parts, part) != 0) return false;
        } else {
            PyObject* copy = PyBytes_FromStringAndSize(
                static_cast<const char*>(bv.view.buf), n);
            if (!copy) return false;
            int rc = PyList_Append(e->parts, copy);
            Py_DECREF(copy);
            if (rc != 0) return false;
        }
        return true;
    }
    return e->put(bv.view.buf, n);
}

bool write_field(Enc* e, PyObject* name, PyObject* v, int kind) {
    switch (kind) {
        case K_I64: {
            int64_t x;
            if (!as_i64(v, &x)) return false;
            return e->i64(x);
        }
        case K_BYTES:
            return write_bytes_field(e, v);
        case K_LIST: {
            PyObject* seq = PySequence_Fast(v, "i64-list field not iterable");
            if (!seq) return false;
            Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
            if (n > 65535) {
                Py_DECREF(seq);
                PyErr_Format(PyExc_ValueError,
                             "list field %U overflows u16 bound", name);
                return false;
            }
            if (!e->u16(static_cast<uint16_t>(n))) {
                Py_DECREF(seq);
                return false;
            }
            PyObject** items = PySequence_Fast_ITEMS(seq);
            for (Py_ssize_t i = 0; i < n; i++) {
                int64_t x;
                if (!as_i64(items[i], &x) || !e->i64(x)) {
                    Py_DECREF(seq);
                    return false;
                }
            }
            Py_DECREF(seq);
            return true;
        }
        case K_F64: {
            double x = PyFloat_AsDouble(v);
            if (x == -1.0 && PyErr_Occurred()) return false;
            return e->f64(x);
        }
        case K_BLIST: {
            PyObject* seq = PySequence_Fast(v, "bytes-list field not iterable");
            if (!seq) return false;
            Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
            if (n > 65535) {
                Py_DECREF(seq);
                PyErr_Format(PyExc_ValueError,
                             "blist field %U overflows u16 bound", name);
                return false;
            }
            if (!e->u16(static_cast<uint16_t>(n))) {
                Py_DECREF(seq);
                return false;
            }
            PyObject** items = PySequence_Fast_ITEMS(seq);
            for (Py_ssize_t i = 0; i < n; i++) {
                if (!write_bytes_field(e, items[i])) {
                    Py_DECREF(seq);
                    return false;
                }
            }
            Py_DECREF(seq);
            return true;
        }
        case K_FLIST: {
            PyObject* seq = PySequence_Fast(v, "f64-list field not iterable");
            if (!seq) return false;
            Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
            if (n > 65535) {
                Py_DECREF(seq);
                PyErr_Format(PyExc_ValueError,
                             "flist field %U overflows u16 bound", name);
                return false;
            }
            if (!e->u16(static_cast<uint16_t>(n))) {
                Py_DECREF(seq);
                return false;
            }
            PyObject** items = PySequence_Fast_ITEMS(seq);
            for (Py_ssize_t i = 0; i < n; i++) {
                double x = PyFloat_AsDouble(items[i]);
                if (x == -1.0 && PyErr_Occurred()) {
                    Py_DECREF(seq);
                    return false;
                }
                if (!e->f64(x)) {
                    Py_DECREF(seq);
                    return false;
                }
            }
            Py_DECREF(seq);
            return true;
        }
    }
    PyErr_Format(PyExc_ValueError, "bad field kind %d", kind);
    return false;
}

// ---------------------------------------------------------------- reader

struct Dec {
    const uint8_t* p;
    Py_ssize_t len, off;

    bool need(Py_ssize_t n) {
        if (off + n > len) {
            PyErr_SetString(PyExc_ValueError,
                            "truncated binary frame");
            return false;
        }
        return true;
    }
    uint8_t u8() { return p[off++]; }
    uint16_t u16() {
        uint16_t v;
        memcpy(&v, p + off, 2);
        off += 2;
        return v;
    }
    uint32_t u32() {
        uint32_t v;
        memcpy(&v, p + off, 4);
        off += 4;
        return v;
    }
    int32_t i32() {
        int32_t v;
        memcpy(&v, p + off, 4);
        off += 4;
        return v;
    }
    int64_t i64() {
        int64_t v;
        memcpy(&v, p + off, 8);
        off += 8;
        return v;
    }
    double f64() {
        double v;
        memcpy(&v, p + off, 8);
        off += 8;
        return v;
    }
};

// one field's VALUE (already past fid/kind); returns new ref or NULL
PyObject* read_value(Dec* d, int kind) {
    switch (kind) {
        case K_I64:
            if (!d->need(8)) return nullptr;
            return PyLong_FromLongLong(d->i64());
        case K_BYTES: {
            if (!d->need(4)) return nullptr;
            uint32_t n = d->u32();
            if (!d->need(n)) {
                PyErr_SetString(PyExc_ValueError,
                                "truncated bytes field in binary frame");
                return nullptr;
            }
            PyObject* b = PyBytes_FromStringAndSize(
                reinterpret_cast<const char*>(d->p + d->off), n);
            d->off += n;
            return b;
        }
        case K_LIST: {
            if (!d->need(2)) return nullptr;
            uint16_t cnt = d->u16();
            if (!d->need(static_cast<Py_ssize_t>(cnt) * 8)) return nullptr;
            PyObject* out = PyList_New(cnt);
            if (!out) return nullptr;
            for (uint16_t i = 0; i < cnt; i++) {
                PyObject* x = PyLong_FromLongLong(d->i64());
                if (!x) {
                    Py_DECREF(out);
                    return nullptr;
                }
                PyList_SET_ITEM(out, i, x);
            }
            return out;
        }
        case K_F64:
            if (!d->need(8)) return nullptr;
            return PyFloat_FromDouble(d->f64());
        case K_BLIST: {
            if (!d->need(2)) return nullptr;
            uint16_t cnt = d->u16();
            PyObject* out = PyList_New(cnt);
            if (!out) return nullptr;
            for (uint16_t i = 0; i < cnt; i++) {
                if (!d->need(4)) {
                    Py_DECREF(out);
                    return nullptr;
                }
                uint32_t n = d->u32();
                if (!d->need(n)) {
                    PyErr_SetString(
                        PyExc_ValueError,
                        "truncated blist item in binary frame");
                    Py_DECREF(out);
                    return nullptr;
                }
                PyObject* b = PyBytes_FromStringAndSize(
                    reinterpret_cast<const char*>(d->p + d->off), n);
                d->off += n;
                if (!b) {
                    Py_DECREF(out);
                    return nullptr;
                }
                PyList_SET_ITEM(out, i, b);
            }
            return out;
        }
        case K_FLIST: {
            if (!d->need(2)) return nullptr;
            uint16_t cnt = d->u16();
            if (!d->need(static_cast<Py_ssize_t>(cnt) * 8)) return nullptr;
            PyObject* out = PyList_New(cnt);
            if (!out) return nullptr;
            for (uint16_t i = 0; i < cnt; i++) {
                PyObject* x = PyFloat_FromDouble(d->f64());
                if (!x) {
                    Py_DECREF(out);
                    return nullptr;
                }
                PyList_SET_ITEM(out, i, x);
            }
            return out;
        }
    }
    PyErr_Format(PyExc_ValueError, "bad field kind %d", kind);
    return nullptr;
}

}  // namespace

// ------------------------------------------------------------- entrypoints

namespace {

// protocol objects handed over by codec.py at setup
PyObject* g_wire_tag = nullptr;      // dict Tag -> int
PyObject* g_tag_by_wire[2048];       // wire id -> Tag member (owned)
PyObject* g_msg_cls = nullptr;       // adlb_tpu.runtime.messages.Msg
PyObject* g_s_tag = nullptr;         // interned "tag"/"src"/"data"/"hang"
PyObject* g_s_src = nullptr;
PyObject* g_s_data = nullptr;
PyObject* g_s_hang = nullptr;

// fields: dict name -> (fid, kind); inline_max: codec.py IOV_INLINE_MAX.
// Idempotent (re-setup replaces the tables); returns 0 / -1.
int setup_tables(PyObject* fields, int inline_max) {
    PyObject* ids = PyDict_New();
    if (!ids) return -1;
    for (auto& fi : g_by_fid) {
        Py_CLEAR(fi.name);
        fi.kind = -1;
    }
    PyObject *key, *val;
    Py_ssize_t pos = 0;
    while (PyDict_Next(fields, &pos, &key, &val)) {
        long fid = PyLong_AsLong(PyTuple_GET_ITEM(val, 0));
        long kind = PyLong_AsLong(PyTuple_GET_ITEM(val, 1));
        if ((fid == -1 || kind == -1) && PyErr_Occurred()) {
            Py_DECREF(ids);
            return -1;
        }
        PyObject* packed = PyLong_FromLong((fid << 8) | kind);
        if (!packed || PyDict_SetItem(ids, key, packed) != 0) {
            Py_XDECREF(packed);
            Py_DECREF(ids);
            return -1;
        }
        Py_DECREF(packed);
        if (fid >= 0 && fid < 256) {
            Py_INCREF(key);
            g_by_fid[fid].name = key;
            g_by_fid[fid].kind = static_cast<int>(kind);
        }
    }
    Py_XDECREF(g_field_ids);
    g_field_ids = ids;
    g_inline_max = inline_max;
    return 0;
}

// encode_binary_iov twin: (wire_tag, src, data dict) -> parts list whose
// concatenation is the frame body; big bytes values ride as their own
// zero-copy parts.
PyObject* encode_iov_raw(int wire_tag, int src, PyObject* data) {
    Enc e{nullptr, 0, 0, nullptr};
    e.parts = PyList_New(0);
    if (!e.parts) return nullptr;

    // nfields must land in the header before any field is streamed, so
    // count the non-None fields first (PyDict_Next is two pointer reads
    // per entry — cheaper than patching across already-sealed parts)
    PyObject *key, *val;
    Py_ssize_t pos = 0;
    Py_ssize_t nfields = 0;
    while (PyDict_Next(data, &pos, &key, &val)) {
        if (val != Py_None) nfields++;
    }

    bool ok = e.u8(0x01) && e.u16(static_cast<uint16_t>(wire_tag)) &&
              e.i32(src) && e.u16(static_cast<uint16_t>(nfields));
    pos = 0;
    while (ok && PyDict_Next(data, &pos, &key, &val)) {
        if (val == Py_None) continue;
        PyObject* packed = PyDict_GetItemWithError(g_field_ids, key);
        if (!packed) {
            if (!PyErr_Occurred()) PyErr_SetObject(PyExc_KeyError, key);
            ok = false;
            break;
        }
        long fk = PyLong_AsLong(packed);
        ok = e.u8(static_cast<uint8_t>(fk >> 8)) &&
             e.u8(static_cast<uint8_t>(fk & 0xff)) &&
             write_field(&e, key, val, static_cast<int>(fk & 0xff));
    }
    if (ok) ok = e.flush();
    PyMem_Free(e.buf);
    if (!ok) {
        Py_DECREF(e.parts);
        return nullptr;
    }
    return e.parts;
}

// decode_binary twin up to Msg construction: body buffer ->
// (wire_tag, src, data dict). Unknown field ids are parsed and
// skipped, exactly like the Python twin.
PyObject* decode_raw(PyObject* body) {
    Py_buffer view;
    if (PyObject_GetBuffer(body, &view, PyBUF_SIMPLE) != 0) return nullptr;
    Dec d{static_cast<const uint8_t*>(view.buf), view.len, 0};
    PyObject* out = nullptr;
    PyObject* dict = nullptr;

    do {
        if (!d.need(9)) break;
        uint8_t magic = d.u8();
        if (magic != 0x01) {
            PyErr_Format(PyExc_ValueError, "bad binary frame magic %#x",
                         magic);
            break;
        }
        uint16_t tag = d.u16();
        int32_t src = d.i32();
        uint16_t nfields = d.u16();
        dict = PyDict_New();
        if (!dict) break;
        bool ok = true;
        for (uint16_t i = 0; ok && i < nfields; i++) {
            if (!d.need(2)) {
                ok = false;
                break;
            }
            uint8_t fid = d.u8();
            uint8_t kind = d.u8();
            PyObject* value = read_value(&d, kind);
            if (!value) {
                ok = false;
                break;
            }
            // unknown fields are skipped, not fatal; a KNOWN fid is
            // stored under its name whatever kind it arrived as — the
            // Python twin's exact rule (FIELD_FOR_WIRE.get, no kind
            // cross-check), kept bug-for-bug so the fuzz can hold the
            // twins identical
            const FieldInfo& fi = g_by_fid[fid];
            if (fi.name != nullptr) {
                ok = PyDict_SetItem(dict, fi.name, value) == 0;
            }
            Py_DECREF(value);
        }
        if (!ok) break;
        out = Py_BuildValue("(iiN)", static_cast<int>(tag),
                            static_cast<int>(src), dict);
        dict = nullptr;  // reference stolen by N
    } while (false);

    Py_XDECREF(dict);
    PyBuffer_Release(&view);
    return out;
}

// ------------------------------------------------- Python-callable layer
//
// The .so is NOT an importable extension module: build.py dlopens it
// with ctypes.PyDLL (the wqcore loading discipline) and calls
// adlb_codec_module() ONCE, which hands back a real module object whose
// functions are METH_FASTCALL builtins — per-frame calls then cost a
// builtin vector call, not a ctypes FFI marshal (measured ~3x the
// difference on small frames).

PyObject* py_setup(PyObject*, PyObject* const* args, Py_ssize_t nargs) {
    // (fields, inline_max, wire_tag: dict Tag->int,
    //  tag_for_wire: dict int->Tag, msg_cls)
    if (nargs != 5) {
        PyErr_SetString(PyExc_TypeError, "setup expects 5 arguments");
        return nullptr;
    }
    long inline_max = PyLong_AsLong(args[1]);
    if (inline_max == -1 && PyErr_Occurred()) return nullptr;
    if (setup_tables(args[0], static_cast<int>(inline_max)) != 0)
        return nullptr;
    Py_XDECREF(g_wire_tag);
    g_wire_tag = args[2];
    Py_INCREF(g_wire_tag);
    for (auto& t : g_tag_by_wire) Py_CLEAR(t);
    PyObject *key, *val;
    Py_ssize_t pos = 0;
    while (PyDict_Next(args[3], &pos, &key, &val)) {
        long wire = PyLong_AsLong(key);
        if (wire == -1 && PyErr_Occurred()) return nullptr;
        if (wire >= 0 && wire < 2048) {
            Py_INCREF(val);
            g_tag_by_wire[wire] = val;
        }
    }
    Py_XDECREF(g_msg_cls);
    g_msg_cls = args[4];
    Py_INCREF(g_msg_cls);
    if (!g_s_tag) {
        g_s_tag = PyUnicode_InternFromString("tag");
        g_s_src = PyUnicode_InternFromString("src");
        g_s_data = PyUnicode_InternFromString("data");
        g_s_hang = PyUnicode_InternFromString("hang");
        if (!g_s_tag || !g_s_src || !g_s_data || !g_s_hang) return nullptr;
    }
    Py_RETURN_NONE;
}

bool ready() {
    if (!g_field_ids || !g_wire_tag || !g_msg_cls) {
        PyErr_SetString(PyExc_RuntimeError, "_adlbcodec.setup not called");
        return false;
    }
    return true;
}

// encode_iov(m: Msg) -> list of body parts
PyObject* py_encode_iov(PyObject*, PyObject* m) {
    if (!ready()) return nullptr;
    PyObject* tag = PyObject_GetAttr(m, g_s_tag);
    if (!tag) return nullptr;
    PyObject* wire = PyDict_GetItemWithError(g_wire_tag, tag);
    if (!wire) {
        if (!PyErr_Occurred()) PyErr_SetObject(PyExc_KeyError, tag);
        Py_DECREF(tag);
        return nullptr;
    }
    Py_DECREF(tag);
    long wire_tag = PyLong_AsLong(wire);
    PyObject* srco = PyObject_GetAttr(m, g_s_src);
    if (!srco) return nullptr;
    long src = PyLong_AsLong(srco);
    Py_DECREF(srco);
    if (src == -1 && PyErr_Occurred()) return nullptr;
    PyObject* data = PyObject_GetAttr(m, g_s_data);
    if (!data) return nullptr;
    if (!PyDict_Check(data)) {
        Py_DECREF(data);
        PyErr_SetString(PyExc_TypeError, "Msg.data must be a dict");
        return nullptr;
    }
    PyObject* out = encode_iov_raw(static_cast<int>(wire_tag),
                                   static_cast<int>(src), data);
    Py_DECREF(data);
    return out;
}

// decode(body) -> Msg
PyObject* py_decode(PyObject*, PyObject* body) {
    if (!ready()) return nullptr;
    PyObject* triple = decode_raw(body);
    if (!triple) return nullptr;
    long wire = PyLong_AsLong(PyTuple_GET_ITEM(triple, 0));
    PyObject* tag = (wire >= 0 && wire < 2048) ? g_tag_by_wire[wire]
                                               : nullptr;
    if (!tag) {
        PyErr_SetObject(PyExc_KeyError, PyTuple_GET_ITEM(triple, 0));
        Py_DECREF(triple);
        return nullptr;
    }
    PyObject* data = PyTuple_GET_ITEM(triple, 2);
    // protocol-level convenience, the Python twin's exact rule:
    // hang arrives as 0/1, delivered as bool
    PyObject* hang = PyDict_GetItemWithError(data, g_s_hang);
    if (hang) {
        int truth = PyObject_IsTrue(hang);
        if (truth < 0 ||
            PyDict_SetItem(data, g_s_hang, truth ? Py_True : Py_False) != 0) {
            Py_DECREF(triple);
            return nullptr;
        }
    } else if (PyErr_Occurred()) {
        Py_DECREF(triple);
        return nullptr;
    }
    PyObject* m = PyObject_CallFunctionObjArgs(
        g_msg_cls, tag, PyTuple_GET_ITEM(triple, 1), data, nullptr);
    Py_DECREF(triple);
    return m;
}

PyMethodDef codec_methods[] = {
    {"setup", reinterpret_cast<PyCFunction>(
                  reinterpret_cast<void*>(py_setup)),
     METH_FASTCALL,
     "setup(fields, inline_max, wire_tag, tag_for_wire, msg_cls)"},
    {"encode_iov", py_encode_iov, METH_O,
     "scatter-gather TLV encode of a Msg -> list of body parts"},
    {"decode", py_decode, METH_O, "TLV body -> Msg"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef codec_moddef = {
    PyModuleDef_HEAD_INIT, "_adlbcodec",
    "compiled TLV wire codec (see adlb_tpu/native/codec.cpp)", -1,
    codec_methods, nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

extern "C" {

// the single ctypes entrypoint: a fully-formed module object (new ref)
PyObject* adlb_codec_module() { return PyModule_Create(&codec_moddef); }

}  // extern "C"
