// Indexed work-queue core, shared between the ctypes library (wqcore.cpp,
// used under the Python server) and the native server daemon (serverd.cpp).
//
// The reference implements its queues as linked lists with O(n) priority
// scans (reference src/xq.c:190-247); this is the rebuild's indexed
// equivalent: per-(type) and per-(target,type) lazy-deletion binary heaps
// over a dense unit table, so insert/match/pin/remove are O(log n).
// Semantics match adlb_tpu.runtime.queues.WorkQueue (property-tested):
// algebraically-largest priority first, FIFO by seqno among equals,
// targeted-before-untargeted for the requesting rank, pinned invisible.

#pragma once

#include <algorithm>
#include <cstdint>
#include <queue>
#include <unordered_map>
#include <vector>

namespace adlbwq {

struct HeapKey {
    int32_t neg_prio;  // -prio: min-heap top = max priority
    int64_t seqno;     // FIFO tie-break
    bool operator>(const HeapKey& o) const {
        if (neg_prio != o.neg_prio) return neg_prio > o.neg_prio;
        return seqno > o.seqno;
    }
};

using MinHeap =
    std::priority_queue<HeapKey, std::vector<HeapKey>, std::greater<HeapKey>>;

struct Unit {
    int64_t seqno;
    int32_t work_type;
    int32_t prio;
    int32_t target_rank;  // -1 = untargeted
    int32_t pin_rank;     // -1 = unpinned
    int64_t payload_len;
};

struct PairHash {
    size_t operator()(const std::pair<int32_t, int32_t>& p) const {
        return std::hash<int64_t>()((int64_t(p.first) << 32) ^
                                    uint32_t(p.second));
    }
};

struct WorkQueue {
    std::unordered_map<int64_t, Unit> units;
    std::unordered_map<int32_t, MinHeap> untargeted;  // type -> heap
    std::unordered_map<std::pair<int32_t, int32_t>, MinHeap, PairHash>
        targeted;  // (target, type) -> heap
    std::unordered_map<int32_t, std::vector<int32_t>>
        targeted_types;  // target -> types with (possibly stale) buckets
    int64_t count = 0;
    int64_t max_count = 0;
    int64_t total_bytes = 0;
    // O(1) mirror of "unpinned && untargeted" (the balancer's
    // availability signal, read every periodic tick): maintained at
    // add/remove/pin/unpin so the tick never walks the unit table
    int64_t unpinned_untargeted = 0;

    void index(const Unit& u) {
        HeapKey k{-u.prio, u.seqno};
        if (u.target_rank < 0) {
            untargeted[u.work_type].push(k);
        } else {
            targeted[{u.target_rank, u.work_type}].push(k);
            auto& types = targeted_types[u.target_rank];
            bool present = false;
            for (int32_t t : types)
                if (t == u.work_type) { present = true; break; }
            if (!present) types.push_back(u.work_type);
        }
    }

    // Best live unit on a heap, popping stale tops. targeted_to >= 0 checks
    // target identity; -1 requires untargeted.
    const Unit* peek_best(MinHeap* heap, int32_t targeted_to) {
        if (heap == nullptr) return nullptr;
        while (!heap->empty()) {
            HeapKey k = heap->top();
            auto it = units.find(k.seqno);
            if (it == units.end() || it->second.pin_rank >= 0 ||
                it->second.prio != -k.neg_prio ||
                (targeted_to >= 0 && it->second.target_rank != targeted_to) ||
                (targeted_to < 0 && it->second.target_rank >= 0)) {
                heap->pop();
                continue;
            }
            return &it->second;
        }
        return nullptr;
    }

    static bool better(const Unit* a, const Unit* b) {  // a beats b?
        if (b == nullptr) return true;
        if (a->prio != b->prio) return a->prio > b->prio;
        return a->seqno < b->seqno;
    }

    const Unit* find_targeted(int32_t rank, const int32_t* req_types,
                              int32_t ntypes) {
        auto tit = targeted_types.find(rank);
        if (tit == targeted_types.end()) return nullptr;
        const Unit* best = nullptr;
        auto& types = tit->second;
        for (size_t i = 0; i < types.size();) {
            int32_t t = types[i];
            bool wanted = (ntypes == 0);
            for (int32_t j = 0; j < ntypes && !wanted; ++j)
                wanted = (req_types[j] == t);
            if (!wanted) { ++i; continue; }
            auto hit = targeted.find({rank, t});
            MinHeap* heap = (hit == targeted.end()) ? nullptr : &hit->second;
            const Unit* u = peek_best(heap, rank);
            if (u == nullptr) {
                if (heap == nullptr || heap->empty()) {
                    // drained bucket: prune (unpin re-indexes)
                    if (hit != targeted.end()) targeted.erase(hit);
                    types[i] = types.back();
                    types.pop_back();
                    continue;
                }
                ++i;
                continue;
            }
            if (better(u, best)) best = u;
            ++i;
        }
        if (types.empty()) targeted_types.erase(tit);
        return best;
    }

    const Unit* find_untargeted(const int32_t* req_types, int32_t ntypes) {
        const Unit* best = nullptr;
        if (ntypes == 0) {
            for (auto& kv : untargeted) {
                const Unit* u = peek_best(&kv.second, -1);
                if (u != nullptr && better(u, best)) best = u;
            }
        } else {
            for (int32_t j = 0; j < ntypes; ++j) {
                auto it = untargeted.find(req_types[j]);
                if (it == untargeted.end()) continue;
                const Unit* u = peek_best(&it->second, -1);
                if (u != nullptr && better(u, best)) best = u;
            }
        }
        return best;
    }
};

}  // namespace adlbwq
