// Native work-queue core: the server's hot matching path in C++.
//
// The reference implements its entire data plane in C (queues:
// reference src/xq.c, with O(n) linked-list priority scans at
// src/xq.c:190-247). This library is the tpu-native rebuild's equivalent,
// but indexed: per-(type) and per-(target,type) lazy-deletion binary heaps
// over a dense unit table, so insert/match/pin/remove are O(log n).
// Semantics are identical to the pure-Python adlb_tpu.runtime.queues
// WorkQueue (property-tested against it): algebraically-largest priority
// first, FIFO by seqno among equals, targeted-before-untargeted for the
// requesting rank, pinned units invisible.
//
// Exposed as a minimal C ABI consumed via ctypes (no pybind11 in this
// environment); payload bytes never cross the boundary — Python keeps them,
// C++ keeps the metadata index.

#include "wqcore.hpp"

using namespace adlbwq;


extern "C" {

void* adlb_wq_new() { return new WorkQueue(); }

void adlb_wq_free(void* h) { delete static_cast<WorkQueue*>(h); }

// 0 on success, -1 on duplicate seqno
int32_t adlb_wq_add(void* h, int64_t seqno, int32_t work_type, int32_t prio,
                    int32_t target_rank, int32_t pinned, int32_t pin_rank,
                    int64_t payload_len) {
    auto* wq = static_cast<WorkQueue*>(h);
    if (wq->units.count(seqno)) return -1;
    Unit u{seqno, work_type, prio, target_rank, pinned ? pin_rank : -1,
           payload_len};
    wq->units.emplace(seqno, u);
    wq->count += 1;
    if (wq->count > wq->max_count) wq->max_count = wq->count;
    wq->total_bytes += payload_len;
    if (!pinned && target_rank < 0) wq->unpinned_untargeted += 1;
    if (!pinned) wq->index(u);
    return 0;
}

int32_t adlb_wq_remove(void* h, int64_t seqno) {
    auto* wq = static_cast<WorkQueue*>(h);
    auto it = wq->units.find(seqno);
    if (it == wq->units.end()) return -1;
    wq->total_bytes -= it->second.payload_len;
    if (it->second.pin_rank < 0 && it->second.target_rank < 0)
        wq->unpinned_untargeted -= 1;
    wq->units.erase(it);
    wq->count -= 1;
    return 0;
}

int32_t adlb_wq_pin(void* h, int64_t seqno, int32_t rank) {
    auto* wq = static_cast<WorkQueue*>(h);
    auto it = wq->units.find(seqno);
    if (it == wq->units.end()) return -1;
    if (it->second.pin_rank < 0 && rank >= 0 &&
        it->second.target_rank < 0)
        wq->unpinned_untargeted -= 1;
    it->second.pin_rank = rank;
    return 0;
}

int32_t adlb_wq_unpin(void* h, int64_t seqno) {
    auto* wq = static_cast<WorkQueue*>(h);
    auto it = wq->units.find(seqno);
    if (it == wq->units.end()) return -1;
    if (it->second.pin_rank >= 0 && it->second.target_rank < 0)
        wq->unpinned_untargeted += 1;
    it->second.pin_rank = -1;
    wq->index(it->second);
    return 0;
}

// Reference match order (src/adlb.c:1204-1237): targeted at `rank` first,
// then best untargeted. ntypes==0 means any type. Returns seqno or -1.
int64_t adlb_wq_find_match(void* h, int32_t rank, const int32_t* req_types,
                           int32_t ntypes) {
    auto* wq = static_cast<WorkQueue*>(h);
    const Unit* u = wq->find_targeted(rank, req_types, ntypes);
    if (u == nullptr) u = wq->find_untargeted(req_types, ntypes);
    return u == nullptr ? -1 : u->seqno;
}

int64_t adlb_wq_find_targeted(void* h, int32_t rank, const int32_t* req_types,
                              int32_t ntypes) {
    auto* wq = static_cast<WorkQueue*>(h);
    const Unit* u = wq->find_targeted(rank, req_types, ntypes);
    return u == nullptr ? -1 : u->seqno;
}

int64_t adlb_wq_find_untargeted(void* h, const int32_t* req_types,
                                int32_t ntypes) {
    auto* wq = static_cast<WorkQueue*>(h);
    const Unit* u = wq->find_untargeted(req_types, ntypes);
    return u == nullptr ? -1 : u->seqno;
}

int32_t adlb_wq_hi_prio_of_type(void* h, int32_t work_type, int32_t* out_prio) {
    auto* wq = static_cast<WorkQueue*>(h);
    auto it = wq->untargeted.find(work_type);
    const Unit* u =
        (it == wq->untargeted.end()) ? nullptr : wq->peek_best(&it->second, -1);
    if (u == nullptr) return -1;
    *out_prio = u->prio;
    return 0;
}

int64_t adlb_wq_count(void* h) { return static_cast<WorkQueue*>(h)->count; }

int64_t adlb_wq_max_count(void* h) {
    return static_cast<WorkQueue*>(h)->max_count;
}

int64_t adlb_wq_total_bytes(void* h) {
    return static_cast<WorkQueue*>(h)->total_bytes;
}

int64_t adlb_wq_num_unpinned(void* h) {
    auto* wq = static_cast<WorkQueue*>(h);
    int64_t n = 0;
    for (auto& kv : wq->units)
        if (kv.second.pin_rank < 0) n += 1;
    return n;
}

int64_t adlb_wq_num_unpinned_untargeted(void* h) {
    // O(1): the counter is maintained at add/remove/pin/unpin — this is
    // the balancer's per-tick availability signal, and the old O(n)
    // walk (paired with the per-call GIL release/re-acquire) was a
    // measurable slice of tpu-mode pop latency
    return static_cast<WorkQueue*>(h)->unpinned_untargeted;
}

// (count, unpinned-untargeted, bytes) in ONE call: the periodic tick's
// queue-depth gauges. Every ctypes crossing releases and re-acquires
// the GIL; on a loaded host each re-acquire can cost milliseconds on
// the reactor thread, so the tick pays one crossing, not three.
void adlb_wq_depth_sample(void* h, int64_t* out) {
    auto* wq = static_cast<WorkQueue*>(h);
    out[0] = wq->count;
    out[1] = wq->unpinned_untargeted;
    out[2] = wq->total_bytes;
}

// Fill out arrays with up to `cap` unpinned untargeted units, sorted by
// descending priority then seqno — the balancer snapshot fast path.
int64_t adlb_wq_snapshot_untargeted(void* h, int64_t cap, int64_t* out_seqnos,
                                    int32_t* out_types, int32_t* out_prios,
                                    int64_t* out_lens) {
    auto* wq = static_cast<WorkQueue*>(h);
    std::vector<const Unit*> avail;
    avail.reserve(wq->units.size());
    for (auto& kv : wq->units)
        if (kv.second.pin_rank < 0 && kv.second.target_rank < 0)
            avail.push_back(&kv.second);
    std::sort(avail.begin(), avail.end(), [](const Unit* a, const Unit* b) {
        if (a->prio != b->prio) return a->prio > b->prio;
        return a->seqno < b->seqno;
    });
    int64_t n = std::min<int64_t>(cap, avail.size());
    for (int64_t i = 0; i < n; ++i) {
        out_seqnos[i] = avail[i]->seqno;
        out_types[i] = avail[i]->work_type;
        out_prios[i] = avail[i]->prio;
        out_lens[i] = avail[i]->payload_len;
    }
    return n;
}

int32_t adlb_wq_get(void* h, int64_t seqno, int32_t* out_type,
                    int32_t* out_prio, int32_t* out_target,
                    int32_t* out_pin_rank, int64_t* out_len) {
    auto* wq = static_cast<WorkQueue*>(h);
    auto it = wq->units.find(seqno);
    if (it == wq->units.end()) return -1;
    *out_type = it->second.work_type;
    *out_prio = it->second.prio;
    *out_target = it->second.target_rank;
    *out_pin_rank = it->second.pin_rank;
    *out_len = it->second.payload_len;
    return 0;
}

}  // extern "C"
