"""ctypes wrapper: a WorkQueue with the matching index in C++.

Drop-in for :class:`adlb_tpu.runtime.queues.WorkQueue` (same method surface,
property-tested for identical behavior). Python keeps the authoritative
unit table — payload bytes and full metadata for protocol responses — while
the C++ side maintains the match index and answers the hot queries
(find_match, qmstat cells, balancer snapshots) without touching Python
objects per candidate.
"""

from __future__ import annotations

import ctypes
from typing import Iterable, Optional

from adlb_tpu.runtime.queues import WorkUnit
from adlb_tpu.types import ADLB_LOWEST_PRIO


def _types_array(req_types: Optional[frozenset[int]]):
    if req_types is None:
        return None, 0
    n = len(req_types)
    arr = (ctypes.c_int32 * n)(*sorted(req_types))
    return arr, n


class NativeWorkQueue:
    def __init__(self) -> None:
        from adlb_tpu.native.build import ensure_built

        self._lib = ensure_built()
        if self._lib is None:
            from adlb_tpu.native.build import build_error

            raise RuntimeError(build_error() or "native core unavailable")
        # O(1) getters go through the PyDLL view (no GIL release —
        # see build._bind); everything else through the CDLL
        self._fast = self._lib._fast
        self._h = self._lib.adlb_wq_new()
        self._units: dict[int, WorkUnit] = {}

    def __del__(self) -> None:
        lib = getattr(self, "_lib", None)
        h = getattr(self, "_h", None)
        if lib is not None and h:
            lib.adlb_wq_free(h)

    # -- insertion / removal -------------------------------------------------

    def add(self, unit: WorkUnit) -> None:
        rc = self._lib.adlb_wq_add(
            self._h,
            unit.seqno,
            unit.work_type,
            unit.prio,
            unit.target_rank,
            1 if unit.pinned else 0,
            unit.pin_rank,
            len(unit.payload),
        )
        assert rc == 0, f"duplicate seqno {unit.seqno}"
        self._units[unit.seqno] = unit

    def get(self, seqno: int) -> Optional[WorkUnit]:
        return self._units.get(seqno)

    def remove(self, seqno: int) -> WorkUnit:
        unit = self._units.pop(seqno)
        self._lib.adlb_wq_remove(self._h, seqno)
        return unit

    # -- pin discipline ------------------------------------------------------

    def pin(self, seqno: int, rank: int) -> None:
        unit = self._units[seqno]
        unit.pinned = True
        unit.pin_rank = rank
        self._lib.adlb_wq_pin(self._h, seqno, rank)

    def unpin(self, seqno: int) -> None:
        unit = self._units[seqno]
        unit.pinned = False
        unit.pin_rank = -1
        self._lib.adlb_wq_unpin(self._h, seqno)

    # -- matching ------------------------------------------------------------

    def _by_seqno(self, seqno: int) -> Optional[WorkUnit]:
        return None if seqno < 0 else self._units[seqno]

    def find_match(
        self, rank: int, req_types: Optional[frozenset[int]]
    ) -> Optional[WorkUnit]:
        arr, n = _types_array(req_types)
        return self._by_seqno(
            self._lib.adlb_wq_find_match(self._h, rank, arr, n)
        )

    def find_targeted(
        self, rank: int, req_types: Optional[frozenset[int]]
    ) -> Optional[WorkUnit]:
        arr, n = _types_array(req_types)
        return self._by_seqno(
            self._lib.adlb_wq_find_targeted(self._h, rank, arr, n)
        )

    def find_untargeted(
        self, req_types: Optional[frozenset[int]]
    ) -> Optional[WorkUnit]:
        arr, n = _types_array(req_types)
        return self._by_seqno(
            self._lib.adlb_wq_find_untargeted(self._h, arr, n)
        )

    def find_unpinned(self) -> Optional[WorkUnit]:
        worst: Optional[WorkUnit] = None
        for u in self._units.values():
            if u.pinned:
                continue
            if u.target_rank < 0 and (worst is None or u.prio < worst.prio):
                worst = u
        if worst is not None:
            return worst
        for u in self._units.values():
            if not u.pinned:
                return u
        return None

    # -- stats ---------------------------------------------------------------

    def num_unpinned(self) -> int:
        return self._fast.adlb_wq_num_unpinned(self._h)

    def num_unpinned_untargeted(self) -> int:
        return self._fast.adlb_wq_num_unpinned_untargeted(self._h)

    # availability signal for the balancer's snapshot gating (the Python
    # queue keeps an O(1) counter; the C core's count is cheap per tick)
    untargeted_avail = property(num_unpinned_untargeted)

    def hi_prio_of_type(self, work_type: int) -> int:
        out = ctypes.c_int32()
        rc = self._fast.adlb_wq_hi_prio_of_type(
            self._h, work_type, ctypes.byref(out)
        )
        return out.value if rc == 0 else ADLB_LOWEST_PRIO

    def count_of_type(self, work_type: int) -> tuple[int, int]:
        n = 0
        nbytes = 0
        for u in self._units.values():
            if u.work_type == work_type:
                n += 1
                nbytes += u.work_len
        return n, nbytes

    def snapshot_untargeted(self, cap: int) -> list[tuple[int, int, int, int]]:
        """Top-`cap` available units by priority — (seqno, type, prio, len);
        the balancer snapshot fast path, sorted in C++."""
        seqnos = (ctypes.c_int64 * cap)()
        types = (ctypes.c_int32 * cap)()
        prios = (ctypes.c_int32 * cap)()
        lens = (ctypes.c_int64 * cap)()
        n = self._lib.adlb_wq_snapshot_untargeted(
            self._h, cap, seqnos, types, prios, lens
        )
        return [
            (seqnos[i], types[i], prios[i], lens[i]) for i in range(n)
        ]

    def units(self) -> Iterable[WorkUnit]:
        return self._units.values()

    @property
    def count(self) -> int:
        return self._fast.adlb_wq_count(self._h)

    @property
    def max_count(self) -> int:
        return self._fast.adlb_wq_max_count(self._h)

    @property
    def total_bytes(self) -> int:
        return self._fast.adlb_wq_total_bytes(self._h)

    def depth_sample(self) -> tuple[int, int, int]:
        """(count, unpinned-untargeted, bytes) — the periodic
        observability tick's queue-depth gauges (twin of the Python
        WorkQueue's depth_sample). ONE C call: every ctypes crossing
        releases and re-acquires the GIL, and on a loaded host each
        re-acquire can stall the reactor thread for milliseconds — the
        old three-property version was a measurable slice of tpu-mode
        pop latency."""
        out = (ctypes.c_int64 * 3)()
        self._fast.adlb_wq_depth_sample(self._h, out)
        return out[0], out[1], out[2]
