"""Bootstrap protocol for the native server daemon (serverd.cpp).

One place for the stdin/stdout handshake both launchers speak
(transport_tcp._native_server_main and capi.run_native_world):

    stdin:  config lines ... "endconfig"
    stdout: "PORT <n>"
    stdin:  "addr <rank> <host> <port>" ... "endaddrs"
    ... runs ...
    stdout: "STATS {json}" (and/or "ABORT <code>")
"""

from __future__ import annotations

import json
import subprocess
from typing import Optional


def spawn_daemon(world, cfg, rank: int) -> subprocess.Popen:
    """Start adlb_serverd for one server rank and ship its config."""
    from adlb_tpu.native.build import ensure_serverd

    proc = subprocess.Popen(
        [ensure_serverd()],
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        text=True,
    )
    lines = [
        f"nranks {world.nranks}",
        f"nservers {world.nservers}",
        f"use_debug_server {1 if world.use_debug_server else 0}",
        "types " + " ".join(str(t) for t in world.types),
        f"rank {rank}",
        f"qmstat_interval {cfg.qmstat_interval}",
        f"qmstat_mode {cfg.qmstat_mode}",
        f"exhaust_check_interval {cfg.exhaust_check_interval}",
        f"max_malloc {cfg.max_malloc_per_server}",
        f"debug_log_interval {cfg.debug_log_interval}",
        f"periodic_log_interval {cfg.periodic_log_interval}",
    ]
    if cfg.restore_path:
        lines.append(f"restore_path {cfg.restore_path}")
    if cfg.balancer == "tpu":
        # the JAX balancer sidecar listens at pseudo-rank world.nranks
        lines += [
            "balancer tpu",
            f"balancer_rank {world.nranks}",
            f"balancer_interval {cfg.balancer_interval}",
            f"balancer_min_gap {cfg.balancer_min_gap}",
            f"balancer_max_tasks {cfg.balancer_max_tasks}",
            f"balancer_max_requesters {cfg.balancer_max_requesters}",
        ]
    lines.append("endconfig")
    proc.stdin.write("\n".join(lines) + "\n")
    proc.stdin.flush()
    return proc


def read_hello(proc: subprocess.Popen, rank: int) -> int:
    """Read the PORT line; raises (after killing the daemon) on anything
    else, so a crashed daemon fails loudly instead of hanging the world."""
    line = (proc.stdout.readline() or "").strip()
    if not line.startswith("PORT "):
        proc.kill()
        raise RuntimeError(
            f"native server rank {rank}: bad hello {line!r} "
            f"(exit={proc.poll()})"
        )
    return int(line.split()[1])


def send_addrs(proc: subprocess.Popen, addr_map: dict) -> None:
    lines = [
        f"addr {r} {host} {port}"
        for r, (host, port) in sorted(addr_map.items())
    ] + ["endaddrs"]
    proc.stdin.write("\n".join(lines) + "\n")
    proc.stdin.flush()


def _parse_trailer(lines):
    """Parse STATS/ABORT lines from an iterable; other output (STAT_APS
    chunks, diagnostics) passes through to stdout so the offline decoder
    and the operator still see it. Returns (stats dict (int key -> float)
    or None, abort code or None)."""
    import sys

    stats: Optional[dict] = None
    abort_code: Optional[int] = None
    for line in lines:
        line = line.rstrip("\n")
        stripped = line.strip()
        if stripped.startswith("STATS "):
            stats = {int(k): v for k, v in json.loads(stripped[6:]).items()}
        elif stripped.startswith("ABORT "):
            abort_code = int(stripped.split()[1])
        elif stripped:
            print(line, file=sys.stdout)
    return stats, abort_code


def drain_output(proc: subprocess.Popen):
    """Consume the daemon's stdout to completion; returns
    (stats, abort_code) per :func:`_parse_trailer`."""
    return _parse_trailer(proc.stdout)


def collect_stats(proc: subprocess.Popen, timeout: float = 15.0):
    """Wait for exit and parse trailing output (for callers that did not
    stream stdout); kills on timeout. Returns (stats, abort_code,
    returncode)."""
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
    stats, abort_code = _parse_trailer((out or "").splitlines())
    return stats, abort_code, proc.returncode
