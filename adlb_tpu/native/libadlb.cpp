// Native client library: the C API of include/adlb/adlb.h over the binary
// TLV wire codec (twin of adlb_tpu/runtime/codec.py — keep tables in sync).
//
// This is the native equivalent of the reference's client-side protocol
// engine (reference src/adlb.c:2638-3176): Put routing + reject/retry with
// least-loaded hints, blocking/non-blocking Reserve, Get_reserved with
// batch-common prefix fetch, batch puts, Info queries, finalize/abort —
// re-targeted from tagged MPI sends to the framework's TCP fabric.
//
// Threads: one acceptor + one reader per inbound connection feed a single
// inbox (deque + condvar); the API itself is strictly request/response like
// the reference's client (blocking MPI_Wait), so no other locking is needed.
// Little-endian hosts assumed (as is the Python struct '<' side).

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdarg>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "../../include/adlb/adlb.h"

namespace {

// ---- wire tags (codec.py WIRE_TAG) ----------------------------------------
enum WireTag : uint16_t {
  T_FA_PUT = 1001,
  T_FA_PUT_COMMON = 1003,
  T_FA_BATCH_DONE = 1005,
  T_FA_DID_PUT_AT_REMOTE = 1006,
  T_FA_RESERVE = 1007,
  T_TA_RESERVE_RESP = 1008,
  T_FA_GET_RESERVED = 1009,
  T_TA_GET_RESERVED_RESP = 1010,
  T_FA_NO_MORE_WORK = 1011,
  T_FA_LOCAL_APP_DONE = 1012,
  T_TA_PUT_RESP = 1020,
  T_FA_ABORT = 1027,
  T_FA_INFO_NUM_WORK_UNITS = 1037,
  T_FA_GET_COMMON = 1038,
  T_TA_GET_COMMON_RESP = 1039,
  T_FA_INFO_GET = 1041,
  T_TA_PUT_COMMON_RESP = 1042,
  T_TA_INFO_NUM_RESP = 1043,
  T_TA_INFO_GET_RESP = 1044,
  T_TA_ABORT = 1046,
  T_FA_CHECKPOINT = 1048,
  T_TA_CHECKPOINT_RESP = 1049,
  T_AM_APP = 1047,
};

// ---- field ids (codec.py FIELDS) ------------------------------------------
enum Field : uint8_t {
  F_PAYLOAD = 1,
  F_WORK_TYPE = 2,
  F_PRIO = 3,
  F_TARGET_RANK = 4,
  F_ANSWER_RANK = 5,
  F_COMMON_LEN = 6,
  F_COMMON_SERVER = 7,
  F_COMMON_SEQNO = 8,
  F_RC = 9,
  F_HINT = 10,
  F_REQ_TYPES = 11,
  F_HANG = 12,
  F_RQSEQNO = 13,
  F_HANDLE = 14,
  F_WORK_LEN = 15,
  F_TIME_ON_Q = 16,
  F_COUNT = 17,
  F_NBYTES = 18,
  F_MAX_WQ = 19,
  F_CODE = 20,
  F_SEQNO = 21,
  F_REFCNT = 22,
  F_SERVER_RANK = 23,
  F_KEY = 24,
  F_VALUE = 25,
  F_APPTAG = 26,
  F_PUT_ID = 58,
  F_FETCH = 59,
  F_FETCH_MAX = 79,
  F_PAYLOADS = 80,
  F_WORK_TYPES = 81,
  F_PRIOS = 82,
  F_ANSWER_RANKS = 83,
  F_PATH = 72,
  F_RETRY_AFTER_MS = 93,
};

enum Kind : uint8_t {
  K_I64 = 0, K_BYTES = 1, K_LIST = 2, K_F64 = 3,
  K_BLIST = 4,  // list of byte strings: u16 count, (u32 len + bytes)*
  K_FLIST = 5,  // list of f64: u16 count, f64*
};

constexpr uint8_t BINARY_MAGIC = 0x01;

struct Msg {
  uint16_t tag = 0;
  int32_t src = -1;
  std::map<uint8_t, int64_t> ints;
  std::map<uint8_t, double> dbls;
  std::map<uint8_t, std::string> blobs;
  std::map<uint8_t, std::vector<int64_t>> lists;
  std::map<uint8_t, std::vector<std::string>> blists;
  std::map<uint8_t, std::vector<double>> flists;

  int64_t geti(uint8_t f, int64_t dflt = 0) const {
    auto it = ints.find(f);
    return it == ints.end() ? dflt : it->second;
  }
};

// ---- encoding -------------------------------------------------------------

void put_u16(std::string &b, uint16_t v) { b.append((const char *)&v, 2); }
void put_u32(std::string &b, uint32_t v) { b.append((const char *)&v, 4); }
void put_i32(std::string &b, int32_t v) { b.append((const char *)&v, 4); }
void put_i64(std::string &b, int64_t v) { b.append((const char *)&v, 8); }
void put_f64(std::string &b, double v) { b.append((const char *)&v, 8); }

struct Encoder {
  std::string body;
  uint16_t nfields = 0;

  explicit Encoder(uint16_t tag, int32_t src) {
    body.push_back((char)BINARY_MAGIC);
    put_u16(body, tag);
    put_i32(body, src);
    put_u16(body, 0);  // nfields backpatched in finish()
  }
  Encoder &i(uint8_t f, int64_t v) {
    body.push_back((char)f);
    body.push_back((char)K_I64);
    put_i64(body, v);
    nfields++;
    return *this;
  }
  Encoder &bytes(uint8_t f, const void *p, size_t n) {
    body.push_back((char)f);
    body.push_back((char)K_BYTES);
    put_u32(body, (uint32_t)n);
    body.append((const char *)p, n);
    nfields++;
    return *this;
  }
  Encoder &list(uint8_t f, const std::vector<int64_t> &v) {
    body.push_back((char)f);
    body.push_back((char)K_LIST);
    put_u16(body, (uint16_t)v.size());
    for (int64_t x : v) put_i64(body, x);
    nfields++;
    return *this;
  }
  std::string finish() {
    memcpy(&body[7], &nfields, 2);  // offset of nfields in the header
    return std::move(body);
  }
};

bool decode(const std::string &body, Msg *out) {
  if (body.size() < 9 || (uint8_t)body[0] != BINARY_MAGIC) return false;
  size_t off = 1;
  auto need = [&](size_t n) { return off + n <= body.size(); };
  auto rd = [&](void *p, size_t n) {
    memcpy(p, body.data() + off, n);
    off += n;
  };
  uint16_t nf;
  rd(&out->tag, 2);
  rd(&out->src, 4);
  rd(&nf, 2);
  for (uint16_t k = 0; k < nf; k++) {
    if (!need(2)) return false;
    uint8_t fid = body[off], kind = body[off + 1];
    off += 2;
    if (kind == K_I64) {
      if (!need(8)) return false;
      int64_t v;
      rd(&v, 8);
      out->ints[fid] = v;
    } else if (kind == K_BYTES) {
      if (!need(4)) return false;
      uint32_t n;
      rd(&n, 4);
      if (!need(n)) return false;
      out->blobs[fid].assign(body.data() + off, n);
      off += n;
    } else if (kind == K_LIST) {
      if (!need(2)) return false;
      uint16_t cnt;
      rd(&cnt, 2);
      if (!need((size_t)8 * cnt)) return false;
      auto &lst = out->lists[fid];
      lst.resize(cnt);
      for (uint16_t j = 0; j < cnt; j++) rd(&lst[j], 8);
    } else if (kind == K_F64) {
      if (!need(8)) return false;
      double v;
      rd(&v, 8);
      out->dbls[fid] = v;
    } else if (kind == K_BLIST) {
      if (!need(2)) return false;
      uint16_t cnt;
      rd(&cnt, 2);
      auto &bl = out->blists[fid];
      bl.reserve(cnt);
      for (uint16_t j = 0; j < cnt; j++) {
        if (!need(4)) return false;
        uint32_t n;
        rd(&n, 4);
        if (!need(n)) return false;
        bl.emplace_back(body.data() + off, n);
        off += n;
      }
    } else if (kind == K_FLIST) {
      if (!need(2)) return false;
      uint16_t cnt;
      rd(&cnt, 2);
      if (!need((size_t)8 * cnt)) return false;
      auto &fl = out->flists[fid];
      fl.resize(cnt);
      for (uint16_t j = 0; j < cnt; j++) rd(&fl[j], 8);
    } else {
      return false;
    }
  }
  // exact-frame check: every legitimate encoder emits no trailing bytes,
  // so leftovers mean garbage that decoded by luck
  if (off != body.size()) return false;
  // client-bound wire tags live in the 1001-1049 block; anything else is
  // crafted or version-skewed and must not reach the dispatch paths,
  // whose unexpected-tag arms are fatal
  if (out->tag < 1001 || out->tag > 1049) return false;
  return true;
}

// ---- context --------------------------------------------------------------

struct Ctx {
  int rank = -1, nranks = 0, nservers = 0, num_app_ranks = 0, home = -1;
  int aprintf_flag = 0;
  std::vector<int> types;
  std::vector<std::pair<std::string, int>> addr;  // per rank

  int listen_fd = -1;
  std::thread acceptor;
  std::vector<std::thread> readers;
  std::mutex mu;
  std::condition_variable cv;
  std::deque<Msg> inbox;
  std::deque<Msg> app_inbox;  // stashed AM_APP frames (the app_comm channel)
  std::map<int, int> out_fds;
  std::atomic<bool> closed{false};

  int rr = 0;       // round-robin cursor over servers
  bool route_home = false;  // ADLB_PUT_ROUTING=home: untargeted puts -> home
  int rqseqno = 0;  // reserve sequence number
  // batch-put state (reference src/adlb.c:2638-2751)
  bool batch_active = false;
  int batch_server = -1, batch_len = 0, batch_refcnt = 0;
  int64_t batch_seqno = -1;
};

Ctx *g = nullptr;

void die(const char *fmt, ...) {
  va_list ap;
  va_start(ap, fmt);
  fprintf(stderr, "[adlb rank %d] ", g ? g->rank : -1);
  vfprintf(stderr, fmt, ap);
  fprintf(stderr, "\n");
  va_end(ap);
  exit(1);
}

// ---- sockets --------------------------------------------------------------

bool read_exact(int fd, void *p, size_t n) {
  char *c = (char *)p;
  while (n > 0) {
    ssize_t r = read(fd, c, n);
    if (r <= 0) return false;
    c += r;
    n -= (size_t)r;
  }
  return true;
}

// Body reads grow with the bytes actually received instead of
// pre-allocating the advertised length: a connection that sends only a
// large length prefix (then stalls) must not pin that memory in recv.
bool read_body(int fd, uint32_t n, std::string *body) {
  body->clear();
  char chunk[65536];
  while (body->size() < n) {
    size_t want = n - body->size();
    if (want > sizeof chunk) want = sizeof chunk;
    ssize_t r = recv(fd, chunk, want, 0);
    if (r <= 0) return false;
    body->append(chunk, (size_t)r);
  }
  return true;
}

bool write_all(int fd, const void *p, size_t n) {
  const char *c = (const char *)p;
  while (n > 0) {
    ssize_t r = write(fd, c, n);
    if (r <= 0) return false;
    c += r;
    n -= (size_t)r;
  }
  return true;
}

void reader_loop(int fd) {
  // Robustness policy (mirrors serverd.cpp): a connection that has never
  // delivered a decodable frame is untrusted — garbage on it closes the
  // connection without touching the world (a stray scanner must not kill
  // a rank, and rank death kills the whole world). Once a frame has
  // decoded, the peer is a real rank: corruption on an ESTABLISHED
  // stream is a protocol error and fails fast — dropping it instead
  // could discard the response a blocking caller is parked on, turning
  // a diagnosable failure into a silent distributed hang.
  static const uint32_t kMaxFrame = 1u << 28;  // 256 MB
  bool established = false;
  for (;;) {
    uint32_t len;
    if (!read_exact(fd, &len, 4)) break;
    if (len > kMaxFrame) {
      // cap before resize(): a hostile 4 GB prefix must not become the
      // allocation that kills this rank
      if (established)
        die("frame length %u exceeds %u cap on an established connection",
            len, kMaxFrame);
      std::fprintf(stderr,
                   "[libadlb] frame length %u exceeds %u cap; closing "
                   "connection\n", len, kMaxFrame);
      break;
    }
    std::string body;
    if (!read_body(fd, len, &body)) break;
    Msg m;
    if (len == 0 || (uint8_t)body[0] != BINARY_MAGIC) {
      if (len > 0 && (uint8_t)body[0] == 0x80 &&
          body.find("adlb_tpu") != std::string::npos) {
        // pickle protocol-2+ magic AND the pickled Msg's embedded module
        // path: a Python server that has not yet learned this rank is a
        // binary peer pickles its frames, and the only unsolicited
        // pickled client-bound message is the TA_ABORT fan-out — honor
        // it. (The module-path check keeps 0x80-prefixed line noise from
        // synthesizing a fatal abort; test_codec.py pins the invariant.)
        m.tag = T_TA_ABORT;
        m.ints[F_CODE] = ADLB_ERROR;
      } else if (!established) {
        std::fprintf(stderr,
                     "[libadlb] closing connection after non-binary "
                     "frame (%u B)\n", len);
        break;
      } else {
        die("non-binary frame (%u bytes) on an established connection",
            len);
      }
    } else if (!decode(body, &m)) {
      if (!established) {
        std::fprintf(stderr,
                     "[libadlb] closing connection after undecodable "
                     "first frame (%u B) — stray connection, or a "
                     "version-skewed peer (if a caller now hangs, "
                     "rebuild both sides from one tree)\n", len);
        break;
      }
      die("undecodable binary frame (%u bytes) from a live peer", len);
    } else {
      established = true;
    }
    {
      std::lock_guard<std::mutex> lk(g->mu);
      g->inbox.push_back(std::move(m));
    }
    g->cv.notify_all();
  }
  close(fd);
}

void accept_loop() {
  for (;;) {
    int fd = accept(g->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (g->closed.load()) return;
      continue;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    std::lock_guard<std::mutex> lk(g->mu);
    g->readers.emplace_back(reader_loop, fd);
  }
}

int connect_to(int dest) {
  auto &hp = g->addr[dest];
  struct addrinfo hints = {}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char port[16];
  snprintf(port, sizeof port, "%d", hp.second);
  // servers may come up after us: retry with backoff for ~15 s
  for (int attempt = 0; attempt < 60; attempt++) {
    if (getaddrinfo(hp.first.c_str(), port, &hints, &res) == 0) {
      int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
      if (fd >= 0 && connect(fd, res->ai_addr, res->ai_addrlen) == 0) {
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
        freeaddrinfo(res);
        return fd;
      }
      if (fd >= 0) close(fd);
      freeaddrinfo(res);
      res = nullptr;
    }
    usleep(250 * 1000);
  }
  die("cannot connect to rank %d at %s:%d", dest, hp.first.c_str(), hp.second);
  return -1;
}

void send_msg(int dest, Encoder &enc) {
  std::string body = enc.finish();
  uint32_t len = (uint32_t)body.size();
  auto it = g->out_fds.find(dest);
  int fd = it == g->out_fds.end() ? -1 : it->second;
  if (fd < 0) {
    fd = connect_to(dest);
    g->out_fds[dest] = fd;
  }
  if (!write_all(fd, &len, 4) || !write_all(fd, body.data(), body.size())) {
    close(fd);
    fd = connect_to(dest);  // one reconnect attempt
    g->out_fds[dest] = fd;
    if (!write_all(fd, &len, 4) || !write_all(fd, body.data(), body.size()))
      die("send to rank %d failed", dest);
  }
}

// Blocks until a frame with `want` arrives.  TA_ABORT terminates the process
// (the reference client dies inside MPI_Abort in the same situation,
// reference src/adlb.c:3165-3176).
// ---- pipelined puts (iput; no reference analogue — upstream's Put is one
// synchronous round trip per unit, src/adlb.c:2811-2843). Requests carry a
// put_id echoed in the response; settle out of band, replaying rejects at
// the hinted server with the synchronous path's pacing. ------------------
int home_server(int app_rank);
int next_server();

struct PendingPut {
  std::string payload;
  int work_type, prio, target_rank, answer_rank, attempts, server;
  int backoff_ms = 0;  // ADLB_BACKOFF retry-after hint awaiting replay
};
static std::map<int64_t, PendingPut> pending_puts;
static std::vector<int64_t> resend_queue;  // rejected ids awaiting replay
static int64_t next_put_id = 1;
static int failed_puts = 0;
static bool failed_nmw = false;

static void send_iput(int64_t id, const PendingPut &pp) {
  Encoder e(T_FA_PUT, g->rank);
  e.bytes(F_PAYLOAD, pp.payload.data(), pp.payload.size())
      .i(F_WORK_TYPE, pp.work_type)
      .i(F_PRIO, pp.prio)
      .i(F_TARGET_RANK, pp.target_rank)
      .i(F_ANSWER_RANK, pp.answer_rank)
      .i(F_COMMON_LEN, 0)
      .i(F_COMMON_SERVER, -1)
      .i(F_COMMON_SEQNO, -1)
      .i(F_PUT_ID, id);
  send_msg(pp.server, e);
}

static void settle_put(const Msg &m) {  // called with g->mu held
  int64_t id = m.geti(F_PUT_ID);
  auto it = pending_puts.find(id);
  if (it == pending_puts.end()) return;
  int rc = (int)m.geti(F_RC);
  if (rc == ADLB_BACKOFF) {
    // backpressured pipelined put: replay toward the same server without
    // burning the reject budget, pacing by the server's carried hint
    // (pump_resends sleeps it with the lock released — the fixed 2 ms
    // resend pace would hammer the saturated server ~12x faster than it
    // asked for, defeating the load shedding)
    it->second.backoff_ms = (int)m.geti(F_RETRY_AFTER_MS, 25);
    resend_queue.push_back(id);
    return;
  }
  if (rc == ADLB_PUT_REJECTED && ++it->second.attempts <= 10) {
    int hint = (int)m.geti(F_HINT, -1);
    it->second.server = hint >= 0 ? hint : next_server();
    // replay happens in pump_resends() with the lock RELEASED: sleeping or
    // sending here would stall the reader threads (and abort delivery)
    resend_queue.push_back(id);
    return;
  }
  if (rc != ADLB_SUCCESS) {
    failed_puts++;
    if (rc == ADLB_NO_MORE_WORK) failed_nmw = true;
  } else if (it->second.target_rank >= 0 &&
             it->second.server != home_server(it->second.target_rank)) {
    Encoder e(T_FA_DID_PUT_AT_REMOTE, g->rank);
    e.i(F_TARGET_RANK, it->second.target_rank)
        .i(F_WORK_TYPE, it->second.work_type)
        .i(F_SERVER_RANK, it->second.server);
    send_msg(home_server(it->second.target_rank), e);
  }
  pending_puts.erase(it);
}

// Replay rejected pipelined puts queued by settle_put. Call WITHOUT g->mu:
// the pacing sleep and the (possibly connect-blocking) send must not stall
// inbound frames.
static void pump_resends() {
  for (;;) {
    int64_t id = -1;
    PendingPut copy;
    {
      std::lock_guard<std::mutex> lk(g->mu);
      while (!resend_queue.empty()) {
        int64_t cand = resend_queue.front();
        resend_queue.erase(resend_queue.begin());
        auto it = pending_puts.find(cand);
        if (it != pending_puts.end()) {
          id = cand;
          copy = it->second;
          it->second.backoff_ms = 0;  // hint consumed by this replay
          break;
        }
      }
    }
    if (id < 0) return;
    // a backpressured put sleeps the server's retry-after hint; a
    // rejected-and-rerouted one paces like the synchronous retry loop
    usleep(copy.backoff_ms > 0 ? (useconds_t)copy.backoff_ms * 1000
                               : 2000);
    send_iput(id, copy);
  }
}

// Handle a frame that is not an awaited protocol response: abort frames
// terminate, app_comm traffic is stashed, anything else is fatal.
void dispatch_passive(Msg m) {
  if (m.tag == T_TA_ABORT) {
    int code = (int)m.geti(F_CODE, ADLB_ERROR);
    fprintf(stderr, "[adlb rank %d] world aborted (code %d)\n", g->rank,
            code);
    exit(code == 0 ? 1 : (code < 0 ? -code : code));
  }
  if (m.tag == T_AM_APP) {
    g->app_inbox.push_back(std::move(m));
    return;
  }
  if (m.tag == T_TA_PUT_RESP && m.ints.count(F_PUT_ID)) {
    settle_put(m);
    return;
  }
  die("unexpected tag %u outside a pending request", m.tag);
}

Msg wait_for(uint16_t want) {
  for (;;) {
    {
      std::unique_lock<std::mutex> lk(g->mu);
      g->cv.wait(lk, [] { return !g->inbox.empty(); });
      Msg m = std::move(g->inbox.front());
      g->inbox.pop_front();
      if (m.tag == want &&
          !(m.tag == T_TA_PUT_RESP && m.ints.count(F_PUT_ID)))
        return m;
      dispatch_passive(std::move(m));
    }
    pump_resends();  // lock released: replays queued by settle_put
  }
}

int home_server(int app_rank) {
  return g->num_app_ranks + (app_rank % g->nservers);
}

int next_server() {
  // data-locality routing (the Python runtime's put_routing="home"): all
  // of this rank's untargeted puts land on its home server, the scenario
  // shape where cross-server balancing is load-bearing
  if (g->route_home) return g->home;
  int s = g->num_app_ranks + g->rr;
  g->rr = (g->rr + 1) % g->nservers;
  return s;
}

bool valid_type(int t) {
  for (int x : g->types)
    if (x == t) return true;
  return false;
}

}  // namespace

// ---- public API -----------------------------------------------------------

extern "C" {

// ---- run-time tracing: the reference's MPE profiling wrapper layer
// (reference src/adlb_prof.c — compile-time LOG_ADLB_INTERNALS per-call
// state events and LOG_GUESS_USER_STATE inferred per-type user intervals
// between Get_reserved calls), gated here by ADLB_TRACE=<path prefix> at
// run time. ADLB_Finalize writes <prefix>.<rank>.trace.json in Chrome
// trace-event format (one file per rank; concatenate the arrays to merge).
static double trace_now() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return double(ts.tv_sec) + double(ts.tv_nsec) * 1e-9;
}
struct TraceEv {
  const char *name;
  int wt;  // work type for inferred user states, -1 for API calls
  double ts, dur;
};
static bool trace_on = false;
static std::string trace_prefix;
static std::vector<TraceEv> trace_events;
static double trace_user_t0 = -1.0;
static int trace_user_wt = -1;
static int trace_last_reserved_wt = -1;

static void trace_api_entry() {
  if (!trace_on) return;
  if (trace_user_t0 >= 0) {  // close the open inferred user-state span
    trace_events.push_back(
        {"user", trace_user_wt, trace_user_t0, trace_now() - trace_user_t0});
    trace_user_t0 = -1.0;
  }
}
static void trace_call(const char *name, double t0) {
  if (!trace_on) return;
  trace_events.push_back({name, -1, t0, trace_now() - t0});
}
static void trace_got_work() {  // successful Get_reserved opens a user span
  if (!trace_on) return;
  trace_user_t0 = trace_now();
  trace_user_wt = trace_last_reserved_wt;
}
static void trace_flush(int rank) {
  if (!trace_on) return;
  trace_api_entry();
  std::string path = trace_prefix + "." + std::to_string(rank) +
                     ".trace.json";
  FILE *f = fopen(path.c_str(), "w");
  if (f == nullptr) return;
  fprintf(f, "[");
  for (size_t i = 0; i < trace_events.size(); ++i) {
    const TraceEv &e = trace_events[i];
    if (i) fprintf(f, ",");
    if (e.wt >= 0)
      fprintf(f,
              "{\"name\":\"user:type%d\",\"ph\":\"X\",\"ts\":%.3f,"
              "\"dur\":%.3f,\"pid\":%d,\"tid\":%d}",
              e.wt, e.ts * 1e6, e.dur * 1e6, rank, rank);
    else
      fprintf(f,
              "{\"name\":\"%s\",\"ph\":\"X\",\"ts\":%.3f,"
              "\"dur\":%.3f,\"pid\":%d,\"tid\":%d}",
              e.name, e.ts * 1e6, e.dur * 1e6, rank, rank);
  }
  fprintf(f, "]\n");
  fclose(f);
}

int ADLBP_Init(int num_servers, int use_debug_server, int aprintf_flag,
               int ntypes, int type_vect[], int *am_server,
               int *am_debug_server, int *num_app_ranks) {
  if (g) return ADLB_ERROR;
  if (num_servers <= 0) {
    // without this, home_server()'s rank % num_servers dies with an
    // unexplained SIGFPE (the reference asserts the same way,
    // src/adlb.c:238)
    fprintf(stderr, "adlb: num_servers must be positive (got %d)\n",
            num_servers);
    return ADLB_ERROR;
  }
  const char *rv = getenv("ADLB_RENDEZVOUS");
  const char *rk = getenv("ADLB_RANK");
  if (!rv || !rk) {
    fprintf(stderr, "adlb: ADLB_RENDEZVOUS and ADLB_RANK must be set\n");
    return ADLB_ERROR;
  }
  g = new Ctx();
  g->rank = atoi(rk);
  g->aprintf_flag = aprintf_flag;
  g->types.assign(type_vect, type_vect + ntypes);

  FILE *f = fopen(rv, "r");
  if (!f) die("cannot open rendezvous file %s", rv);
  int r, port;
  char host[256];
  int maxrank = -1;
  std::map<int, std::pair<std::string, int>> entries;
  while (fscanf(f, "%d %255s %d", &r, host, &port) == 3) {
    entries[r] = {host, port};
    if (r > maxrank) maxrank = r;
  }
  fclose(f);
  g->nranks = maxrank + 1;
  g->addr.resize(g->nranks);
  for (auto &kv : entries) g->addr[kv.first] = kv.second;
  g->nservers = num_servers;
  g->num_app_ranks = g->nranks - num_servers - (use_debug_server ? 1 : 0);
  if (g->rank < 0 || g->rank >= g->num_app_ranks)
    die("ADLB_RANK %d is not an app rank (0..%d)", g->rank,
        g->num_app_ranks - 1);
  g->home = home_server(g->rank);
  g->rr = g->rank % g->nservers;
  const char *routing = getenv("ADLB_PUT_ROUTING");
  g->route_home = (routing != nullptr && strcmp(routing, "home") == 0);

  // bind our listener at the advertised address
  g->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(g->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in sa = {};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = htonl(INADDR_ANY);
  sa.sin_port = htons((uint16_t)g->addr[g->rank].second);
  if (bind(g->listen_fd, (struct sockaddr *)&sa, sizeof sa) != 0)
    die("cannot bind port %d", g->addr[g->rank].second);
  if (listen(g->listen_fd, 64) != 0) die("listen failed");
  g->acceptor = std::thread(accept_loop);

  if (am_server) *am_server = 0;
  if (am_debug_server) *am_debug_server = 0;
  if (num_app_ranks) *num_app_ranks = g->num_app_ranks;
  return ADLB_SUCCESS;
}

int ADLB_Init(int num_servers, int use_debug_server, int aprintf_flag,
              int ntypes, int type_vect[], int *am_server,
              int *am_debug_server, int *num_app_ranks) {
  int rc = ADLBP_Init(num_servers, use_debug_server, aprintf_flag, ntypes,
                      type_vect, am_server, am_debug_server, num_app_ranks);
  const char *tp = getenv("ADLB_TRACE");
  if (rc == ADLB_SUCCESS && tp != nullptr && tp[0] != '\0') {
    trace_on = true;
    trace_prefix = tp;
  }
  return rc;
}

int ADLBP_Server(double, double) { return ADLB_ERROR; }
int ADLB_Server(double a, double b) { return ADLBP_Server(a, b); }
int ADLBP_Debug_server(double) { return ADLB_ERROR; }
int ADLB_Debug_server(double t) { return ADLBP_Debug_server(t); }


int ADLBP_Put(void *work_buf, int work_len, int target_rank, int answer_rank,
              int work_type, int work_prio) {
  if (!g) return ADLB_ERROR;
  if (!valid_type(work_type)) die("Put of unregistered type %d", work_type);
  if (g->batch_active) g->batch_refcnt++;
  int server;
  if (target_rank >= 0)
    server = home_server(target_rank);
  else
    server = next_server();
  int attempts = 0;
  int rc;
  for (;;) {
    Encoder e(T_FA_PUT, g->rank);
    e.bytes(F_PAYLOAD, work_buf, (size_t)work_len)
        .i(F_WORK_TYPE, work_type)
        .i(F_PRIO, work_prio)
        .i(F_TARGET_RANK, target_rank)
        .i(F_ANSWER_RANK, answer_rank)
        .i(F_COMMON_LEN, g->batch_active ? g->batch_len : 0)
        .i(F_COMMON_SERVER, g->batch_active ? g->batch_server : -1)
        .i(F_COMMON_SEQNO, g->batch_active ? g->batch_seqno : -1);
    send_msg(server, e);
    Msg resp = wait_for(T_TA_PUT_RESP);
    rc = (int)resp.geti(F_RC);
    if (rc == ADLB_BACKOFF) {
      // overload backpressure: the fleet is above its hard watermark, so
      // hopping servers would not help — wait out the carried hint and
      // retry the SAME server without burning the reject budget
      usleep((useconds_t)resp.geti(F_RETRY_AFTER_MS, 25) * 1000);
      continue;
    }
    if (rc != ADLB_PUT_REJECTED) break;
    if (++attempts > 10) {  // reference retry loop, src/adlb.c:2779-2796
      if (g->batch_active) g->batch_refcnt--;
      return ADLB_PUT_REJECTED;
    }
    int hint = (int)resp.geti(F_HINT, -1);
    server = hint >= 0 ? hint : next_server();
    usleep(2000);
  }
  if (rc != ADLB_SUCCESS && g->batch_active) g->batch_refcnt--;
  if (rc == ADLB_SUCCESS && target_rank >= 0 &&
      server != home_server(target_rank)) {
    Encoder e(T_FA_DID_PUT_AT_REMOTE, g->rank);
    e.i(F_TARGET_RANK, target_rank)
        .i(F_WORK_TYPE, work_type)
        .i(F_SERVER_RANK, server);
    send_msg(home_server(target_rank), e);
  }
  return rc;
}
int ADLB_Put(void *b, int l, int t, int a, int w, int p) {
  if (!trace_on) return ADLBP_Put(b, l, t, a, w, p);
  trace_api_entry();
  double t0 = trace_now();
  int rc = ADLBP_Put(b, l, t, a, w, p);
  trace_call("adlb:put", t0);
  return rc;
}

static int reserve_impl(int *req_types, int *work_type, int *work_prio,
                        int *work_handle, int *work_len, int *answer_rank,
                        int hang, int fetch = 0, Msg *raw = nullptr,
                        int fetch_max = 1) {
  if (!g) return ADLB_ERROR;
  std::vector<int64_t> types;
  bool any = false;
  if (!req_types || req_types[0] == ADLB_RESERVE_REQUEST_ANY) {
    any = true;
  } else {
    for (int i = 0; i < 16 && req_types[i] != ADLB_RESERVE_EOL; i++) {
      if (!valid_type(req_types[i]))
        die("Reserve of unregistered type %d", req_types[i]);
      types.push_back(req_types[i]);
    }
    if (types.empty()) any = true;
  }
  g->rqseqno++;
  Encoder e(T_FA_RESERVE, g->rank);
  e.i(F_HANG, hang).i(F_RQSEQNO, g->rqseqno);
  if (fetch) e.i(F_FETCH, 1);
  if (fetch_max > 1) e.i(F_FETCH_MAX, fetch_max);
  if (!any) e.list(F_REQ_TYPES, types);
  send_msg(g->home, e);
  Msg resp = wait_for(T_TA_RESERVE_RESP);
  int rc = (int)resp.geti(F_RC);
  if (rc != ADLB_SUCCESS) return rc;
  if (work_type) *work_type = (int)resp.geti(F_WORK_TYPE);
  trace_last_reserved_wt = (int)resp.geti(F_WORK_TYPE);
  if (work_prio) *work_prio = (int)resp.geti(F_PRIO);
  if (work_len) *work_len = (int)resp.geti(F_WORK_LEN);
  if (answer_rank) *answer_rank = (int)resp.geti(F_ANSWER_RANK, -1);
  if (raw != nullptr) {  // fused caller inspects payload-vs-handle itself
    *raw = std::move(resp);
    return ADLB_SUCCESS;
  }
  auto it = resp.lists.find(F_HANDLE);
  if (it == resp.lists.end() || it->second.size() != ADLB_HANDLE_SIZE)
    die("malformed reserve handle");
  for (int i = 0; i < ADLB_HANDLE_SIZE; i++)
    work_handle[i] = (int)it->second[i];
  return ADLB_SUCCESS;
}

int ADLBP_Reserve(int *rt, int *wt, int *wp, int *wh, int *wl, int *ar) {
  return reserve_impl(rt, wt, wp, wh, wl, ar, 1);
}
int ADLB_Reserve(int *rt, int *wt, int *wp, int *wh, int *wl, int *ar) {
  if (!trace_on) return reserve_impl(rt, wt, wp, wh, wl, ar, 1);
  trace_api_entry();
  double t0 = trace_now();
  int rc = reserve_impl(rt, wt, wp, wh, wl, ar, 1);
  trace_call("adlb:reserve", t0);
  return rc;
}
int ADLBP_Ireserve(int *rt, int *wt, int *wp, int *wh, int *wl, int *ar) {
  return reserve_impl(rt, wt, wp, wh, wl, ar, 0);
}
int ADLB_Ireserve(int *rt, int *wt, int *wp, int *wh, int *wl, int *ar) {
  if (!trace_on) return reserve_impl(rt, wt, wp, wh, wl, ar, 0);
  trace_api_entry();
  double t0 = trace_now();
  int rc = reserve_impl(rt, wt, wp, wh, wl, ar, 0);
  trace_call("adlb:ireserve", t0);
  return rc;
}

// Fetch a batch-common prefix into *out; advances *out past the prefix.
// Shared by the Get_reserved handle path and the fused suffix+common
// reservation response (the Python server inlines only the SUFFIX of a
// prefixed unit since the remote-fused-fetch change — the client
// assembles prefix + suffix itself). Returns the server's rc: a GC'd
// prefix (reclaim edge) must surface as an error, never as a silently
// truncated payload.
static int fetch_common_prefix(int common_server, int64_t common_seqno,
                               char **out) {
  Encoder e(T_FA_GET_COMMON, g->rank);
  e.i(F_COMMON_SEQNO, common_seqno);
  send_msg(common_server, e);
  Msg resp = wait_for(T_TA_GET_COMMON_RESP);
  int rc = (int)resp.geti(F_RC, ADLB_SUCCESS);
  if (rc != ADLB_SUCCESS) return rc;
  const std::string &prefix = resp.blobs[F_PAYLOAD];
  memcpy(*out, prefix.data(), prefix.size());
  *out += prefix.size();
  return ADLB_SUCCESS;
}

int ADLBP_Get_reserved_timed(void *work_buf, int *work_handle,
                             double *time_on_queue) {
  if (!g) return ADLB_ERROR;
  // handle = {seqno, holder server, common_len, common_server, common_seqno}
  // (reference src/adlb.c:2935-2947)
  int64_t seqno = work_handle[0];
  int holder = work_handle[1];
  int common_len = work_handle[2];
  int common_server = work_handle[3];
  int64_t common_seqno = work_handle[4];
  char *out = (char *)work_buf;
  if (common_len > 0) {
    int rc = fetch_common_prefix(common_server, common_seqno, &out);
    if (rc != ADLB_SUCCESS) return rc;
  }
  Encoder e(T_FA_GET_RESERVED, g->rank);
  e.i(F_SEQNO, seqno);
  send_msg(holder, e);
  Msg resp = wait_for(T_TA_GET_RESERVED_RESP);
  int rc = (int)resp.geti(F_RC);
  // ADLB_FENCED surfaces here as-is: this rank's lease expired while it
  // was silent (lease_timeout_s armed on a Python-server world) and the
  // unit went to another worker — drop the handle and re-reserve
  if (rc != ADLB_SUCCESS) return rc;
  const std::string &payload = resp.blobs[F_PAYLOAD];
  memcpy(out, payload.data(), payload.size());
  if (time_on_queue) {
    auto it = resp.dbls.find(F_TIME_ON_Q);
    *time_on_queue = it == resp.dbls.end() ? 0.0 : it->second;
  }
  return ADLB_SUCCESS;
}
int ADLB_Get_reserved_timed(void *b, int *h, double *t) {
  if (!trace_on) return ADLBP_Get_reserved_timed(b, h, t);
  trace_api_entry();
  double t0 = trace_now();
  int rc = ADLBP_Get_reserved_timed(b, h, t);
  trace_call("adlb:get_reserved", t0);
  if (rc == ADLB_SUCCESS) trace_got_work();
  return rc;
}
int ADLBP_Get_reserved(void *b, int *h) {
  return ADLBP_Get_reserved_timed(b, h, nullptr);
}
int ADLB_Get_reserved(void *b, int *h) {
  return ADLB_Get_reserved_timed(b, h, nullptr);
}

int ADLBP_Begin_batch_put(void *common_buf, int len_common) {
  if (!g || g->batch_active) return ADLB_ERROR;
  int server = next_server();
  Encoder e(T_FA_PUT_COMMON, g->rank);
  e.bytes(F_PAYLOAD, common_buf, (size_t)len_common);
  send_msg(server, e);
  Msg resp = wait_for(T_TA_PUT_COMMON_RESP);
  int rc = (int)resp.geti(F_RC);
  if (rc != ADLB_SUCCESS) return rc;
  g->batch_active = true;
  g->batch_server = server;
  g->batch_len = len_common;
  g->batch_seqno = resp.geti(F_COMMON_SEQNO, -1);
  g->batch_refcnt = 0;
  return ADLB_SUCCESS;
}
int ADLB_Begin_batch_put(void *b, int l) { return ADLBP_Begin_batch_put(b, l); }

int ADLBP_End_batch_put(void) {
  if (!g || !g->batch_active) return ADLB_ERROR;
  Encoder e(T_FA_BATCH_DONE, g->rank);
  e.i(F_COMMON_SEQNO, g->batch_seqno).i(F_REFCNT, g->batch_refcnt);
  send_msg(g->batch_server, e);
  g->batch_active = false;
  return ADLB_SUCCESS;
}
int ADLB_End_batch_put(void) { return ADLBP_End_batch_put(); }

int ADLBP_Set_problem_done(void) {
  if (!g) return ADLB_ERROR;
  Encoder e(T_FA_NO_MORE_WORK, g->rank);
  send_msg(g->home, e);
  return ADLB_SUCCESS;
}
int ADLB_Set_problem_done(void) { return ADLBP_Set_problem_done(); }
int ADLBP_Set_no_more_work(void) { return ADLBP_Set_problem_done(); }
int ADLB_Set_no_more_work(void) { return ADLBP_Set_problem_done(); }

int ADLBP_Info_get(int key, double *value) {
  if (!g) return ADLB_ERROR;
  Encoder e(T_FA_INFO_GET, g->rank);
  e.i(F_KEY, key);
  send_msg(g->home, e);
  Msg resp = wait_for(T_TA_INFO_GET_RESP);
  if (value) {
    auto it = resp.dbls.find(F_VALUE);
    *value = it == resp.dbls.end() ? 0.0 : it->second;
  }
  return (int)resp.geti(F_RC);
}
int ADLB_Info_get(int k, double *v) { return ADLBP_Info_get(k, v); }

int ADLBP_Checkpoint(const char *path_prefix, int *units_captured) {
  // Snapshot the whole pool to <prefix>.<server>.ckpt shards (this
  // framework's extension — the reference has no pool serialization;
  // restore via the daemon's restore_path config). Blocks until every
  // server has written its shard.
  if (!g || path_prefix == nullptr) return ADLB_ERROR;
  Encoder e(T_FA_CHECKPOINT, g->rank);
  e.bytes(F_PATH, path_prefix, strlen(path_prefix));
  send_msg(g->home, e);
  Msg resp = wait_for(T_TA_CHECKPOINT_RESP);
  if (units_captured) *units_captured = (int)resp.geti(F_COUNT);
  return (int)resp.geti(F_RC);
}
int ADLB_Checkpoint(const char *p, int *n) { return ADLBP_Checkpoint(p, n); }

int ADLBP_Info_num_work_units(int work_type, int *num_units, int *num_bytes,
                              int *max_wq_count) {
  if (!g) return ADLB_ERROR;
  Encoder e(T_FA_INFO_NUM_WORK_UNITS, g->rank);
  e.i(F_WORK_TYPE, work_type);
  send_msg(g->home, e);
  Msg resp = wait_for(T_TA_INFO_NUM_RESP);
  if (num_units) *num_units = (int)resp.geti(F_COUNT);
  if (num_bytes) *num_bytes = (int)resp.geti(F_NBYTES);
  if (max_wq_count) *max_wq_count = (int)resp.geti(F_MAX_WQ);
  return (int)resp.geti(F_RC);
}
int ADLB_Info_num_work_units(int w, int *n, int *b, int *m) {
  return ADLBP_Info_num_work_units(w, n, b, m);
}

int ADLBP_Finalize(void) {
  if (!g) return ADLB_ERROR;
  if (!pending_puts.empty()) {
    // un-settled pipelined puts must land before LOCAL_APP_DONE, or the
    // shutdown ring could outrun them
    int rc = ADLBP_Flush_puts();
    if (rc != ADLB_SUCCESS && rc != ADLB_NO_MORE_WORK)
      fprintf(stderr,
              "[adlb rank %d] finalize: pipelined puts terminally "
              "rejected (rc=%d)\n", g->rank, rc);
  }
  Encoder e(T_FA_LOCAL_APP_DONE, g->rank);
  send_msg(g->home, e);
  g->closed.store(true);
  for (auto &kv : g->out_fds) {
    shutdown(kv.second, SHUT_WR);  // FIN after data; no unread inbound
    close(kv.second);
  }
  shutdown(g->listen_fd, SHUT_RDWR);
  close(g->listen_fd);
  return ADLB_SUCCESS;
}
int ADLB_Finalize(void) {
  trace_flush(g ? g->rank : -1);
  return ADLBP_Finalize();
}

int ADLBP_Abort(int code) {
  if (g) {
    Encoder e(T_FA_ABORT, g->rank);
    e.i(F_CODE, code);
    send_msg(g->home, e);
    usleep(100 * 1000);  // let the frame flush before hard exit
  }
  fprintf(stderr, "[adlb rank %d] ADLB_Abort(%d)\n", g ? g->rank : -1, code);
  exit(code == 0 ? 1 : (code < 0 ? -code : code));
}
int ADLB_Abort(int code) { return ADLBP_Abort(code); }

// ---- app <-> app messaging (the reference's app_comm: ADLB_Init returns a
// communicator for direct point-to-point traffic among app ranks, e.g.
// c1.c's TAG_B_ANSWER flow; here the same fabric carries it as AM_APP
// frames with a user tag inside) --------------------------------------------

int ADLBP_App_send(int dest_app_rank, void *buf, int len, int apptag) {
  if (!g) return ADLB_ERROR;
  if (dest_app_rank < 0 || dest_app_rank >= g->num_app_ranks)
    die("App_send: %d is not an app rank", dest_app_rank);
  Encoder e(T_AM_APP, g->rank);
  e.bytes(F_PAYLOAD, buf, (size_t)len).i(F_APPTAG, apptag);
  send_msg(dest_app_rank, e);
  return ADLB_SUCCESS;
}
int ADLB_App_send(int d, void *b, int l, int t) {
  if (!trace_on) return ADLBP_App_send(d, b, l, t);
  trace_api_entry();
  double t0 = trace_now();
  int rc = ADLBP_App_send(d, b, l, t);
  trace_call("adlb:app_send", t0);
  return rc;
}

// drain frames already delivered while idle; call with g->mu held
static void drain_inbox_locked() {
  while (!g->inbox.empty()) {
    Msg m = std::move(g->inbox.front());
    g->inbox.pop_front();
    dispatch_passive(std::move(m));
  }
}

int ADLBP_App_iprobe(int *src, int *apptag, int *len) {
  if (!g) return ADLB_ERROR;
  std::unique_lock<std::mutex> lk(g->mu);
  drain_inbox_locked();
  if (g->app_inbox.empty()) return 0;
  const Msg &m = g->app_inbox.front();
  if (src) *src = m.src;
  if (apptag) *apptag = (int)m.geti(F_APPTAG, 0);
  if (len) {
    auto it = m.blobs.find(F_PAYLOAD);
    *len = it == m.blobs.end() ? 0 : (int)it->second.size();
  }
  return 1;
}
int ADLB_App_iprobe(int *s_, int *t, int *l) {
  if (!trace_on) return ADLBP_App_iprobe(s_, t, l);
  trace_api_entry();
  double t0 = trace_now();
  int rc = ADLBP_App_iprobe(s_, t, l);
  trace_call("adlb:app_iprobe", t0);
  return rc;
}

int ADLBP_App_recv(void *buf, int maxlen, int *src, int *apptag) {
  if (!g) return ADLB_ERROR;
  std::unique_lock<std::mutex> lk(g->mu);
  for (;;) {
    drain_inbox_locked();
    if (!g->app_inbox.empty()) break;
    g->cv.wait(lk, [] { return !g->inbox.empty(); });
  }
  Msg m = std::move(g->app_inbox.front());
  g->app_inbox.pop_front();
  auto it = m.blobs.find(F_PAYLOAD);
  int n = it == m.blobs.end() ? 0 : (int)it->second.size();
  if (n > maxlen)
    die("App_recv: message of %d bytes exceeds buffer of %d", n, maxlen);
  if (n > 0) memcpy(buf, it->second.data(), (size_t)n);
  if (src) *src = m.src;
  if (apptag) *apptag = (int)m.geti(F_APPTAG, 0);
  return n;
}
int ADLB_App_recv(void *b, int m, int *s_, int *t) {
  if (!trace_on) return ADLBP_App_recv(b, m, s_, t);
  trace_api_entry();
  double t0 = trace_now();
  int rc = ADLBP_App_recv(b, m, s_, t);
  trace_call("adlb:app_recv", t0);
  return rc;
}

// ---- pipelined puts + fused reserve/get (framework extensions) ----------

int ADLBP_Iput(void *work_buf, int work_len, int target_rank, int answer_rank,
               int work_type, int work_prio) {
  if (!g) return ADLB_ERROR;
  if (!valid_type(work_type)) die("Iput of unregistered type %d", work_type);
  if (g->batch_active)
    die("Iput inside Begin_batch_put is not supported (the common-prefix "
        "refcount must be exact)");
  if (target_rank >= 0 && target_rank >= g->num_app_ranks)
    die("Iput target rank %d is not an app rank", target_rank);
  PendingPut copy;
  int64_t id;
  {
    std::unique_lock<std::mutex> lk(g->mu);
    drain_inbox_locked();  // settle delivered responses: stay bounded
    PendingPut pp;
    pp.payload.assign((const char *)work_buf, (size_t)work_len);
    pp.work_type = work_type;
    pp.prio = work_prio;
    pp.target_rank = target_rank;
    pp.answer_rank = answer_rank;
    pp.attempts = 0;
    pp.server = target_rank >= 0 ? home_server(target_rank) : next_server();
    id = next_put_id++;
    pending_puts[id] = pp;
    copy = std::move(pp);
  }
  send_iput(id, copy);  // lock released: sends may block on connect
  pump_resends();
  return ADLB_SUCCESS;
}
int ADLB_Iput(void *b, int l, int t, int a, int w, int p) {
  if (!trace_on) return ADLBP_Iput(b, l, t, a, w, p);
  trace_api_entry();
  double t0 = trace_now();
  int rc = ADLBP_Iput(b, l, t, a, w, p);
  trace_call("adlb:iput", t0);
  return rc;
}

int ADLBP_Flush_puts(void) {
  if (!g) return ADLB_ERROR;
  for (;;) {
    {
      std::unique_lock<std::mutex> lk(g->mu);
      drain_inbox_locked();
      if (pending_puts.empty() && resend_queue.empty()) break;
      if (resend_queue.empty())
        g->cv.wait_for(lk, std::chrono::milliseconds(100),
                       [] { return !g->inbox.empty(); });
    }
    pump_resends();  // lock released: pacing + sends must not stall readers
  }
  std::lock_guard<std::mutex> lk(g->mu);
  int failed = failed_puts;
  bool nmw = failed_nmw;
  failed_puts = 0;
  failed_nmw = false;
  if (nmw) return ADLB_NO_MORE_WORK;
  return failed ? ADLB_PUT_REJECTED : ADLB_SUCCESS;
}
int ADLB_Flush_puts(void) {
  if (!trace_on) return ADLBP_Flush_puts();
  trace_api_entry();
  double t0 = trace_now();
  int rc = ADLBP_Flush_puts();
  trace_call("adlb:flush_puts", t0);
  return rc;
}

int ADLBP_Get_work(int *req_types, int *work_type, int *work_prio,
                   void *work_buf, int max_len, int *work_len,
                   int *answer_rank) {
  // the single-unit call IS a 1-slot batch (scalar out-pointers are
  // 1-element arrays); one copy of the fused/handle fallback logic
  int ng = 0, wl = 0;
  int rc = ADLBP_Get_work_batch(req_types, 1, &ng, work_type, work_prio,
                                work_buf, max_len, &wl, answer_rank);
  if (work_len) *work_len = wl;
  return rc;
}
int ADLBP_Get_work_batch(int *req_types, int max_units, int *num_got,
                         int *work_types, int *work_prios,
                         void *payload_buf, int max_len_per_unit,
                         int *work_lens, int *answer_ranks) {
  if (!g) return ADLB_ERROR;
  if (max_units < 1) die("Get_work_batch: max_units must be >= 1");
  if (num_got) *num_got = 0;
  Msg resp;
  int rc = reserve_impl(req_types, nullptr, nullptr, nullptr, nullptr,
                        nullptr, /*hang=*/1, /*fetch=*/1, &resp, max_units);
  if (rc != ADLB_SUCCESS) return rc;
  char *out = (char *)payload_buf;
  auto blit = resp.blists.find(F_PAYLOADS);
  if (blit != resp.blists.end()) {  // batch-fused: all units consumed
    const std::vector<std::string> &pl = blit->second;
    if ((int)pl.size() > max_units)
      die("Get_work_batch: server sent %zu units for a %d-slot buffer",
          pl.size(), max_units);
    const std::vector<int64_t> &wt = resp.lists[F_WORK_TYPES];
    const std::vector<int64_t> &wp = resp.lists[F_PRIOS];
    const std::vector<int64_t> &ar = resp.lists[F_ANSWER_RANKS];
    for (size_t i = 0; i < pl.size(); i++) {
      int n = (int)pl[i].size();
      if (n > max_len_per_unit)
        die("Get_work_batch: payload of %d bytes exceeds per-unit buffer "
            "of %d", n, max_len_per_unit);
      memcpy(out + (size_t)i * max_len_per_unit, pl[i].data(), (size_t)n);
      if (work_lens) work_lens[i] = n;
      if (work_types && i < wt.size()) work_types[i] = (int)wt[i];
      if (work_prios && i < wp.size()) work_prios[i] = (int)wp[i];
      if (answer_ranks && i < ar.size()) answer_ranks[i] = (int)ar[i];
    }
    trace_last_reserved_wt = wt.empty() ? trace_last_reserved_wt
                                        : (int)wt[0];
    if (num_got) *num_got = (int)pl.size();
    return ADLB_SUCCESS;
  }
  // single-unit shapes (a park wake-up, a remote/prefixed fallback, or a
  // peer that ignores fetch_max)
  if (work_types) work_types[0] = (int)resp.geti(F_WORK_TYPE);
  if (work_prios) work_prios[0] = (int)resp.geti(F_PRIO);
  if (answer_ranks) answer_ranks[0] = (int)resp.geti(F_ANSWER_RANK, -1);
  auto bit = resp.blobs.find(F_PAYLOAD);
  if (bit != resp.blobs.end()) {  // fused single
    // a batch-common unit inlines only its SUFFIX + the prefix handle;
    // assemble prefix + suffix here (one extra fetch per unit — the
    // Python client amortizes it through its prefix cache)
    int common_len = (int)resp.geti(F_COMMON_LEN, 0);
    int n = (int)bit->second.size() + common_len;
    if (n > max_len_per_unit)
      die("Get_work_batch: payload of %d bytes exceeds per-unit buffer of "
          "%d", n, max_len_per_unit);
    char *w = out;
    if (common_len > 0) {
      int prc = fetch_common_prefix((int)resp.geti(F_COMMON_SERVER, -1),
                                    resp.geti(F_COMMON_SEQNO, -1), &w);
      if (prc != ADLB_SUCCESS) return prc;
    }
    memcpy(w, bit->second.data(), bit->second.size());
    if (work_lens) work_lens[0] = n;
    if (num_got) *num_got = 1;
    return ADLB_SUCCESS;
  }
  auto hit = resp.lists.find(F_HANDLE);
  if (hit == resp.lists.end() || hit->second.size() != ADLB_HANDLE_SIZE)
    die("malformed reserve handle");
  int handle[ADLB_HANDLE_SIZE];
  for (int i = 0; i < ADLB_HANDLE_SIZE; i++)
    handle[i] = (int)hit->second[i];
  int wl = (int)resp.geti(F_WORK_LEN);
  if (wl > max_len_per_unit)
    die("Get_work_batch: payload of %d bytes exceeds per-unit buffer of %d",
        wl, max_len_per_unit);
  if (work_lens) work_lens[0] = wl;
  rc = ADLBP_Get_reserved_timed(out, handle, nullptr);
  if (rc == ADLB_SUCCESS && num_got) *num_got = 1;
  return rc;
}
int ADLB_Get_work_batch(int *rt, int max_units, int *ng, int *wt, int *wp,
                        void *b, int mlpu, int *wl, int *ar) {
  if (!trace_on)
    return ADLBP_Get_work_batch(rt, max_units, ng, wt, wp, b, mlpu, wl, ar);
  trace_api_entry();
  double t0 = trace_now();
  int rc = ADLBP_Get_work_batch(rt, max_units, ng, wt, wp, b, mlpu, wl, ar);
  trace_call("adlb:get_work_batch", t0);
  return rc;
}
int ADLB_Get_work(int *rt, int *wt, int *wp, void *b, int ml, int *wl,
                  int *ar) {
  if (!trace_on) return ADLBP_Get_work(rt, wt, wp, b, ml, wl, ar);
  trace_api_entry();
  double t0 = trace_now();
  int rc = ADLBP_Get_work(rt, wt, wp, b, ml, wl, ar);
  trace_call("adlb:get_work", t0);
  if (rc == ADLB_SUCCESS) trace_got_work();
  return rc;
}

// Stamped debug printing (reference src/adlb.c:3395-3417): rank, source
// line and seconds-since-init prefix, gated by both the call-site flag and
// the aprintf_flag given to ADLB_Init.
void adlbp_dbgprintf(int flag, int linenum, const char *fmt, ...) {
  if (!flag || g == nullptr || !g->aprintf_flag) return;
  static double t0 = trace_now();
  fprintf(stderr, "[r=%d] <%d> %.6f: ", g->rank, linenum, trace_now() - t0);
  va_list ap;
  va_start(ap, fmt);
  vfprintf(stderr, fmt, ap);
  va_end(ap);
  fflush(stderr);
}

int ADLB_World_rank(void) { return g ? g->rank : -1; }
int ADLB_World_size(void) { return g ? g->nranks : -1; }
int ADLB_Num_app_ranks(void) { return g ? g->num_app_ranks : -1; }

}  // extern "C"
