"""Balancer: the TPU brain that replaces the reference's gossip + stealing.

The reference approximates global state with a 0.1 s ring-gossiped status
vector and makes per-server greedy decisions (qmstat/RFR/push, reference
``src/adlb.c:806-822,1802-2070``). Here servers stream fixed-shape queue-state
snapshots to a balancer, which computes a *global* task->requester assignment
as one vectorized solve under ``jax.jit`` — on TPU the compatibility matrix
and conflict resolution map onto the MXU/VPU. The distributed variant
(``adlb_tpu.balancer.distributed``) shards the task table over a device mesh
with ``shard_map`` + ``all_gather``.
"""

from adlb_tpu.balancer.solve import AssignmentSolver  # noqa: F401
