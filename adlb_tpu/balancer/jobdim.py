"""The job dimension of the planning tiers (multi-job balancing).

The tpu balancer historically planned job 0 only: non-default
namespaces were kept out of balancer snapshots and fell back to the
qmstat RFR pull. Multi-job planning lifts that by giving every
snapshot row an optional JOB COLUMN and folding it into a COMPOSITE
TYPE INDEX::

    ci = job * T + base_type_index        (T = len(world.types))

so every matching kernel — the jitted greedy scan, the Pallas sweep,
the sharded candidate-gen/merge/auction program — stays completely
untouched: they see ``T' = max_jobs * T`` generic types and the job
isolation (a unit only ever matches requesters of its own namespace)
is structural, carried by the mask/type columns the packers build.
Only the packers change, and all of them (ledger twins, the
single-device dict path, the sharded tuple path) change through the
helpers below, so the pair-list-identity contract between the tiers
is preserved by construction (tests/test_ledger_parity.py and
tests/test_device_auction.py fuzz the job arm).

Wire shape: tasks grow a 5th element ``(seqno, type, prio, len, job)``
and reqs a 5th ``(rank, rqseqno, types, fetch, job)`` ONLY when the
job is non-default — single-job worlds stay byte-identical on every
frame. ``max_jobs <= 1`` (the default) reproduces the historical
planner exactly: same shapes, same compiled programs, same pairs.

Weights: per-job shares enter the assignment score as an int32-safe
PRIORITY BIAS folded into the clipped-prio columns at pack time::

    eff_prio = clip(prio, +/-1e9) + bias(job)
    bias(w)  = round((w - 1.0) * 1e6), clipped to +/-1e9

Weight 1.0 (the default) is bias 0 — frame and pair identity for
unweighted worlds. The bias headroom fits int32 (2e9 < 2^31-1) and
stays strictly above the _NEG padding sentinel. A weight of 1.001
outranks ~1000 native priority levels; weights are SHARES, priorities
stay the intra-job ordering.

Job ids are small sequential ints allocated by the master (0 = the
default namespace), so job -> slot is the identity while ``job <
max_jobs``. Overflow jobs (id >= max_jobs) stay invisible to the
planner — their tasks pack as the unknown-type sentinel (-1, never
matched) and their cross-server path remains the per-job qmstat RFR
fallback the steal mode uses.
"""

from __future__ import annotations

from typing import Optional, Sequence

#: clip shared with the solvers' priority clip: |bias| <= 1e9 keeps
#: eff_prio inside int32 with the +/-1e9 prio clip already applied
_BIAS_CLIP = 10**9

#: one weight point = this many priority levels
_BIAS_SCALE = 1_000_000


def weight_bias(weight: float) -> int:
    """Int32-safe priority bias for one job weight (1.0 -> 0)."""
    b = int(round((float(weight) - 1.0) * _BIAS_SCALE))
    return max(-_BIAS_CLIP, min(_BIAS_CLIP, b))


def bias_vector(job_weights: Optional[dict], max_jobs: int) -> tuple:
    """Per-slot bias tuple (length ``max(max_jobs, 1)``) from a
    ``{job_id: weight}`` map; jobs beyond ``max_jobs`` are ignored
    (the planner cannot see them)."""
    n = max(max_jobs, 1)
    bias = [0] * n
    for j, w in (job_weights or {}).items():
        j = int(j)
        if 0 <= j < n:
            bias[j] = weight_bias(w)
    return tuple(bias)


def expand_types(types: Sequence, max_jobs: int) -> tuple:
    """The composite type tuple the solvers/ledgers are shaped by:
    the base types themselves for single-job planning (exact
    back-compat, including type-value semantics for off-world types),
    else ``(job, base_type)`` pairs in job-major order — so composite
    index = job * T + base index, and type-value lookups stay one
    dict probe via :func:`type_key`."""
    if max_jobs <= 1:
        return tuple(types)
    return tuple((j, t) for j in range(max_jobs) for t in types)


def type_key(job: int, wtype, max_jobs: int):
    """The composite type-index key for one (job, raw type) pair —
    the raw type itself under single-job planning."""
    return wtype if max_jobs <= 1 else (job, wtype)


def task_job(t) -> int:
    """Job column of a snapshot task tuple (0 when absent)."""
    return t[4] if len(t) > 4 else 0


def req_job(r) -> int:
    """Job column of a snapshot req tuple (0 when absent)."""
    return r[4] if len(r) > 4 else 0
