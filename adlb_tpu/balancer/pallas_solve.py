"""Pallas TPU kernel for the greedy assignment inner loop.

The batched global solve (see :mod:`adlb_tpu.balancer.solve`) is this
framework's hot op — the TPU-native replacement for the reference's
per-Reserve O(|wq|·16) linear scans (reference ``src/xq.c:190-247``) run
once per balancer round over every server's queue at once.

Kernel design (SURVEY §7 stage 5, "Pallas for the auction inner loop"):

* An XLA pre-pass folds priority ordering, padding, requester validity and
  the type mask into one ``[NT, NRp]`` int32 *compatibility matrix*
  (``compat[k, r] = 1`` iff the k-th task in descending-priority order may
  go to requester ``r``) — pure vectorized gather work XLA fuses well.
* The Pallas kernel then runs the inherently sequential greedy sweep with
  the live state resident in VMEM: a grid over task-row *blocks* (so the
  compatibility matrix streams through VMEM block by block instead of
  having to fit whole — 16k x 2k once hit the 128M VMEM cap exactly), one
  ``fori_loop`` over the block's rows, each step a VPU-width mask/min over
  the open-requester vector, a scalar winner write, and an in-place
  open-vector update.  The open vector lives in persistent VMEM scratch
  across grid steps (TPU grids execute sequentially).  No HBM traffic
  inside the loop, no per-step XLA dispatch — exactly the "keep the inner
  loop on-chip" recipe.
* Winner inversion (task-order → per-requester assignment) is another tiny
  XLA scatter after the kernel.

Semantics are bit-identical to :func:`adlb_tpu.balancer.solve._host_greedy`
(tasks in stable descending-priority order, each taking the lowest-index
open compatible requester), so all three backends — host numpy, jitted XLA
scan, Pallas — are interchangeable and cross-checked in tests.

On non-TPU backends the kernel runs in interpreter mode (tests, CPU dev);
on TPU it compiles with Mosaic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from adlb_tpu.balancer.solve import _NEG

_LANE = 128  # TPU lane width: requester vectors are padded to a multiple
# per-grid-step compat slab budget, in compat-matrix BYTES (int8 when
# streaming, int32 otherwise; see _BIG_ELEMS)
_SLAB_BYTES = 2 << 20
# Above this compat-matrix size (elements) the sweep is DMA-bound and the
# matrix streams from HBM as int8 (4x less traffic; measured 14.7 -> 10 ms
# at 65k x 8k). Mosaic cannot prove alignment for dynamic single-row loads
# from an int8 (32-sublane-tiled) block, so each grid step first upcasts
# its whole block into an int32 VMEM scratch (one aligned full-block op)
# and the row loop reads that. BELOW the threshold the matrix stays int32
# and rows load straight from the input block: the upcast is a relayout
# (retiling) whose cost exceeds the DMA it saves at small shapes
# (measured 0.6 -> 1.1 ms regression at 4k x 512).
_BIG_ELEMS = 16 << 20


def _greedy_sweep_kernel(nopen0_ref, compat_ref, winner_ref, open_scr,
                         nopen_scr, *blk_scr, upcast: bool):
    """Sequential greedy over one block of priority-ordered task rows.

    nopen0_ref: [1] int32 scalar prefetch — number of MATCHABLE requesters
                (valid with a non-empty type mask) open at sweep start
    compat_ref: [B, NRp] int8 (upcast=True) or int32 (1 = this task may
                go to this requester)
    winner_ref: [B, 1] int32 out — requester index per task row, -1 = none
    open_scr:   [1, NRp] int32 scratch — 1 while a requester is unmatched;
                persists across the (sequential) task-block grid
    nopen_scr:  [1] int32 SMEM scratch — open matchable requesters left;
                every match decrements it, and a block that starts at zero
                skips its sweep (and upcast) outright: at most NR of the
                NT priority-ordered tasks can win, so for NT >> NR most
                of the sweep is this skip
    blk_scr:    (only when upcast) [B, NRp] int32 scratch — the int8
                block upcast once per grid step; see _BIG_ELEMS
    """
    nb = compat_ref.shape[0]
    nrp = compat_ref.shape[1]

    @pl.when(pl.program_id(0) == 0)
    def _init():
        open_scr[:] = jnp.ones((1, nrp), dtype=jnp.int32)
        nopen_scr[0] = nopen0_ref[0]

    lane = jax.lax.broadcasted_iota(jnp.int32, (1, nrp), 1)
    # decide BEFORE the sweep mutates the counter, so the two branches
    # below cannot both fire on the block where exhaustion happens
    active = nopen_scr[0] > 0

    @pl.when(active)
    def _sweep():
        if upcast:
            blk_scr[0][:] = compat_ref[:].astype(jnp.int32)
            rows = blk_scr[0]
        else:
            rows = compat_ref

        def body(t, _):
            row = rows[pl.ds(t, 1), :] * open_scr[:]
            # lowest-index open compatible requester (the host twin's
            # argmax on a bool mask picks the same first-True index)
            idx = jnp.min(jnp.where(row > 0, lane, nrp))
            found = idx < nrp
            winner_ref[pl.ds(t, 1), :] = jnp.where(found, idx, -1).reshape(
                1, 1
            )
            open_scr[:] = jnp.where(found & (lane == idx), 0, open_scr[:])
            nopen_scr[0] = nopen_scr[0] - found.astype(jnp.int32)
            return 0

        jax.lax.fori_loop(0, nb, body, 0)

    @pl.when(jnp.logical_not(active))
    def _exhausted():
        winner_ref[:] = jnp.full((nb, 1), -1, dtype=jnp.int32)


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_greedy_assign(
    task_prio: jax.Array,  # [NT] int32, _NEG for padding
    task_type: jax.Array,  # [NT] int32 type index, -1 for padding
    req_mask: jax.Array,  # [NR, T] bool
    req_valid: jax.Array,  # [NR] bool
    interpret: bool = False,
) -> jax.Array:
    """Drop-in twin of :func:`adlb_tpu.balancer.solve._greedy_assign` with
    the sweep as a Pallas kernel. Returns assign[NR] int32 (task index per
    requester, -1 if none)."""
    NT = task_prio.shape[0]
    NR = req_mask.shape[0]
    NRp = _round_up(max(NR, 1), _LANE)
    # layout decision is static (shapes are): int8 streaming + upcast
    # scratch for big DMA-bound matrices, plain int32 otherwise
    upcast = NT * NRp >= _BIG_ELEMS
    cbytes = 1 if upcast else 4
    # task-block size: keep each block's compat slab small (see
    # _SLAB_BYTES; with upcast the int32 scratch is 4x the slab)
    block = max(min(NT, _SLAB_BYTES // (cbytes * NRp)), 8)
    block = min(_round_up(block, 8), _round_up(NT, 8))
    NTp = _round_up(NT, block)

    # XLA pre-pass: stable descending-priority order + compat matrix
    order = jnp.argsort(-task_prio, stable=True)
    s_prio = task_prio[order]
    s_type = task_type[order]
    live = (s_prio > _NEG) & (s_type >= 0)
    compat = (
        live[:, None]
        & req_valid[None, :]
        & req_mask[:, jnp.clip(s_type, 0)].T
    )
    compat = jnp.pad(compat, ((0, NTp - NT), (0, NRp - NR))).astype(
        jnp.int8 if upcast else jnp.int32
    )
    # matchable = can ever be assigned; requesters with empty masks (or
    # invalid slots) must not count toward the exhaustion check
    nopen0 = (req_valid & req_mask.any(axis=1)).sum().astype(jnp.int32)

    scratch = [
        pltpu.VMEM((1, NRp), jnp.int32),
        pltpu.SMEM((1,), jnp.int32),
    ]
    if upcast:
        scratch.append(pltpu.VMEM((block, NRp), jnp.int32))
    winner = pl.pallas_call(
        functools.partial(_greedy_sweep_kernel, upcast=upcast),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(NTp // block,),
            in_specs=[
                pl.BlockSpec((block, NRp), lambda i, s: (i, 0),
                             memory_space=pltpu.VMEM)
            ],
            out_specs=pl.BlockSpec((block, 1), lambda i, s: (i, 0),
                                   memory_space=pltpu.VMEM),
            scratch_shapes=scratch,
        ),
        out_shape=jax.ShapeDtypeStruct((NTp, 1), jnp.int32),
        interpret=interpret,
    )(nopen0.reshape(1), compat)[:NT, 0]

    # invert winner-per-ordered-task into per-requester assignment; each
    # requester wins at most once so the scatter is 1-1
    valid = winner >= 0
    assign = jnp.full((NR,), -1, dtype=jnp.int32)
    assign = assign.at[jnp.where(valid, winner, NR)].set(
        jnp.where(valid, order.astype(jnp.int32), -1), mode="drop"
    )
    return assign


def make_pallas_assign(interpret: bool | None = None):
    """Returns a (task_prio, task_type, req_mask, req_valid) -> assign
    callable; interpret defaults to True off-TPU so tests and CPU dev runs
    exercise the same kernel code path."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return functools.partial(pallas_greedy_assign, interpret=interpret)
