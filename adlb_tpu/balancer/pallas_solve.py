"""Pallas TPU kernel for the greedy assignment inner loop.

The batched global solve (see :mod:`adlb_tpu.balancer.solve`) is this
framework's hot op — the TPU-native replacement for the reference's
per-Reserve O(|wq|·16) linear scans (reference ``src/xq.c:190-247``) run
once per balancer round over every server's queue at once.

Kernel design (SURVEY §7 stage 5, "Pallas for the auction inner loop"):

* An XLA pre-pass folds priority ordering, padding, requester validity and
  the type mask into one ``[NT, NRp]`` int32 *compatibility matrix*
  (``compat[k, r] = 1`` iff the k-th task in descending-priority order may
  go to requester ``r``) — pure vectorized gather work XLA fuses well.
* The Pallas kernel then runs the inherently sequential greedy sweep with
  the live state resident in VMEM: a grid over task-row *blocks* (so the
  compatibility matrix streams through VMEM block by block instead of
  having to fit whole — 16k x 2k once hit the 128M VMEM cap exactly), one
  ``fori_loop`` over the block's rows, each step a VPU-width mask/min over
  the open-requester vector, a scalar winner write, and an in-place
  open-vector update.  The open vector lives in persistent VMEM scratch
  across grid steps (TPU grids execute sequentially).  No HBM traffic
  inside the loop, no per-step XLA dispatch — exactly the "keep the inner
  loop on-chip" recipe.
* Winner inversion (task-order → per-requester assignment) is another tiny
  XLA scatter after the kernel.

Semantics are bit-identical to :func:`adlb_tpu.balancer.solve._host_greedy`
(tasks in stable descending-priority order, each taking the lowest-index
open compatible requester), so all three backends — host numpy, jitted XLA
scan, Pallas — are interchangeable and cross-checked in tests.

On non-TPU backends the kernel runs in interpreter mode (tests, CPU dev);
on TPU it compiles with Mosaic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from adlb_tpu.balancer.solve import _NEG

_LANE = 128  # TPU lane width: requester vectors are padded to a multiple
# per-grid-step compat slab budget; Mosaic double-buffers windowed inputs
# and the scoped VMEM budget is 16 MiB (tests shrink this to force
# multi-block sweeps at small shapes)
_SLAB_BYTES = 4 << 20


def _greedy_sweep_kernel(compat_ref, winner_ref, open_scr):
    """Sequential greedy over one block of priority-ordered task rows.

    compat_ref: [B, NRp] int32 (1 = this task may go to this requester)
    winner_ref: [B, 1] int32 out — requester index per task row, -1 = none
    open_scr:   [1, NRp] int32 scratch — 1 while a requester is unmatched;
                persists across the (sequential) task-block grid
    """
    nb = compat_ref.shape[0]
    nrp = compat_ref.shape[1]

    @pl.when(pl.program_id(0) == 0)
    def _init():
        open_scr[:] = jnp.ones((1, nrp), dtype=jnp.int32)

    lane = jax.lax.broadcasted_iota(jnp.int32, (1, nrp), 1)

    def body(t, _):
        row = compat_ref[pl.ds(t, 1), :] * open_scr[:]
        # lowest-index open compatible requester (the host twin's argmax on
        # a bool mask picks the same first-True index)
        idx = jnp.min(jnp.where(row > 0, lane, nrp))
        found = idx < nrp
        winner_ref[pl.ds(t, 1), :] = jnp.where(found, idx, -1).reshape(1, 1)
        open_scr[:] = jnp.where(found & (lane == idx), 0, open_scr[:])
        return 0

    jax.lax.fori_loop(0, nb, body, 0)


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_greedy_assign(
    task_prio: jax.Array,  # [NT] int32, _NEG for padding
    task_type: jax.Array,  # [NT] int32 type index, -1 for padding
    req_mask: jax.Array,  # [NR, T] bool
    req_valid: jax.Array,  # [NR] bool
    interpret: bool = False,
) -> jax.Array:
    """Drop-in twin of :func:`adlb_tpu.balancer.solve._greedy_assign` with
    the sweep as a Pallas kernel. Returns assign[NR] int32 (task index per
    requester, -1 if none)."""
    NT = task_prio.shape[0]
    NR = req_mask.shape[0]
    NRp = _round_up(max(NR, 1), _LANE)
    # task-block size: keep each block's compat slab small (see _SLAB_BYTES)
    block = max(min(NT, _SLAB_BYTES // (4 * NRp)), 8)
    block = min(_round_up(block, 8), _round_up(NT, 8))
    NTp = _round_up(NT, block)

    # XLA pre-pass: stable descending-priority order + compat matrix
    order = jnp.argsort(-task_prio, stable=True)
    s_prio = task_prio[order]
    s_type = task_type[order]
    live = (s_prio > _NEG) & (s_type >= 0)
    compat = (
        live[:, None]
        & req_valid[None, :]
        & req_mask[:, jnp.clip(s_type, 0)].T
    )
    compat = jnp.pad(compat, ((0, NTp - NT), (0, NRp - NR))).astype(jnp.int32)

    winner = pl.pallas_call(
        _greedy_sweep_kernel,
        grid=(NTp // block,),
        out_shape=jax.ShapeDtypeStruct((NTp, 1), jnp.int32),
        in_specs=[
            pl.BlockSpec((block, NRp), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec((block, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((1, NRp), jnp.int32)],
        interpret=interpret,
    )(compat)[:NT, 0]

    # invert winner-per-ordered-task into per-requester assignment; each
    # requester wins at most once so the scatter is 1-1
    valid = winner >= 0
    assign = jnp.full((NR,), -1, dtype=jnp.int32)
    assign = assign.at[jnp.where(valid, winner, NR)].set(
        jnp.where(valid, order.astype(jnp.int32), -1), mode="drop"
    )
    return assign


def make_pallas_assign(interpret: bool | None = None):
    """Returns a (task_prio, task_type, req_mask, req_valid) -> assign
    callable; interpret defaults to True off-TPU so tests and CPU dev runs
    exercise the same kernel code path."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return functools.partial(pallas_greedy_assign, interpret=interpret)
