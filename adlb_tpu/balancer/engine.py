"""Plan engine: one balancer round over queue-state snapshots.

Pure planning — callers transport the results. Used by two hosts:

* the in-server balancer thread (Python servers, ``runtime/server.py``);
* the sidecar process driving the native C++ data plane
  (``balancer/sidecar.py``) — SURVEY §7's language split: C++ for the
  data plane, Python/JAX only for the balancer brain.

A round takes the latest per-server snapshots
``{server_rank: {"tasks": [(seqno, type, prio, len)...],
"reqs": [(rank, rqseqno, types|None)...], "nbytes": int, "consumers": int,
"stamp": float}}`` and returns

* ``matches`` — ``(holder, seqno, req_home, for_rank, rqseqno)`` tuples:
  cross-server task->requester assignments from the batched solve;
* ``migrations`` — ``(src, dest, [seqnos], mig_id)``: fair-share inventory
  moves so each server holds its consumer-weighted share of the global
  pool (the global solve's structural advantage over per-unit stealing
  round trips). ``mig_id`` is the planner's batch id; the transport must
  deliver it with the batch so the destination can acknowledge it in
  later snapshots (``mig_acks``).

Re-planning storms are suppressed by remembering when each requester/task
was last planned: both stay ineligible until a *fresh* snapshot (stamp
newer than the plan) shows them still parked/queued. Plan staleness is
compensated at enactment (holders validate against live state).
"""

from __future__ import annotations

import collections
import time
from typing import Optional

from adlb_tpu.balancer.jobdim import req_job, task_job

# Plan-age samples: for every round that produced output, the age of the
# OLDEST snapshot the plan was computed from (seconds between that
# state's capture and the plan being handed to the transport). This is
# the end-to-end staleness the snapshot->solve->enact pipeline delivers —
# the quantity the reference's design fixes at qmstat_interval x ring
# hops (reference src/adlb.c:165,1705-1757) and this architecture keeps
# event-driven. Module-level so benches can read it across whichever
# engines (in-server threads, sidecar) a world spawned in-process.
_PLAN_AGES: "collections.deque[float]" = collections.deque(maxlen=4096)


def drain_plan_ages() -> list:
    out = list(_PLAN_AGES)
    _PLAN_AGES.clear()
    return out


def round_gap(min_gap: float, matches, migrations) -> float:
    """Inter-round sleep for a balancer loop (in-proc thread AND sidecar):
    rate-limit idle churn at the full gap, but keep plan-bearing rounds
    coming fast (startup fill, end-game drain) — a full-gap sleep after a
    match round adds the whole gap to every handoff's latency for
    nothing; the ledger suppression already prevents re-planning storms."""
    return min_gap * 0.25 if (matches or migrations) else min_gap


class PlanEngine:
    def __init__(
        self,
        types,
        max_tasks: int,
        max_requesters: int,
        backend: str = "auto",
        max_malloc_per_server: float = 0.0,
        use_mesh: bool = False,
        nservers: Optional[int] = None,
        host_threshold_reqs: Optional[int] = None,
        lookahead: Optional[int] = None,
        look_max: Optional[int] = None,
        grow_window: Optional[float] = None,
        inflow_ttl: Optional[float] = None,
        inflow_min_age: Optional[float] = None,
        host_ledger: str = "array",
        auction: str = "device",
        max_jobs: int = 1,
        job_weights: Optional[dict] = None,
        metrics=None,
    ) -> None:
        from adlb_tpu.balancer.solve import AssignmentSolver

        # multi-job planning (balancer/jobdim.py): how many namespaces
        # the solvers/ledger plan (1 = historical job-0-only, exact),
        # and the live fair-share weights the packers fold into the
        # assignment score as priority biases
        self.max_jobs = max(int(max_jobs), 1)
        self.base_types = tuple(types)
        self._job_weights = dict(job_weights) if job_weights else {}

        # optional obs registry (adlb_tpu/obs/metrics.py): round duration,
        # plan age, and pairs/migrations emitted — attached by the
        # in-server balancer thread (and the sidecar, which owns its own)
        self.metrics = metrics
        # last-seen reason totals for the ledger's cadence resyncs and
        # the sharded solver's full shard re-sweeps; diffed per round so
        # /metrics carries monotone labelled counters (ledger_resyncs /
        # solver_resweeps) without the engine owning the source counts
        self._obs_resync: dict[str, int] = {}
        self._obs_resweep: dict[str, int] = {}

        self.solver = None
        if use_mesh:
            # multi-chip: shard the task table over a device mesh
            # (balancer/distributed.py); falls back to the single-device
            # solver on a 1-device host, AND on any accelerator-init
            # failure — engine construction happens before the callers'
            # solver-failure recovery loops, so it must not be able to
            # kill the balancer (tpu mode has no other matching mechanism)
            try:
                import jax

                devs = jax.devices()
                if len(devs) > 1:
                    import numpy as np
                    from jax.sharding import Mesh

                    from adlb_tpu.balancer.distributed import (
                        DistributedAssignmentSolver,
                    )

                    spd = 1
                    if nservers is not None and nservers > len(devs):
                        spd = -(-nservers // len(devs))
                    self.solver = DistributedAssignmentSolver(
                        types=tuple(types),
                        max_tasks_per_server=max_tasks,
                        max_requesters=max_requesters,
                        mesh=Mesh(np.array(devs), axis_names=("s",)),
                        servers_per_device=spd,
                        auction=auction,
                        max_jobs=self.max_jobs,
                        job_weights=self._job_weights,
                    )
            except Exception as e:  # noqa: BLE001 — degrade, don't die
                import sys

                print(
                    f"[adlb balancer] mesh solver unavailable ({e!r}); "
                    f"using the single-device solver",
                    file=sys.stderr,
                )
                self.solver = None
        if self.solver is None:
            kw = {}
            if host_threshold_reqs is not None:
                kw["host_threshold_reqs"] = host_threshold_reqs
            self.solver = AssignmentSolver(
                types=tuple(types),
                max_tasks=max_tasks,
                max_requesters=max_requesters,
                backend=backend,
                max_jobs=self.max_jobs,
                job_weights=self._job_weights,
                **kw,
            )
        self.max_malloc_per_server = max_malloc_per_server
        # per-instance overrides of the pump constants (Config knobs)
        if lookahead is not None:
            self.LOOKAHEAD = lookahead
        if look_max is not None:
            self.LOOK_MAX = look_max
        if grow_window is not None:
            self.LOOK_GROW_WINDOW = grow_window
        if inflow_ttl is not None:
            self.INFLOW_TTL = inflow_ttl
        if inflow_min_age is not None:
            self.INFLOW_MIN_AGE = inflow_min_age
        if self.INFLOW_MIN_AGE > self.INFLOW_TTL:
            raise ValueError("inflow_min_age must be <= inflow_ttl")
        if self.LOOK_MAX < max(1, self.LOOKAHEAD):
            raise ValueError("look_max must be >= max(1, lookahead)")
        # Plan ledgers: when each requester/task was last planned. The
        # HOST TIER keeps these and everything derived from them (the
        # per-round filter, suppression budgets, the cross-feasibility
        # gate, the pump pre-check, the solver's packed inputs) resident
        # in numpy columns (balancer/ledger.py, host_ledger="array",
        # default) so round admission costs O(changed rows); the
        # pure-Python twin ("py") is the retained reference semantics,
        # fuzz-proven identical by tests/test_ledger_parity.py. The
        # dicts below stay the authoritative mark store either way —
        # the array ledger's columns cache them via mutation hooks.
        if host_ledger not in ("array", "py"):
            raise ValueError(f"unknown host_ledger {host_ledger!r}")
        from adlb_tpu.balancer.ledger import ArrayLedger, PyLedger, _Marks

        self._planned_reqs: dict[tuple, float] = {}
        self._planned_tasks: dict[tuple, float] = {}
        if host_ledger == "array":
            led = ArrayLedger(self, tuple(types), max_tasks, max_requesters,
                              max_jobs=self.max_jobs,
                              job_weights=self._job_weights)
            self._planned_reqs = _Marks(led.on_req_mark, led.on_req_mark)
            self._planned_tasks = _Marks(led.on_task_mark, led.on_task_mark)
            self._ledger = led
        else:
            self._ledger = PyLedger(self)
        # rank -> [(plan time, nunits, mig_id, src, frozenset(types))] for
        # migration batches en route there; until those units land they
        # are invisible in the
        # destination's inventory, and without crediting them the planner
        # chains phantom top-ups to a destination that is already being
        # fed. Clearing is EXACT when snapshots carry "mig_acks" (src ->
        # highest batch id received from that source): a credit whose id
        # is acked is visible in that snapshot's inventory, an unacked
        # one is still in flight — no transit-time heuristics needed.
        # Snapshots without the field (older planes) fall back to the
        # stamp/min-age window; the TTL backstop covers lost batches
        # either way.
        self._mig_next = 1  # batch-id counter (monotone per dest follows)
        self._planned_in: dict[int, list] = {}
        # rank -> last time OUR plan touched its ledger view (drives the
        # sharded solver's effective ingest stamps)
        self._rank_planned: dict[int, float] = {}
        # rank -> adaptive per-consumer lookahead window and the time it
        # last triggered a top-up (see LOOKAHEAD)
        self._look: dict[int, float] = {}
        self._look_last: dict[int, float] = {}
        self._last_pump = -1e9
        # rank -> last time a snapshot showed a requester actually parked
        # there (RAW reqs, not the ledger-filtered view) — the measured
        # "workers waited here recently" signal the anticipatory pump is
        # gated on (see _plan_migrations)
        self._last_parked: dict[int, float] = {}

    def set_job_weights(self, job_weights: Optional[dict]) -> bool:
        """Live fair-share update (controller / POST /jobs/<id>): fold
        the new biases into every packer twin — the ledger's resident
        columns (forced full rebuild) and the solver's own dict-path
        bias copy (cache flush where the packed prios embed it).
        Returns True when anything actually changed."""
        weights = dict(job_weights) if job_weights else {}
        if weights == self._job_weights:
            return False
        self._job_weights = weights
        changed = False
        if hasattr(self._ledger, "set_job_bias"):
            changed |= self._ledger.set_job_bias(weights)
        if hasattr(self.solver, "set_job_bias"):
            changed |= self.solver.set_job_bias(weights)
        return changed

    def force_host_path(self) -> None:
        """After a device/backend failure: keep planning on numpy — for the
        mesh solver, by swapping in a single-device host-path solver."""
        if hasattr(self.solver, "host_threshold_reqs"):
            self.solver.host_threshold_reqs = 10**9
        else:
            from adlb_tpu.balancer.solve import AssignmentSolver

            self.solver = AssignmentSolver(
                # BASE types: the replacement re-expands the composite
                # axis itself from (base types, max_jobs)
                types=getattr(self.solver, "base_types", self.solver.types),
                max_tasks=self.solver.K,
                max_requesters=self.solver.R,
                host_threshold_reqs=10**9,
                max_jobs=self.max_jobs,
                job_weights=self._job_weights,
            )

    def _prune_credits(self, snapshots: dict, now: float) -> None:
        """Clear in-flight migration credits that this round's snapshots
        acknowledge (per-source ``mig_acks``), plus the TTL backstop and
        the legacy stamp/min-age fallback for ack-less planes. Runs once
        at the top of every round so BOTH the requester-suppression
        filter and the migration planner see clean credits."""
        if not self._planned_in:
            return
        horizon = now - self.INFLOW_TTL
        young = now - self.INFLOW_MIN_AGE
        for rank in list(self._planned_in):
            snap = snapshots.get(rank)
            if snap is None:
                # rank stopped appearing (ended server): TTL-only pruning
                kept = [e for e in self._planned_in[rank] if e[0] > horizon]
                if kept:
                    self._planned_in[rank] = kept
                else:
                    del self._planned_in[rank]
                continue
            tstamp = snap.get("task_stamp", snap.get("stamp", now))
            acks = snap.get("mig_acks")
            live = []
            for e in self._planned_in[rank]:
                ts, _n, mid, src, _types = e
                if ts <= horizon:
                    continue  # TTL backstop: the batch is lost
                if acks is not None:
                    if mid <= acks.get(src, 0):
                        continue  # landed: visible in this snapshot
                elif not (ts > tstamp or ts > young):
                    continue  # legacy stamp/min-age clearing
                live.append(e)
            if live:
                self._planned_in[rank] = live
            else:
                del self._planned_in[rank]

    def round(self, snapshots: dict, world=None):
        """One planning round; returns (matches, migrations)."""
        if not snapshots:
            return [], []
        now = time.monotonic()
        self._prune_credits(snapshots, now)
        led = self._ledger
        # incremental resident-state sync (array ledger: O(changed rows),
        # keyed on the same stamp/delta_seq/req_seq change keys the
        # sharded solver's ingest fast path uses; py twin: no-op)
        led.sync(snapshots, now)
        # raw-park recency, stamped with the SNAPSHOT's capture time, not
        # now: the master re-reads the same snapshot every round, and a
        # satisfied park must age out, not stay forever "recent". The
        # array ledger feeds the O(changed) rebuild events (a rank's
        # park stamp can only move when its snapshot did); the py twin
        # walks the snapshots like it always has.
        parked = led.parked_updates(now)
        if parked is None:
            parked = (
                (rank, snap.get("stamp", now))
                for rank, snap in snapshots.items() if snap["reqs"]
            )
        for rank, stamp in parked:
            if stamp > self._last_parked.get(rank, -1e9):
                self._last_parked[rank] = stamp
        # suppression budgets: only YOUNG credits (a lost batch must
        # not block per-unit matching for the whole 2 s TTL — it
        # stops suppressing after SUPPRESS_TTL and the solve takes
        # over), and at most as many requesters as there are units
        # in flight (a 1-unit batch must not park a whole pool)
        sup: dict = {}
        for rank, entries in self._planned_in.items():
            fed: set = set()
            budget = 0
            for e in entries:
                if e[0] > now - self.SUPPRESS_TTL:
                    fed |= e[4]
                    budget += e[1]
            if budget > 0 and fed:
                sup[rank] = (fed, budget)
        # requester-side ledger filter first (kept rows are few): rounds
        # run at event rate, so a round that can plan nothing must cost
        # O(changed rows), not O(world). A requester whose home server
        # has a live inflow credit covering a type it wants is suppressed
        # outright: the batch already in flight will match it LOCALLY
        # within milliseconds, and solving it too would both burn a
        # round's CPU and deliver a second unit via the expensive
        # per-unit remote-fetch path (the round-3 native-64-rank
        # regression: ~3.6k double-served matches per run).
        led.filter_reqs(snapshots, sup, now)
        have_reqs = led.have_reqs()
        # The solve's only useful output is CROSS-server pairs: same-server
        # pairs are dropped below (the data plane's immediate local matching
        # already covers them), so a round where no parked requester's
        # wanted type has supply on a *different* server can skip the solve
        # entirely. In saturated compute-bound worlds (nq/tsp/sudoku) nearly
        # every round is such a round — workers park only transiently
        # against local supply — and on a shared core every skipped solve
        # is cycles handed back to the workers. The gate reads RAW task
        # supply (no per-task ledger lookups): in-flight planned tasks can
        # over-admit a solve for one snapshot generation, which the
        # filtered solve input then corrects.
        cross = have_reqs and led.cross_feasible(snapshots)
        # The fair-share pump runs at most once per PUMP_INTERVAL AND
        # only when the cheap pre-check sees a plausible deficit:
        # deficits cannot change faster than batches land, and each pump
        # round walks every snapshot task (O(servers x K) — milliseconds
        # on wide worlds, stolen from the workers on a shared core).
        # Match-bearing rounds (cross demand) are never delayed, but
        # since round 4 they no longer walk the pump unconditionally
        # either — in balanced scarce economies that walk was ~5% of
        # throughput for moves that never shipped.
        pump_due = False
        if now - self._last_pump >= self.PUMP_INTERVAL:
            # array ledger answers from resident aggregate columns; it
            # returns None when not synced with these snapshots (direct
            # unit-test calls) and the Python pre-check runs instead
            imb = led.maybe_imbalanced(self, snapshots)
            pump_due = self._maybe_imbalanced(snapshots) if imb is None \
                else imb
        if not cross and not pump_due:
            return [], []  # nothing plannable: skip the task-ledger walk
        if pump_due:
            self._last_pump = now
        # The solver consumes the ledger's resident arrays directly (the
        # "view": packed kept-requester masks + eligible-task rows, per-
        # server generation counters for the sharded solver's delta
        # ingest) — the legacy per-rank dict of filtered tuple lists is
        # materialized only for pump rounds (the migration planner walks
        # tuples) and for the py twin. Materialization happens BEFORE
        # the plan marks below so the pump sees the same pre-plan
        # filtered view it always did.
        view = led.view() if getattr(self.solver, "SUPPORTS_VIEW", False) \
            else None
        filtered = None
        if view is None or pump_due:
            filtered = self._materialize(snapshots, now)
        if cross:
            pairs = self.solver.solve(
                view if view is not None else filtered, world)
        else:
            pairs = []  # still consider migrations below
        t_planned = time.monotonic()
        matches = []
        planned_away: dict[int, set] = {}
        matched_reqs: set = set()
        for holder, seqno, req_home, for_rank, rqseqno in pairs:
            planned_away.setdefault(holder, set()).add(seqno)
            # local pairs are dropped (the data plane matches them), but
            # their unit already sits in planned_away — the requester is
            # spoken for either way, so withholding must skip it too
            matched_reqs.add((req_home, for_rank, rqseqno))
            if holder == req_home:
                continue
            self._planned_reqs[(req_home, for_rank, rqseqno)] = t_planned
            self._planned_tasks[(holder, seqno)] = t_planned
            self._rank_planned[holder] = t_planned
            self._rank_planned[req_home] = t_planned
            matches.append((holder, seqno, req_home, for_rank, rqseqno))
        migrations = []
        if pump_due:
            migrations = self._plan_migrations(
                snapshots, filtered, planned_away, t_planned, matched_reqs,
                now=now,
            )
        if matches or migrations:
            involved = (
                {h for h, *_ in matches}
                | {m[2] for m in matches}  # req_home: the demand side
                | {mv[0] for mv in migrations}
                | {mv[1] for mv in migrations}  # deficit side
            )
            ages = [
                t_planned - snapshots[r].get("stamp", t_planned)
                for r in involved
                if r in snapshots
            ]
            if ages:
                _PLAN_AGES.append(max(ages))
                if self.metrics is not None:
                    self.metrics.histogram("balancer_plan_age_s").observe(
                        max(ages)
                    )
        for src_rank, dest, _seqnos, _mid in migrations:
            self._rank_planned[src_rank] = t_planned
            self._rank_planned[dest] = t_planned
        if self.metrics is not None:
            dur = time.monotonic() - now
            self.metrics.histogram("balancer_round_s").observe(dur)
            # gauges for live scraping (/metrics): last planning-round
            # wall time, and the sharded solver's last device sweep
            self.metrics.gauge("balancer_round_ms").set(dur * 1e3)
            sweep = getattr(self.solver, "last_sweep_ms", None)
            if sweep is not None:
                self.metrics.gauge("solve_shard_ms").set(sweep)
            if led.is_array:
                # host-tier resident ledger: row count + last
                # incremental-sync cost (USERGUIDE §11 "host tier")
                self.metrics.gauge("ledger_rows").set(led.rows_resident())
                self.metrics.gauge("ledger_patch_us").set(
                    round(led.last_sync_us, 1))
            # O(Δ)-steady-state monitors: full ledger rebuilds and full
            # shard re-sweeps, labelled by why they happened. Emitted as
            # deltas of the source dicts so the counters stay monotone
            # across solver/ledger swaps (force_host_path).
            for fam, src, seen in (
                ("ledger_resyncs",
                 getattr(led, "resync_reasons", None), self._obs_resync),
                ("solver_resweeps",
                 getattr(self.solver, "sweep_reasons", None),
                 self._obs_resweep),
            ):
                if src:
                    for reason, total in src.items():
                        d = total - seen.get(reason, 0)
                        if d > 0:
                            self.metrics.counter(
                                fam, reason=reason).inc(d)
                            seen[reason] = total
            if matches:
                self.metrics.counter("balancer_pairs").inc(len(matches))
            if migrations:
                self.metrics.counter("balancer_migrations").inc(
                    len(migrations)
                )
                self.metrics.counter("balancer_migrated_units").inc(
                    sum(len(mv[2]) for mv in migrations)
                )
        # bound the memory of the plan ledgers (per-key deletes so the
        # array ledger's mark hooks keep its columns coherent)
        if len(self._planned_reqs) > 4096 or len(self._planned_tasks) > 4096:
            cutoff = t_planned - 5.0
            for d in (self._planned_reqs, self._planned_tasks):
                for k in [k for k, v in d.items() if v <= cutoff]:
                    del d[k]
        return matches, migrations

    def _materialize(self, snapshots: dict, now: float) -> dict:
        """The legacy filtered-snapshot dict (exact tuple lists), built
        from the ledger's kept/eligible row state. Task eligibility uses
        the task-side stamp: a reqs-only park snapshot must not
        re-eligibilize in-flight planned tasks. Stamps ride along so the
        sharded solver's tuple-path ingest can skip unchanged servers
        without diffing their lists (the single-device solver ignores
        the extra keys): event task deltas / dead-rank req patches
        mutate the snapshot in place WITHOUT a stamp bump (see
        server._merge_task_delta / _patch_snapshots_for_dead), and OUR
        own plans/migrations change the ledger-filtered view with no
        snapshot at all — the sequence numbers and the ledger stamp
        carry those changes. ledger_stamp is a SEPARATE field (never
        max()ed into the snapshot stamps): stamps are the SENDING
        host's monotonic clock while the ledger stamp is the planner's —
        ordering across the two domains is meaningless, and the solver
        only ever compares the key tuple for (in)equality."""
        led = self._ledger
        filtered = {}
        for rank, snap in snapshots.items():
            filtered[rank] = {
                "tasks": led.elig_tasks(rank),
                "reqs": led.kept_reqs(rank),
                "task_stamp": snap.get("task_stamp", snap.get("stamp", now)),
                "stamp": snap.get("stamp", now),
                "delta_seq": snap.get("delta_seq", 0),
                "req_seq": snap.get("req_seq", 0),
                "ledger_stamp": self._rank_planned.get(rank, -1.0),
            }
        return filtered

    def _cross_feasible(self, freqs: dict, snapshots: dict) -> bool:
        """True if some parked requester could be served from another
        server's inventory (the only matches the solve can contribute).
        Demand first (reqs are few), then scan tasks with an early exit —
        a round that can plan nothing must stay cheap even when queues
        are deep."""
        if self.max_jobs <= 1:
            demand: dict[int, set] = {}  # work type -> demander homes
            any_dem: set = set()  # homes of any-type requesters
            for r, reqs in freqs.items():
                for req in reqs:
                    if req[2] is None:
                        any_dem.add(r)
                    else:
                        for t in req[2]:
                            demand.setdefault(t, set()).add(r)
            if not demand and not any_dem:
                return False
            for rank, snap in snapshots.items():
                for t in snap["tasks"]:
                    dem = demand.get(t[1])
                    if dem and (len(dem) > 1 or rank not in dem):
                        return True
                    if any_dem and (
                        len(any_dem) > 1 or rank not in any_dem
                    ):
                        return True
            return False
        # Multi-job worlds: demand is keyed (job, type) — a requester
        # only ever matches units of its own namespace, so an any-type
        # req expands over its OWN job's base types, not everyone's.
        # Overflow jobs (id >= max_jobs) plan via the qmstat fallback,
        # never the solve: skip them on both sides.
        J = self.max_jobs
        jdemand: dict[tuple, set] = {}  # (job, type) -> demander homes
        for r, reqs in freqs.items():
            for req in reqs:
                jb = req_job(req)
                if not 0 <= jb < J:
                    continue
                types = self.base_types if req[2] is None else req[2]
                for t in types:
                    jdemand.setdefault((jb, t), set()).add(r)
        if not jdemand:
            return False
        for rank, snap in snapshots.items():
            for t in snap["tasks"]:
                dem = jdemand.get((task_job(t), t[1]))
                if dem and (len(dem) > 1 or rank not in dem):
                    return True
        return False

    # Per-consumer lookahead window: a server already holding this many
    # ready units per local consumer is never migration-deficient, no
    # matter how far below its proportional share it sits. Without the
    # cap, abundant-but-uneven pools (saturated compute-bound worlds whose
    # untargeted puts round-robin roughly evenly) churn a steady stream of
    # proportional-rebalance moves — each one transfer messages plus a
    # briefly unavailable unit — that no consumer ever needed. Starved
    # servers (hotspot's empty ones) sit far below the window and still
    # trigger immediately.
    #
    # The window is ADAPTIVE per destination: units are a poor proxy for
    # time (a fine-grained workload drains 8 units in a millisecond), so a
    # destination that re-triggers its deficit shortly after the last
    # top-up has its window doubled — transfer batches grow until one
    # batch covers the drain rate times the re-plan round trip (batches
    # are O(1) messages regardless of size, so bigger batches amortize) —
    # and a destination that stays quiet decays back toward the floor.
    LOOKAHEAD = 8
    LOOK_MAX = 512  # per consumer
    LOOK_GROW_WINDOW = 0.25  # s: re-trigger sooner than this -> double
    # Credits for in-flight migration batches expire after this long even
    # if the destination never ships a fresh task snapshot (idle empty
    # servers suppress repeat empty snapshots, and an enactment may drop
    # the batch entirely) — a lost batch must delay re-supply, not
    # suppress it forever.
    INFLOW_TTL = 2.0
    # ... and survive at least this long regardless of snapshot stamps: a
    # destination's snapshot captured after the plan but before the batch
    # LANDS must not wipe the credit (that would re-create the phantom
    # top-up chain for destinations that snapshot faster than batch
    # transit).
    INFLOW_MIN_AGE = 0.05
    # minimum spacing of fair-share pump rounds (see round()); 3 ms
    # (round 4, down from 10): mid-run drain imbalances parked whole
    # worker pools for the old interval at a time. The expensive
    # O(tasks) pump walk is additionally gated on the cheap
    # _maybe_imbalanced pre-check in EVERY round (round 4: previously
    # match-bearing rounds walked unconditionally, which taxed
    # balanced scarce economies ~5% — an adaptive 3/10 ms backoff was
    # tried instead and reverted: storms are bursts, so the first
    # response to each fresh imbalance paid the idle interval again).
    PUMP_INTERVAL = 0.003
    # in-flight credits older than this stop suppressing the solve for
    # their destination's requesters (the batch is probably lost; the TTL
    # keeps it counted as pump inflow a while longer, but workers must
    # not stay unmatchable for the full TTL)
    SUPPRESS_TTL = 0.25
    # supply counts as CONCENTRATED (enabling the starved full-share
    # bypass) when one server holds more than this fraction of the
    # available pool; hotspot's single-source backlog holds ~everything,
    # while balanced economies' transient bursts rarely clear it
    CONC_FRAC = 0.5
    # WINDOW GROWTH is gated on MEASURED recent waiting: a destination
    # earns transfer-batch growth only if some requester actually parked
    # there within this window (or is parked right now). Hotspot's
    # destinations park hard (startup, between-batch dips) and keep
    # earning scale; a destination that never waits decays to the floor,
    # bounding the batch sizes the pump can shuffle in balanced
    # economies. NOTE: gating the top-ups THEMSELVES on this signal was
    # measured and reverted (see _plan_migrations) — pre-positioning
    # ahead of demand is exactly what long steady-state sinks need.
    PARK_RECENT = 0.5

    def _window(self, rank: int) -> float:
        return self._look.get(rank, float(self.LOOKAHEAD))

    def _need(self, share: int, consumers: int, rank: int) -> int:
        return min(share, int(self._window(rank)) * consumers)

    def _touch_window(self, rank: int, now: float,
                      grow_ok: bool = True) -> None:
        """Called when `rank` triggered a top-up: grow on quick re-trigger,
        decay otherwise. Growth requires ``grow_ok`` — a destination
        whose workers were actually PARKED when fed (they outpace their
        supply; bigger batches pay). Feeding a busy server that merely
        dipped below the band (sudoku's bursty-but-balanced DFS pools)
        must not inflate the window: each doubling there just moves more
        units nobody is waiting for, and the churn compounds."""
        look = self._window(rank)
        if grow_ok and now - self._look_last.get(rank, -1e9) \
                < self.LOOK_GROW_WINDOW:
            self._look[rank] = min(look * 2.0, float(self.LOOK_MAX))
        else:
            # slow re-trigger OR nobody parked: decay toward the floor.
            # A gated quick re-trigger must decay too — otherwise a
            # window inflated during a parked phase would stay pinned at
            # the inflated batch size for as long as the destination
            # keeps dipping below the band
            self._look[rank] = max(float(self.LOOKAHEAD), look / 2.0)
        self._look_last[rank] = now

    def _maybe_imbalanced(self, snaps: dict) -> bool:
        """Cheap pre-check (raw snapshot counts; the ledger is consulted
        only for the handful of req-parked ranks in the scarce branch) for
        whether fair-share migration planning could possibly trigger; the
        exact check re-runs on filtered inventory. Errs a round late on
        ledger-heavy edges, which the next fresh snapshot corrects."""
        consumers = {
            r: snaps[r].get("consumers", 0) for r in snaps
        }
        total_c = sum(consumers.values())
        if total_c == 0:
            return False
        raw = {r: len(snaps[r]["tasks"]) for r in snaps}
        total = sum(raw.values())
        if total < total_c:
            # scarcity: matches handle it (see below) — unless the
            # scarce supply is one server's opening burst and starved
            # parked destinations are waiting on it
            if total == 0 or max(raw.values()) <= self.CONC_FRAC * total:
                return False
            return any(
                c > 0
                and snaps[r].get("reqs")
                and (raw[r] == 0 or self._only_planned_away(r, snaps[r]))
                for r, c in consumers.items()
            )
        return any(
            c > 0
            and 2 * raw[r] < self._need(-(-total * c // total_c), c, r)
            for r, c in consumers.items()
        )

    def _only_planned_away(self, rank: int, snap: dict) -> bool:
        """True when every unit a stale snapshot still lists for ``rank``
        is already spoken for by the plan ledger (matched or migrating
        away). Such a rank is starved NOW even though its raw count is
        nonzero — without this the startup-fill pump stays gated a whole
        snapshot generation after its opening burst is planned out, which
        is exactly the stall class the round-4 fix targeted. Cost is a
        dict lookup per listed unit and only runs for req-parked ranks in
        the scarce branch (few, by construction)."""
        tasks = snap["tasks"]
        if not tasks:
            return True
        tstamp = snap.get("task_stamp", snap.get("stamp", 0.0))
        return all(
            self._planned_tasks.get((rank, t[0]), -1.0) >= tstamp
            for t in tasks
        )

    def _plan_migrations(
        self, snaps: dict, filtered: dict, planned_away: dict,
        t_planned: float, matched_reqs: Optional[set] = None,
        now: Optional[float] = None,
    ):
        """Fair-share inventory placement (see module docstring)."""
        inv: dict[int, list] = {}
        consumers: dict[int, int] = {}
        inflow: dict[int, int] = {}
        for rank, f in filtered.items():
            avail = [
                t for t in f["tasks"] if t[0] not in planned_away.get(rank, ())
            ]
            if f["reqs"] and avail:
                # Withhold one locally-matchable unit per parked requester:
                # the data plane's local matching hands these over with no
                # cross-server traffic, and when the solve was gated off
                # (supply local-only) nothing else protects them from
                # being migrated out from under their local demander.
                # Requesters the solve just matched cross-server are
                # skipped — they are already consumed by the match, and
                # withholding a second unit for them double-reserves
                # supply against migration sources.
                withheld: set = set()
                for req in f["reqs"]:
                    if matched_reqs and (rank, req[0], req[1]) in matched_reqs:
                        continue
                    types = req[2]
                    rj = req_job(req)
                    for t in avail:
                        if (
                            t[0] not in withheld
                            and task_job(t) == rj
                            and (types is None or t[1] in types)
                        ):
                            withheld.add(t[0])
                            break
                if withheld:
                    avail = [t for t in avail if t[0] not in withheld]
            inv[rank] = avail
            consumers[rank] = snaps.get(rank, {}).get("consumers", 0)
            # credits were pruned at the top of the round (_prune_credits):
            # what remains is in flight
            inflow[rank] = sum(
                e[1] for e in self._planned_in.get(rank, ())
            )
        total_consumers = sum(consumers.values())
        if total_consumers == 0:
            return []
        total_avail = sum(len(v) for v in inv.values())
        # Anticipatory placement only pays when there is a real backlog to
        # pre-position (hotspot's bulk). When work is scarcer than one unit
        # per consumer, the demand-driven match path moves individual units
        # more directly than a migrate round-trip — and scarce pools are
        # exactly where migrate churn (a unit bouncing between servers,
        # briefly unavailable each hop) hurts most (gfmc's shallow
        # answer-economy queues). EXCEPT when the scarce supply is
        # CONCENTRATED on one server (a producer's opening burst): then
        # every match is a per-unit fetch against the one hot reactor
        # that is also absorbing the put stream, and distributing what
        # little is visible starts workers on LOCAL fetches immediately
        # (the round-4 startup-fill fix). Scarce+concentrated admits only
        # the starved path below — anticipatory top-ups stay off.
        scarce = total_avail < total_consumers
        concentrated = (
            max((len(lst) for lst in inv.values()), default=0)
            > self.CONC_FRAC * total_avail
        )
        if scarce and not concentrated:
            return []

        def share(r: int) -> int:
            # ceil of the consumer-weighted share, so rounding never
            # strands a destination at zero
            c = consumers.get(r, 0)
            return -(-total_avail * c // total_consumers) if c else 0

        # Hysteresis: only treat a server as deficient when it holds less
        # than HALF its demand-capped need (see LOOKAHEAD). Without the
        # band, servers hovering near the threshold trigger a constant
        # shuffle of inventory moves for no placement benefit.
        #
        # STARVED destinations (nothing on hand, nothing in flight, a
        # requester actually parked there, AND supply CONCENTRATED on one
        # server — the hotspot shape this balancer exists for) bypass
        # both the band and the window cap: the cap exists to stop churn
        # on servers NEAR their share, and an empty server with waiting
        # workers facing a one-server backlog is not that. Ramping the
        # adaptive window from its floor would trickle window-sized
        # refills (a fraction of fair share) while whole worker pools sit
        # idle a re-plan round trip at a time; one full-share batch is
        # the same O(1) messages and seeds the window at the proven
        # drain scale. The guards keep balanced economies on the capped
        # path: transiently-empty servers whose workers are mid-compute
        # (tsp's fluctuating B&B frontier) fail the parked-requester
        # condition (RAW reqs, not the ledger-filtered view), and evenly
        # spread pools (gfmc's round-robin inventory) fail the
        # concentration test — full-share moves there are churn nobody
        # is waiting for. (``concentrated`` is computed alongside the
        # scarcity gate above.)
        starved: set = set()
        deficits: dict[int, int] = {}
        # recentness is judged at snapshot-READ time (round start), not
        # t_planned: a slow solve (first compile) between the two must
        # not age otherwise-fresh parks out of the window. A requester
        # VISIBLE parked in the current snapshot counts as recent no
        # matter the stamp age: servers suppress repeat-identical
        # snapshots, so a continuously-parked destination's stamp goes
        # stale precisely because nothing changed — aging it out of the
        # window would starve the most-waiting destinations (observed:
        # native 64-rank wait%% doubled before this clause).
        t_ref = now if now is not None else t_planned
        recent: dict[int, bool] = {
            r: (
                # LEDGER-FILTERED reqs, not raw: a requester the solve
                # already satisfied (still listed in a stale/suppressed
                # snapshot) must not keep earning growth
                bool(filtered.get(r, {}).get("reqs"))
                or t_ref - self._last_parked.get(r, -1e9) <= self.PARK_RECENT
            )
            for r in consumers
        }
        for r, c in consumers.items():
            if c <= 0:
                continue
            have = len(inv[r]) + inflow.get(r, 0)
            sh = share(r)
            if (
                have == 0 and sh > 0 and concentrated
                and snaps.get(r, {}).get("reqs")
            ):
                starved.add(r)
                deficits[r] = sh
            elif not scarce:
                # anticipatory placement (scarce+concentrated admits only
                # the starved path above). Round 4 MEASURED a stronger
                # gate here — feed only destinations whose workers parked
                # within PARK_RECENT (VERDICT item 6) — and reverted it:
                # native 64-rank acquisition wait DOUBLED (10.5% -> 22%,
                # long steady-state runs cycle busy->dry->park instead of
                # being smoothly pre-positioned), while sudoku did not
                # improve (disabling anticipatory feeding there measures
                # 7443 -> 6377 tasks/s — the pump HELPS sudoku; its
                # residual mode gap is fixed per-message/per-round cost,
                # see BASELINE.md). The recent-parked signal still gates
                # WINDOW GROWTH below, which is where the churn bound
                # belongs.
                need = self._need(sh, c, r)
                if 2 * have < need:
                    deficits[r] = need - have
        if not deficits:
            return []
        surpluses = {
            r: lst[share(r):]
            for r, lst in inv.items()
            if len(lst) > share(r)
        }
        cap = self.max_malloc_per_server
        moves: dict[tuple[int, int], list] = {}  # (src,dest)->[(seqno,type)]
        for dest, want in sorted(deficits.items(), key=lambda kv: -kv[1]):
            dest_bytes = snaps.get(dest, {}).get("nbytes", 0)
            for src_rank, lst in surpluses.items():
                if want <= 0:
                    break
                if src_rank == dest or not lst:
                    continue
                take = []
                for t in lst:
                    if len(take) >= want:
                        break
                    if cap > 0 and dest_bytes + t[3] > 0.9 * cap:
                        break  # planner-side admission: dest believed full
                    take.append(t)
                    dest_bytes += t[3]
                if take:
                    surpluses[src_rank] = lst = lst[len(take):]
                    moves.setdefault((src_rank, dest), []).extend(
                        (t[0], t[1]) for t in take
                    )
                    want -= len(take)
        out = []
        got: dict[int, int] = {}
        for (src_rank, dest), seqnos_types in moves.items():
            seqnos = [q for q, _ in seqnos_types]
            mid = self._mig_next
            self._mig_next += 1
            for q in seqnos:
                self._planned_tasks[(src_rank, q)] = t_planned
            self._planned_in.setdefault(dest, []).append(
                (t_planned, len(seqnos), mid, src_rank,
                 frozenset(wt for _, wt in seqnos_types))
            )
            got[dest] = got.get(dest, 0) + len(seqnos)
            out.append((src_rank, dest, seqnos, mid))
        # adapt windows only for destinations that were actually SHIPPED a
        # batch: a deficit no surplus could serve must not inflate the
        # window (it would silently disable the cap when supply returns)
        for dest, n_got in got.items():
            if dest in starved:
                # seed the window at the shipped scale so follow-up
                # top-ups continue at fair-share size instead of
                # re-ramping from the floor
                c = consumers.get(dest, 0) or 1
                self._look[dest] = min(
                    max(self._window(dest), n_got / c),
                    float(self.LOOK_MAX),
                )
                self._look_last[dest] = t_planned
            else:
                # growth keyed on RECENT parking, not currently-parked:
                # a well-timed anticipatory top-up prevents the park it
                # exists to prevent, which under the old
                # currently-parked test made success decay the window
                # (smaller batches -> more dips). A destination whose
                # workers waited within PARK_RECENT keeps earning
                # growth; one that never waits decays to the floor and
                # (per the deficit gate above) stops being fed at all.
                self._touch_window(dest, t_planned, grow_ok=recent[dest])
        return out
