"""Distributed (multi-chip) assignment solve — the production path.

SPMD decomposition of :mod:`adlb_tpu.balancer.solve` over a
``jax.sharding.Mesh``: the task table — the big axis, scaling with servers x
queue depth — lives device-resident, sharded by server over mesh axis
``"s"`` (``NamedSharding``), and is updated *incrementally* from per-server
snapshot deltas (only changed rows ship; unchanged servers are skipped by
a stamp fast path). Each planning round is three fixed-shape steps:

1. **sharded candidate generation** (on the mesh) — every device presorts
   its task shard by (type, priority desc, gid) — three composed stable
   single-key sorts; the multi-key comparator sort is ~10x slower on CPU
   backends — and slices each type's top-D candidates, D = C + m + 1.
   This is the only work that scales with table size, which is exactly
   what the mesh parallelizes; it never retraces (fixed [S, K] shapes).
2. **cross-shard merge** of the [ndev, T, 2D] per-device winner tuples
   into global per-type candidate lists ordered by (prio desc, gid asc)
   — two composed stable single-key sorts (gid, then prio): the elastic
   slot map decouples device row order from rank order, so gid-ascending
   is restored explicitly before the priority sort.
3. **auction rounds** — pure head-pointer logic over the merged per-type
   candidate lists and the [T, C] requester-slot tables (O(plan size)):
   rank-k candidate pairs with the k-th open accepting requester,
   cross-type conflicts resolve by (prio, -gid), a global threshold
   defers any winner that a displaced higher-priority task could cascade
   into, and prefix commits keep every shard's consumed tasks a prefix
   of its sorted type segment (which is what makes step 1's head slices
   exact).

The solver runs one of two tiers over those steps:

- ``auction="device"`` (default): all three steps fuse into ONE jitted
  ``shard_map`` program (:func:`_build_plan_fn`) — candidate generation
  per shard, a ``lax.all_gather`` over the ``"s"`` axis, the replicated
  merge, and the auction as a fixed-shape ``lax.while_loop`` over
  host-compacted requester ids (U = T*C distinct ids at most, so the
  per-round scatters never touch O(requesters) state). A planning round
  is one device dispatch plus one [T, C+1] commit-table readback — no
  per-round host merge of the [ndev, T, 2D] gather, no O(S) host work.
- ``auction="host"``: the PR 7 twin, retained verbatim — steps 2-3 on
  the planner host (numpy), with the merged candidate lists cached and
  patched in place between device sweeps. The twin is what the device
  tier is fuzz-checked against (exact same commits from the same state).

Task ids are **rank-keyed**: gid = rank * K + ki (``row_rank`` maps the
resident row to its server rank; int32 on device, so rank * K must stay
under 2**31 — enforced at registration). Because the greedy tie-break is
the gid order itself, slot assignment is free-listed: an elastic join or
leave (PR 15 epoch bump) patches exactly one row and never remaps the
world — no full mesh re-sweep on churn.

The auction reproduces the exact sequential greedy matching of
:func:`adlb_tpu.balancer.solve._host_greedy` — same matched requester
set, same committed task multiset, same total score (fuzz-verified at
mesh sizes 1/2/8 by ``tests/test_sharded_parity.py``) — truncation
aside: at most ``C`` requesters per type are visible per round and
``m`` commits per type can land per auction round, and leftovers are
re-planned by the next balancer tick (the protocol's standing staleness
contract: plan entries are hints validated at enactment).

This replaces the reference's qmstat ring gossip (reference
``src/adlb.c:806-822,1705-1757``): instead of an O(0.1 s) staleness
window on an approximate load vector, the whole queue state is solved
every round, and scale comes from adding devices along ``"s"``.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from adlb_tpu.balancer.jobdim import bias_vector, expand_types
from adlb_tpu.balancer.solve import (
    _I32MAX, _NEG, _PRIO_CLIP, _stable_argsort3)


def _shard_candidates(tp, tt, rk, T: int, D: int):
    """Per-shard candidate generation (traced inside shard_map): presort
    the local [Sl, K] task block by (type, prio desc, gid) and slice each
    type's top-D window. gid = rank * K + ki — rank-keyed, NOT row-keyed,
    so the candidate identity (and hence the greedy tie-break) survives
    elastic slot reuse. Returns (cand_prio, cand_gid) [T, D]."""
    Sl, K = tp.shape
    Kl = Sl * K
    tp, tt = tp.reshape(-1), tt.reshape(-1)
    gids = (rk[:, None].astype(jnp.int32) * K
            + jnp.arange(K, dtype=jnp.int32)[None, :]).reshape(-1)
    live = (tp > _NEG) & (tt >= 0)
    prio = jnp.clip(tp, -_PRIO_CLIP, _PRIO_CLIP)
    sort_t = jnp.where(live, tt, T).astype(jnp.int32)
    order = _stable_argsort3(sort_t, -prio, gids)
    s_prio = prio[order]
    s_gid = gids[order]
    scount = jnp.zeros((T + 1,), jnp.int32).at[sort_t].add(
        1, mode="drop")
    seg_off = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(scount[:T])])
    idx = seg_off[:T, None] + jnp.arange(D, dtype=jnp.int32)[None, :]
    ok = idx < seg_off[1:, None]
    idc = jnp.clip(idx, 0, Kl - 1)
    cp = jnp.where(ok, s_prio[idc], _NEG)
    cg = jnp.where(ok, s_gid[idc], _I32MAX)
    return cp, cg


def _build_gather_fn(mesh: Mesh, T: int, D: int, axis: str = "s"):
    """Sharded candidate generation: fn(task_prio [S,K], task_type [S,K],
    row_rank [S]) -> (cand_prio, cand_gid) [ndev, T, D] — each device's
    per-type top-D (prio desc, gid asc) candidates, gid = rank * K + ki.
    This is the device leg of the ``auction="host"`` twin tier."""

    def shard_fn(tp, tt, rk):
        cp, cg = _shard_candidates(tp, tt, rk, T, D)
        return cp[None], cg[None]

    fn = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(axis)),
        out_specs=(P(axis, None, None), P(axis, None, None)),
        check_rep=False,
    )
    return jax.jit(fn)


def _build_plan_fn(mesh: Mesh, T: int, D: int, C: int, rounds: int,
                   m: int, axis: str = "s"):
    """The fully on-device planning round: ONE jitted shard_map program
    fusing candidate generation, the cross-shard merge, and the auction.

    fn(task_prio [S,K], task_type [S,K], row_rank [S],  -- mesh-sharded
       reqwin_c [T,C], lens [T], open0 [U+1])           -- replicated
    -> assigned [ndev, T, C+1] of committed gids (-1 = none; column C is
    the scatter dump for non-commits). Every shard computes the same
    replicated answer after the all_gather; the caller reads shard 0.

    ``reqwin_c`` is the requester slot table over HOST-COMPACTED ids
    (np.unique of the reqwin row ids; U = T*C is the static id-space
    bound and doubles as the dump id), so the per-round winner/open
    scatters touch [U+1] arrays — a few KB — never O(requesters) state.
    ``open0[u]`` is True for every real compacted id, False at the dump.

    The auction body is the exact device transcription of
    :func:`_host_auction` — same head slices, same (prio, -gid) conflict
    winner (two int32 scatter passes: max prio per requester, then min
    gid among prio-ties), same global commit threshold including each
    type's truncation sentinel, same prefix commits (a loss blocks every
    later rank via an exclusive cumsum — keys descend in rank, so the
    host's sequential break is exactly this mask), same zero-commit
    early exit (the while_loop condition). Fuzz-pinned against the host
    twin by tests/test_device_auction.py and tests/test_sharded_parity.py."""
    ndev = mesh.devices.size
    L = ndev * D
    U = T * C

    def shard_fn(tp, tt, rk, rwc, lens, open0):
        cp, cg = _shard_candidates(tp, tt, rk, T, D)
        # cross-shard merge, replicated on every device: restore gid
        # order, then stable-sort by prio desc (ties keep gid asc)
        ap = jax.lax.all_gather(cp, axis)  # [ndev, T, D]
        ag = jax.lax.all_gather(cg, axis)
        ap = ap.transpose(1, 0, 2).reshape(T, L)
        ag = ag.transpose(1, 0, 2).reshape(T, L)
        o = jnp.argsort(ag, axis=1, stable=True)
        ap = jnp.take_along_axis(ap, o, axis=1)
        ag = jnp.take_along_axis(ag, o, axis=1)
        o = jnp.argsort(-ap, axis=1, stable=True)
        gp = jnp.take_along_axis(ap, o, axis=1)
        gg = jnp.take_along_axis(ag, o, axis=1)
        # ---- auction rounds (fixed shapes; replicated) ----
        nlive = (gp > _NEG).sum(axis=1).astype(jnp.int32)
        slot_valid = jnp.arange(C, dtype=jnp.int32)[None, :] < lens[:, None]
        trange = jnp.arange(T, dtype=jnp.int32)
        rows_c = jnp.broadcast_to(trange[:, None], (T, C))
        cols_c = jnp.broadcast_to(
            jnp.arange(C, dtype=jnp.int32)[None, :], (T, C))
        rows_m = jnp.broadcast_to(trange[:, None], (T, m))
        arange_m1 = jnp.arange(m + 1, dtype=jnp.int32)

        def body(state):
            head, open_, assigned, rnd, _last = state
            # next m+1 untaken candidates per type (head slice)
            cidx = head[:, None] + arange_m1[None, :]
            okc = cidx < nlive[:, None]
            cl = jnp.minimum(cidx, L - 1)
            mp_full = jnp.where(okc, gp[trange[:, None], cl], _NEG)
            mg_full = jnp.where(okc, gg[trange[:, None], cl], _I32MAX)
            mp, mg = mp_full[:, :m], mg_full[:, :m]
            trunc_p, trunc_g = mp_full[:, m], mg_full[:, m]
            # first m open slots per type: scatter-min each open slot's
            # column at its open-rank (ranks >= m and closed slots fall
            # off the [T, m] table via mode="drop")
            slot_open = slot_valid & open_[rwc]
            sr = jnp.cumsum(slot_open, axis=1)
            nopen = sr[:, -1]
            jrank = jnp.where(slot_open, sr - 1, m).astype(jnp.int32)
            pair_slot = jnp.full((T, m), C, jnp.int32).at[
                rows_c, jrank].min(cols_c, mode="drop")
            valid = (mp > _NEG) & (pair_slot < C)
            psc = jnp.clip(pair_slot, 0, C - 1)
            rid = jnp.where(valid, rwc[trange[:, None], psc], U)
            # cross-type conflicts: winner per requester by (prio, -gid)
            bp = jnp.full((U + 1,), _NEG, jnp.int32).at[rid].max(
                jnp.where(valid, mp, _NEG))
            is_pmax = valid & (mp == bp[rid])
            bg = jnp.full((U + 1,), _I32MAX, jnp.int32).at[rid].min(
                jnp.where(is_pmax, mg, _I32MAX))
            win = is_pmax & (mg == bg[rid])
            lose = valid & ~win
            # global commit threshold: best key among losers and each
            # type's truncation sentinel (only while it has an open
            # slot); lexicographic max as (max prio, min gid among ties)
            sent = (nopen > 0) & (trunc_p > _NEG)
            lp = jnp.maximum(
                jnp.max(jnp.where(lose, mp, _NEG)),
                jnp.max(jnp.where(sent, trunc_p, _NEG)))
            lg = jnp.minimum(
                jnp.min(jnp.where(lose & (mp == lp), mg, _I32MAX)),
                jnp.min(jnp.where(sent & (trunc_p == lp), trunc_g,
                                  _I32MAX)))
            keygt = (mp > lp) | ((mp == lp) & (mg < lg))
            lose_before = (jnp.cumsum(lose, axis=1) - lose) > 0
            commit = win & keygt & ~lose_before
            assigned = assigned.at[
                rows_m, jnp.where(commit, psc, C)].max(
                jnp.where(commit, mg, -1))
            open_ = open_.at[jnp.where(commit, rid, U)].set(False)
            head = head + commit.sum(axis=1).astype(jnp.int32)
            return (head, open_, assigned, rnd + 1,
                    commit.sum().astype(jnp.int32))

        def cond(state):
            return (state[3] < rounds) & (state[4] > 0)

        init = (
            jnp.zeros((T,), jnp.int32),
            open0,
            jnp.full((T, C + 1), -1, jnp.int32),
            jnp.asarray(0, jnp.int32),
            jnp.asarray(1, jnp.int32),
        )
        assigned = jax.lax.while_loop(cond, body, init)[2]
        return assigned[None]

    fn = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(axis),
                  P(None, None), P(None), P(None)),
        out_specs=P(axis, None, None),
        check_rep=False,
    )
    return jax.jit(fn)


def _reqwin(req_mask, req_valid, T: int, C: int, perm=None):
    """Requester slot tables: ``reqwin [T, C]`` — the first C valid
    requester row ids accepting each type, in scan order (the greedy
    "first open compatible requester" order) — plus per-type lengths.

    ``perm`` (a full row permutation) sets the scan order; the stateful
    solver passes its rank-sorted row order so the windows match the
    single-device packer's sorted-rank rows exactly even though the
    elastic slot map free-lists physical rows. The WINDOW ENTRIES stay
    physical row ids (extraction indexes the resident refs).

    Chunked early-exit scan: with deep requester tables (1M parked)
    the window is filled from the first few thousand rows, so the
    common-case cost is O(chunk * T), not O(NR * T)."""
    NR = req_valid.shape[0]
    reqwin = np.full((T, C), -1, dtype=np.int32)
    lens = np.zeros((T,), dtype=np.int32)
    CHUNK = 16384
    for a in range(0, NR, CHUNK):
        b = min(a + CHUNK, NR)
        if perm is None:
            vm = req_mask[a:b] & req_valid[a:b, None]  # [chunk, T]
        else:
            rows = perm[a:b]
            vm = req_mask[rows] & req_valid[rows][:, None]
        done = True
        for t in range(T):
            n = int(lens[t])
            if n >= C:
                continue
            idx = np.flatnonzero(vm[:, t])[: C - n]
            if idx.size:
                reqwin[t, n: n + idx.size] = (
                    idx + a if perm is None else rows[idx])
                lens[t] = n + idx.size
            if lens[t] < C:
                done = False
        if done:
            break
    return reqwin, lens


def _host_auction(gp, gg, reqwin, lens, req_open, rounds: int, m: int):
    """The auction rounds (numpy, O(plan size) per round).

    gp/gg: [T, L] merged candidate (prio, gid) lists, prio desc / gid
    asc, _NEG-padded. reqwin/lens: slot tables from :func:`_reqwin`.
    req_open: bool over requester rows, mutated in place. Returns
    ``assigned [T, C]`` of committed gids (-1 = none).

    Exits early the first round that commits nothing: the globally best
    candidate with an open accepting slot always commits (it wins any
    conflict and tops any threshold), so a zero-commit round proves the
    matching is maximal."""
    T, L = gp.shape
    C = reqwin.shape[1]
    head = np.zeros((T,), dtype=np.int64)
    nlive = (gp > _NEG).sum(axis=1)
    slot_valid = np.arange(C)[None, :] < lens[:, None]
    assigned = np.full((T, C), -1, dtype=np.int64)
    arange_m1 = np.arange(m + 1)
    trange = np.arange(T)
    for _ in range(rounds):
        # next m+1 untaken candidates per type (head slice)
        cidx = head[:, None] + arange_m1[None, :]
        okc = cidx < nlive[:, None]
        cl = np.minimum(cidx, L - 1)
        mp_full = np.where(okc, gp[trange[:, None], cl], int(_NEG))
        mg_full = np.where(okc, gg[trange[:, None], cl], _I32MAX)
        mp, mg = mp_full[:, :m], mg_full[:, :m]
        trunc_p, trunc_g = mp_full[:, m], mg_full[:, m]
        # first m open slots per type
        open_ = slot_valid & req_open[np.clip(reqwin, 0, None)]
        sr = np.cumsum(open_, axis=1)
        nopen = sr[:, -1] if C else np.zeros((T,), np.int64)
        # pair_slot[t, j] = index of the (j+1)-th open slot (C = none)
        pair_slot = np.full((T, m), C, dtype=np.int64)
        for t in range(T):
            if nopen[t]:
                k = int(min(nopen[t], m))
                pair_slot[t, :k] = np.flatnonzero(open_[t])[:k]
        valid = (mp > int(_NEG)) & (pair_slot < C)
        rid = np.where(
            valid, reqwin[trange[:, None], np.clip(pair_slot, 0, C - 1)],
            -1)
        # cross-type conflicts: winner per requester by (prio, -gid)
        win = np.zeros((T, m), dtype=bool)
        best: dict = {}
        vt, vj = np.nonzero(valid)
        for t, j in zip(vt.tolist(), vj.tolist()):
            key = (int(mp[t, j]), -int(mg[t, j]))
            r = int(rid[t, j])
            if r not in best or key > best[r][0]:
                best[r] = (key, t, j)
        for r, (_k, t, j) in best.items():
            win[t, j] = True
        win &= valid
        lose = valid & ~win
        # global commit threshold: the best key among losers and each
        # type's truncation sentinel (only while it has an open slot)
        L_key = (int(_NEG), -_I32MAX)
        lt, lj = np.nonzero(lose)
        for t, j in zip(lt.tolist(), lj.tolist()):
            k = (int(mp[t, j]), -int(mg[t, j]))
            if k > L_key:
                L_key = k
        for t in range(T):
            if nopen[t] and trunc_p[t] > int(_NEG):
                k = (int(trunc_p[t]), -int(trunc_g[t]))
                if k > L_key:
                    L_key = k
        # prefix commit above the threshold
        ncommit = 0
        for t in range(T):
            for j in range(m):
                if lose[t, j]:
                    break  # a loss blocks every later rank this round
                if not win[t, j]:
                    continue
                if (int(mp[t, j]), -int(mg[t, j])) <= L_key:
                    continue
                c = int(pair_slot[t, j])
                assigned[t, c] = mg[t, j]
                req_open[rid[t, j]] = False
                head[t] += 1
                ncommit += 1
        if ncommit == 0:
            break
    return assigned


def _sharded_to_host(x) -> np.ndarray:
    """Device->host of a [ndev, ...] mesh-sharded array, read
    shard-by-shard in device order (the sharded array's own __array__
    assembly is an order of magnitude slower on host-platform meshes)."""
    shards = sorted(
        x.addressable_shards, key=lambda s: s.index[0].start or 0)
    return np.concatenate([np.asarray(s.data) for s in shards])


def _slot_sizes(slots_per_type: Optional[int], cand_width: int,
                rounds: int, NR: int) -> tuple[int, int]:
    """(C, D): requester slots per type and the candidate depth the
    sweep must gather. D = C + m + 1 is load-bearing for exactness —
    heads advance at most C and the threshold sentinel reads m past the
    head — so both solvers size through this one helper."""
    C = min(slots_per_type or max(64, cand_width * max(rounds, 1)), NR)
    C = C or 1
    return C, C + cand_width + 1


def _merge_shard_major(cp, cg):
    """Merge [ndev, T, D] per-shard candidate tables into exact global
    (prio desc, gid asc) lists [T, ndev*D]: two composed stable
    single-key sorts — gid first, then prio desc. (Rank-keyed gids are
    NOT monotone across the shard-major concatenation once the elastic
    slot map reuses rows, so gid order must be restored explicitly
    before the priority sort; padding gids are _I32MAX and sort last
    within the _NEG-priority run, as before.)"""
    T = cp.shape[1]
    ap = cp.transpose(1, 0, 2).reshape(T, -1)
    ag = cg.transpose(1, 0, 2).reshape(T, -1)
    o = np.argsort(ag, axis=1, kind="stable")
    ap = np.take_along_axis(ap, o, axis=1)
    ag = np.take_along_axis(ag, o, axis=1)
    mi = np.argsort(-ap, axis=1, kind="stable")
    return (
        np.take_along_axis(ap, mi, axis=1),
        np.take_along_axis(ag, mi, axis=1),
    )


def build_distributed_solver(mesh: Mesh, rounds: int = 16, axis: str = "s",
                             cand_width: int = 32,
                             slots_per_type: Optional[int] = None):
    """Returns fn(task_prio [S,K], task_type [S,K], req_mask [NR,T],
    req_valid [NR]) -> assign [NR] of global task ids (-1 = none), with
    the task tables sharded over `axis` of `mesh`.

    Server rows that are not a multiple of the mesh size are padded with
    empty rows automatically (padding is appended, so real task ids are
    unchanged, and padded rows — priority floor, no type — can never win
    an assignment: nothing to strip from the returned plan)."""
    ndev = mesh.devices.size
    built = {}

    def solve(task_prio, task_type, req_mask, req_valid):
        task_prio = np.asarray(task_prio)
        task_type = np.asarray(task_type)
        req_mask = np.asarray(req_mask)
        req_valid = np.asarray(req_valid)
        S, K = task_prio.shape
        NR, T = req_mask.shape
        pad = (-S) % ndev
        if pad:
            task_prio = np.concatenate(
                [task_prio,
                 np.full((pad, K), int(_NEG), task_prio.dtype)])
            task_type = np.concatenate(
                [task_type, np.full((pad, K), -1, task_type.dtype)])
        m = cand_width
        C, D = _slot_sizes(slots_per_type, m, rounds, NR)
        key = (task_prio.shape[0], K, T, C)
        if key not in built:
            built[key] = _build_gather_fn(mesh, T, D, axis=axis)
        gather_fn = built[key]
        shard = NamedSharding(mesh, P(axis, None))
        tp = jax.device_put(jnp.asarray(task_prio), shard)
        tt = jax.device_put(jnp.asarray(task_type), shard)
        # row index as rank: the functional path has no slot reuse, so
        # gid = si * K + ki exactly as before
        rk = jax.device_put(
            jnp.arange(task_prio.shape[0], dtype=jnp.int32),
            NamedSharding(mesh, P(axis)))
        cp, cg = gather_fn(tp, tt, rk)
        gp, gg = _merge_shard_major(_sharded_to_host(cp),
                                    _sharded_to_host(cg))
        rw, lens = _reqwin(req_mask, req_valid, T, C)
        req_open = req_valid.copy()
        assigned = _host_auction(gp, gg, rw, lens, req_open, rounds, m)
        assign = np.full((NR,), -1, dtype=np.int32)
        t_idx, c_idx = np.nonzero(assigned >= 0)
        assign[rw[t_idx, c_idx]] = assigned[t_idx, c_idx]
        return assign

    return solve


class DistributedAssignmentSolver:
    """Host wrapper mirroring AssignmentSolver.solve() but with the task
    table device-resident and sharded over the mesh, updated
    incrementally from per-server snapshot deltas.

    ``solve(snapshots, world)`` is the engine-compatible entry: it diffs
    the snapshots against the resident state (``ingest``) — a stamp fast
    path skips unchanged servers outright when snapshots carry
    ``task_stamp``/``stamp`` (the engine forwards them), falling back to
    a tuple compare otherwise — ships only changed rows to the mesh,
    runs the fixed-shape planning round (``plan``), and unpacks plan
    entries. Phase timings land in ``last_ingest_ms`` /
    ``last_solve_ms`` / ``last_extract_ms`` for the obs gauges.

    Stamp fast-path caveat (documented contract): a server whose
    filtered task list changes with no stamp bump and no plan of ours
    touching it (engine plan-ledger TTL expiry) is picked up at its next
    snapshot — at most one idle-heartbeat interval late, well inside the
    protocol's plans-are-hints staleness tolerance."""

    #: the engine may hand solve() a LedgerView instead of a snapshot
    #: dict (array-resident host tier, balancer/ledger.py): ingest then
    #: copies packed rows for servers whose ledger generation moved —
    #: no tuple re-derivation, no stamp-key diffing
    SUPPORTS_VIEW = True

    #: changed-row count above which a plan re-sweeps the table on the
    #: mesh instead of patching the merged candidate lists in place
    DELTA_RESYNC_ROWS = 16
    #: force a full device sweep at least every this many plans, so the
    #: incremental candidate view can never drift unbounded (it is exact
    #: by construction; the resync is belt-and-braces + keeps the mesh
    #: path continuously exercised)
    RESYNC_INTERVAL = 64

    def __init__(
        self,
        types: Sequence[int],
        max_tasks_per_server: int,
        max_requesters: int,
        mesh: Mesh,
        rounds: int = 16,
        servers_per_device: int = 1,
        cand_width: int = 32,
        slots_per_type: Optional[int] = None,
        auction: str = "device",
        max_jobs: int = 1,
        job_weights: Optional[dict] = None,
    ) -> None:
        if auction not in ("device", "host"):
            raise ValueError(
                f"auction must be 'device' or 'host', got {auction!r}")
        self.auction = auction
        self.base_types = tuple(types)
        self.base_T = max(len(self.base_types), 1)
        self.max_jobs = max(int(max_jobs), 1)
        # composite (job, type) axis under multi-job planning — the
        # base types verbatim when single-job (balancer/jobdim.py);
        # the mesh kernels see T' generic types and stay untouched
        self.types = expand_types(self.base_types, self.max_jobs)
        self.job_bias = bias_vector(job_weights, self.max_jobs)
        self.type_index = {t: i for i, t in enumerate(self.types)}
        self.K = max_tasks_per_server
        self.R = max_requesters
        self.mesh = mesh
        self.ndev = mesh.devices.size
        self.rounds = rounds
        self.S = self.ndev * servers_per_device
        T = max(len(self.types), 1)
        self.T = T
        self.m = cand_width
        NR = self.S * self.R
        self.C, self.D = _slot_sizes(
            slots_per_type, cand_width, rounds, NR)

        # ---- host mirrors of the resident device state ----
        self._tp = np.full((self.S, self.K), int(_NEG), dtype=np.int32)
        self._tt = np.full((self.S, self.K), -1, dtype=np.int32)
        self._req_valid = np.zeros((NR,), dtype=bool)
        self._req_mask = np.zeros((NR, T), dtype=bool)
        self._task_cache: dict[int, tuple] = {}
        self._req_cache: dict[int, tuple] = {}
        self._task_stamp: dict[int, float] = {}
        self._req_stamp: dict[int, float] = {}
        self._servers: list = []  # registered ranks (slot order free)
        self._si: dict[int, int] = {}
        # rank behind each resident row (-1 = free): the gid key space.
        # Slots are free-listed, never remapped — the auction tie-break
        # is the rank-keyed gid, not the row index
        self._row_rank = np.full((self.S,), -1, dtype=np.int64)
        self._free_si: list[int] = []
        self._next_si = 0
        # ranks whose candidate entries the next host-tier patch must
        # drop (a freed slot's row_rank is already recycled by then)
        self._dropped_ranks: set = set()
        # rank-sorted requester row order (see _reqwin): rebuilt only
        # when membership changes — the requester tie-break, like the
        # task gid, must follow rank order, not physical slot order
        self._row_perm: Optional[np.ndarray] = None
        self._task_ref: list = [[None] * self.K for _ in range(self.S)]
        self._req_ref: list = [None] * NR
        self._reqs_dirty = True
        self._full_reload = False
        # servers whose tasks/reqs our own last plan consumed: their
        # ledger-filtered snapshot content changes without a stamp bump
        self._planned_servers: set = set()
        # view-ingest bookkeeping: the ledger membership generation and
        # per-slot task/req generations last consumed (slot-indexed
        # arrays, diffed vectorized; generations are globally monotonic
        # so a slot reused for a new rank can never alias)
        self._seen_member_gen = None
        self._seen_tgen: Optional[np.ndarray] = None
        self._seen_rgen: Optional[np.ndarray] = None

        # device state & jitted fns, built lazily (constructing a solver
        # must not force accelerator init before first use)
        self._dev_tp = None
        self._dev_tt = None
        self._dev_rk = None
        self._gather_fn = None
        self._plan_fn = None
        # device-tier requester tables (rebuilt when reqs change):
        # compacted reqwin + initial open vector (see _build_plan_fn)
        self._rwc: Optional[np.ndarray] = None
        self._open0: Optional[np.ndarray] = None
        # merged per-type candidate lists [T, ndev*D] (prio desc, gid
        # asc, _NEG-padded): materialized by the device sweep, patched
        # in place for small deltas (exactly what a sweep would produce
        # — asserted by tests), re-swept when a delta is large or every
        # RESYNC_INTERVAL plans
        self._gp: Optional[np.ndarray] = None
        self._gg: Optional[np.ndarray] = None
        self._cand_dirty = True
        self._plans_since_sweep = 0
        self.sweep_count = 0
        # why each host-tier re-sweep ran (obs: solver_resweeps counter;
        # the device tier regenerates candidates every plan on-device,
        # so it never re-sweeps and these stay zero)
        self.sweep_reasons: dict = {"cold": 0, "delta": 0, "cadence": 0}
        self.last_sweep_ms = 0.0

        self.last_ingest_ms = 0.0
        self.last_solve_ms = 0.0
        self.last_extract_ms = 0.0
        self.solve_count = 0

    # ------------------------------------------------------------------
    def set_job_bias(self, job_weights: Optional[dict]) -> bool:
        """Install new fair-share biases and invalidate every cached
        task row (packed prios embed the bias; the stamp/tuple caches
        compare RAW snapshot tuples, which a weight change does not
        touch — so they must be dropped, not diffed). The view path
        needs no flush here: a weight change forces the ledger's own
        full rebuild, which bumps every slot generation."""
        bias = bias_vector(job_weights, self.max_jobs)
        if bias == self.job_bias:
            return False
        self.job_bias = bias
        self._task_cache.clear()
        self._task_stamp.clear()
        self._cand_dirty = True
        return True

    def _ensure_built(self) -> None:
        if self._gather_fn is not None:
            return
        self._gather_fn = _build_gather_fn(self.mesh, self.T, self.D)
        self._shard = NamedSharding(self.mesh, P("s", None))
        self._shard1 = NamedSharding(self.mesh, P("s"))
        self._devices = list(self.mesh.devices.reshape(-1))
        self._Sl = self.S // self.ndev
        # the resident table is kept as per-device shard pieces: a delta
        # re-uploads only the touched devices' [Sl, K] blocks (a few KB)
        # and the sharded array reassembles around the untouched ones
        # zero-copy — no mesh-wide scatter dispatch, no replication of
        # update args to every device
        self._piece_p = [None] * self.ndev
        self._piece_t = [None] * self.ndev
        self._piece_r = [None] * self.ndev
        self._reload_devices(range(self.ndev))

    def _reload_devices(self, devs) -> None:
        Sl = self._Sl
        for d in devs:
            blk = slice(d * Sl, (d + 1) * Sl)
            self._piece_p[d] = jax.device_put(
                self._tp[blk], self._devices[d])
            self._piece_t[d] = jax.device_put(
                self._tt[blk], self._devices[d])
            # free rows upload rank 0: they are dead (priority floor),
            # so their gids can never surface as candidates
            self._piece_r[d] = jax.device_put(
                np.maximum(self._row_rank[blk], 0).astype(np.int32),
                self._devices[d])
        shape = (self.S, self.K)
        self._dev_tp = jax.make_array_from_single_device_arrays(
            shape, self._shard, self._piece_p)
        self._dev_tt = jax.make_array_from_single_device_arrays(
            shape, self._shard, self._piece_t)
        self._dev_rk = jax.make_array_from_single_device_arrays(
            (self.S,), self._shard1, self._piece_r)

    def _map_server(self, s) -> Optional[int]:
        si = self._si.get(s)
        if si is not None:
            return si
        if self._free_si:
            si = self._free_si.pop()
        elif self._next_si < self.S:
            si = self._next_si
            self._next_si += 1
        else:
            # beyond capacity: unmapped until a registered server dies
            # (ingest still re-diffs every REGISTERED server each
            # round, so capacity overflow never leaves stale resident
            # rows — only unplanned extras)
            return None
        if s * self.K + self.K - 1 > _I32MAX:
            raise ValueError(
                f"server rank {s} overflows the int32 gid space "
                f"(rank * max_tasks_per_server must stay under 2**31)")
        # slots are free-listed and NEVER remapped: the auction
        # tie-break is the rank-keyed gid (rank * K + ki), not the row
        # index, so an elastic join patches one row instead of
        # re-packing the world
        self._servers.append(s)
        self._si[s] = si
        self._row_rank[si] = s
        self._row_perm = None  # rank order changed: rebuild lazily
        return si

    def _unregister(self, s, changed: list) -> None:
        """A vanished server (drain/failover): clear its resident rows
        and recycle the slot. Rank-keyed gids make this purely local —
        no other row moves, and the slot's next tenant brings its own
        gid range."""
        si = self._si.pop(s)
        self._servers.remove(s)
        if (self._tp[si] > int(_NEG)).any():
            changed.append(si)
            # the host-tier candidate patch must drop this rank's
            # entries even after row_rank forgets it
            self._dropped_ranks.add(int(s))
        self._tp[si, :] = int(_NEG)
        self._tt[si, :] = -1
        self._task_ref[si] = [None] * self.K
        base = si * self.R
        if self._req_valid[base:base + self.R].any():
            self._req_valid[base:base + self.R] = False
            self._req_mask[base:base + self.R, :] = False
            for i in range(self.R):
                self._req_ref[base + i] = None
            self._reqs_dirty = True
        self._task_cache.pop(s, None)
        self._req_cache.pop(s, None)
        self._task_stamp.pop(s, None)
        self._req_stamp.pop(s, None)
        self._row_rank[si] = -1
        self._free_si.append(si)
        self._row_perm = None  # rank order changed: rebuild lazily

    def _pack_tasks(self, s: int, tasks: tuple) -> None:
        si = self._si[s]
        row_p = self._tp[si]
        row_t = self._tt[si]
        row_p.fill(int(_NEG))
        row_t.fill(-1)
        ref = self._task_ref[si]
        for ki in range(self.K):
            ref[ki] = None
        # task tuples are (seqno, type, prio, len) — a 5th (job)
        # element rides along under multi-job planning; index, don't
        # unpack. The composite index / weight bias handling is the
        # exact twin of solve.py's dict packer and ledger._rebuild_tasks
        J, bias, nb = self.max_jobs, self.job_bias, len(self.job_bias)
        for ki, tk in enumerate(tasks[: self.K]):
            seqno, wtype, prio = tk[0], tk[1], tk[2]
            jb = (tk[4] if len(tk) > 4 else 0) if J > 1 else 0
            b = bias[jb] if 0 <= jb < nb else 0
            row_p[ki] = max(-_PRIO_CLIP, min(_PRIO_CLIP, prio)) + b
            row_t[ki] = self.type_index.get(
                wtype if J <= 1 else (jb, wtype), -1)
            ref[ki] = (s, seqno)
        self._task_cache[s] = tasks

    def _pack_reqs(self, s: int, reqs: tuple) -> None:
        si = self._si[s]
        R = self.R
        base = si * R
        self._req_valid[base: base + R] = False
        self._req_mask[base: base + R, :] = False
        for ri in range(R):
            self._req_ref[base + ri] = None
        J, T0 = self.max_jobs, self.base_T
        for ri, req in enumerate(reqs[:R]):
            # req tuples are (rank, rqseqno, types|None) — a 4th
            # (fused-reserve) element may ride along since the
            # remote-fused-fetch change, and a 5th (job) since
            # multi-job planning; index, don't unpack. Job handling
            # twins ledger._rebuild_reqs exactly: any-type = job-block
            # mask, overflow job = empty mask
            rank, rqseqno, req_types = req[0], req[1], req[2]
            jb = (req[4] if len(req) > 4 else 0) if J > 1 else 0
            i = base + ri
            self._req_valid[i] = True
            if J > 1 and not 0 <= jb < J:
                pass  # overflow job: planner-invisible
            elif req_types is None:
                if J <= 1:
                    self._req_mask[i, :] = True
                else:
                    self._req_mask[i, jb * T0:(jb + 1) * T0] = True
            else:
                for t in req_types:
                    ti = self.type_index.get(t if J <= 1 else (jb, t))
                    if ti is not None:
                        self._req_mask[i, ti] = True
            self._req_ref[i] = (s, rank, rqseqno)
        self._req_cache[s] = reqs
        self._reqs_dirty = True

    # ------------------------------------------------------------------
    def ingest(self, snapshots: dict) -> int:
        """Diff snapshots against the resident state; ship only changed
        server rows to the device mesh. Returns changed-row count."""
        t0 = time.perf_counter()
        self._ensure_built()
        changed: list[int] = []
        planned = self._planned_servers
        # every snapshot is OFFERED a row (registered servers always
        # keep theirs; new ones register while capacity lasts, extras
        # map to None). Slicing to the lowest-S ranks here instead
        # would strand a registered server outside the slice: still in
        # `snapshots`, so the vanished-server sweep below never clears
        # it, and its frozen rows would keep winning auctions.
        for s in sorted(snapshots):
            si = self._map_server(s)
            if si is None:
                continue
            snap = snapshots[s]
            # the key tuples pair the snapshot stamps with the
            # event-delta sequences (in-place snapshot mutations carry
            # no stamp bump — see server._merge_task_delta) and the
            # engine's ledger stamp (our plans change the filtered view
            # with no snapshot at all). Compared for (in)equality ONLY:
            # the components come from different hosts' monotonic
            # clocks, so ordering across them is meaningless.
            led = snap.get("ledger_stamp")
            tstamp = snap.get("task_stamp", snap.get("stamp"))
            tkey = (tstamp, snap.get("delta_seq", 0), led)
            if (
                tstamp is None
                or s in planned
                or self._task_stamp.get(s) != tkey
            ):
                tasks = tuple(map(tuple, snap["tasks"][: self.K]))
                if self._task_cache.get(s) != tasks:
                    self._pack_tasks(s, tasks)
                    changed.append(self._si[s])
                if tstamp is not None:
                    self._task_stamp[s] = tkey
            rstamp = snap.get("stamp")
            rkey = (rstamp, snap.get("req_seq", 0), led)
            if (
                rstamp is None
                or s in planned
                or self._req_stamp.get(s) != rkey
            ):
                reqs = tuple(map(tuple, snap["reqs"][: self.R]))
                if self._req_cache.get(s) != reqs:
                    self._pack_reqs(s, reqs)
                if rstamp is not None:
                    self._req_stamp[s] = rkey
        planned.clear()
        # servers that vanished (failover): unregister — clear their
        # rows AND free the slot for the next join. Checked every
        # ingest (O(S) dict lookups) — gating on a shrinking snapshot
        # COUNT missed a death that coincides with another server
        # joining, or a world larger than capacity S, leaving a dead
        # server's resident rows winning auctions forever
        for s in [r for r in self._servers if r not in snapshots]:
            self._unregister(s, changed)
        self._finish_ingest(changed)
        self.last_ingest_ms = (time.perf_counter() - t0) * 1e3
        return len(changed)

    def _finish_ingest(self, changed: list) -> None:
        """Shared ingest tail (tuple and view paths): ship changed
        device blocks, patch or dirty the host tier's merged candidate
        lists, rebuild the requester slot windows."""
        if self._full_reload:
            self._reload_devices(range(self.ndev))
            self._full_reload = False
            self._cand_dirty = True
        elif changed:
            self._reload_devices(sorted({si // self._Sl for si in changed}))
            if self.auction == "device":
                # the device tier regenerates candidates from the
                # resident table every plan — nothing to patch
                self._dropped_ranks.clear()
            elif (
                self._gp is None
                or len(changed) > max(self.DELTA_RESYNC_ROWS, self.ndev)
            ):
                self._cand_dirty = True
            else:
                self._patch_candidates(changed)
        if self._reqs_dirty:
            if self._row_perm is None:
                # rank-sorted slots first, then the unused slots (all
                # their rows invalid — order among them is irrelevant)
                used = sorted(self._si.items())  # (rank, si) rank-asc
                rest = sorted(
                    set(range(self.S)) - {si for _, si in used})
                slot_seq = np.asarray(
                    [si for _, si in used] + rest, dtype=np.int64)
                self._row_perm = (
                    slot_seq[:, None] * self.R
                    + np.arange(self.R, dtype=np.int64)[None, :]
                ).reshape(-1)
            self._rw, self._lens = _reqwin(
                self._req_mask, self._req_valid, self.T, self.C,
                self._row_perm)
            if self.auction == "device":
                self._build_req_tables()
            self._reqs_dirty = False

    def _build_req_tables(self) -> None:
        """Device-tier requester tables: compact the reqwin row ids to
        a dense [0, U) id space (U = T*C static; U itself is the dump
        id) so the on-device auction's winner/open scatters are a few
        KB, independent of the requester-table depth."""
        U = self.T * self.C
        flat = self._rw.reshape(-1)
        pos = np.flatnonzero(flat >= 0)
        uniq, inv = np.unique(flat[pos], return_inverse=True)
        rwc = np.full((self.T * self.C,), U, dtype=np.int32)
        rwc[pos] = inv.astype(np.int32)
        self._rwc = rwc.reshape(self.T, self.C)
        open0 = np.zeros((U + 1,), dtype=bool)
        open0[: uniq.size] = True
        self._open0 = open0

    def _ingest_view(self, view) -> int:
        """Delta ingest from the engine's array-resident host ledger:
        copy the packed rows of every slot whose ledger generation
        moved since we last consumed it. The ledger already applied the
        plan-mark/suppression filtering, so there is no stamp-key
        bookkeeping and no tuple compare here — the generation counters
        ARE the change signal (they cover in-place deltas, dead-rank
        patches, and the engine's own plan touches alike).

        Fully vectorized: the changed-slot set is two numpy compares
        against the seen-generation mirrors, and the O(S) membership
        walk runs only when the ledger's ``member_gen`` moved (churn) —
        a steady-state round does O(changed) python work, which is what
        holds the idle planning round flat at 10k servers."""
        t0 = time.perf_counter()
        self._ensure_built()
        # layout agreement is load-bearing: refs index [K]/[R] rows
        assert (view.K, view.R, tuple(view.types)) == (
            self.K, self.R, self.types)
        changed: list[int] = []
        ncap = view.t_gen.shape[0]
        if (
            view.member_gen != self._seen_member_gen
            or self._seen_tgen is None
            or self._seen_tgen.shape[0] != ncap
        ):
            # membership walk (cold start / churn / ledger realloc):
            # register joins, unregister vanished ranks (a death may
            # coincide with a join or a beyond-capacity world, so the
            # check is membership-exact, not count-based), grow the
            # seen-generation mirrors
            fresh: list = []
            for s in view.servers:
                if s not in self._si and self._map_server(s) is not None:
                    fresh.append(s)
            sset = set(view.servers)
            for s in [r for r in self._servers if r not in sset]:
                self._unregister(s, changed)
            old_t, old_r = self._seen_tgen, self._seen_rgen
            self._seen_tgen = np.zeros(ncap, np.int64)
            self._seen_rgen = np.zeros(ncap, np.int64)
            if old_t is not None:
                n = min(old_t.shape[0], ncap)
                self._seen_tgen[:n] = old_t[:n]
                self._seen_rgen[:n] = old_r[:n]
            for s in fresh:
                # a rank we just registered (join, or an extra that
                # finally got capacity): its slot gens may predate our
                # mirror — force the copy (gen 0 precedes every bump)
                slot = view.slot_of(s)
                self._seen_tgen[slot] = 0
                self._seen_rgen[slot] = 0
            self._seen_member_gen = view.member_gen
        R = self.R
        slot_rank = view.slot_rank
        for slot in np.flatnonzero(
                view.t_gen != self._seen_tgen).tolist():
            self._seen_tgen[slot] = view.t_gen[slot]
            si = self._si.get(int(slot_rank[slot]))
            if si is None:
                continue  # freed slot, or beyond-capacity extra
            self._tp[si, :] = view.pk_tp[slot]
            self._tt[si, :] = view.pk_tt[slot]
            self._task_ref[si] = list(view.pk_trefs[slot])
            changed.append(si)
        for slot in np.flatnonzero(
                view.r_gen != self._seen_rgen).tolist():
            self._seen_rgen[slot] = view.r_gen[slot]
            si = self._si.get(int(slot_rank[slot]))
            if si is None:
                continue
            base = si * R
            self._req_valid[base:base + R] = view.pk_rv[slot]
            self._req_mask[base:base + R, :] = view.pk_rm[slot]
            rrefs = view.pk_rrefs[slot]
            for i in range(R):
                self._req_ref[base + i] = rrefs[i]
            self._reqs_dirty = True
        # plan() keeps recording its touches for the tuple path; the
        # view path's generations already carry them — drop so the set
        # cannot grow unboundedly
        self._planned_servers.clear()
        self._finish_ingest(changed)
        self.last_ingest_ms = (time.perf_counter() - t0) * 1e3
        return len(changed)

    def _patch_candidates(self, changed: list) -> None:
        """Patch the merged candidate lists for a small delta by
        re-merging every AFFECTED SHARD whole from the host mirror —
        not just the changed servers' rows: a sweep's per-shard top-D
        window can have excluded a shard-mate's lower-priority tasks,
        and when a delta drains the shard's top entries those must
        resurface immediately, not at the next resync. The result
        equals (is a superset of, truncated at the same capacity) what
        a fresh sweep would produce down to every auction-reachable
        rank (D), as long as a type's list stays under its capacity L.
        A type that saturates L gets truncated at the TAIL (still exact
        to depth D this round) and flags a full mesh re-sweep for the
        next plan, so deep-tail entries can never silently go missing
        across rounds."""
        K = self.K
        Sl = self._Sl
        gp, gg = self._gp, self._gg
        L = gp.shape[1]
        # shards whose sweep window truncated nothing hold ALL their
        # live entries in the merged lists, so patching just the
        # changed servers' rows is exact and O(delta). A truncated
        # shard must re-merge WHOLE from the host mirror (its
        # shard-mates' beyond-window tasks may need to resurface) —
        # after which it is complete and drops out of the set.
        heavy = sorted({
            d for d in {si // Sl for si in changed}
            if self._shard_trunc[d]
        })
        row_set = sorted(
            set(changed)
            | {r for d in heavy for r in range(d * Sl, (d + 1) * Sl)}
        )
        rows = np.asarray(row_set, dtype=np.int64)
        # entries are dropped by the RANK their gid carries — the
        # affected rows' current tenants plus any rank whose slot was
        # freed since the last patch (its row_rank is already recycled)
        ranks = {int(r) for r in self._row_rank[rows] if r >= 0}
        ranks |= self._dropped_ranks
        self._dropped_ranks = set()
        drop = np.isin(
            gg // K, np.asarray(sorted(ranks), dtype=np.int64)
        ) & (gp > int(_NEG))
        for d in heavy:
            self._shard_trunc[d] = False
        # fresh entries: the affected rows' blocks from the host mirror
        # (freed rows carry rank -1 — negative gids, excluded by `live`)
        new_gid = (self._row_rank[rows][:, None] * K
                   + np.arange(K, dtype=np.int64)[None, :]).reshape(-1)
        new_p = self._tp[rows].reshape(-1)
        new_t = self._tt[rows].reshape(-1)
        live = (new_p > int(_NEG)) & (new_t >= 0)
        for t in range(self.T):
            sel = live & (new_t == t)
            keep = ~drop[t] & (gp[t] > int(_NEG))
            merged_p = np.concatenate([gp[t][keep], new_p[sel]])
            merged_g = np.concatenate([gg[t][keep], new_gid[sel]])
            # stable prio sort alone is not gid-exact across the two
            # concatenated pieces; sort one composite (prio, -gid) key,
            # then truncate the sorted result to capacity (never the
            # kept list before merging — that dropped live candidates)
            ck = merged_p.astype(np.int64) * (1 << 32) + (
                (1 << 32) - 1 - merged_g)
            order = np.argsort(-ck)[:L]
            n = order.shape[0]
            if merged_p.shape[0] > L:
                self._cand_dirty = True  # saturated: re-sweep next plan
            gp[t, :n] = merged_p[order]
            gg[t, :n] = merged_g[order]
            gp[t, n:] = int(_NEG)
            gg[t, n:] = _I32MAX

    def _sweep(self) -> None:
        """Full device sweep: the sharded candidate generation on the
        mesh plus the ONE device->host transfer of the planning round,
        re-materializing the merged candidate lists."""
        t0 = time.perf_counter()
        cp, cg = self._gather_fn(self._dev_tp, self._dev_tt,
                                 self._dev_rk)
        # read shard-by-shard: the sharded array's own __array__
        # assembly is an order of magnitude slower on host-platform
        # meshes
        self._gp, self._gg = _merge_shard_major(
            _sharded_to_host(cp), _sharded_to_host(cg))
        self._dropped_ranks.clear()  # re-materialized from live rows
        self._gg = self._gg.astype(np.int64)
        self._gp = self._gp.astype(np.int64)
        # which shards' top-D windows truncated anything: per-(shard,
        # type) live counts over the host mirror (one bincount)
        live = (self._tp > int(_NEG)) & (self._tt >= 0)
        shard_ids = np.repeat(
            np.arange(self.ndev, dtype=np.int64), self._Sl * self.K)
        keys = shard_ids[live.reshape(-1)] * self.T + np.clip(
            self._tt.reshape(-1)[live.reshape(-1)], 0, self.T - 1)
        counts = np.bincount(keys, minlength=self.ndev * self.T)
        self._shard_trunc = (
            counts.reshape(self.ndev, self.T) > self.D).any(axis=1)
        self._cand_dirty = False
        self._plans_since_sweep = 0
        self.sweep_count += 1
        self.last_sweep_ms = (time.perf_counter() - t0) * 1e3

    def _device_plan(self) -> np.ndarray:
        """The device-tier planning round: one jitted dispatch of the
        fused candidate-gen/merge/auction program, one [T, C+1]
        readback (shard 0 — every shard holds the replicated answer)."""
        if self._plan_fn is None:
            self._plan_fn = _build_plan_fn(
                self.mesh, self.T, self.D, self.C, self.rounds, self.m)
        out = self._plan_fn(
            self._dev_tp, self._dev_tt, self._dev_rk,
            self._rwc, self._lens.astype(np.int32), self._open0)
        shard = min(out.addressable_shards,
                    key=lambda sh: sh.index[0].start or 0)
        return np.asarray(shard.data)[0, :, : self.C]

    def plan(self) -> list:
        """One fixed-shape planning round over the resident state."""
        if not self._req_valid.any():
            return []
        t0 = time.perf_counter()
        self._ensure_built()
        if self.auction == "device":
            assigned = self._device_plan()
        else:
            if (
                self._cand_dirty
                or self._plans_since_sweep >= self.RESYNC_INTERVAL
            ):
                self.sweep_reasons[
                    "cold" if self._gp is None
                    else "delta" if self._cand_dirty
                    else "cadence"] += 1
                self._sweep()
            self._plans_since_sweep += 1
            req_open = self._req_valid.copy()
            assigned = _host_auction(
                self._gp, self._gg, self._rw, self._lens, req_open,
                self.rounds, self.m)
        t1 = time.perf_counter()
        self.last_solve_ms = (t1 - t0) * 1e3
        pairs = []
        t_idx, c_idx = np.nonzero(assigned >= 0)
        gids = assigned[t_idx, c_idx].tolist()
        rids = self._rw[t_idx, c_idx].tolist()
        K = self.K
        for g, rid in zip(gids, rids):
            rank, ki = divmod(int(g), K)
            si = self._si.get(rank)
            tref = self._task_ref[si][ki] if si is not None else None
            rref = self._req_ref[rid]
            if tref is None or rref is None:
                continue
            holder, seqno = tref
            req_home, for_rank, rqseqno = rref
            pairs.append((holder, seqno, req_home, for_rank, rqseqno))
            self._planned_servers.add(holder)
            self._planned_servers.add(req_home)
        self.last_extract_ms = (time.perf_counter() - t1) * 1e3
        self.solve_count += 1
        return pairs

    def solve(self, snapshots, world) -> list:
        """Engine-compatible one-call path: ingest deltas, then plan.
        Accepts either the filtered-snapshot dict or the engine's
        array-resident ledger view."""
        if getattr(snapshots, "is_array", False):
            self._ingest_view(snapshots)
        else:
            self.ingest(snapshots)
        return self.plan()
