"""Distributed (multi-chip) assignment solve.

SPMD decomposition of :mod:`adlb_tpu.balancer.solve` over a
``jax.sharding.Mesh``: the task table — the big axis, scaling with servers x
queue depth — is sharded over mesh axis ``"s"``; the requester table — small,
bounded by world size — is replicated via ``all_gather``. Each auction round:

1. every device scores its *local* task shard against all requesters and
   reduces to each requester's best local (score, task);
2. one ``all_gather`` of the per-device bests resolves the global winner
   device per requester (ICI traffic: S x NR x 2 ints per round, a few KB);
3. the winning device commits assignments for the requesters it won, with
   local scatter-min conflict resolution among requesters that picked the
   same task;
4. an ``all_gather`` of requester-assigned flags closes the round.

This replaces the reference's qmstat ring gossip (reference
``src/adlb.c:806-822,1705-1757``): instead of an O(0.1 s) staleness window on
an approximate load vector, the whole queue state is solved exactly every
round, and scale comes from adding devices along ``"s"``.
"""

from __future__ import annotations

import functools
from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from adlb_tpu.balancer.solve import _NEG


def _local_round_body(
    task_prio: jax.Array,  # [Kl] this device's task shard
    task_type: jax.Array,  # [Kl]
    req_mask: jax.Array,  # [NR, T] replicated
    req_valid: jax.Array,  # [NR] replicated
    assign_flag: jax.Array,  # [NR] bool, replicated
    task_taken: jax.Array,  # [Kl] bool, local
    axis: str,
):
    NR = req_mask.shape[0]
    Kl = task_prio.shape[0]
    my = jax.lax.axis_index(axis)

    compat = jnp.where(
        (task_type[None, :] >= 0) & req_valid[:, None],
        jnp.take_along_axis(
            req_mask, jnp.clip(task_type, 0)[None, :].repeat(NR, 0), axis=1
        ),
        False,
    )  # [NR, Kl]
    open_req = (~assign_flag) & req_valid
    score = jnp.where(
        compat & open_req[:, None] & (~task_taken)[None, :],
        task_prio[None, :],
        _NEG,
    )  # [NR, Kl]
    best_local_task = jnp.argmax(score, axis=1)  # [NR]
    best_local_score = jnp.max(score, axis=1)  # [NR]

    # Which device offers each requester its best task? Gather per-device
    # bests (small: [S, NR]) and pick the max score, lowest device id on ties.
    all_scores = jax.lax.all_gather(best_local_score, axis)  # [S, NR]
    winner_dev = jnp.argmax(all_scores, axis=0)  # [NR]
    global_best = jnp.max(all_scores, axis=0)
    i_won = (winner_dev == my) & (global_best > _NEG)  # [NR]

    # Local conflict resolution among requesters I won that chose the same
    # local task: lowest requester index wins (deterministic, matches the
    # single-chip auction).
    ridx = jnp.arange(NR, dtype=jnp.int32)
    bids = jnp.where(i_won, ridx, jnp.int32(NR))
    task_winner = (
        jnp.full((Kl,), NR, dtype=jnp.int32)
        .at[jnp.where(i_won, best_local_task, 0)]
        .min(bids)
    )
    committed = i_won & (task_winner[best_local_task] == ridx)  # [NR]
    task_taken = task_taken.at[jnp.where(committed, best_local_task, Kl)].set(
        True, mode="drop"
    )
    # global task id = device * Kl + local index
    new_assign = jnp.where(
        committed, (my * Kl + best_local_task).astype(jnp.int32), jnp.int32(-1)
    )
    # every device learns which requesters got assigned this round
    any_committed = jax.lax.all_gather(committed, axis).any(axis=0)
    assign_flag = assign_flag | any_committed
    return assign_flag, task_taken, new_assign


def build_distributed_solver(mesh: Mesh, rounds: int = 6, axis: str = "s"):
    """Returns a jitted fn(task_prio [S,K], task_type [S,K], req_mask [NR,T],
    req_valid [NR]) -> assign [rounds, NR] of global task ids (-1 = none),
    with the task tables sharded over `axis` of `mesh`."""

    def solve(task_prio, task_type, req_mask, req_valid):
        S, K = task_prio.shape
        if S % mesh.devices.size != 0:
            raise ValueError(
                f"server rows {S} must be a multiple of mesh size "
                f"{mesh.devices.size} (pad with empty rows)"
            )

        def shard_fn(tp, tt, rm, rv):
            # tp/tt arrive as [S/devices, K] local shards; flatten to one
            # local task list (global flat id stays si_global*K + ki)
            tp, tt = tp.reshape(-1), tt.reshape(-1)
            NR = rm.shape[0]

            def body(state, _):
                assign_flag, task_taken, assign = state
                assign_flag, task_taken, new_assign = _local_round_body(
                    tp, tt, rm, rv, assign_flag, task_taken, axis
                )
                # combine: each requester is assigned on at most one device
                # per round (i_won is exclusive), so non-committing devices
                # contribute (-1 + 1) = 0 to the psum
                merged_new = jax.lax.psum(new_assign + 1, axis) - 1
                assign = jnp.maximum(assign, merged_new)
                return (assign_flag, task_taken, assign), None

            assign0 = jnp.full((NR,), -1, dtype=jnp.int32)
            # mark device-varying carries for the new shard_map vma tracking
            flag0 = jax.lax.pvary(jnp.zeros((NR,), dtype=bool), (axis,))
            taken0 = jax.lax.pvary(jnp.zeros(tp.shape, dtype=bool), (axis,))
            (flag, taken, assign), _ = jax.lax.scan(
                body, (flag0, taken0, assign0), None, length=rounds
            )
            return assign[None, :]  # [1, NR] per shard; identical once psum'd

        out = shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(P(axis, None), P(axis, None), P(None, None), P(None,)),
            out_specs=P(axis, None),
        )(task_prio, task_type, req_mask, req_valid)
        # all shards hold the same merged assignment; take shard 0
        return out[0]

    return jax.jit(solve)


class DistributedAssignmentSolver:
    """Host wrapper mirroring AssignmentSolver.solve() but running the sharded
    solve over a device mesh. Used by multi-host deployments (one task-shard
    per device) and by the multichip dry-run."""

    def __init__(
        self,
        types: Sequence[int],
        max_tasks_per_server: int,
        max_requesters: int,
        mesh: Mesh,
        rounds: int = 6,
        servers_per_device: int = 1,
    ) -> None:
        self.types = tuple(types)
        self.type_index = {t: i for i, t in enumerate(self.types)}
        self.K = max_tasks_per_server
        self.R = max_requesters
        self.mesh = mesh
        self.S = mesh.devices.size * servers_per_device
        self._fn = build_distributed_solver(mesh, rounds=rounds)

    def solve(self, snapshots: dict, world) -> list:
        servers = sorted(snapshots)[: self.S]
        S, K, R, T = self.S, self.K, self.R, len(self.types)
        task_prio = np.full((S, K), int(_NEG), dtype=np.int32)
        task_type = np.full((S, K), -1, dtype=np.int32)
        task_ref: list = [[None] * K for _ in range(S)]
        req_mask = np.zeros((S * R, T), dtype=bool)
        req_valid = np.zeros((S * R,), dtype=bool)
        req_ref: list = [None] * (S * R)

        for si, s in enumerate(servers):
            snap = snapshots[s]
            for ki, (seqno, wtype, prio, _len) in enumerate(snap["tasks"][:K]):
                task_prio[si, ki] = prio
                task_type[si, ki] = self.type_index.get(wtype, -1)
                task_ref[si][ki] = (s, seqno)
            for ri, (rank, rqseqno, req_types) in enumerate(snap["reqs"][:R]):
                i = si * R + ri
                req_valid[i] = True
                if req_types is None:
                    req_mask[i, :] = True
                else:
                    for t in req_types:
                        ti = self.type_index.get(t)
                        if ti is not None:
                            req_mask[i, ti] = True
                req_ref[i] = (s, rank, rqseqno)

        if not req_valid.any():
            return []
        assign = np.asarray(
            self._fn(
                jnp.asarray(task_prio),
                jnp.asarray(task_type),
                jnp.asarray(req_mask),
                jnp.asarray(req_valid),
            )
        )
        pairs = []
        for i, g in enumerate(assign):
            if g < 0 or req_ref[i] is None:
                continue
            si, ki = divmod(int(g), self.K)
            if si >= len(servers) or task_ref[si][ki] is None:
                continue
            holder, seqno = task_ref[si][ki]
            req_home, for_rank, rqseqno = req_ref[i]
            pairs.append((holder, seqno, req_home, for_rank, rqseqno))
        return pairs
