"""Distributed (multi-chip) assignment solve — the production path.

SPMD decomposition of :mod:`adlb_tpu.balancer.solve` over a
``jax.sharding.Mesh``: the task table — the big axis, scaling with servers x
queue depth — lives device-resident, sharded by server over mesh axis
``"s"`` (``NamedSharding``), and is updated *incrementally* from per-server
snapshot deltas (only changed rows ship; unchanged servers are skipped by
a stamp fast path). Each planning round is three fixed-shape steps:

1. **sharded candidate generation** (on the mesh) — every device presorts
   its task shard by (type, priority desc, seqno) — two composed stable
   single-key sorts; the multi-key comparator sort is ~10x slower on CPU
   backends — and slices each type's top-D candidates, D = C + m + 1.
   This is the only work that scales with table size, which is exactly
   what the mesh parallelizes; it never retraces (fixed [S, K] shapes).
2. **one cross-shard gather** — the [ndev, T, 2D] winner tuples collapse
   to the planner host in a single transfer (a few hundred KB at 1,000
   servers). This is the round's entire communication: no per-round
   collectives, no O(requesters) device state.
3. **auction rounds at the planner** — pure head-pointer logic over the
   merged per-type candidate lists and the [T, C] requester-slot tables
   (O(plan size), numpy): rank-k candidate pairs with the k-th open
   accepting requester, cross-type conflicts resolve by (prio, -seqno),
   a global threshold defers any winner that a displaced higher-priority
   task could cascade into, and prefix commits keep every shard's
   consumed tasks a prefix of its sorted type segment (which is what
   makes step 1's head slices exact). The merge itself is ONE stable
   sort: shard-major concatenation is already seqno-ascending within
   every equal-priority run.

The auction reproduces the exact sequential greedy matching of
:func:`adlb_tpu.balancer.solve._host_greedy` — same matched requester
set, same committed task multiset, same total score (fuzz-verified at
mesh sizes 1/2/8 by ``tests/test_sharded_parity.py``) — truncation
aside: at most ``C`` requesters per type are visible per round and
``m`` commits per type can land per auction round, and leftovers are
re-planned by the next balancer tick (the protocol's standing staleness
contract: plan entries are hints validated at enactment).

This replaces the reference's qmstat ring gossip (reference
``src/adlb.c:806-822,1705-1757``): instead of an O(0.1 s) staleness
window on an approximate load vector, the whole queue state is solved
every round, and scale comes from adding devices along ``"s"``.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from adlb_tpu.balancer.solve import _NEG, _PRIO_CLIP

_I32MAX = 2**31 - 1


def _stable_argsort2(primary, secondary):
    """argsort by (primary asc, secondary asc, index asc) — the
    lexsort((secondary, primary)) order — composed from two single-key
    stable sorts (XLA's variadic comparator sort is ~10x slower on CPU
    hosts than its single-key fast path)."""
    o1 = jnp.argsort(secondary, stable=True)
    o2 = jnp.argsort(primary[o1], stable=True)
    return o1[o2]


def _build_gather_fn(mesh: Mesh, T: int, D: int, axis: str = "s"):
    """Sharded candidate generation: fn(task_prio [S,K], task_type [S,K])
    -> (cand_prio, cand_gid) [ndev, T, D] — each device's per-type top-D
    (prio desc, gid asc) candidates. gid is the global flat task id
    (si * K + ki), so shard-major order is gid order."""

    def shard_fn(tp, tt):
        Sl, K = tp.shape
        Kl = Sl * K
        my = jax.lax.axis_index(axis)
        tp, tt = tp.reshape(-1), tt.reshape(-1)
        gids = my.astype(jnp.int32) * Kl + jnp.arange(Kl, dtype=jnp.int32)
        live = (tp > _NEG) & (tt >= 0)
        prio = jnp.clip(tp, -_PRIO_CLIP, _PRIO_CLIP)
        sort_t = jnp.where(live, tt, T).astype(jnp.int32)
        # (type asc, prio desc, gid asc): argsort(-prio) is stable, so
        # equal priorities keep index order = gid order
        order = _stable_argsort2(sort_t, -prio)
        s_prio = prio[order]
        s_gid = gids[order]
        scount = jnp.zeros((T + 1,), jnp.int32).at[sort_t].add(
            1, mode="drop")
        seg_off = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(scount[:T])])
        idx = seg_off[:T, None] + jnp.arange(D, dtype=jnp.int32)[None, :]
        ok = idx < seg_off[1:, None]
        idc = jnp.clip(idx, 0, Kl - 1)
        cp = jnp.where(ok, s_prio[idc], _NEG)
        cg = jnp.where(ok, s_gid[idc], _I32MAX)
        return cp[None], cg[None]

    fn = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None)),
        out_specs=(P(axis, None, None), P(axis, None, None)),
        check_rep=False,
    )
    return jax.jit(fn)


def _reqwin(req_mask, req_valid, T: int, C: int):
    """Requester slot tables: ``reqwin [T, C]`` — the first C valid
    requester row ids accepting each type, in row order (the greedy
    "first open compatible requester" order) — plus per-type lengths.

    Chunked early-exit scan: with deep requester tables (100k parked)
    the window is filled from the first few thousand rows, so the
    common-case cost is O(chunk * T), not O(NR * T)."""
    NR = req_valid.shape[0]
    reqwin = np.full((T, C), -1, dtype=np.int32)
    lens = np.zeros((T,), dtype=np.int32)
    CHUNK = 16384
    for a in range(0, NR, CHUNK):
        b = min(a + CHUNK, NR)
        vm = req_mask[a:b] & req_valid[a:b, None]  # [chunk, T]
        done = True
        for t in range(T):
            n = int(lens[t])
            if n >= C:
                continue
            idx = np.flatnonzero(vm[:, t])[: C - n]
            if idx.size:
                reqwin[t, n: n + idx.size] = idx + a
                lens[t] = n + idx.size
            if lens[t] < C:
                done = False
        if done:
            break
    return reqwin, lens


def _host_auction(gp, gg, reqwin, lens, req_open, rounds: int, m: int):
    """The auction rounds (numpy, O(plan size) per round).

    gp/gg: [T, L] merged candidate (prio, gid) lists, prio desc / gid
    asc, _NEG-padded. reqwin/lens: slot tables from :func:`_reqwin`.
    req_open: bool over requester rows, mutated in place. Returns
    ``assigned [T, C]`` of committed gids (-1 = none).

    Exits early the first round that commits nothing: the globally best
    candidate with an open accepting slot always commits (it wins any
    conflict and tops any threshold), so a zero-commit round proves the
    matching is maximal."""
    T, L = gp.shape
    C = reqwin.shape[1]
    head = np.zeros((T,), dtype=np.int64)
    nlive = (gp > _NEG).sum(axis=1)
    slot_valid = np.arange(C)[None, :] < lens[:, None]
    assigned = np.full((T, C), -1, dtype=np.int64)
    arange_m1 = np.arange(m + 1)
    trange = np.arange(T)
    for _ in range(rounds):
        # next m+1 untaken candidates per type (head slice)
        cidx = head[:, None] + arange_m1[None, :]
        okc = cidx < nlive[:, None]
        cl = np.minimum(cidx, L - 1)
        mp_full = np.where(okc, gp[trange[:, None], cl], int(_NEG))
        mg_full = np.where(okc, gg[trange[:, None], cl], _I32MAX)
        mp, mg = mp_full[:, :m], mg_full[:, :m]
        trunc_p, trunc_g = mp_full[:, m], mg_full[:, m]
        # first m open slots per type
        open_ = slot_valid & req_open[np.clip(reqwin, 0, None)]
        sr = np.cumsum(open_, axis=1)
        nopen = sr[:, -1] if C else np.zeros((T,), np.int64)
        # pair_slot[t, j] = index of the (j+1)-th open slot (C = none)
        pair_slot = np.full((T, m), C, dtype=np.int64)
        for t in range(T):
            if nopen[t]:
                k = int(min(nopen[t], m))
                pair_slot[t, :k] = np.flatnonzero(open_[t])[:k]
        valid = (mp > int(_NEG)) & (pair_slot < C)
        rid = np.where(
            valid, reqwin[trange[:, None], np.clip(pair_slot, 0, C - 1)],
            -1)
        # cross-type conflicts: winner per requester by (prio, -gid)
        win = np.zeros((T, m), dtype=bool)
        best: dict = {}
        vt, vj = np.nonzero(valid)
        for t, j in zip(vt.tolist(), vj.tolist()):
            key = (int(mp[t, j]), -int(mg[t, j]))
            r = int(rid[t, j])
            if r not in best or key > best[r][0]:
                best[r] = (key, t, j)
        for r, (_k, t, j) in best.items():
            win[t, j] = True
        win &= valid
        lose = valid & ~win
        # global commit threshold: the best key among losers and each
        # type's truncation sentinel (only while it has an open slot)
        L_key = (int(_NEG), -_I32MAX)
        lt, lj = np.nonzero(lose)
        for t, j in zip(lt.tolist(), lj.tolist()):
            k = (int(mp[t, j]), -int(mg[t, j]))
            if k > L_key:
                L_key = k
        for t in range(T):
            if nopen[t] and trunc_p[t] > int(_NEG):
                k = (int(trunc_p[t]), -int(trunc_g[t]))
                if k > L_key:
                    L_key = k
        # prefix commit above the threshold
        ncommit = 0
        for t in range(T):
            for j in range(m):
                if lose[t, j]:
                    break  # a loss blocks every later rank this round
                if not win[t, j]:
                    continue
                if (int(mp[t, j]), -int(mg[t, j])) <= L_key:
                    continue
                c = int(pair_slot[t, j])
                assigned[t, c] = mg[t, j]
                req_open[rid[t, j]] = False
                head[t] += 1
                ncommit += 1
        if ncommit == 0:
            break
    return assigned


def _sharded_to_host(x) -> np.ndarray:
    """Device->host of a [ndev, ...] mesh-sharded array, read
    shard-by-shard in device order (the sharded array's own __array__
    assembly is an order of magnitude slower on host-platform meshes)."""
    shards = sorted(
        x.addressable_shards, key=lambda s: s.index[0].start or 0)
    return np.concatenate([np.asarray(s.data) for s in shards])


def _slot_sizes(slots_per_type: Optional[int], cand_width: int,
                rounds: int, NR: int) -> tuple[int, int]:
    """(C, D): requester slots per type and the candidate depth the
    sweep must gather. D = C + m + 1 is load-bearing for exactness —
    heads advance at most C and the threshold sentinel reads m past the
    head — so both solvers size through this one helper."""
    C = min(slots_per_type or max(64, cand_width * max(rounds, 1)), NR)
    C = C or 1
    return C, C + cand_width + 1


def _merge_shard_major(cp, cg):
    """Merge [ndev, T, D] per-shard candidate tables into exact global
    (prio desc, gid asc) lists [T, ndev*D]: ONE stable sort suffices —
    the shard-major concatenation is already gid-ascending within every
    equal-priority run (gid = shard block + in-block presorted order)."""
    T = cp.shape[1]
    ap = cp.transpose(1, 0, 2).reshape(T, -1)
    ag = cg.transpose(1, 0, 2).reshape(T, -1)
    mi = np.argsort(-ap, axis=1, kind="stable")
    return (
        np.take_along_axis(ap, mi, axis=1),
        np.take_along_axis(ag, mi, axis=1),
    )


def build_distributed_solver(mesh: Mesh, rounds: int = 16, axis: str = "s",
                             cand_width: int = 32,
                             slots_per_type: Optional[int] = None):
    """Returns fn(task_prio [S,K], task_type [S,K], req_mask [NR,T],
    req_valid [NR]) -> assign [NR] of global task ids (-1 = none), with
    the task tables sharded over `axis` of `mesh`.

    Server rows that are not a multiple of the mesh size are padded with
    empty rows automatically (padding is appended, so real task ids are
    unchanged, and padded rows — priority floor, no type — can never win
    an assignment: nothing to strip from the returned plan)."""
    ndev = mesh.devices.size
    built = {}

    def solve(task_prio, task_type, req_mask, req_valid):
        task_prio = np.asarray(task_prio)
        task_type = np.asarray(task_type)
        req_mask = np.asarray(req_mask)
        req_valid = np.asarray(req_valid)
        S, K = task_prio.shape
        NR, T = req_mask.shape
        pad = (-S) % ndev
        if pad:
            task_prio = np.concatenate(
                [task_prio,
                 np.full((pad, K), int(_NEG), task_prio.dtype)])
            task_type = np.concatenate(
                [task_type, np.full((pad, K), -1, task_type.dtype)])
        m = cand_width
        C, D = _slot_sizes(slots_per_type, m, rounds, NR)
        key = (task_prio.shape[0], K, T, C)
        if key not in built:
            built[key] = _build_gather_fn(mesh, T, D, axis=axis)
        gather_fn = built[key]
        shard = NamedSharding(mesh, P(axis, None))
        tp = jax.device_put(jnp.asarray(task_prio), shard)
        tt = jax.device_put(jnp.asarray(task_type), shard)
        cp, cg = gather_fn(tp, tt)
        gp, gg = _merge_shard_major(_sharded_to_host(cp),
                                    _sharded_to_host(cg))
        rw, lens = _reqwin(req_mask, req_valid, T, C)
        req_open = req_valid.copy()
        assigned = _host_auction(gp, gg, rw, lens, req_open, rounds, m)
        assign = np.full((NR,), -1, dtype=np.int32)
        t_idx, c_idx = np.nonzero(assigned >= 0)
        assign[rw[t_idx, c_idx]] = assigned[t_idx, c_idx]
        return assign

    return solve


class DistributedAssignmentSolver:
    """Host wrapper mirroring AssignmentSolver.solve() but with the task
    table device-resident and sharded over the mesh, updated
    incrementally from per-server snapshot deltas.

    ``solve(snapshots, world)`` is the engine-compatible entry: it diffs
    the snapshots against the resident state (``ingest``) — a stamp fast
    path skips unchanged servers outright when snapshots carry
    ``task_stamp``/``stamp`` (the engine forwards them), falling back to
    a tuple compare otherwise — ships only changed rows to the mesh,
    runs the fixed-shape planning round (``plan``), and unpacks plan
    entries. Phase timings land in ``last_ingest_ms`` /
    ``last_solve_ms`` / ``last_extract_ms`` for the obs gauges.

    Stamp fast-path caveat (documented contract): a server whose
    filtered task list changes with no stamp bump and no plan of ours
    touching it (engine plan-ledger TTL expiry) is picked up at its next
    snapshot — at most one idle-heartbeat interval late, well inside the
    protocol's plans-are-hints staleness tolerance."""

    #: the engine may hand solve() a LedgerView instead of a snapshot
    #: dict (array-resident host tier, balancer/ledger.py): ingest then
    #: copies packed rows for servers whose ledger generation moved —
    #: no tuple re-derivation, no stamp-key diffing
    SUPPORTS_VIEW = True

    #: changed-row count above which a plan re-sweeps the table on the
    #: mesh instead of patching the merged candidate lists in place
    DELTA_RESYNC_ROWS = 16
    #: force a full device sweep at least every this many plans, so the
    #: incremental candidate view can never drift unbounded (it is exact
    #: by construction; the resync is belt-and-braces + keeps the mesh
    #: path continuously exercised)
    RESYNC_INTERVAL = 64

    def __init__(
        self,
        types: Sequence[int],
        max_tasks_per_server: int,
        max_requesters: int,
        mesh: Mesh,
        rounds: int = 16,
        servers_per_device: int = 1,
        cand_width: int = 32,
        slots_per_type: Optional[int] = None,
    ) -> None:
        self.types = tuple(types)
        self.type_index = {t: i for i, t in enumerate(self.types)}
        self.K = max_tasks_per_server
        self.R = max_requesters
        self.mesh = mesh
        self.ndev = mesh.devices.size
        self.rounds = rounds
        self.S = self.ndev * servers_per_device
        T = max(len(self.types), 1)
        self.T = T
        self.m = cand_width
        NR = self.S * self.R
        self.C, self.D = _slot_sizes(
            slots_per_type, cand_width, rounds, NR)

        # ---- host mirrors of the resident device state ----
        self._tp = np.full((self.S, self.K), int(_NEG), dtype=np.int32)
        self._tt = np.full((self.S, self.K), -1, dtype=np.int32)
        self._req_valid = np.zeros((NR,), dtype=bool)
        self._req_mask = np.zeros((NR, T), dtype=bool)
        self._task_cache: dict[int, tuple] = {}
        self._req_cache: dict[int, tuple] = {}
        self._task_stamp: dict[int, float] = {}
        self._req_stamp: dict[int, float] = {}
        self._servers: list = []  # sorted ranks; index = si
        self._si: dict[int, int] = {}
        self._task_ref: list = [[None] * self.K for _ in range(self.S)]
        self._req_ref: list = [None] * NR
        self._reqs_dirty = True
        self._full_reload = False
        # servers whose tasks/reqs our own last plan consumed: their
        # ledger-filtered snapshot content changes without a stamp bump
        self._planned_servers: set = set()
        # view-ingest bookkeeping: last consumed ledger generation per
        # server (rank-keyed; generations are globally monotonic so a
        # slot reused for a new rank can never alias)
        self._vgen_t: dict[int, int] = {}
        self._vgen_r: dict[int, int] = {}

        # device state & jitted fns, built lazily (constructing a solver
        # must not force accelerator init before first use)
        self._dev_tp = None
        self._dev_tt = None
        self._gather_fn = None
        # merged per-type candidate lists [T, ndev*D] (prio desc, gid
        # asc, _NEG-padded): materialized by the device sweep, patched
        # in place for small deltas (exactly what a sweep would produce
        # — asserted by tests), re-swept when a delta is large or every
        # RESYNC_INTERVAL plans
        self._gp: Optional[np.ndarray] = None
        self._gg: Optional[np.ndarray] = None
        self._cand_dirty = True
        self._plans_since_sweep = 0
        self.sweep_count = 0
        self.last_sweep_ms = 0.0

        self.last_ingest_ms = 0.0
        self.last_solve_ms = 0.0
        self.last_extract_ms = 0.0
        self.solve_count = 0

    # ------------------------------------------------------------------
    def _ensure_built(self) -> None:
        if self._gather_fn is not None:
            return
        self._gather_fn = _build_gather_fn(self.mesh, self.T, self.D)
        self._shard = NamedSharding(self.mesh, P("s", None))
        self._devices = list(self.mesh.devices.reshape(-1))
        self._Sl = self.S // self.ndev
        # the resident table is kept as per-device shard pieces: a delta
        # re-uploads only the touched devices' [Sl, K] blocks (a few KB)
        # and the sharded array reassembles around the untouched ones
        # zero-copy — no mesh-wide scatter dispatch, no replication of
        # update args to every device
        self._piece_p = [None] * self.ndev
        self._piece_t = [None] * self.ndev
        self._reload_devices(range(self.ndev))

    def _reload_devices(self, devs) -> None:
        Sl = self._Sl
        for d in devs:
            blk = slice(d * Sl, (d + 1) * Sl)
            self._piece_p[d] = jax.device_put(
                self._tp[blk], self._devices[d])
            self._piece_t[d] = jax.device_put(
                self._tt[blk], self._devices[d])
        shape = (self.S, self.K)
        self._dev_tp = jax.make_array_from_single_device_arrays(
            shape, self._shard, self._piece_p)
        self._dev_tt = jax.make_array_from_single_device_arrays(
            shape, self._shard, self._piece_t)

    def _map_server(self, s) -> Optional[int]:
        si = self._si.get(s)
        if si is not None:
            return si
        if len(self._servers) >= self.S:
            # beyond capacity: unmapped until a registered server dies
            # (slots are first-registered; ingest still re-diffs every
            # REGISTERED server each round, so capacity overflow never
            # leaves stale resident rows — only unplanned extras)
            return None
        # si assignment keeps sorted-rank order (matches the
        # single-device packer, so requester row order — the greedy
        # tie-break — is identical); a server sorting before existing
        # ones forces a remap + full reload (failover-rare)
        self._servers.append(s)
        if self._servers != sorted(self._servers):
            self._servers.sort()
            self._si = {r: i for i, r in enumerate(self._servers)}
            self._remap_all()
        else:
            self._si[s] = len(self._servers) - 1
        return self._si[s]

    def _remap_all(self) -> None:
        task_cache, req_cache = self._task_cache, self._req_cache
        self._tp.fill(int(_NEG))
        self._tt.fill(-1)
        self._req_valid.fill(False)
        self._req_mask.fill(False)
        self._task_ref = [[None] * self.K for _ in range(self.S)]
        self._req_ref = [None] * (self.S * self.R)
        self._task_cache = {}
        self._req_cache = {}
        for s in self._servers:
            if s in task_cache:
                self._pack_tasks(s, task_cache[s])
            if s in req_cache:
                self._pack_reqs(s, req_cache[s])
        self._full_reload = True

    def _pack_tasks(self, s: int, tasks: tuple) -> None:
        si = self._si[s]
        row_p = self._tp[si]
        row_t = self._tt[si]
        row_p.fill(int(_NEG))
        row_t.fill(-1)
        ref = self._task_ref[si]
        for ki in range(self.K):
            ref[ki] = None
        for ki, (seqno, wtype, prio, _len) in enumerate(tasks[: self.K]):
            row_p[ki] = max(-_PRIO_CLIP, min(_PRIO_CLIP, prio))
            row_t[ki] = self.type_index.get(wtype, -1)
            ref[ki] = (s, seqno)
        self._task_cache[s] = tasks

    def _pack_reqs(self, s: int, reqs: tuple) -> None:
        si = self._si[s]
        R = self.R
        base = si * R
        self._req_valid[base: base + R] = False
        self._req_mask[base: base + R, :] = False
        for ri in range(R):
            self._req_ref[base + ri] = None
        for ri, req in enumerate(reqs[:R]):
            # req tuples are (rank, rqseqno, types|None) — a 4th
            # (fused-reserve) element may ride along since the
            # remote-fused-fetch change; index, don't unpack
            rank, rqseqno, req_types = req[0], req[1], req[2]
            i = base + ri
            self._req_valid[i] = True
            if req_types is None:
                self._req_mask[i, :] = True
            else:
                for t in req_types:
                    ti = self.type_index.get(t)
                    if ti is not None:
                        self._req_mask[i, ti] = True
            self._req_ref[i] = (s, rank, rqseqno)
        self._req_cache[s] = reqs
        self._reqs_dirty = True

    # ------------------------------------------------------------------
    def ingest(self, snapshots: dict) -> int:
        """Diff snapshots against the resident state; ship only changed
        server rows to the device mesh. Returns changed-row count."""
        t0 = time.perf_counter()
        self._ensure_built()
        changed: list[int] = []
        planned = self._planned_servers
        # every snapshot is OFFERED a row (registered servers always
        # keep theirs; new ones register while capacity lasts, extras
        # map to None). Slicing to the lowest-S ranks here instead
        # would strand a registered server outside the slice: still in
        # `snapshots`, so the vanished-server sweep below never clears
        # it, and its frozen rows would keep winning auctions.
        for s in sorted(snapshots):
            si = self._map_server(s)
            if si is None:
                continue
            snap = snapshots[s]
            # the key tuples pair the snapshot stamps with the
            # event-delta sequences (in-place snapshot mutations carry
            # no stamp bump — see server._merge_task_delta) and the
            # engine's ledger stamp (our plans change the filtered view
            # with no snapshot at all). Compared for (in)equality ONLY:
            # the components come from different hosts' monotonic
            # clocks, so ordering across them is meaningless.
            led = snap.get("ledger_stamp")
            tstamp = snap.get("task_stamp", snap.get("stamp"))
            tkey = (tstamp, snap.get("delta_seq", 0), led)
            if (
                tstamp is None
                or s in planned
                or self._task_stamp.get(s) != tkey
            ):
                tasks = tuple(map(tuple, snap["tasks"][: self.K]))
                if self._task_cache.get(s) != tasks:
                    self._pack_tasks(s, tasks)
                    changed.append(self._si[s])
                if tstamp is not None:
                    self._task_stamp[s] = tkey
            rstamp = snap.get("stamp")
            rkey = (rstamp, snap.get("req_seq", 0), led)
            if (
                rstamp is None
                or s in planned
                or self._req_stamp.get(s) != rkey
            ):
                reqs = tuple(map(tuple, snap["reqs"][: self.R]))
                if self._req_cache.get(s) != reqs:
                    self._pack_reqs(s, reqs)
                if rstamp is not None:
                    self._req_stamp[s] = rkey
        planned.clear()
        # servers that vanished (failover): clear their rows. Checked
        # every ingest (O(S) dict lookups) — gating on a shrinking
        # snapshot COUNT missed a death that coincides with another
        # server joining, or a world larger than capacity S, leaving a
        # dead server's resident rows winning auctions forever
        for s in self._servers:
            if s not in snapshots:
                if self._task_cache.get(s):
                    self._pack_tasks(s, ())
                    changed.append(self._si[s])
                if self._req_cache.get(s):
                    self._pack_reqs(s, ())
        self._finish_ingest(changed)
        self.last_ingest_ms = (time.perf_counter() - t0) * 1e3
        return len(changed)

    def _finish_ingest(self, changed: list) -> None:
        """Shared ingest tail (tuple and view paths): ship changed
        device blocks, patch or dirty the merged candidate lists,
        rebuild the requester slot windows."""
        if self._full_reload:
            self._reload_devices(range(self.ndev))
            self._full_reload = False
            self._cand_dirty = True
        elif changed:
            self._reload_devices(sorted({si // self._Sl for si in changed}))
            if (
                self._gp is None
                or len(changed) > max(self.DELTA_RESYNC_ROWS, self.ndev)
            ):
                self._cand_dirty = True
            else:
                self._patch_candidates(changed)
        if self._reqs_dirty:
            self._rw, self._lens = _reqwin(
                self._req_mask, self._req_valid, self.T, self.C)
            self._reqs_dirty = False

    def _ingest_view(self, view) -> int:
        """Delta ingest from the engine's array-resident host ledger:
        copy the packed rows of every server whose ledger generation
        moved since we last consumed it. The ledger already applied the
        plan-mark/suppression filtering, so there is no stamp-key
        bookkeeping and no tuple compare here — the generation counters
        ARE the change signal (they cover in-place deltas, dead-rank
        patches, and the engine's own plan touches alike)."""
        t0 = time.perf_counter()
        self._ensure_built()
        # layout agreement is load-bearing: refs index [K]/[R] rows
        assert (view.K, view.R, tuple(view.types)) == (
            self.K, self.R, self.types)
        servers = view.servers
        for s in servers:
            self._map_server(s)  # may remap + flag a full reload
        full = self._full_reload
        changed: list[int] = []
        R = self.R
        for s in servers:
            si = self._si.get(s)
            if si is None:
                continue  # beyond capacity: unplanned extras (as ever)
            slot = view.slot_of(s)
            tg = view.t_gen_of(s)
            if full or self._vgen_t.get(s) != tg:
                self._tp[si, :] = view.pk_tp[slot]
                self._tt[si, :] = view.pk_tt[slot]
                self._task_ref[si] = list(view.pk_trefs[slot])
                self._vgen_t[s] = tg
                changed.append(si)
            rg = view.r_gen_of(s)
            if full or self._vgen_r.get(s) != rg:
                base = si * R
                self._req_valid[base:base + R] = view.pk_rv[slot]
                self._req_mask[base:base + R, :] = view.pk_rm[slot]
                rrefs = view.pk_rrefs[slot]
                for i in range(R):
                    self._req_ref[base + i] = rrefs[i]
                self._vgen_r[s] = rg
                self._reqs_dirty = True
        # vanished servers: clear their resident rows (unconditional
        # membership check, same rationale as the tuple path — a death
        # may coincide with a join or a beyond-capacity world)
        sset = set(servers)
        for s in self._servers:
            if s in sset:
                continue
            si = self._si[s]
            if (self._tp[si] > int(_NEG)).any():
                self._tp[si, :] = int(_NEG)
                self._tt[si, :] = -1
                self._task_ref[si] = [None] * self.K
                changed.append(si)
            base = si * R
            if self._req_valid[base:base + R].any():
                self._req_valid[base:base + R] = False
                self._req_mask[base:base + R, :] = False
                for i in range(R):
                    self._req_ref[base + i] = None
                self._reqs_dirty = True
            self._vgen_t.pop(s, None)
            self._vgen_r.pop(s, None)
        # plan() keeps recording its touches for the tuple path; the
        # view path's generations already carry them — drop so the set
        # cannot grow unboundedly
        self._planned_servers.clear()
        self._finish_ingest(changed)
        self.last_ingest_ms = (time.perf_counter() - t0) * 1e3
        return len(changed)

    def _patch_candidates(self, changed: list) -> None:
        """Patch the merged candidate lists for a small delta by
        re-merging every AFFECTED SHARD whole from the host mirror —
        not just the changed servers' rows: a sweep's per-shard top-D
        window can have excluded a shard-mate's lower-priority tasks,
        and when a delta drains the shard's top entries those must
        resurface immediately, not at the next resync. The result
        equals (is a superset of, truncated at the same capacity) what
        a fresh sweep would produce down to every auction-reachable
        rank (D), as long as a type's list stays under its capacity L.
        A type that saturates L gets truncated at the TAIL (still exact
        to depth D this round) and flags a full mesh re-sweep for the
        next plan, so deep-tail entries can never silently go missing
        across rounds."""
        K = self.K
        Sl = self._Sl
        gp, gg = self._gp, self._gg
        L = gp.shape[1]
        # shards whose sweep window truncated nothing hold ALL their
        # live entries in the merged lists, so patching just the
        # changed servers' rows is exact and O(delta). A truncated
        # shard must re-merge WHOLE from the host mirror (its
        # shard-mates' beyond-window tasks may need to resurface) —
        # after which it is complete and drops out of the set.
        heavy = sorted({
            d for d in {si // Sl for si in changed}
            if self._shard_trunc[d]
        })
        row_set = sorted(
            set(changed)
            | {r for d in heavy for r in range(d * Sl, (d + 1) * Sl)}
        )
        rows = np.asarray(row_set, dtype=np.int64)
        drop = np.isin(gg // K, rows) & (gp > int(_NEG))
        for d in heavy:
            self._shard_trunc[d] = False
        # fresh entries: the affected rows' blocks from the host mirror
        new_gid = (rows[:, None] * K
                   + np.arange(K, dtype=np.int64)[None, :]).reshape(-1)
        new_p = self._tp[rows].reshape(-1)
        new_t = self._tt[rows].reshape(-1)
        live = (new_p > int(_NEG)) & (new_t >= 0)
        for t in range(self.T):
            sel = live & (new_t == t)
            keep = ~drop[t] & (gp[t] > int(_NEG))
            merged_p = np.concatenate([gp[t][keep], new_p[sel]])
            merged_g = np.concatenate([gg[t][keep], new_gid[sel]])
            # stable prio sort alone is not gid-exact across the two
            # concatenated pieces; sort one composite (prio, -gid) key,
            # then truncate the sorted result to capacity (never the
            # kept list before merging — that dropped live candidates)
            ck = merged_p.astype(np.int64) * (1 << 32) + (
                (1 << 32) - 1 - merged_g)
            order = np.argsort(-ck)[:L]
            n = order.shape[0]
            if merged_p.shape[0] > L:
                self._cand_dirty = True  # saturated: re-sweep next plan
            gp[t, :n] = merged_p[order]
            gg[t, :n] = merged_g[order]
            gp[t, n:] = int(_NEG)
            gg[t, n:] = _I32MAX

    def _sweep(self) -> None:
        """Full device sweep: the sharded candidate generation on the
        mesh plus the ONE device->host transfer of the planning round,
        re-materializing the merged candidate lists."""
        t0 = time.perf_counter()
        cp, cg = self._gather_fn(self._dev_tp, self._dev_tt)
        # read shard-by-shard: the sharded array's own __array__
        # assembly is an order of magnitude slower on host-platform
        # meshes
        self._gp, self._gg = _merge_shard_major(
            _sharded_to_host(cp), _sharded_to_host(cg))
        self._gg = self._gg.astype(np.int64)
        self._gp = self._gp.astype(np.int64)
        # which shards' top-D windows truncated anything: per-(shard,
        # type) live counts over the host mirror (one bincount)
        live = (self._tp > int(_NEG)) & (self._tt >= 0)
        shard_ids = np.repeat(
            np.arange(self.ndev, dtype=np.int64), self._Sl * self.K)
        keys = shard_ids[live.reshape(-1)] * self.T + np.clip(
            self._tt.reshape(-1)[live.reshape(-1)], 0, self.T - 1)
        counts = np.bincount(keys, minlength=self.ndev * self.T)
        self._shard_trunc = (
            counts.reshape(self.ndev, self.T) > self.D).any(axis=1)
        self._cand_dirty = False
        self._plans_since_sweep = 0
        self.sweep_count += 1
        self.last_sweep_ms = (time.perf_counter() - t0) * 1e3

    def plan(self) -> list:
        """One fixed-shape planning round over the resident state."""
        if not self._req_valid.any():
            return []
        t0 = time.perf_counter()
        self._ensure_built()
        if (
            self._cand_dirty
            or self._plans_since_sweep >= self.RESYNC_INTERVAL
        ):
            self._sweep()
        self._plans_since_sweep += 1
        req_open = self._req_valid.copy()
        assigned = _host_auction(
            self._gp, self._gg, self._rw, self._lens, req_open,
            self.rounds, self.m)
        t1 = time.perf_counter()
        self.last_solve_ms = (t1 - t0) * 1e3
        pairs = []
        t_idx, c_idx = np.nonzero(assigned >= 0)
        gids = assigned[t_idx, c_idx].tolist()
        rids = self._rw[t_idx, c_idx].tolist()
        K = self.K
        for g, rid in zip(gids, rids):
            si, ki = divmod(g, K)
            tref = self._task_ref[si][ki] if si < self.S else None
            rref = self._req_ref[rid]
            if tref is None or rref is None:
                continue
            holder, seqno = tref
            req_home, for_rank, rqseqno = rref
            pairs.append((holder, seqno, req_home, for_rank, rqseqno))
            self._planned_servers.add(holder)
            self._planned_servers.add(req_home)
        self.last_extract_ms = (time.perf_counter() - t1) * 1e3
        self.solve_count += 1
        return pairs

    def solve(self, snapshots, world) -> list:
        """Engine-compatible one-call path: ingest deltas, then plan.
        Accepts either the filtered-snapshot dict or the engine's
        array-resident ledger view."""
        if getattr(snapshots, "is_array", False):
            self._ingest_view(snapshots)
        else:
            self.ingest(snapshots)
        return self.plan()
