"""Distributed (multi-chip) assignment solve.

SPMD decomposition of :mod:`adlb_tpu.balancer.solve` over a
``jax.sharding.Mesh``: the task table — the big axis, scaling with servers x
queue depth — is sharded over mesh axis ``"s"``; the requester table — small,
bounded by world size — is replicated. Each round:

1. every device runs the *local* sequential greedy over its own task shard
   (descending priority, first open compatible requester), producing at most
   one proposal per requester;
2. one ``all_gather`` of per-device proposal priorities resolves the global
   winner device per requester (ICI traffic: S x NR ints per round, KBs);
3. the winning device commits its proposals; losing devices keep their tasks
   and re-propose next round; a ``psum`` merges the round's assignments.

Rounds progress monotonically (any open requester with any open compatible
task somewhere gets a winner), so `rounds >= requesters` reaches the maximal
fixpoint; in practice a handful of rounds match almost everything, and
leftovers are re-planned by the next balancer tick. The exact cross-shard
pairing may differ from the single-device scan — parallel rounds instead of
one sequential global order — which the protocol absorbs: plan entries are
hints validated against live server state at enactment.

This replaces the reference's qmstat ring gossip (reference
``src/adlb.c:806-822,1705-1757``): instead of an O(0.1 s) staleness window on
an approximate load vector, the whole queue state is solved every round, and
scale comes from adding devices along ``"s"``.
"""

from __future__ import annotations

import functools
from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from adlb_tpu.balancer.solve import _NEG


def _mark_varying(x, axis: str):
    """Tag an array as device-varying for shard_map's vma tracking
    (jax.lax.pcast on new jax, pvary on older)."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, (axis,), to="varying")
    return jax.lax.pvary(x, (axis,))


def _local_greedy_proposals(
    task_prio: jax.Array,  # [Kl] this device's task shard (flattened)
    task_type: jax.Array,  # [Kl]
    req_mask: jax.Array,  # [NR, T] replicated
    open_req: jax.Array,  # [NR] bool
    task_taken: jax.Array,  # [Kl] bool, local
    axis: str,
):
    """Local sequential greedy: this device's open tasks, in descending
    priority, each propose to the first open compatible requester. Returns
    (proposal_task[NR] local idx or -1, proposal_prio[NR])."""
    Kl = task_prio.shape[0]
    NR = req_mask.shape[0]
    ridx = jnp.arange(NR, dtype=jnp.int32)
    eff_prio = jnp.where(task_taken, _NEG, task_prio)
    order = jnp.argsort(-eff_prio, stable=True)

    def step(carry, t_idx):
        open_r, prop_task, prop_prio = carry
        prio = eff_prio[t_idx]
        ttype = task_type[t_idx]
        compat = (
            open_r
            & (prio > _NEG)
            & (ttype >= 0)
            & req_mask[:, jnp.clip(ttype, 0)]
        )
        r = jnp.argmax(compat)
        found = compat[r]
        hit = found & (ridx == r)
        open_r = open_r & ~hit
        prop_task = jnp.where(hit, t_idx.astype(jnp.int32), prop_task)
        prop_prio = jnp.where(hit, prio, prop_prio)
        return (open_r, prop_task, prop_prio), None

    init = (
        open_req,
        _mark_varying(jnp.full((NR,), -1, dtype=jnp.int32), axis),
        _mark_varying(jnp.full((NR,), _NEG, dtype=jnp.int32), axis),
    )
    (_, prop_task, prop_prio), _ = jax.lax.scan(step, init, order)
    return prop_task, prop_prio


def _local_round_body(
    task_prio: jax.Array,  # [Kl] this device's task shard
    task_type: jax.Array,  # [Kl]
    req_mask: jax.Array,  # [NR, T] replicated
    req_valid: jax.Array,  # [NR] replicated
    assign_flag: jax.Array,  # [NR] bool
    task_taken: jax.Array,  # [Kl] bool, local
    axis: str,
):
    """One round: full local greedy matching per device, then global
    per-requester conflict resolution (max proposal priority wins; lowest
    device id on ties). Losing devices keep their tasks and retry next
    round, so a handful of rounds converge even when one device holds all
    the best work."""
    NR = req_mask.shape[0]
    Kl = task_prio.shape[0]
    my = jax.lax.axis_index(axis)

    open_req = (~assign_flag) & req_valid
    prop_task, prop_prio = _local_greedy_proposals(
        task_prio, task_type, req_mask, open_req, task_taken, axis
    )

    # global winner per requester: [S, NR] gather of proposal priorities
    all_prio = jax.lax.all_gather(prop_prio, axis)  # [S, NR]
    winner_dev = jnp.argmax(all_prio, axis=0)  # lowest device on ties
    global_best = jnp.max(all_prio, axis=0)
    committed = (
        (winner_dev == my) & (global_best > _NEG) & (prop_task >= 0) & open_req
    )
    task_taken = task_taken.at[jnp.where(committed, prop_task, Kl)].set(
        True, mode="drop"
    )
    new_assign = jnp.where(
        committed, my.astype(jnp.int32) * Kl + prop_task, jnp.int32(-1)
    )
    any_committed = global_best > _NEG  # a winner exists for these requesters
    assign_flag = assign_flag | (any_committed & open_req)
    return assign_flag, task_taken, new_assign


def build_distributed_solver(mesh: Mesh, rounds: int = 16, axis: str = "s"):
    """Returns a jitted fn(task_prio [S,K], task_type [S,K], req_mask [NR,T],
    req_valid [NR]) -> assign [rounds, NR] of global task ids (-1 = none),
    with the task tables sharded over `axis` of `mesh`."""

    def solve(task_prio, task_type, req_mask, req_valid):
        S, K = task_prio.shape
        if S % mesh.devices.size != 0:
            raise ValueError(
                f"server rows {S} must be a multiple of mesh size "
                f"{mesh.devices.size} (pad with empty rows)"
            )

        def shard_fn(tp, tt, rm, rv):
            # tp/tt arrive as [S/devices, K] local shards; flatten to one
            # local task list (global flat id stays si_global*K + ki)
            tp, tt = tp.reshape(-1), tt.reshape(-1)
            NR = rm.shape[0]

            def body(state, _):
                assign_flag, task_taken, assign = state
                assign_flag, task_taken, new_assign = _local_round_body(
                    tp, tt, rm, rv, assign_flag, task_taken, axis
                )
                # combine: each requester is assigned on at most one device
                # per round (i_won is exclusive), so non-committing devices
                # contribute (-1 + 1) = 0 to the psum
                merged_new = jax.lax.psum(new_assign + 1, axis) - 1
                assign = jnp.maximum(assign, merged_new)
                return (assign_flag, task_taken, assign), None

            assign0 = jnp.full((NR,), -1, dtype=jnp.int32)
            # mark device-varying carries for the new shard_map vma tracking
            flag0 = _mark_varying(jnp.zeros((NR,), dtype=bool), axis)
            taken0 = _mark_varying(jnp.zeros(tp.shape, dtype=bool), axis)
            (flag, taken, assign), _ = jax.lax.scan(
                body, (flag0, taken0, assign0), None, length=rounds
            )
            return assign[None, :]  # [1, NR] per shard; identical once psum'd

        out = shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(P(axis, None), P(axis, None), P(None, None), P(None,)),
            out_specs=P(axis, None),
        )(task_prio, task_type, req_mask, req_valid)
        # all shards hold the same merged assignment; take shard 0
        return out[0]

    return jax.jit(solve)


class DistributedAssignmentSolver:
    """Host wrapper mirroring AssignmentSolver.solve() but running the sharded
    solve over a device mesh. Used by multi-host deployments (one task-shard
    per device) and by the multichip dry-run."""

    def __init__(
        self,
        types: Sequence[int],
        max_tasks_per_server: int,
        max_requesters: int,
        mesh: Mesh,
        rounds: int = 16,
        servers_per_device: int = 1,
    ) -> None:
        self.types = tuple(types)
        self.type_index = {t: i for i, t in enumerate(self.types)}
        self.K = max_tasks_per_server
        self.R = max_requesters
        self.mesh = mesh
        self.S = mesh.devices.size * servers_per_device
        self._fn = build_distributed_solver(mesh, rounds=rounds)

    def solve(self, snapshots: dict, world) -> list:
        servers = sorted(snapshots)[: self.S]
        S, K, R, T = self.S, self.K, self.R, len(self.types)
        task_prio = np.full((S, K), int(_NEG), dtype=np.int32)
        task_type = np.full((S, K), -1, dtype=np.int32)
        task_ref: list = [[None] * K for _ in range(S)]
        req_mask = np.zeros((S * R, T), dtype=bool)
        req_valid = np.zeros((S * R,), dtype=bool)
        req_ref: list = [None] * (S * R)

        for si, s in enumerate(servers):
            snap = snapshots[s]
            for ki, (seqno, wtype, prio, _len) in enumerate(snap["tasks"][:K]):
                task_prio[si, ki] = prio
                task_type[si, ki] = self.type_index.get(wtype, -1)
                task_ref[si][ki] = (s, seqno)
            # req tuples may carry a 4th (fused-reserve) element since the
            # remote-fused-fetch change; index, don't unpack
            for ri, req in enumerate(snap["reqs"][:R]):
                rank, rqseqno, req_types = req[0], req[1], req[2]
                i = si * R + ri
                req_valid[i] = True
                if req_types is None:
                    req_mask[i, :] = True
                else:
                    for t in req_types:
                        ti = self.type_index.get(t)
                        if ti is not None:
                            req_mask[i, ti] = True
                req_ref[i] = (s, rank, rqseqno)

        if not req_valid.any():
            return []
        assign = np.asarray(
            self._fn(
                jnp.asarray(task_prio),
                jnp.asarray(task_type),
                jnp.asarray(req_mask),
                jnp.asarray(req_valid),
            )
        )
        pairs = []
        for i, g in enumerate(assign):
            if g < 0 or req_ref[i] is None:
                continue
            si, ki = divmod(int(g), self.K)
            if si >= len(servers) or task_ref[si][ki] is None:
                continue
            holder, seqno = task_ref[si][ki]
            req_home, for_rank, rqseqno = req_ref[i]
            pairs.append((holder, seqno, req_home, for_rank, rqseqno))
        return pairs
