"""Array-resident host ledger: O(changed rows) round admission.

The plan engine's per-round admission work — the requester ledger filter
(plan-suppression staleness checks), credit-suppression budgets, the
cross-feasibility solve gate, the pump pre-check, and the packing of the
solver's fixed-shape inputs — used to re-walk every parked requester and
every snapshot task in pure Python each round.  That is O(world) per
round, and past ~10k parked requesters it dominates the planning round
(the sharded solve itself is sub-10 ms at 100k parked; see ROADMAP item
1's closing note).

This module keeps that state **resident in numpy arrays**, maintained
incrementally from the same change keys the engine already forwards to
the sharded solver's ingest fast path:

* per-snapshot stamps (``stamp``/``task_stamp``) — full refreshes;
* event sequences (``delta_seq``/``req_seq``) — in-place snapshot
  mutations that deliberately carry no stamp bump (task-delta appends,
  dead-rank requester patches);
* the engine's own plan marks (``_planned_reqs``/``_planned_tasks``) —
  hook-fed per key, so a round that matched 5 servers re-derives 5
  servers' columns, not the world's.

Per round the admission work is then a handful of vectorized column
operations (bool masks over resident columns, [S, T] aggregate
compares), with a full rebuild only on resync — mirroring the sharded
solver's sweep/patch split (``LEDGER_RESYNC_INTERVAL``).

Two interchangeable implementations behind one interface:

* :class:`PyLedger` — the pre-existing pure-Python filter, extracted
  verbatim.  Retained as the semantic twin: ``Config(host_ledger="py")``
  selects it, and ``tests/test_ledger_parity.py`` fuzz-proves the
  vectorized ledger produces identical kept-requester / eligible-task
  sets (and therefore identical plans) across randomized delta /
  suppression / expiry / dead-rank sequences.
* :class:`ArrayLedger` — the vectorized ledger (default).  It also IS
  the :class:`LedgerView` the solvers consume directly (``solve.py`` /
  ``distributed.py`` accept it in ``solve()``), so the solver inputs are
  the resident arrays themselves — no per-round tuple re-derivation.

Exactness contract (same as the sharded solver's stamp fast path): a
snapshot whose content changes with NO key change (no stamp bump, no
sequence bump, no plan of ours touching it) is picked up at its next
keyed refresh.  The runtime never does this — every in-place mutation
bumps a sequence (``server._merge_task_delta`` / ``_patch_snapshots_for_
dead``; the sidecar's delta merge gained its bump in this change) — and
a row-count change without a key bump is additionally caught by a cheap
length check each round.  Snapshots without stamps at all (unit tests,
hand-built harnesses) are re-derived every round, which is exactly the
always-eligible semantics the Python filter gives them.
"""

from __future__ import annotations

import bisect
import time
from typing import Optional

import numpy as np

from adlb_tpu.balancer.jobdim import bias_vector, expand_types

# priority clip shared with the solvers (import kept lazy-free: solve.py
# imports jax; the ledger must stay importable on accelerator-less hosts
# without touching it — jobdim above is numpy-free pure Python)
_NEG = -(2**31) + 1
_PRIO_CLIP = 10**9


class _Marks(dict):
    """The engine's plan-mark dicts (``_planned_reqs``/``_planned_tasks``)
    with mutation hooks, so the array ledger's resident columns stay
    coherent even when a test (or future code) pokes the dict directly.
    Only the mutators the engine and tests actually use are hooked."""

    __slots__ = ("_on_set", "_on_del")

    def __init__(self, on_set=None, on_del=None):
        super().__init__()
        self._on_set = on_set
        self._on_del = on_del

    def __setitem__(self, key, value):
        dict.__setitem__(self, key, value)
        if self._on_set is not None:
            self._on_set(key, value)

    def __delitem__(self, key):
        dict.__delitem__(self, key)
        if self._on_del is not None:
            self._on_del(key)

    def pop(self, key, *default):
        had = key in self
        out = dict.pop(self, key, *default)
        if had and self._on_del is not None:
            self._on_del(key)
        return out


class SnapshotStore(dict):
    """A snapshot dict that *narrates its own changes*: every mutation
    bumps a monotonic version and appends ``(ver, rank)`` to a
    dedup-compacted change log, and membership changes (new rank, death)
    additionally bump ``member_ver``.  :meth:`ArrayLedger.sync` uses
    these to touch only the ranks that changed since its last sync —
    killing the per-round O(servers) compare scan that was the 1k-parked
    admission floor (MULTICHIP_r07) — while staying a plain dict for
    every other consumer (the ``host_ledger="py"`` twin, the sharded
    solver's stamp path, tests).

    In-place snapshot mutations that bypass ``__setitem__`` (the
    task-delta append, dead-rank requester patches) must call
    :meth:`bump`; the producers do (``server._merge_task_delta`` /
    ``_patch_snapshots_for_dead``, the sidecar's delta merge).  A missed
    bump is caught by the ledger's cadence resync, same contract as the
    stamp fast paths.

    :meth:`fork` takes the balancer round's shallow copy (the same
    ``dict(snapshots)`` the worker always took) carrying the version
    counters along, so a concurrently-mutating producer never tears a
    round: the consumer reads the log only up to the fork's ``ver``.
    """

    def __init__(self, *args, **kwargs):
        super().__init__()
        self.ver = 1
        self.member_ver = 1
        # lineage token: forks share it, distinct stores never do — a
        # consumer's seen-version marks are only meaningful against the
        # same version sequence
        self.lineage = id(self)
        self._log: list = []  # (ver, rank) ascending, dedup-compacted
        if args or kwargs:
            for rank, snap in dict(*args, **kwargs).items():
                self[rank] = snap

    def _touch(self, rank) -> None:
        self.ver += 1
        self._log.append((self.ver, rank))
        if len(self._log) > max(256, 2 * len(self) + 8):
            # lossless dedup-compaction: keep only each rank's LAST
            # entry — any consumer position either already processed the
            # dropped older entries or still sees the survivor
            last: dict = {}
            for v, r in self._log:
                last[r] = v
            self._log = sorted((v, r) for r, v in last.items())

    def __setitem__(self, rank, snap) -> None:
        if rank not in self:
            self.member_ver = self.ver + 1
        dict.__setitem__(self, rank, snap)
        self._touch(rank)

    def bump(self, rank) -> None:
        """Record an in-place mutation of ``self[rank]``."""
        if rank in self:
            self._touch(rank)

    def __delitem__(self, rank) -> None:
        dict.__delitem__(self, rank)
        self.ver += 1
        self.member_ver = self.ver

    def pop(self, rank, *default):
        had = rank in self
        out = dict.pop(self, rank, *default)
        if had:
            self.ver += 1
            self.member_ver = self.ver
        return out

    def fork(self) -> "SnapshotStore":
        """Shallow round-scoped copy sharing the (append-only) change
        log; snapshot values are shared, as the worker's ``dict()`` copy
        always did."""
        f = SnapshotStore()
        # counters first, content second: a producer racing the fork can
        # only make the copy NEWER than its version marks, so the reader
        # at worst re-processes a rank next round — never misses one
        f.ver = self.ver
        f.member_ver = self.member_ver
        f.lineage = self.lineage
        f._log = self._log
        dict.update(f, self)
        return f


class PyLedger:
    """The pure-Python twin: the engine's pre-vectorization per-round
    filter, verbatim.  Stateless across rounds beyond the engine's own
    plan-mark dicts (which it reads in place)."""

    is_array = False

    def __init__(self, engine) -> None:
        self.engine = engine
        self._freqs: dict = {}
        self._snapshots: dict = {}
        self._now = 0.0
        # twin-side counters mirror the array ledger's surface so bench
        # and smoke code can read them unconditionally
        self.patch_count = 0
        self.resync_count = 0
        self.last_sync_us = 0.0

    def sync(self, snapshots: dict, now: float) -> None:
        self._snapshots = snapshots
        self._now = now

    def filter_reqs(self, snapshots: dict, sup: dict, now: float) -> None:
        """``sup``: rank -> (fed type set, budget) for ranks with live
        young in-flight credits (engine-computed; see round())."""
        planned = self.engine._planned_reqs
        freqs = {}
        for rank, snap in snapshots.items():
            stamp = snap.get("stamp", now)
            fed, budget = sup.get(rank, (None, 0))
            kept = []
            for r in snap["reqs"]:
                if planned.get((rank, r[0], r[1]), -1.0) >= stamp:
                    continue
                if (
                    budget > 0
                    and fed
                    and (r[2] is None or not fed.isdisjoint(r[2]))
                ):
                    budget -= 1
                    continue
                kept.append(r)
            freqs[rank] = kept
        self._freqs = freqs

    def have_reqs(self) -> bool:
        return any(self._freqs.values())

    def cross_feasible(self, snapshots: dict) -> bool:
        return self.engine._cross_feasible(self._freqs, snapshots)

    def kept_reqs(self, rank: int) -> list:
        return self._freqs.get(rank, [])

    def elig_tasks(self, rank: int) -> list:
        snap = self._snapshots[rank]
        planned = self.engine._planned_tasks
        tstamp = snap.get("task_stamp", snap.get("stamp", self._now))
        return [
            t for t in snap["tasks"]
            if planned.get((rank, t[0]), -1.0) < tstamp
        ]

    def maybe_imbalanced(self, engine, snapshots: dict) -> Optional[bool]:
        return None  # engine runs its own (identical) Python pre-check

    def parked_updates(self, now: float) -> Optional[list]:
        return None  # engine walks the snapshots itself (the twin loop)

    def view(self):
        return None  # no array view: solvers get the materialized dict

    def rows_resident(self) -> int:
        return 0


class _Srv:
    """One server's resident rows (requester + task columns)."""

    __slots__ = (
        "rank", "slot", "consumers",
        # requester side
        "reqs", "r_n", "r_stamp", "r_key", "r_rank", "r_seq", "r_any",
        "r_mask", "r_planned", "r_elig", "r_index", "r_dups", "r_unknown",
        "round_sup",
        # task side
        "tasks", "t_n", "t_stamp", "t_key", "t_seq", "t_tix", "t_prio",
        "t_planned", "t_elig", "t_index", "t_dups",
    )

    def __init__(self, rank: int, slot: int) -> None:
        self.rank = rank
        self.slot = slot
        self.consumers = 0
        self.reqs = []
        self.r_n = 0
        self.r_stamp = None
        self.r_key = None
        self.r_rank = _EMPTY_I8
        self.r_seq = _EMPTY_I8
        self.r_any = _EMPTY_B
        self.r_mask = None
        self.r_planned = _EMPTY_F8
        self.r_elig = _EMPTY_B
        self.r_index = {}
        self.r_dups = False
        self.r_unknown = False
        self.round_sup = _EMPTY_I8
        self.tasks = []
        self.t_n = 0
        self.t_stamp = None
        self.t_key = None
        self.t_seq = _EMPTY_I8
        self.t_tix = _EMPTY_I4
        self.t_prio = _EMPTY_I8
        self.t_planned = _EMPTY_F8
        self.t_elig = _EMPTY_B
        self.t_index = {}
        self.t_dups = False


_EMPTY_I8 = np.zeros(0, np.int64)
_EMPTY_I4 = np.zeros(0, np.int32)
_EMPTY_F8 = np.zeros(0, np.float64)
_EMPTY_B = np.zeros(0, bool)


class ArrayLedger:
    """The vectorized ledger — and the :class:`LedgerView` the solvers
    consume (one object, two roles: resident maintenance and packed
    exposure; the packed arrays ARE the resident state).

    Solver-facing surface (the "view"): ``servers`` (sorted live ranks),
    ``slot_order`` (their slots), ``pk_tp``/``pk_tt``/``pk_trefs``
    (per-slot [K] task rows, clipped int32 priorities / type indices /
    ``(rank, seqno)`` refs), ``pk_rv``/``pk_rm``/``pk_rrefs`` (per-slot
    [R] kept-requester rows), and per-slot generation counters
    ``t_gen``/``r_gen`` a stateful consumer diffs against.
    """

    is_array = True

    #: full rebuild cadence (belt-and-braces, mirroring the sharded
    #: solver's RESYNC_INTERVAL: the incremental path is exact by
    #: construction, and the resync bounds any drift a key-less
    #: in-place snapshot mutation could ever introduce)
    LEDGER_RESYNC_INTERVAL = 256

    def __init__(self, engine, types, max_tasks: int,
                 max_requesters: int, max_jobs: int = 1,
                 job_weights: Optional[dict] = None) -> None:
        self.engine = engine
        self.base_types = tuple(types)
        self.base_T = max(len(self.base_types), 1)
        self.max_jobs = max(int(max_jobs), 1)
        # composite (job, type) axis — the base types themselves when
        # single-job (exact back-compat); see balancer/jobdim.py
        self.types = expand_types(self.base_types, self.max_jobs)
        self.tix = {t: i for i, t in enumerate(self.types)}
        self.T = max(len(self.types), 1)
        self.job_bias = bias_vector(job_weights, self.max_jobs)
        self.K = max_tasks
        self.R = max_requesters
        self._srv: dict[int, _Srv] = {}
        self._free: list[int] = []
        self._cap = 0
        self._gen = 1
        self._rounds = 0
        self._round_token = 0
        self._order_stale = True
        self._order = np.zeros(0, np.int64)
        self.servers: list = []
        # repack-needed ranks (elig changed without a snapshot rebuild)
        self._stale_rq: set = set()
        self._stale_tk: set = set()
        self._sup_touched: set = set()
        self._round_kept = 0
        self._any_unknown_req = False
        self._unknown_n = 0
        self._parked: list = []
        # SnapshotStore consumption state: the store lineage plus the
        # version and membership version this ledger has fully absorbed
        self._seen_ver = 0
        self._seen_member_ver = None
        self._seen_lineage = None
        # ranks whose snapshots carry no stamp: re-derived every round
        # (the Python filter's "stamp defaults to now" semantics), so
        # the store fast path must visit them even when unchanged
        self._stampless: set = set()
        # membership generation for stateful view consumers (the
        # sharded solver's vectorized ingest): bumped whenever a slot
        # is taken or dropped, so a consumer can skip its own O(S)
        # membership walk on the (vastly common) no-churn round
        self.member_gen = 1
        # stats surfaced by bench / CI smoke / obs gauges
        self.patch_count = 0     # incremental per-server (re)builds
        self.resync_count = 0    # full rebuilds (cold + cadence)
        # why each full pass ran — "cadence" is the periodic safety
        # rebuild; store-backed rounds also classify "cold" (new store
        # lineage / first sync) and "membership" (join/drain/failover
        # moved member_ver). Steady state must show only cadence growth;
        # the engine mirrors these onto /metrics as ledger_resyncs.
        self.resync_reasons: dict = {"cadence": 0, "cold": 0,
                                     "membership": 0, "weights": 0}
        self.last_sync_us = 0.0
        # a pending forced full rebuild and its reason key (a weight
        # change re-biases every resident priority column)
        self._force_resync: Optional[str] = None
        self._alloc(16)

    def set_job_bias(self, job_weights: Optional[dict]) -> bool:
        """Install new per-job priority biases; a change forces a full
        rebuild at the next sync (every packed prio column embeds the
        bias). Returns True when the bias actually changed."""
        bias = bias_vector(job_weights, self.max_jobs)
        if bias == self.job_bias:
            return False
        self.job_bias = bias
        self._force_resync = "weights"
        return True

    # -- storage -----------------------------------------------------------

    def _alloc(self, cap: int) -> None:
        """(Re)allocate the global slot-indexed arrays to ``cap`` slots,
        preserving content.  Only runs at construction and on world
        growth — steady-state rounds never reallocate (guarded by
        tests/test_ledger_parity.py)."""
        T, K, R = self.T, self.K, self.R
        old = self._cap
        if old == 0:
            self.g_dem = np.zeros((cap, T), np.int64)
            self.g_any = np.zeros(cap, np.int64)
            self.g_eligreq = np.zeros(cap, np.int64)
            self.g_sup = np.zeros((cap, T), np.int64)
            self.g_taskcnt = np.zeros(cap, np.int64)
            self.g_eligtask = np.zeros(cap, np.int64)
            # twin of _only_planned_away: every listed task marked at or
            # after the task view (tstamp default 0.0, NOT now — the
            # Python check's exact default for stampless snapshots)
            self.g_planned_away = np.ones(cap, bool)
            self.g_hasreqs = np.zeros(cap, bool)
            self.g_consumers = np.zeros(cap, np.int64)
            self.pk_tp = np.full((cap, K), _NEG, np.int32)
            self.pk_tt = np.full((cap, K), -1, np.int32)
            self.pk_rv = np.zeros((cap, R), bool)
            self.pk_rm = np.zeros((cap, R, T), bool)
            self.t_gen = np.zeros(cap, np.int64)
            self.r_gen = np.zeros(cap, np.int64)
            self.slot_rank = np.full(cap, -1, np.int64)
            self.pk_trefs = [[None] * K for _ in range(cap)]
            self.pk_rrefs = [[None] * R for _ in range(cap)]
        else:
            for name, fill in (
                ("g_dem", 0), ("g_any", 0), ("g_eligreq", 0), ("g_sup", 0),
                ("g_taskcnt", 0), ("g_eligtask", 0),
                ("g_planned_away", True), ("g_hasreqs", False),
                ("g_consumers", 0), ("pk_tp", _NEG), ("pk_tt", -1),
                ("pk_rv", False), ("pk_rm", False), ("t_gen", 0),
                ("r_gen", 0), ("slot_rank", -1),
            ):
                a = getattr(self, name)
                n = np.full((cap,) + a.shape[1:], fill, a.dtype)
                n[:old] = a
                setattr(self, name, n)
            self.pk_trefs.extend([None] * self.K for _ in range(cap - old))
            self.pk_rrefs.extend([None] * self.R for _ in range(cap - old))
        self._free.extend(range(old, cap))
        self._cap = cap

    def _take_slot(self, rank: int) -> _Srv:
        if not self._free:
            self._alloc(self._cap * 2)
        srv = _Srv(rank, self._free.pop())
        self._srv[rank] = srv
        self.slot_rank[srv.slot] = rank
        self.member_gen += 1
        self._order_stale = True
        return srv

    def _drop(self, rank: int) -> None:
        srv = self._srv.pop(rank)
        s = srv.slot
        self.g_dem[s] = 0
        self.g_any[s] = 0
        self.g_eligreq[s] = 0
        self.g_sup[s] = 0
        self.g_taskcnt[s] = 0
        self.g_eligtask[s] = 0
        self.g_planned_away[s] = True
        self.g_hasreqs[s] = False
        self.g_consumers[s] = 0
        self.pk_tp[s] = _NEG
        self.pk_tt[s] = -1
        self.pk_rv[s] = False
        self.pk_rm[s] = False
        self.pk_trefs[s] = [None] * self.K
        self.pk_rrefs[s] = [None] * self.R
        self.t_gen[s] = self._bump()
        self.r_gen[s] = self._bump()
        self.slot_rank[s] = -1
        self.member_gen += 1
        self._free.append(s)
        self._order_stale = True
        self._stale_rq.discard(rank)
        self._stale_tk.discard(rank)
        self._sup_touched.discard(rank)
        self._stampless.discard(rank)
        if srv.r_unknown:
            self._unknown_n -= 1

    def _bump(self) -> int:
        self._gen += 1
        return self._gen

    # -- incremental sync --------------------------------------------------

    def sync(self, snapshots: dict, now: float) -> None:
        t0 = time.perf_counter()
        self._round_token = id(snapshots)
        self._rounds += 1
        resync = self._rounds % self.LEDGER_RESYNC_INTERVAL == 0
        reason = "cadence" if resync else self._force_resync
        if reason is not None:
            resync = True
            self._force_resync = None
            self.resync_count += 1
            self.resync_reasons[reason] = \
                self.resync_reasons.get(reason, 0) + 1
        ver = getattr(snapshots, "ver", None)
        if (
            ver is not None
            and not resync
            and getattr(snapshots, "lineage", None) == self._seen_lineage
            and snapshots.member_ver == self._seen_member_ver
        ):
            # store fast path — membership unchanged since the last
            # sync, so only the change log's tail (ranks whose store
            # version moved past our seen mark) plus the stampless set
            # (re-derived every round by contract) are visited. An idle
            # round touches nothing: O(changed), not O(servers).
            seen = self._seen_ver
            if ver != seen:
                log = snapshots._log
                done: set = set()
                for v, rank in log[bisect.bisect_left(log, (seen + 1,)):]:
                    if v > ver:
                        break  # appended after our fork was taken
                    if rank in done:
                        continue
                    done.add(rank)
                    snap = snapshots.get(rank)
                    if snap is not None:
                        self._sync_one(rank, snap, False, now)
                for rank in tuple(self._stampless):
                    if rank not in done and rank in snapshots:
                        self._sync_one(rank, snapshots[rank], False, now)
            elif self._stampless:
                for rank in tuple(self._stampless):
                    if rank in snapshots:
                        self._sync_one(rank, snapshots[rank], False, now)
            self._seen_ver = ver
        else:
            # full pass: plain dicts (unit tests, hand-built harnesses),
            # the cadence resync, and any store membership change (join,
            # drain, failover — the O(S) walk is paid only on churn)
            if ver is not None and not resync:
                if getattr(snapshots, "lineage", None) != self._seen_lineage:
                    self.resync_reasons["cold"] += 1
                else:
                    self.resync_reasons["membership"] += 1
            for rank, snap in snapshots.items():
                self._sync_one(rank, snap, resync, now)
            if len(self._srv) != len(snapshots):
                for rank in [r for r in self._srv if r not in snapshots]:
                    self._drop(rank)
            if ver is not None:
                self._seen_ver = ver
                self._seen_member_ver = snapshots.member_ver
                self._seen_lineage = getattr(snapshots, "lineage", None)
        if self._order_stale:
            self.servers = sorted(self._srv)
            self._order = np.fromiter(
                (self._srv[r].slot for r in self.servers), np.int64,
                len(self.servers),
            )
            self._order_stale = False
        self._any_unknown_req = self._unknown_n > 0
        self.last_sync_us = (time.perf_counter() - t0) * 1e6

    def _sync_one(self, rank: int, snap: dict, resync: bool,
                  now: float) -> None:
        srv = self._srv.get(rank)
        if srv is None:
            srv = self._take_slot(rank)
        # stampless snapshots re-derive every round (the Python
        # filter's "stamp defaults to now" semantics); the length
        # check catches a key-less in-place append (belt-and-braces
        # next to the resync cadence). Keys are compared component-
        # wise — this body is the per-rank compare floor, so no tuple
        # allocations on the unchanged fast path.
        stamp = snap.get("stamp")
        if (
            resync
            or stamp is None
            or srv.r_stamp != stamp
            or srv.r_key != snap.get("req_seq", 0)
            or srv.r_n != len(snap["reqs"])
        ):
            self._rebuild_reqs(srv, snap, stamp,
                               snap.get("req_seq", 0), now)
            self.patch_count += 1
        tstamp = snap.get("task_stamp", stamp)
        if (
            resync
            or tstamp is None
            or srv.t_stamp != tstamp
            or srv.t_key != snap.get("delta_seq", 0)
            or srv.t_n != len(snap["tasks"])
        ):
            self._rebuild_tasks(srv, snap, tstamp,
                                snap.get("delta_seq", 0), now)
            self.patch_count += 1
        c = snap.get("consumers", 0)
        if srv.consumers != c:
            srv.consumers = c
            self.g_consumers[srv.slot] = c
        if stamp is None or tstamp is None:
            self._stampless.add(rank)
        else:
            self._stampless.discard(rank)

    def _rebuild_reqs(self, srv: _Srv, snap: dict, stamp, rseq,
                      now: float) -> None:
        reqs = list(snap["reqs"])
        n = len(reqs)
        srv.reqs = reqs
        srv.r_n = n
        srv.r_stamp = stamp
        srv.r_key = rseq
        if n:
            # raw-park recency feed for the engine's _last_parked (the
            # pump's window-growth signal): a rank's park stamp can only
            # move when its snapshot rebuilt, so the engine applies
            # these O(changed) events instead of walking every server
            self._parked.append((srv.rank, stamp))
        T = self.T
        tix = self.tix
        planned = self.engine._planned_reqs
        rank = srv.rank
        r_rank = np.empty(n, np.int64)
        r_seq = np.empty(n, np.int64)
        r_any = np.zeros(n, bool)
        r_mask = np.zeros((n, T), bool)
        r_planned = np.empty(n, np.float64)
        index: dict = {}
        dups = unknown = False
        # NOTE: this types->mask packing is the view-producer twin of
        # the dict-path packers in solve.AssignmentSolver.solve and
        # distributed._pack_reqs (which silently drop unknown types;
        # here they flag r_unknown so cross_feasible can fall back
        # exactly). A change to req-type semantics must touch all
        # three — the parity fuzz pins them together. Multi-job: the
        # job column selects the composite (job, type) slots; any-type
        # reqs become full job-BLOCK masks (never r_any, so the
        # vectorized paths stay job-exact) and overflow namespaces get
        # an empty mask — present but never matched (jobdim.py).
        J = self.max_jobs
        T0 = self.base_T
        for i, r in enumerate(reqs):
            fr, sq, types = r[0], r[1], r[2]
            r_rank[i] = fr
            r_seq[i] = sq
            jb = (r[4] if len(r) > 4 else 0) if J > 1 else 0
            if J > 1 and not 0 <= jb < J:
                pass  # overflow job: qmstat-RFR fallback territory
            elif types is None:
                if J <= 1:
                    r_any[i] = True
                    r_mask[i, :] = True
                else:
                    r_mask[i, jb * T0:(jb + 1) * T0] = True
            else:
                for t in types:
                    ti = tix.get(t if J <= 1 else (jb, t))
                    if ti is None:
                        unknown = True
                    else:
                        r_mask[i, ti] = True
            if (fr, sq) in index:
                dups = True
            index[(fr, sq)] = i
            r_planned[i] = planned.get((rank, fr, sq), -1.0)
        srv.r_rank, srv.r_seq = r_rank, r_seq
        srv.r_any, srv.r_mask, srv.r_planned = r_any, r_mask, r_planned
        srv.r_index = index
        srv.r_dups = dups
        if unknown != srv.r_unknown:
            self._unknown_n += 1 if unknown else -1
        srv.r_unknown = unknown
        srv.r_elig = r_planned < (now if stamp is None else stamp)
        srv.round_sup = _EMPTY_I8
        self.g_hasreqs[srv.slot] = n > 0
        self._req_aggregate(srv)
        self._pack_reqs(srv)

    def _rebuild_tasks(self, srv: _Srv, snap: dict, tstamp, tseq,
                       now: float) -> None:
        tasks = list(snap["tasks"])
        n = len(tasks)
        srv.tasks = tasks
        srv.t_n = n
        srv.t_stamp = tstamp
        srv.t_key = tseq
        tix = self.tix
        planned = self.engine._planned_tasks
        rank = srv.rank
        t_seq = np.empty(n, np.int64)
        t_tix = np.empty(n, np.int32)
        t_prio = np.empty(n, np.int64)
        t_planned = np.empty(n, np.float64)
        index: dict = {}
        dups = False
        J = self.max_jobs
        bias = self.job_bias
        nb = len(bias)
        for i, t in enumerate(tasks):
            sq = t[0]
            t_seq[i] = sq
            jb = (t[4] if len(t) > 4 else 0) if J > 1 else 0
            t_tix[i] = tix.get(t[1] if J <= 1 else (jb, t[1]), -1)
            # weight bias folds into the clipped prio at pack time —
            # identically in every packer twin (jobdim.weight_bias
            # keeps the sum int32-safe and above the _NEG sentinel)
            b = bias[jb] if 0 <= jb < nb else 0
            t_prio[i] = max(-_PRIO_CLIP, min(_PRIO_CLIP, t[2])) + b
            if sq in index:
                dups = True
            index[sq] = i
            t_planned[i] = planned.get((rank, sq), -1.0)
        srv.t_seq, srv.t_tix, srv.t_prio = t_seq, t_tix, t_prio
        srv.t_planned = t_planned
        srv.t_index = index
        srv.t_dups = dups
        srv.t_elig = t_planned < (now if tstamp is None else tstamp)
        s = srv.slot
        self.g_taskcnt[s] = n
        known = t_tix[t_tix >= 0]
        self.g_sup[s] = np.bincount(known, minlength=self.T) if known.size \
            else 0
        self.g_eligtask[s] = int(srv.t_elig.sum())
        self.g_planned_away[s] = self._task_away(srv)
        self._pack_tasks(srv)

    def _task_away(self, srv: _Srv) -> bool:
        """Twin of ``PlanEngine._only_planned_away``: tstamp defaults to
        0.0 (not now) for stampless snapshots, exactly like the Python
        check it mirrors."""
        if srv.t_n == 0:
            return True
        ref = srv.t_stamp if srv.t_stamp is not None else 0.0
        return bool((srv.t_planned >= ref).all())

    def _req_aggregate(self, srv: _Srv) -> None:
        s = srv.slot
        e = srv.r_elig
        self.g_eligreq[s] = int(e.sum())
        self.g_any[s] = int((e & srv.r_any).sum())
        te = e & ~srv.r_any
        self.g_dem[s] = srv.r_mask[te].sum(0) if te.any() else 0

    # -- plan-mark hooks (fed by the engine's _Marks dicts) ----------------

    def on_req_mark(self, key, value=None) -> None:
        srv = self._srv.get(key[0])
        if srv is None:
            return
        if srv.r_dups:
            # ambiguous row mapping: re-derive the whole column (rare —
            # duplicate (rank, rqseqno) keys in one snapshot)
            self._recompute_req_planned(srv)
            return
        row = srv.r_index.get((key[1], key[2]))
        if row is None:
            return
        v = self.engine._planned_reqs.get(key, -1.0)
        srv.r_planned[row] = v
        stamp = srv.r_stamp
        elig = True if stamp is None else bool(v < stamp)
        if elig != bool(srv.r_elig[row]):
            srv.r_elig[row] = elig
            self._req_aggregate(srv)
            self._stale_rq.add(srv.rank)

    def on_task_mark(self, key, value=None) -> None:
        srv = self._srv.get(key[0])
        if srv is None:
            return
        if srv.t_dups:
            self._recompute_task_planned(srv)
            return
        row = srv.t_index.get(key[1])
        if row is None:
            return
        v = self.engine._planned_tasks.get(key, -1.0)
        srv.t_planned[row] = v
        tstamp = srv.t_stamp
        elig = True if tstamp is None else bool(v < tstamp)
        if elig != bool(srv.t_elig[row]):
            srv.t_elig[row] = elig
            self.g_eligtask[srv.slot] = int(srv.t_elig.sum())
            self._stale_tk.add(srv.rank)
        self.g_planned_away[srv.slot] = self._task_away(srv)

    def _recompute_req_planned(self, srv: _Srv) -> None:
        planned = self.engine._planned_reqs
        rank = srv.rank
        for i, r in enumerate(srv.reqs):
            srv.r_planned[i] = planned.get((rank, r[0], r[1]), -1.0)
        stamp = srv.r_stamp
        srv.r_elig = (
            np.ones(srv.r_n, bool) if stamp is None
            else srv.r_planned < stamp
        )
        self._req_aggregate(srv)
        self._stale_rq.add(rank)

    def _recompute_task_planned(self, srv: _Srv) -> None:
        planned = self.engine._planned_tasks
        rank = srv.rank
        for i, t in enumerate(srv.tasks):
            srv.t_planned[i] = planned.get((rank, t[0]), -1.0)
        tstamp = srv.t_stamp
        srv.t_elig = (
            np.ones(srv.t_n, bool) if tstamp is None
            else srv.t_planned < tstamp
        )
        self.g_eligtask[srv.slot] = int(srv.t_elig.sum())
        self.g_planned_away[srv.slot] = self._task_away(srv)
        self._stale_tk.add(rank)

    # -- per-round admission ----------------------------------------------

    def filter_reqs(self, snapshots: dict, sup: dict, now: float) -> None:
        """Round-scoped credit suppression over the resident eligibility
        columns.  Only ranks with live young credits are touched — the
        steady state (no migrations in flight) costs nothing here."""
        kept = int(self.g_eligreq[self._order].sum())
        touched = set()
        for rank, (fed, budget) in sup.items():
            srv = self._srv.get(rank)
            if srv is None:
                continue
            touched.add(rank)
            if srv.r_unknown or any(t not in self.tix for t in fed):
                # unknown types on either side: exact per-rank Python
                # fallback (never happens with world-typed traffic)
                rows = self._py_sup_rows(srv, fed, budget)
            else:
                fed_ix = [self.tix[t] for t in fed]
                match = srv.r_elig & (
                    srv.r_any | srv.r_mask[:, fed_ix].any(1)
                )
                rows = np.flatnonzero(match)[:budget]
            if rows.size or srv.round_sup.size:
                if not np.array_equal(rows, srv.round_sup):
                    srv.round_sup = np.asarray(rows, np.int64)
                    self._stale_rq.add(rank)
            kept -= int(len(rows))
        # ranks whose suppression lapsed must repack without it
        for rank in self._sup_touched - touched:
            srv = self._srv.get(rank)
            if srv is not None and srv.round_sup.size:
                srv.round_sup = _EMPTY_I8
                self._stale_rq.add(rank)
        self._sup_touched = touched
        self._round_kept = kept

    def _py_sup_rows(self, srv: _Srv, fed, budget: int) -> np.ndarray:
        rows = []
        for i, r in enumerate(srv.reqs):
            if not srv.r_elig[i]:
                continue
            if budget > 0 and (r[2] is None or not fed.isdisjoint(r[2])):
                rows.append(i)
                budget -= 1
        return np.asarray(rows, np.int64)

    def have_reqs(self) -> bool:
        return self._round_kept > 0

    def cross_feasible(self, snapshots: dict) -> bool:
        """Vectorized twin of ``PlanEngine._cross_feasible`` over the
        maintained [S, T] aggregates (raw supply vs kept demand)."""
        if self._any_unknown_req:
            # exact fallback: materialize kept lists (rare; unit tests
            # with off-world types only)
            freqs = {r: self.kept_reqs(r) for r in snapshots}
            return self.engine._cross_feasible(freqs, snapshots)
        act = self._order
        if act.size == 0:
            return False
        D = self.g_dem[act] > 0            # [S, T] typed-demand homes
        anyh = self.g_any[act] > 0         # [S] any-type demand homes
        for rank in self._sup_touched:
            srv = self._srv.get(rank)
            if srv is None or not srv.round_sup.size:
                continue
            si = self.servers.index(rank)
            kept = srv.r_elig.copy()
            kept[srv.round_sup] = False
            anyh[si] = bool((kept & srv.r_any).any())
            te = kept & ~srv.r_any
            D[si] = srv.r_mask[te].any(0) if te.any() else False
        taskcnt = self.g_taskcnt[act]
        n_any = int(anyh.sum())
        if n_any:
            total = int(taskcnt.sum())
            if n_any > 1:
                if total > 0:
                    return True
            elif total - int(taskcnt[int(np.argmax(anyh))]) > 0:
                return True
        nd = D.sum(0)                      # [T] demand-home counts
        H = self.g_sup[act] > 0            # [S, T] supply homes
        ns = H.sum(0)
        feas = (nd > 1) & (ns > 0)
        single = nd == 1
        if single.any():
            sole = D.argmax(0)             # sole demand home per type
            feas |= single & (
                (ns - H[sole, np.arange(self.T)].astype(np.int64)) > 0
            )
        return bool(feas.any())

    def maybe_imbalanced(self, engine, snapshots: dict) -> Optional[bool]:
        """Vectorized twin of ``PlanEngine._maybe_imbalanced`` over the
        resident aggregate columns.  Returns None when the ledger is not
        synced with these snapshots (direct unit-test calls) so the
        engine falls back to the Python pre-check."""
        if self._round_token != id(snapshots) or len(self._srv) != len(
                snapshots):
            return None
        act = self._order
        cons = self.g_consumers[act]
        total_c = int(cons.sum())
        if total_c == 0:
            return False
        raw = self.g_taskcnt[act]
        total = int(raw.sum())
        if total < total_c:
            if total == 0 or int(raw.max()) <= engine.CONC_FRAC * total:
                return False
            starved = (
                (cons > 0)
                & self.g_hasreqs[act]
                & ((raw == 0) | self.g_planned_away[act])
            )
            return bool(starved.any())
        look = engine._look
        win = np.full(act.size, float(engine.LOOKAHEAD))
        if look:
            for i, rank in enumerate(self.servers):
                w = look.get(rank)
                if w is not None:
                    win[i] = w
        share = -(-(total * cons) // total_c)
        need = np.minimum(share, win.astype(np.int64) * cons)
        return bool(((cons > 0) & (2 * raw < need)).any())

    # -- materialization (legacy dict path: pump rounds, py solvers) -------

    def kept_reqs(self, rank: int) -> list:
        srv = self._srv[rank]
        idx = np.flatnonzero(srv.r_elig)
        if srv.round_sup.size:
            idx = np.setdiff1d(idx, srv.round_sup, assume_unique=True)
        reqs = srv.reqs
        return [reqs[i] for i in idx.tolist()]

    def elig_tasks(self, rank: int) -> list:
        srv = self._srv[rank]
        tasks = srv.tasks
        return [tasks[i] for i in np.flatnonzero(srv.t_elig).tolist()]

    # -- solver view -------------------------------------------------------

    def _pack_tasks(self, srv: _Srv) -> None:
        s = srv.slot
        K = self.K
        kidx = np.flatnonzero(srv.t_elig)[:K]
        k = kidx.size
        self.pk_tp[s, :] = _NEG
        self.pk_tt[s, :] = -1
        if k:
            self.pk_tp[s, :k] = srv.t_prio[kidx]
            self.pk_tt[s, :k] = srv.t_tix[kidx]
        refs = self.pk_trefs[s]
        rank = srv.rank
        seqs = srv.t_seq
        for i in range(K):
            refs[i] = (rank, int(seqs[kidx[i]])) if i < k else None
        self.t_gen[s] = self._bump()

    def _pack_reqs(self, srv: _Srv) -> None:
        s = srv.slot
        R = self.R
        idx = np.flatnonzero(srv.r_elig)
        if srv.round_sup.size:
            idx = np.setdiff1d(idx, srv.round_sup, assume_unique=True)
        idx = idx[:R]
        k = idx.size
        self.pk_rv[s, :] = False
        self.pk_rm[s, :, :] = False
        if k:
            self.pk_rv[s, :k] = True
            self.pk_rm[s, :k, :] = srv.r_mask[idx]
        refs = self.pk_rrefs[s]
        rank = srv.rank
        rr, rs = srv.r_rank, srv.r_seq
        ilist = idx.tolist()
        for i in range(R):
            refs[i] = (
                (rank, int(rr[ilist[i]]), int(rs[ilist[i]]))
                if i < k else None
            )
        self.r_gen[s] = self._bump()

    def view(self) -> "ArrayLedger":
        """Freshen the packed rows of every server whose eligibility or
        suppression changed since the last view, then hand out the
        resident arrays (self doubles as the view object)."""
        for rank in self._stale_tk:
            srv = self._srv.get(rank)
            if srv is not None:
                self._pack_tasks(srv)
        for rank in self._stale_rq:
            srv = self._srv.get(rank)
            if srv is not None:
                self._pack_reqs(srv)
        self._stale_tk.clear()
        self._stale_rq.clear()
        return self

    @property
    def slot_order(self) -> np.ndarray:
        return self._order

    def slot_of(self, rank: int) -> int:
        return self._srv[rank].slot

    def t_gen_of(self, rank: int) -> int:
        return int(self.t_gen[self._srv[rank].slot])

    def r_gen_of(self, rank: int) -> int:
        return int(self.r_gen[self._srv[rank].slot])

    def parked_updates(self, now: float) -> list:
        """Drain the (rank, stamp) park events of this sync (stampless
        snapshots report the round's now, like the Python loop they
        replace)."""
        out = [
            (r, s if s is not None else now) for r, s in self._parked
        ]
        self._parked.clear()
        return out

    def rows_resident(self) -> int:
        return sum(s.r_n + s.t_n for s in self._srv.values())
