"""The batched global assignment solve.

Inputs are fixed-shape tensors (S servers x K tasks, S x R requesters, T
types) so the jitted computation never recompiles; variable-size queue state
is truncated on the host side (highest priorities first) and anything that
does not fit is simply handled next round — staleness is already part of the
protocol contract (plan entries are validated against live state at
enactment, like the reference's push/RFR races, ``src/adlb.c:2182-2192``).

Algorithm (single device): exact sequential greedy under ``lax.scan`` — tasks
in descending priority order (stable, so FIFO on ties, matching the
reference's algebraically-largest-``work_prio`` + seqno contract), each
taking the first open compatible requester. One scan step is O(NR) vector
work; the whole solve is one fused loop on device. This is exactly the
matching the reference's per-server ``wq_find_hi_prio`` loop would produce if
it could see every server's queue at once (reference ``src/xq.c:190-247``) —
which is the point: same semantics, global scope, O(1) staleness.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from adlb_tpu.balancer.jobdim import bias_vector, expand_types

# Sentinel far below any real priority (int32-safe; real priorities are
# clipped to +/-1e9, reference priorities are C ints). A plain int, NOT a
# jnp scalar: materializing a device array at import would initialize the
# accelerator backend for every importer, including ones that only ever use
# the numpy host path (and a wedged accelerator tunnel would hang them).
_NEG = -(2**31) + 1
_PRIO_CLIP = 10**9
_I32MAX = 2**31 - 1


def _stable_argsort2(primary, secondary):
    """argsort by (primary asc, secondary asc, index asc) — the
    lexsort((secondary, primary)) order — composed from two single-key
    stable sorts (XLA's variadic comparator sort is ~10x slower on CPU
    hosts than its single-key fast path).  Shared by the sharded
    candidate generation and the on-device auction
    (balancer/distributed.py)."""
    o1 = jnp.argsort(secondary, stable=True)
    o2 = jnp.argsort(primary[o1], stable=True)
    return o1[o2]


def _stable_argsort3(primary, secondary, tertiary):
    """argsort by (primary asc, secondary asc, tertiary asc) from three
    composed single-key stable sorts — innermost key first."""
    o = jnp.argsort(tertiary, stable=True)
    o = o[jnp.argsort(secondary[o], stable=True)]
    return o[jnp.argsort(primary[o], stable=True)]


@jax.jit
def _greedy_assign(
    task_prio: jax.Array,  # [NT] int32, _NEG for padding
    task_type: jax.Array,  # [NT] int32 type *index*, -1 for padding
    req_mask: jax.Array,  # [NR, T] bool: requester accepts type index
    req_valid: jax.Array,  # [NR] bool
) -> jax.Array:
    """Returns assign[NR] int32: task index assigned to each requester, -1 if none."""
    NT = task_prio.shape[0]
    NR = req_mask.shape[0]
    ridx = jnp.arange(NR, dtype=jnp.int32)

    # descending priority, stable (ties resolve to lower task index = seqno)
    order = jnp.argsort(-task_prio, stable=True)

    def step(open_req, t_idx):
        prio = task_prio[t_idx]
        ttype = task_type[t_idx]
        compat = (
            open_req
            & req_valid
            & (prio > _NEG)
            & (ttype >= 0)
            & req_mask[:, jnp.clip(ttype, 0)]
        )
        r = jnp.argmax(compat)  # first open compatible requester
        found = compat[r]
        open_req = open_req & ~(found & (ridx == r))
        return open_req, jnp.where(found, r.astype(jnp.int32), jnp.int32(-1))

    open0 = jnp.ones((NR,), dtype=bool)
    _, winner_per_task = jax.lax.scan(step, open0, order)
    # invert: winner_per_task[k] is the requester chosen for task order[k]
    # (-1 = none). Requesters win at most once, so the scatter is 1-1.
    valid = winner_per_task >= 0
    assign = jnp.full((NR,), -1, dtype=jnp.int32)
    assign = assign.at[jnp.where(valid, winner_per_task, NR)].set(
        jnp.where(valid, order.astype(jnp.int32), -1), mode="drop"
    )
    return assign


def _auction_assign(task_prio, task_type, req_mask, req_valid, rounds=6):
    """Back-compat alias (the greedy scan superseded the bid auction, which
    converged one-task-per-type-per-round under crowding)."""
    del rounds
    return _greedy_assign(task_prio, task_type, req_mask, req_valid)


def _host_greedy(task_prio, task_type, req_mask, req_valid):
    """Numpy twin of :func:`_greedy_assign` — bit-identical semantics, used
    below a size threshold where an accelerator dispatch round-trip costs
    more than the whole solve.

    Considers only tasks whose type some open requester accepts (tasks of
    other types can never match, so skipping them cannot change the greedy
    outcome) and early-exits once every requester is matched — so a round
    where the only parked requester wants a type with no queued inventory
    (gfmc's answer collector) costs one vectorized mask, not a scan."""
    NR = req_mask.shape[0]
    assign = np.full((NR,), -1, dtype=np.int32)
    open_req = req_valid.copy()
    n_open = int(open_req.sum())
    if n_open == 0:
        return assign
    wanted = req_mask[open_req].any(axis=0)  # [T]
    live = (task_prio > int(_NEG)) & (task_type >= 0)
    live &= wanted[np.clip(task_type, 0, None)]
    cand = np.nonzero(live)[0]
    if cand.size == 0:
        return assign
    order = cand[np.argsort(-task_prio[cand], kind="stable")]
    for t in order:
        tt = task_type[t]
        compat = open_req & req_mask[:, tt]
        r = int(np.argmax(compat))
        if not compat[r]:
            continue
        assign[r] = t
        open_req[r] = False
        n_open -= 1
        if n_open == 0:
            break
    return assign


class AssignmentSolver:
    """Host-side wrapper: packs per-server snapshots into fixed-shape arrays,
    runs the greedy solve, unpacks plan entries.

    Adaptive placement: instances with few live requesters run the numpy twin
    on the host (an accelerator dispatch round-trip would dominate); larger
    instances run the jitted scan on device. Both produce the identical
    matching (same greedy order), so the threshold is purely a latency
    knob.

    ``solve`` also accepts the engine's array-resident host ledger (a
    :class:`adlb_tpu.balancer.ledger.ArrayLedger` view) in place of the
    snapshot dict: the packed kept-requester / eligible-task rows are
    consumed directly — no per-row tuple walk — and the matching is
    identical to the dict path (fuzz-proven by tests/test_ledger_parity)."""

    #: the engine may hand solve() a LedgerView instead of a snapshot dict
    SUPPORTS_VIEW = True

    def __init__(
        self, types: Sequence[int], max_tasks: int, max_requesters: int,
        rounds: int = 6, host_threshold_reqs: Optional[int] = 64,
        backend: str = "xla", max_jobs: int = 1,
        job_weights: Optional[dict] = None,
    ) -> None:
        """backend: "xla" = the jitted lax.scan greedy; "pallas" = the
        VMEM-resident Pallas sweep kernel (adlb_tpu.balancer.pallas_solve),
        interpreted off-TPU; "auto" = pallas on a real TPU backend (where it
        measures ~4x faster than the scan at S*K=1024), xla elsewhere (the
        interpreted kernel is too slow to be the default on CPU). All
        backends produce the identical matching. "auto" is resolved lazily
        at the first device solve — probing jax.default_backend() here would
        initialize the accelerator for hosts whose every solve stays on the
        numpy path (and would run outside the balancer thread's
        error-recovery loop)."""
        if backend not in ("auto", "xla", "pallas"):
            raise ValueError(f"unknown solver backend {backend!r}")
        self.base_types = tuple(types)
        self.base_T = max(len(self.base_types), 1)
        self.max_jobs = max(int(max_jobs), 1)
        # composite (job, type) axis under multi-job planning — the
        # base types verbatim when single-job (balancer/jobdim.py)
        self.types = expand_types(self.base_types, self.max_jobs)
        self.job_bias = bias_vector(job_weights, self.max_jobs)
        self.type_index = {t: i for i, t in enumerate(self.types)}
        self.K = max_tasks
        self.R = max_requesters
        self.rounds = rounds
        self.host_threshold_reqs = host_threshold_reqs
        self.backend = backend
        self._device_fn = None  # lazily resolved (pallas import is deferred)
        self.solve_count = 0
        self.host_solve_count = 0

    def set_job_bias(self, job_weights: Optional[dict]) -> bool:
        """Install new fair-share biases for the dict-path packers (the
        view path inherits the ledger's — the engine keeps both in
        step). Returns True when the bias changed."""
        bias = bias_vector(job_weights, self.max_jobs)
        if bias == self.job_bias:
            return False
        self.job_bias = bias
        return True

    def _device_assign(self):
        if self._device_fn is None:
            backend = self.backend
            if backend == "auto":
                backend = "pallas" if jax.default_backend() == "tpu" else "xla"
            if backend == "pallas":
                from adlb_tpu.balancer.pallas_solve import make_pallas_assign

                self._device_fn = make_pallas_assign()
            else:
                self._device_fn = _greedy_assign
        return self._device_fn

    def solve(self, snapshots, world) -> list:
        """snapshots: server_rank -> {"tasks": [(seqno, type, prio, len)...],
        "reqs": [(rank, rqseqno, req_types|None)...]} — or an
        ArrayLedger view (see class docstring).

        Returns [(holder_server, seqno, req_home_server, for_rank, rqseqno)].
        """
        if getattr(snapshots, "is_array", False):
            return self._solve_view(snapshots)
        servers = sorted(snapshots)
        S, K, R, T = len(servers), self.K, self.R, len(self.types)
        if S == 0:
            return []
        req_mask = np.zeros((S * R, T), dtype=bool)
        req_valid = np.zeros((S * R,), dtype=bool)
        req_ref: list = [None] * (S * R)
        J, T0 = self.max_jobs, self.base_T
        for si, s in enumerate(servers):
            # req tuples are (rank, rqseqno, types) — a 4th element
            # (fused-reserve flag, consumed by the plan-match sender)
            # may ride along since the remote-fused-fetch change, and a
            # 5th (job) since multi-job planning. Job handling is the
            # exact twin of ledger._rebuild_reqs: any-type becomes a
            # job-block mask, overflow jobs pack an empty mask.
            for ri, req in enumerate(snapshots[s]["reqs"][:R]):
                rank, rqseqno, req_types = req[0], req[1], req[2]
                jb = (req[4] if len(req) > 4 else 0) if J > 1 else 0
                i = si * R + ri
                req_valid[i] = True
                if J > 1 and not 0 <= jb < J:
                    pass  # overflow job: planner-invisible
                elif req_types is None:
                    if J <= 1:
                        req_mask[i, :] = True
                    else:
                        req_mask[i, jb * T0:(jb + 1) * T0] = True
                else:
                    for t in req_types:
                        ti = self.type_index.get(t if J <= 1 else (jb, t))
                        if ti is not None:
                            req_mask[i, ti] = True
                req_ref[i] = (s, rank, rqseqno)
        n_reqs = int(req_valid.sum())
        if n_reqs == 0:
            return []

        host = (
            self.host_threshold_reqs is not None
            and n_reqs <= self.host_threshold_reqs
        )
        if host:
            # pack only tasks of a type some requester wants: others can
            # never match, and skipping them up front keeps the per-round
            # host cost proportional to useful work, not queue depth
            wanted = req_mask[req_valid].any(axis=0)  # [T]
            prios: list = []
            ttypes: list = []
            task_ref = []
            bias, nb = self.job_bias, len(self.job_bias)
            for si, s in enumerate(servers):
                for tk in snapshots[s]["tasks"][:K]:
                    seqno, wtype, prio = tk[0], tk[1], tk[2]
                    jb = (tk[4] if len(tk) > 4 else 0) if J > 1 else 0
                    ti = self.type_index.get(
                        wtype if J <= 1 else (jb, wtype), -1)
                    if ti < 0 or not wanted[ti]:
                        continue
                    b = bias[jb] if 0 <= jb < nb else 0
                    prios.append(
                        max(-_PRIO_CLIP, min(_PRIO_CLIP, prio)) + b)
                    ttypes.append(ti)
                    task_ref.append((s, seqno))
            if not task_ref:
                return []
            task_prio = np.asarray(prios, dtype=np.int32)
            task_type = np.asarray(ttypes, dtype=np.int32)
            assign = _host_greedy(task_prio, task_type, req_mask, req_valid)
            self.host_solve_count += 1
        else:
            task_prio = np.full((S * K,), int(_NEG), dtype=np.int32)
            task_type = np.full((S * K,), -1, dtype=np.int32)
            task_ref = [None] * (S * K)
            bias, nb = self.job_bias, len(self.job_bias)
            for si, s in enumerate(servers):
                for ki, tk in enumerate(snapshots[s]["tasks"][:K]):
                    seqno, wtype, prio = tk[0], tk[1], tk[2]
                    jb = (tk[4] if len(tk) > 4 else 0) if J > 1 else 0
                    i = si * K + ki
                    b = bias[jb] if 0 <= jb < nb else 0
                    task_prio[i] = \
                        max(-_PRIO_CLIP, min(_PRIO_CLIP, prio)) + b
                    task_type[i] = self.type_index.get(
                        wtype if J <= 1 else (jb, wtype), -1)
                    task_ref[i] = (s, seqno)
            if (task_type < 0).all():
                return []
            assign = np.asarray(
                self._device_assign()(
                    jnp.asarray(task_prio),
                    jnp.asarray(task_type),
                    jnp.asarray(req_mask),
                    jnp.asarray(req_valid),
                )
            )
        self.solve_count += 1

        pairs = []
        for i, t in enumerate(assign):
            if t < 0 or req_ref[i] is None or task_ref[t] is None:
                continue
            holder, seqno = task_ref[t]
            req_home, for_rank, rqseqno = req_ref[i]
            pairs.append((holder, seqno, req_home, for_rank, rqseqno))
        return pairs

    def _solve_view(self, view) -> list:
        """The array-ledger fast path: identical greedy matching over the
        ledger's packed per-server rows (kept requesters truncated [:R],
        eligible tasks [:K], sorted-server row order — exactly the dict
        packer's layout), with no per-row Python walk."""
        K, R, T = self.K, self.R, len(self.types)
        # the ledger is built from the same engine Config; the row
        # layouts must agree or refs would misindex
        assert (view.K, view.R, tuple(view.types)) == (K, R, self.types)
        slots = view.slot_order
        S = slots.size
        if S == 0:
            return []
        req_valid = view.pk_rv[slots].reshape(-1)
        n_reqs = int(req_valid.sum())
        if n_reqs == 0:
            return []
        req_mask = view.pk_rm[slots].reshape(S * R, T)
        task_prio = view.pk_tp[slots].reshape(-1)
        task_type = view.pk_tt[slots].reshape(-1)
        host = (
            self.host_threshold_reqs is not None
            and n_reqs <= self.host_threshold_reqs
        )
        if host:
            # _host_greedy's internal wanted/live filter makes the
            # compacted pre-pack of the dict path unnecessary: same
            # candidates, same stable order, same matching
            assign = _host_greedy(task_prio, task_type, req_mask, req_valid)
            self.host_solve_count += 1
            if not (assign >= 0).any():
                return []
        else:
            if (task_type < 0).all():
                return []
            assign = np.asarray(
                self._device_assign()(
                    jnp.asarray(task_prio),
                    jnp.asarray(task_type),
                    jnp.asarray(req_mask),
                    jnp.asarray(req_valid),
                )
            )
        self.solve_count += 1
        pairs = []
        slot_list = slots.tolist()
        trefs, rrefs = view.pk_trefs, view.pk_rrefs
        for i in np.flatnonzero(assign >= 0).tolist():
            t = int(assign[i])
            tref = trefs[slot_list[t // K]][t % K]
            rref = rrefs[slot_list[i // R]][i % R]
            if tref is None or rref is None:
                continue
            holder, seqno = tref
            req_home, for_rank, rqseqno = rref
            pairs.append((holder, seqno, req_home, for_rank, rqseqno))
        return pairs
