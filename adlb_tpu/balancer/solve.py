"""The batched global assignment solve.

Inputs are fixed-shape tensors (S servers x K tasks, S x R requesters, T
types) so the jitted computation never recompiles; variable-size queue state
is truncated on the host side (highest priorities first) and anything that
does not fit is simply handled next round — staleness is already part of the
protocol contract (plan entries are validated against live state at
enactment, like the reference's push/RFR races, ``src/adlb.c:2182-2192``).

Algorithm: synchronous auction rounds, the classic parallelizable relaxation
of bipartite matching (Bertsekas). Each round, every unassigned requester
bids for its best compatible unassigned task (priority-ordered, matching the
reference's algebraically-largest-``work_prio`` contract); ties are broken by
requester index via a scatter-min, winners are committed, and the round
repeats. Every round commits at least one assignment, and in practice almost
everything lands in the first rounds, so a small fixed round count suffices
for the fixed shapes involved.
"""

from __future__ import annotations

import functools
from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp

# Sentinel far below any real priority (int32-safe; real priorities are
# clipped to +/-1e9, reference priorities are C ints).
_NEG = jnp.int32(-(2**31) + 1)
_PRIO_CLIP = 10**9


@functools.partial(jax.jit, static_argnames=("rounds",))
def _auction_assign(
    task_prio: jax.Array,  # [NT] int32, _NEG for padding
    task_type: jax.Array,  # [NT] int32 type *index*, -1 for padding
    req_mask: jax.Array,  # [NR, T] bool: requester accepts type index
    req_valid: jax.Array,  # [NR] bool
    rounds: int = 6,
) -> jax.Array:
    """Returns assign[NR] int32: task index assigned to each requester, -1 if none."""
    NT = task_prio.shape[0]
    NR = req_mask.shape[0]

    # [NR, NT] compatibility: requester r accepts task t's type
    compat = jnp.where(
        (task_type[None, :] >= 0) & req_valid[:, None],
        jnp.take_along_axis(
            req_mask, jnp.clip(task_type, 0)[None, :].repeat(NR, 0), axis=1
        ),
        False,
    )

    def one_round(state, _):
        assign, task_taken = state
        open_req = (assign < 0) & req_valid
        open_task = ~task_taken
        # score[r, t]: priority if biddable else sentinel
        score = jnp.where(
            compat & open_req[:, None] & open_task[None, :],
            task_prio[None, :],
            _NEG,
        )
        best_task = jnp.argmax(score, axis=1)  # [NR]
        best_score = jnp.max(score, axis=1)
        bidding = best_score > _NEG
        # conflict resolution: lowest requester index wins each task
        ridx = jnp.arange(NR, dtype=jnp.int32)
        bids = jnp.where(bidding, ridx, jnp.int32(NR))
        winner = (
            jnp.full((NT,), NR, dtype=jnp.int32)
            .at[jnp.where(bidding, best_task, 0)]
            .min(jnp.where(bidding, bids, jnp.int32(NR)))
        )
        won = bidding & (winner[best_task] == ridx)
        assign = jnp.where(won, best_task.astype(jnp.int32), assign)
        task_taken = task_taken.at[jnp.where(won, best_task, NT)].set(
            True, mode="drop"
        )
        return (assign, task_taken), None

    assign0 = jnp.full((NR,), -1, dtype=jnp.int32)
    taken0 = jnp.zeros((NT,), dtype=bool)
    (assign, _), _ = jax.lax.scan(one_round, (assign0, taken0), None, length=rounds)
    return assign


class AssignmentSolver:
    """Host-side wrapper: packs per-server snapshots into fixed-shape arrays,
    runs the jitted auction, unpacks plan entries."""

    def __init__(
        self, types: Sequence[int], max_tasks: int, max_requesters: int,
        rounds: int = 6,
    ) -> None:
        self.types = tuple(types)
        self.type_index = {t: i for i, t in enumerate(self.types)}
        self.K = max_tasks
        self.R = max_requesters
        self.rounds = rounds
        self.solve_count = 0

    def solve(self, snapshots: dict, world) -> list:
        """snapshots: server_rank -> {"tasks": [(seqno, type, prio, len)...],
        "reqs": [(rank, rqseqno, req_types|None)...]}.

        Returns [(holder_server, seqno, req_home_server, for_rank, rqseqno)].
        """
        servers = sorted(snapshots)
        S, K, R, T = len(servers), self.K, self.R, len(self.types)
        if S == 0:
            return []
        task_prio = np.full((S * K,), int(_NEG), dtype=np.int32)
        task_type = np.full((S * K,), -1, dtype=np.int32)
        task_ref: list = [None] * (S * K)
        req_mask = np.zeros((S * R, T), dtype=bool)
        req_valid = np.zeros((S * R,), dtype=bool)
        req_ref: list = [None] * (S * R)

        for si, s in enumerate(servers):
            snap = snapshots[s]
            for ki, (seqno, wtype, prio, _len) in enumerate(snap["tasks"][:K]):
                i = si * K + ki
                task_prio[i] = max(-_PRIO_CLIP, min(_PRIO_CLIP, prio))
                task_type[i] = self.type_index.get(wtype, -1)
                task_ref[i] = (s, seqno)
            for ri, (rank, rqseqno, req_types) in enumerate(snap["reqs"][:R]):
                i = si * R + ri
                req_valid[i] = True
                if req_types is None:
                    req_mask[i, :] = True
                else:
                    for t in req_types:
                        ti = self.type_index.get(t)
                        if ti is not None:
                            req_mask[i, ti] = True
                req_ref[i] = (s, rank, rqseqno)

        if not req_valid.any() or (task_type < 0).all():
            return []

        assign = np.asarray(
            _auction_assign(
                jnp.asarray(task_prio),
                jnp.asarray(task_type),
                jnp.asarray(req_mask),
                jnp.asarray(req_valid),
                rounds=self.rounds,
            )
        )
        self.solve_count += 1

        pairs = []
        for i, t in enumerate(assign):
            if t < 0 or req_ref[i] is None or task_ref[t] is None:
                continue
            holder, seqno = task_ref[t]
            req_home, for_rank, rqseqno = req_ref[i]
            pairs.append((holder, seqno, req_home, for_rank, rqseqno))
        return pairs
