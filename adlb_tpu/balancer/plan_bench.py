"""Planning-latency sweep for the sharded (multichip) balancer.

Measures the full planning round — snapshot-delta ingest -> sharded
solve -> plan extracted on host — on a host-simulated device mesh, at a
ladder of world sizes up to 1,000 servers / 100k parked requesters
(ROADMAP item 1's scale target). Steady state is engine-faithful: every
round ships task deltas for a handful of servers, the previous round's
plan is consumed by the data plane (matched tasks leave their queues,
matched requesters unpark), and stamps ride the snapshots so the
solver's unchanged-server fast path is exercised the way the engine
drives it.

Run standalone (self-provisions the virtual mesh):

    python -m adlb_tpu.balancer.plan_bench [--quick] [--ndev 8]

or from scripts/sim_scale.py --plan-sweep. bench.py shells out to this
module so the virtual-mesh provisioning cannot disturb the parent
process's accelerator backend.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

#: (servers, max_tasks K, max_requesters R) ladder; the last row is the
#: acceptance scale: 10,000 servers x 100 parked requesters each = 1M
#: (--quick keeps the first, 1k and 10k rows: the smoke still covers
#: the acceptance scale AND the 1k row the plan_round_1k_ms continuity
#: key — guarded since BENCH_r06 — is derived from)
SCALES = [(64, 16, 16), (256, 16, 32), (1000, 16, 100), (10000, 16, 100)]
TYPES = tuple(range(1, 9))
DELTA_SERVERS = 8  # servers receiving a task burst per steady round


def _mk_reqs(rng, s, R):
    return [
        (s * 200 + i, i + 1, [int(rng.integers(1, len(TYPES) + 1))])
        for i in range(R)
    ]


def run_sweep(scales=None, reps: int = 40, ndev: int = 8,
              rounds: int = 16, auction: str = "device") -> dict:
    """Requires >= ndev visible JAX devices. Returns the result dict."""
    import jax
    from jax.sharding import Mesh

    from adlb_tpu.balancer.distributed import DistributedAssignmentSolver

    devs = np.array(jax.devices()[:ndev])
    assert len(devs) >= ndev, f"need {ndev} devices, have {len(devs)}"
    mesh = Mesh(devs, axis_names=("s",))
    rows = []
    for S, K, R in scales or SCALES:
        rng = np.random.default_rng(S)
        solver = DistributedAssignmentSolver(
            TYPES, K, R, mesh, rounds=rounds,
            servers_per_device=-(-S // ndev),
            auction=auction,
        )
        clock = [1.0]

        def stamp():
            clock[0] += 1.0
            return clock[0]

        snaps = {}
        for s in range(S):
            st = stamp()
            snaps[100 + s] = {
                "tasks": [], "reqs": _mk_reqs(rng, s, R),
                "stamp": st, "task_stamp": st,
            }
        t0 = time.perf_counter()
        solver.ingest(snaps)
        cold_ingest_ms = (time.perf_counter() - t0) * 1e3
        t0 = time.perf_counter()
        solver.plan()
        compile_ms = (time.perf_counter() - t0) * 1e3

        seq = [10**6]

        def add_tasks(sv, n):
            snap = snaps[sv]
            burst = [
                (seq[0] + i, int(rng.integers(1, len(TYPES) + 1)),
                 int(rng.integers(-50, 50)), 64)
                for i in range(n)
            ]
            seq[0] += n
            snap["tasks"] = sorted(
                snap["tasks"] + burst, key=lambda t: -t[2])[:K]
            snap["task_stamp"] = stamp()

        lat, npairs = [], []
        rq = [10**7]
        for it in range(reps):
            for d in range(DELTA_SERVERS):
                add_tasks(100 + (it * DELTA_SERVERS + d) % S, K)
            t0 = time.perf_counter()
            solver.ingest(snaps)
            pairs = solver.plan()
            lat.append((time.perf_counter() - t0) * 1e3)
            npairs.append(len(pairs))
            # the data plane consumes the plan; a served worker computes,
            # then re-parks (fresh rqseqno) — the pool stays at scale
            touched: dict = {}
            for holder, seqno, req_home, for_rank, rqseqno in pairs:
                touched.setdefault(holder, set()).add(seqno)
                rs = snaps[req_home]
                rq[0] += 1
                rs["reqs"] = [
                    r for r in rs["reqs"]
                    if not (r[0] == for_rank and r[1] == rqseqno)
                ] + [(for_rank, rq[0],
                      [int(rng.integers(1, len(TYPES) + 1))])]
                rs["stamp"] = stamp()
            for h, seqs in touched.items():
                hs = snaps[h]
                hs["tasks"] = [
                    t for t in hs["tasks"] if t[0] not in seqs]
                hs["task_stamp"] = stamp()
        lat.sort()
        # warm full-mesh sweep cost (the first sweep above paid compile)
        t0 = time.perf_counter()
        solver._sweep()
        warm_sweep_ms = (time.perf_counter() - t0) * 1e3

        def pct(p):
            return round(lat[min(int(p * len(lat)), len(lat) - 1)], 2)

        rows.append({
            "servers": S, "K": K, "R": R, "parked_reqs": S * R,
            "plan_round_p50_ms": pct(0.50),
            "plan_round_p90_ms": pct(0.90),
            "plan_round_max_ms": round(lat[-1], 2),
            "pairs_per_round_p50": int(np.median(npairs)),
            "device_sweep_ms": round(warm_sweep_ms, 2),
            "sweeps": solver.sweep_count,
            "cold_ingest_ms": round(cold_ingest_ms, 1),
            "compile_ms": round(compile_ms, 1),
        })
        print(
            f"plan-sweep {S:5d} servers x {R:4d} reqs "
            f"({S*R} parked): p50 {rows[-1]['plan_round_p50_ms']:7.2f} ms  "
            f"p90 {rows[-1]['plan_round_p90_ms']:7.2f} ms  "
            f"pairs/round {rows[-1]['pairs_per_round_p50']}  "
            f"device sweep {rows[-1]['device_sweep_ms']:.1f} ms "
            f"(x{rows[-1]['sweeps']})"
        )
    out = {
        "metric": "plan_round_latency",
        "n_devices": ndev,
        "rounds": rounds,
        "auction": auction,
        "delta_servers_per_round": DELTA_SERVERS,
        "rows": rows,
        "note": (
            "full planning round (snapshot-delta ingest -> sharded solve "
            "-> plan extracted on host) on an 8-way host-simulated mesh; "
            "steady state is engine-faithful (plans consumed, stamps "
            "ride snapshots). device_sweep_ms is the full mesh re-sweep "
            "paid at cold start / large deltas / every RESYNC_INTERVAL "
            "plans; small deltas patch the merged candidate lists "
            "incrementally (exact, see balancer/distributed.py)."
        ),
    }
    # compact scalar keys for scripts/bench_guard.py's raw-text scan
    for r in rows:
        if r["servers"] == 1000:
            out["plan_round_1k_ms"] = r["plan_round_p50_ms"]
        elif r["servers"] == 10000:
            out["plan_round_10k_ms"] = r["plan_round_p50_ms"]
    return out


#: engine-round overhead ladder: (servers, tasks-per-supply-server,
#: reqs-per-server) — parked totals 1k / 10k / 100k
ENGINE_SCALES = [(1000, 16, 1), (1000, 16, 10), (1000, 16, 100)]
SUPPLY_SERVERS = 64  # servers holding queued inventory (cross demand)


class _NullSolver:
    """Measures ENGINE-side admission only: accepts either input shape
    and plans nothing (the solve itself is plan_round_1k_ms's job)."""

    SUPPORTS_VIEW = True

    def solve(self, snapshots, world) -> list:
        return []


def run_engine_sweep(scales=None, reps: int = 40) -> dict:
    """engine.round() overhead at 1k/10k/100k parked requesters, array
    ledger vs the pure-Python twin (the pre-vectorization cost), on a
    steady state that stamps DELTA_SERVERS fresh snapshots per round —
    the O(changed rows) path the resident ledger exists for. Needs no
    devices (null solver): this isolates admission — ledger filter,
    suppression, cross-feasibility gate, pump pre-check, solver-input
    packing — from the solve."""
    import time as _time

    from adlb_tpu.balancer.engine import PlanEngine
    from adlb_tpu.balancer.ledger import SnapshotStore

    rows = []
    for S, K, R in scales or ENGINE_SCALES:
        row = {"servers": S, "parked_reqs": S * R}
        for ledger in ("array", "py"):
            rng = np.random.default_rng(S * R)
            eng = PlanEngine(
                types=TYPES, max_tasks=K, max_requesters=max(R, 4),
                host_ledger=ledger,
            )
            eng.solver = _NullSolver()
            seq = [10**6]
            # the array arm is driven the way the runtime drives it: a
            # versioned SnapshotStore, so the ledger sync touches only
            # the DELTA_SERVERS re-stamped ranks per round instead of
            # comparing all S snapshots (the r07 1k-parked floor). The
            # py twin keeps the plain dict — it re-derives everything
            # per round by definition, store or not.
            snaps: dict = SnapshotStore() if ledger == "array" else {}
            t0 = _time.monotonic()
            for s in range(S):
                tasks = []
                if s < SUPPLY_SERVERS:
                    tasks = [
                        (seq[0] + i, int(rng.integers(1, len(TYPES) + 1)),
                         int(rng.integers(-50, 50)), 64)
                        for i in range(K)
                    ]
                    seq[0] += K
                # reqs park on NON-supply servers: cross-server demand,
                # so every round admits the solve (the representative
                # steady state for a serving fleet; consumers stay 0 so
                # the pump never fires — its walk is measured by the
                # hotspot benches)
                reqs = _mk_reqs(rng, s, R) if s >= SUPPLY_SERVERS else []
                snaps[100 + s] = {
                    "tasks": tasks, "reqs": reqs, "consumers": 0,
                    "stamp": t0, "task_stamp": t0,
                }
            lat = []
            rq = [10**7]
            for it in range(max(reps, 4)):
                t1 = _time.perf_counter()
                eng.round(snaps, None)
                dt = (_time.perf_counter() - t1) * 1e6
                if it >= 3:  # first rounds pay allocation/registration
                    lat.append(dt)
                # steady state: a handful of servers re-stamp with fresh
                # parks (everything else rides the unchanged fast path)
                t2 = _time.monotonic()
                for d in range(DELTA_SERVERS):
                    s = SUPPLY_SERVERS + (
                        (it * DELTA_SERVERS + d) % (S - SUPPLY_SERVERS))
                    snap = snaps[100 + s]
                    rq[0] += 1
                    snap["reqs"] = list(snap["reqs"][1:]) + [
                        (s * 200, rq[0],
                         [int(rng.integers(1, len(TYPES) + 1))])
                    ]
                    snap["stamp"] = t2
                    if ledger == "array":
                        snaps.bump(100 + s)  # in-place re-stamp
            lat.sort()
            p50 = lat[len(lat) // 2]
            key = "engine_round_us" if ledger == "array" \
                else "engine_round_py_us"
            row[key] = round(p50, 1)
            if ledger == "array":
                led = eng._ledger
                # the fast path must actually be taken: patches happened,
                # and NOT MORE than the workload explains — cold start
                # builds 2 columns per server, each steady round rebuilds
                # the DELTA_SERVERS re-stamped servers' req columns (a
                # change-key bug that silently rebuilt the world every
                # round would blow straight through this bound), plus a
                # full-resync allowance; full rebuilds only at cadence
                assert led.patch_count > 0, "ledger fast path never taken"
                budget = (
                    2 * S + (max(reps, 4) + 1) * 2 * DELTA_SERVERS
                    + led.resync_count * 2 * S
                )
                assert led.patch_count <= budget, (
                    f"fast path lost: {led.patch_count} patches > "
                    f"{budget} explained by the workload")
                assert led.resync_count <= reps // led.LEDGER_RESYNC_INTERVAL + 1, (
                    led.resync_count)
                # the O(Δ) steady-state claim, reason-labelled: after
                # the one cold full pass, full walks happen ONLY at the
                # cadence resync — a membership-classified walk here
                # would mean the store fast path was never engaged
                assert led.resync_reasons.get("cold", 0) <= 1, (
                    led.resync_reasons)
                assert led.resync_reasons.get("membership", 0) == 0, (
                    f"steady state paid membership walks: "
                    f"{led.resync_reasons}")
                row["ledger_patches"] = led.patch_count
                row["ledger_resyncs"] = led.resync_count
                row["ledger_resync_reasons"] = {
                    k: v for k, v in led.resync_reasons.items() if v}
                row["ledger_rows"] = led.rows_resident()
        row["speedup"] = round(row["engine_round_py_us"]
                               / max(row["engine_round_us"], 1e-9), 1)
        rows.append(row)
        print(
            f"engine-round {row['parked_reqs']:6d} parked: array p50 "
            f"{row['engine_round_us']:9.1f} us  py twin "
            f"{row['engine_round_py_us']:9.1f} us  "
            f"({row['speedup']}x, {row['ledger_patches']} patches, "
            f"{row['ledger_resyncs']} resyncs)"
        )
    out = {
        "metric": "engine_round_overhead",
        "delta_servers_per_round": DELTA_SERVERS,
        "rows": rows,
        "note": (
            "engine.round() admission overhead (ledger filter + "
            "suppression + cross gate + pump pre-check + solver-input "
            "packing; null solver, so the solve itself is excluded) on "
            "a steady state re-stamping DELTA_SERVERS snapshots per "
            "round. engine_round_us = array-resident host ledger "
            "(balancer/ledger.py), engine_round_py_us = the retained "
            "pure-Python twin (the pre-PR-10 cost). The array arm runs "
            "on a versioned SnapshotStore, as the runtime does since "
            "the O(S) scan kill."
        ),
    }
    # compact scalar keys for scripts/bench_guard.py's raw-text scan
    for r in rows:
        if r["parked_reqs"] == 1000:
            out["admission_1k_ms"] = round(r["engine_round_us"] / 1e3, 3)
        elif r["parked_reqs"] == 100000:
            out["engine_round_us_100k"] = r["engine_round_us"]
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true",
                    help="fewer reps, smallest+largest scales only")
    ap.add_argument("--ndev", type=int, default=8)
    ap.add_argument("--auction", choices=("device", "host"),
                    default="device",
                    help="sharded-solver auction tier to measure "
                         "(host = the retained reference twin)")
    ap.add_argument("--engine-rounds", action="store_true",
                    help="measure engine.round admission overhead "
                         "(host-ledger ladder) instead of the mesh "
                         "planning sweep; needs no devices")
    ap.add_argument("--json-only", action="store_true",
                    help="suppress progress lines (JSON on stdout)")
    args = ap.parse_args(argv)

    if args.engine_rounds:
        def run():
            scales = (
                [ENGINE_SCALES[0], ENGINE_SCALES[-1]] if args.quick
                else ENGINE_SCALES
            )
            return run_engine_sweep(
                scales=scales, reps=20 if args.quick else 40)
    else:
        from adlb_tpu.utils.jaxenv import force_cpu_devices

        force_cpu_devices(args.ndev)
        scales = [SCALES[0], SCALES[2], SCALES[-1]] if args.quick else SCALES
        reps = 20 if args.quick else 40

        def run():
            return run_sweep(scales=scales, reps=reps, ndev=args.ndev,
                             auction=args.auction)

    if args.json_only:
        import contextlib
        import io
        import sys

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            out = run()
        _stamp_provenance(out)
        sys.stdout.write(json.dumps(out) + "\n")
    else:
        out = run()
        _stamp_provenance(out)
        print(json.dumps(out))
    return 0


def _stamp_provenance(out) -> None:
    """Core count + load on every MULTICHIP record (the r07 caveat made
    policy): scheduler-bound numbers from a 1-core box must be readable
    as such, and bench_guard skips-with-note across core-count changes."""
    if isinstance(out, dict):
        import os as _os

        out.setdefault("cpu_count", _os.cpu_count() or 1)
        if hasattr(_os, "getloadavg"):
            out.setdefault("loadavg_1m", round(_os.getloadavg()[0], 2))


if __name__ == "__main__":
    raise SystemExit(main())
