"""Planning-latency sweep for the sharded (multichip) balancer.

Measures the full planning round — snapshot-delta ingest -> sharded
solve -> plan extracted on host — on a host-simulated device mesh, at a
ladder of world sizes up to 1,000 servers / 100k parked requesters
(ROADMAP item 1's scale target). Steady state is engine-faithful: every
round ships task deltas for a handful of servers, the previous round's
plan is consumed by the data plane (matched tasks leave their queues,
matched requesters unpark), and stamps ride the snapshots so the
solver's unchanged-server fast path is exercised the way the engine
drives it.

Run standalone (self-provisions the virtual mesh):

    python -m adlb_tpu.balancer.plan_bench [--quick] [--ndev 8]

or from scripts/sim_scale.py --plan-sweep. bench.py shells out to this
module so the virtual-mesh provisioning cannot disturb the parent
process's accelerator backend.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

#: (servers, max_tasks K, max_requesters R) ladder; the last row is the
#: acceptance scale: 1,000 servers x 100 parked requesters each = 100k
SCALES = [(64, 16, 16), (256, 16, 32), (1000, 16, 100)]
TYPES = tuple(range(1, 9))
DELTA_SERVERS = 8  # servers receiving a task burst per steady round


def _mk_reqs(rng, s, R):
    return [
        (s * 200 + i, i + 1, [int(rng.integers(1, len(TYPES) + 1))])
        for i in range(R)
    ]


def run_sweep(scales=None, reps: int = 40, ndev: int = 8,
              rounds: int = 16) -> dict:
    """Requires >= ndev visible JAX devices. Returns the result dict."""
    import jax
    from jax.sharding import Mesh

    from adlb_tpu.balancer.distributed import DistributedAssignmentSolver

    devs = np.array(jax.devices()[:ndev])
    assert len(devs) >= ndev, f"need {ndev} devices, have {len(devs)}"
    mesh = Mesh(devs, axis_names=("s",))
    rows = []
    for S, K, R in scales or SCALES:
        rng = np.random.default_rng(S)
        solver = DistributedAssignmentSolver(
            TYPES, K, R, mesh, rounds=rounds,
            servers_per_device=-(-S // ndev),
        )
        clock = [1.0]

        def stamp():
            clock[0] += 1.0
            return clock[0]

        snaps = {}
        for s in range(S):
            st = stamp()
            snaps[100 + s] = {
                "tasks": [], "reqs": _mk_reqs(rng, s, R),
                "stamp": st, "task_stamp": st,
            }
        t0 = time.perf_counter()
        solver.ingest(snaps)
        cold_ingest_ms = (time.perf_counter() - t0) * 1e3
        t0 = time.perf_counter()
        solver.plan()
        compile_ms = (time.perf_counter() - t0) * 1e3

        seq = [10**6]

        def add_tasks(sv, n):
            snap = snaps[sv]
            burst = [
                (seq[0] + i, int(rng.integers(1, len(TYPES) + 1)),
                 int(rng.integers(-50, 50)), 64)
                for i in range(n)
            ]
            seq[0] += n
            snap["tasks"] = sorted(
                snap["tasks"] + burst, key=lambda t: -t[2])[:K]
            snap["task_stamp"] = stamp()

        lat, npairs = [], []
        rq = [10**7]
        for it in range(reps):
            for d in range(DELTA_SERVERS):
                add_tasks(100 + (it * DELTA_SERVERS + d) % S, K)
            t0 = time.perf_counter()
            solver.ingest(snaps)
            pairs = solver.plan()
            lat.append((time.perf_counter() - t0) * 1e3)
            npairs.append(len(pairs))
            # the data plane consumes the plan; a served worker computes,
            # then re-parks (fresh rqseqno) — the pool stays at scale
            touched: dict = {}
            for holder, seqno, req_home, for_rank, rqseqno in pairs:
                touched.setdefault(holder, set()).add(seqno)
                rs = snaps[req_home]
                rq[0] += 1
                rs["reqs"] = [
                    r for r in rs["reqs"]
                    if not (r[0] == for_rank and r[1] == rqseqno)
                ] + [(for_rank, rq[0],
                      [int(rng.integers(1, len(TYPES) + 1))])]
                rs["stamp"] = stamp()
            for h, seqs in touched.items():
                hs = snaps[h]
                hs["tasks"] = [
                    t for t in hs["tasks"] if t[0] not in seqs]
                hs["task_stamp"] = stamp()
        lat.sort()
        # warm full-mesh sweep cost (the first sweep above paid compile)
        t0 = time.perf_counter()
        solver._sweep()
        warm_sweep_ms = (time.perf_counter() - t0) * 1e3

        def pct(p):
            return round(lat[min(int(p * len(lat)), len(lat) - 1)], 2)

        rows.append({
            "servers": S, "K": K, "R": R, "parked_reqs": S * R,
            "plan_round_p50_ms": pct(0.50),
            "plan_round_p90_ms": pct(0.90),
            "plan_round_max_ms": round(lat[-1], 2),
            "pairs_per_round_p50": int(np.median(npairs)),
            "device_sweep_ms": round(warm_sweep_ms, 2),
            "sweeps": solver.sweep_count,
            "cold_ingest_ms": round(cold_ingest_ms, 1),
            "compile_ms": round(compile_ms, 1),
        })
        print(
            f"plan-sweep {S:5d} servers x {R:4d} reqs "
            f"({S*R} parked): p50 {rows[-1]['plan_round_p50_ms']:7.2f} ms  "
            f"p90 {rows[-1]['plan_round_p90_ms']:7.2f} ms  "
            f"pairs/round {rows[-1]['pairs_per_round_p50']}  "
            f"device sweep {rows[-1]['device_sweep_ms']:.1f} ms "
            f"(x{rows[-1]['sweeps']})"
        )
    return {
        "metric": "plan_round_latency",
        "n_devices": ndev,
        "rounds": rounds,
        "delta_servers_per_round": DELTA_SERVERS,
        "rows": rows,
        "note": (
            "full planning round (snapshot-delta ingest -> sharded solve "
            "-> plan extracted on host) on an 8-way host-simulated mesh; "
            "steady state is engine-faithful (plans consumed, stamps "
            "ride snapshots). device_sweep_ms is the full mesh re-sweep "
            "paid at cold start / large deltas / every RESYNC_INTERVAL "
            "plans; small deltas patch the merged candidate lists "
            "incrementally (exact, see balancer/distributed.py)."
        ),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true",
                    help="fewer reps, smallest+largest scales only")
    ap.add_argument("--ndev", type=int, default=8)
    ap.add_argument("--json-only", action="store_true",
                    help="suppress progress lines (JSON on stdout)")
    args = ap.parse_args(argv)

    from adlb_tpu.utils.jaxenv import force_cpu_devices

    force_cpu_devices(args.ndev)
    scales = [SCALES[0], SCALES[-1]] if args.quick else SCALES
    reps = 20 if args.quick else 40
    if args.json_only:
        import contextlib
        import io
        import sys

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            out = run_sweep(scales=scales, reps=reps, ndev=args.ndev)
        sys.stdout.write(json.dumps(out) + "\n")
    else:
        out = run_sweep(scales=scales, reps=reps, ndev=args.ndev)
        print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
