"""Balancer sidecar: the Python/JAX brain driving the native C++ data plane.

SURVEY §7's language split realized end-to-end: native servers
(``adlb_tpu/native/serverd.cpp``) keep the entire data plane — queues,
protocol, payloads — and stream fixed-shape queue-state snapshots
(``SS_STATE``: flattened task/requester metadata, a few KB) to this
process, which runs the batched assignment solve (:mod:`.engine` /
:mod:`.solve`, Pallas on TPU) and answers with ``SS_PLAN_MATCH`` /
``SS_PLAN_MIGRATE``. Payload bytes never cross into Python — exactly the
"balancer brain in a sidecar exchanging fixed-shape arrays" design.

The sidecar occupies a pseudo-rank one past the world (it is not an app or
a server; no role math changes), speaks the binary TLV codec toward
servers, and exits when every server has sent DS_END (or on abort).
"""

from __future__ import annotations

import threading
import time

from adlb_tpu.runtime.messages import Tag, msg


def start_sidecar(world, cfg, abort_event=None, host: str = "127.0.0.1"):
    """Bind the sidecar's endpoint at pseudo-rank ``world.nranks`` and build
    its (not-yet-started) thread. Returns (endpoint, thread): add the
    endpoint's port to the world's address map, update ``ep.addr_map``,
    then ``thread.start()``. Use :func:`stop_sidecar` to tear down — also
    on bootstrap failure, or the thread/endpoint leak. Pass the host other
    machines reach this one at for multi-host worlds (servers on other
    hosts must stream snapshots here)."""
    from adlb_tpu.runtime.transport_tcp import TcpEndpoint

    ep = TcpEndpoint(
        world.nranks, {world.nranks: (host, 0)},
        binary_peers=set(world.server_ranks),
    )
    thread = threading.Thread(
        target=run_sidecar,
        args=(world, cfg, ep, abort_event),
        daemon=True,
        name="adlb-balancer-sidecar",
    )
    return ep, thread


def stop_sidecar(ep, thread, abort_event=None, timeout: float = 10.0) -> None:
    """Join (the loop exits on the servers' DS_ENDs, or on abort_event) and
    close the endpoint."""
    if thread.is_alive():
        thread.join(timeout=timeout)
        if thread.is_alive() and abort_event is not None:
            abort_event.set()
            thread.join(timeout=2.0)
    ep.close()


def decode_snapshot(m) -> dict:
    """Unflatten a native SS_STATE frame into the engine's snapshot shape."""
    tf = m.data.get("tasks_flat") or []
    tasks = [
        (tf[i], tf[i + 1], tf[i + 2], tf[i + 3]) for i in range(0, len(tf), 4)
    ]
    rf = m.data.get("reqs_flat") or []
    reqs = []
    i = 0
    while i < len(rf):
        rank, rqseqno, ntypes = rf[i], rf[i + 1], rf[i + 2]
        i += 3
        if ntypes < 0:
            types = None
        else:
            types = [int(t) for t in rf[i:i + ntypes]]
            i += ntypes
        reqs.append((rank, rqseqno, types))
    return {
        "tasks": tasks,
        "reqs": reqs,
        "nbytes": m.data.get("nbytes", 0),
        "consumers": m.data.get("consumers", 0),
        "stamp": time.monotonic(),  # receiver clock: never mix hosts' clocks
        # flattened (src, highest id) pairs; absent on pre-ack daemons ->
        # engine falls back to stamp clearing
        "mig_acks": (
            {ma[i]: ma[i + 1] for i in range(0, len(ma), 2)}
            if (ma := m.data.get("mig_acks")) is not None else None
        ),
    }


def run_sidecar(world, cfg, ep, abort_event=None) -> int:
    """Serve balancer rounds until every server says DS_END; returns the
    number of planning rounds executed."""
    from adlb_tpu.balancer.engine import PlanEngine, round_gap
    from adlb_tpu.obs.metrics import Registry, attach

    # the sidecar is its own process/thread: it owns its registry (round
    # duration, plan ages, pairs) and instruments its endpoint's per-tag
    # traffic like any server
    metrics = Registry(rank=world.nranks)
    attach(ep, metrics)
    engine = PlanEngine(
        types=world.types,
        metrics=metrics,
        max_tasks=cfg.balancer_max_tasks,
        max_requesters=cfg.balancer_max_requesters,
        backend=cfg.solver_backend,
        max_malloc_per_server=cfg.max_malloc_per_server,
        use_mesh=cfg.balancer_mesh == "auto",
        nservers=world.nservers,
        host_threshold_reqs=cfg.solver_host_threshold,
        lookahead=cfg.balancer_lookahead,
        look_max=cfg.balancer_look_max,
        grow_window=cfg.balancer_grow_window,
        inflow_ttl=cfg.balancer_inflow_ttl,
        inflow_min_age=cfg.balancer_inflow_min_age,
        host_ledger=cfg.host_ledger,
        auction=cfg.balancer_auction,
        # job axis: the native plane advertises only the default
        # namespace today (4-wide flat tasks), but the engine kwargs
        # stay in lockstep with the in-server master so a multi-job
        # config plans identically on either plane
        max_jobs=cfg.balancer_max_jobs,
        job_weights=cfg.job_weights,
    )
    # versioned snapshot table (balancer/ledger.py): the ledger's sync
    # touches only ranks whose snapshots changed since the last round.
    # The sidecar loop is single-threaded, so the engine reads the live
    # store (no fork needed); in-place merges below bump() it.
    from adlb_tpu.balancer.ledger import SnapshotStore

    snapshots: SnapshotStore = SnapshotStore()
    ended: set[int] = set()
    servers = set(world.server_ranks)
    rounds = 0
    dirty = False
    # one state machine shared with the in-server master: growth
    # broadcasts immediately, shrinks held for grace (see hungry.py)
    from adlb_tpu.balancer.hungry import HungryTracker

    tracker = HungryTracker()
    me = world.nranks  # pseudo-rank

    def safe_send(dest: int, m) -> None:
        """Send, treating an unreachable server as ended.

        At end-of-world a server can close its listener between sending
        DS_END and the sidecar draining its inbox (or while a broadcast
        is mid-flight); connection refusal there is the normal teardown
        race, not an error — marking the rank ended lets the loop drain
        out instead of dying with an unhandled thread exception.
        connect_grace is short because every peer here snapshots only
        AFTER binding its listener, so a refusal never means "still
        coming up" — without it each dead destination would stall the
        loop for the transport's 15 s startup grace. A rank wrongly
        ended by a transient error is resurrected by its next
        SS_STATE."""
        try:
            ep.send(dest, m, connect_grace=0.25)
        except OSError:
            ended.add(dest)
            snapshots.pop(dest, None)
            tracker.drop(dest)

    def broadcast(payload) -> None:
        if payload is None:
            return
        is_hungry, req_types, grew = payload
        for s in sorted(servers - ended):
            safe_send(
                s,
                msg(Tag.SS_HUNGRY, me, hungry=int(is_hungry),
                    req_types=req_types, grew=int(grew)),
            )

    try:
        while ended < servers:
            if abort_event is not None and abort_event.is_set():
                break
            m = ep.recv(timeout=0.25)
            while m is not None:
                if m.tag is Tag.SS_STATE:
                    # a fresh snapshot proves the server is alive: resurrect
                    # it if a transient send error wrongly marked it ended
                    # (DS_END is final — an ended-by-DS_END server never
                    # snapshots again, so this cannot resurrect those)
                    ended.discard(m.src)
                    snapshots[m.src] = decode_snapshot(m)
                    broadcast(tracker.update(m.src, snapshots[m.src]["reqs"]))
                    dirty = True
                elif m.tag is Tag.SS_STATE_DELTA:
                    # put-event: append task(s) to the sender's last full
                    # snapshot (stamp unchanged — requester re-eligibility only
                    # comes from full snapshots; see the server's merge).
                    # Batched shape (parallel lists) since round 4; the
                    # single-unit shape is kept for older daemons.
                    snap = snapshots.get(m.src)
                    if snap is not None:
                        if m.data.get("seqnos") is not None:
                            # "jobs" (field 106) rides only when some
                            # unit is non-default; absent -> all job 0
                            jbs = m.data.get("jobs") or [0] * len(m.seqnos)
                            units = zip(m.seqnos, m.work_types, m.prios,
                                        m.work_lens, jbs)
                        else:
                            units = [(m.seqno, m.work_type, m.prio,
                                      m.work_len, 0)]
                        for sq, wt, pr, ln, jb in units:
                            if len(snap["tasks"]) >= cfg.balancer_max_tasks:
                                break
                            if jb:
                                if not 0 <= jb < cfg.balancer_max_jobs:
                                    continue  # overflow namespace
                                snap["tasks"].append((sq, wt, pr, ln, jb))
                            else:
                                snap["tasks"].append((sq, wt, pr, ln))
                        snap["nbytes"] = m.data.get("nbytes", snap["nbytes"])
                        # in-place append with no stamp bump: the delta
                        # sequence is the change signal the resident
                        # ledgers/solver fast paths key on (the server's
                        # _merge_task_delta has always bumped it; the
                        # sidecar merge was the one spot that didn't)
                        snap["delta_seq"] = snap.get("delta_seq", 0) + 1
                        snapshots.bump(m.src)  # in-place append
                        dirty = True
                elif m.tag is Tag.DS_END:
                    ended.add(m.src)
                    snapshots.pop(m.src, None)
                    tracker.drop(m.src)
                elif m.tag is Tag.SS_SERVER_DEAD:
                    # defensive only: TODAY this never fires — the sidecar
                    # plane drives NATIVE daemons, which Config rejects for
                    # on_server_failure="failover", and the Python-plane
                    # fan-out targets only world server ranks. Kept so a
                    # future native failover protocol that does relay the
                    # fan-out retires the dead server's snapshot/tracker
                    # state (like a DS_END) instead of planning onto it.
                    dead_srv = m.rank
                    snapshots.pop(dead_srv, None)
                    tracker.drop(dead_srv)
                    ended.add(dead_srv)
                    dirty = True
                elif m.tag is Tag.SS_RANK_DEAD:
                    # a worker died under on_worker_failure="reclaim":
                    # retire its parked requests from every held snapshot
                    # so the next plan stops matching/migrating toward it
                    # (stale entries would only cost an UNRESERVE bounce,
                    # but the dead rank must not keep attracting work).
                    # Forward-compat: today reclaim requires python
                    # servers (whose master patches its own snapshots),
                    # so this only fires if a future native plane or an
                    # operator tool relays the death here.
                    dead = m.rank
                    for src, snap in snapshots.items():
                        kept = [r for r in snap["reqs"] if r[0] != dead]
                        if len(kept) != len(snap["reqs"]):
                            snap["reqs"] = kept
                            snapshots.bump(src)  # in-place patch
                            dirty = True
                            broadcast(tracker.update(src, kept))
                m = ep.recv(timeout=0.0)
            broadcast(tracker.flush(time.monotonic()))
            if not dirty or not snapshots:
                continue
            dirty = False
            try:
                matches, migrations = engine.round(snapshots, world)
            except Exception as e:  # noqa: BLE001 — must keep serving
                import sys

                print(
                    f"[adlb sidecar] solve failed ({e!r}); forcing host path",
                    file=sys.stderr,
                )
                engine.force_host_path()
                continue
            rounds += 1
            for holder, seqno, req_home, for_rank, rqseqno in matches:
                if holder in ended:  # died earlier in this very plan loop
                    continue
                safe_send(
                    holder,
                    msg(Tag.SS_PLAN_MATCH, me, seqno=seqno, for_rank=for_rank,
                        req_home=req_home, rqseqno=rqseqno),
                )
            for src_rank, dest, seqnos, mig_id in migrations:
                if src_rank in ended or dest in ended:
                    continue
                safe_send(
                    src_rank,
                    msg(Tag.SS_PLAN_MIGRATE, me, dest=dest, seqnos=seqnos,
                        mig_id=mig_id),
                )
            if cfg.balancer_min_gap > 0:
                # shared cadence with the in-proc _BalancerWorker
                time.sleep(round_gap(cfg.balancer_min_gap, matches, migrations))
    finally:
        # the registry's round/plan-age/traffic numbers become reachable
        # as a flight artifact when the world opted in — written in a
        # finally so a serve-loop crash (the one case a post-mortem is
        # FOR) still leaves one; the sidecar is the one balancer brain a
        # server post-mortem cannot see into otherwise
        from adlb_tpu.obs.flight import write_artifact

        write_artifact(
            cfg.flight_dir,
            "sidecar",
            {
                "role": "sidecar",
                "rank": me,
                "reason": "aborted" if (abort_event is not None
                                        and abort_event.is_set()) else "exit",
                "rounds": rounds,
                "metrics": metrics.snapshot(),
            },
        )
    return rounds
