"""Shared tracker for the SS_HUNGRY wanted-type set.

Both balancer hosts — the in-server master thread (``runtime/server.py``)
and the native-plane sidecar (``balancer/sidecar.py``) — must agree on
when servers should pay for put-side event snapshots: some requester is
parked somewhere whose requested types new untargeted inventory could
satisfy. This class owns that state machine so the two planes cannot
drift: set GROWTH broadcasts immediately (a newly wanted type must start
flowing event deltas now); set SHRINKAGE is held for a grace period,
because fine-grained workloads park/unpark the same types many times a
second and flapping would churn broadcasts plus the grew-triggered
snapshot refreshes on every server.
"""

from __future__ import annotations

import time
from typing import Optional


class HungryTracker:
    """Feed per-source parked-requester lists; get broadcast decisions.

    ``update(src, reqs)`` and ``flush(now)`` return ``None`` (nothing to
    broadcast) or ``(hungry, req_types, grew)`` — the SS_HUNGRY payload:
    ``hungry`` bool, ``req_types`` a sorted list of wanted types or None
    for "an any-type requester is parked", ``grew`` whether the wanted
    set grew (receivers refresh their snapshot on growth).
    """

    def __init__(self, shrink_grace: float = 0.1) -> None:
        self.shrink_grace = shrink_grace
        self.hungry = False
        self.hungry_any = False
        self.hungry_types: frozenset = frozenset()
        self._per_src: dict[int, tuple] = {}  # src -> (any, types)
        self._shrink_since: Optional[float] = None

    def _now_state(self) -> tuple[bool, frozenset]:
        return (
            any(v[0] for v in self._per_src.values()),
            frozenset(t for v in self._per_src.values() for t in v[1]),
        )

    def _apply(self, any_type: bool, types: frozenset, grew: bool):
        self.hungry_any = any_type
        self.hungry_types = types
        self.hungry = any_type or bool(types)
        return (
            self.hungry,
            None if any_type else sorted(types),
            grew,
        )

    def update(self, src: int, reqs):
        """Record ``src``'s parked requesters ((rank, rqseqno, types|None)
        tuples); returns a broadcast payload or None."""
        any_type = any(r[2] is None for r in reqs)
        types = frozenset(t for r in reqs if r[2] is not None for t in r[2])
        self._per_src[src] = (any_type, types)
        now_any, now_types = self._now_state()
        grew = (now_any and not self.hungry_any) or bool(
            now_types - self.hungry_types
        )
        if grew:
            self._shrink_since = None
            return self._apply(now_any, now_types, grew=True)
        if (now_any, now_types) == (self.hungry_any, self.hungry_types):
            self._shrink_since = None
            return None
        # pure shrink: hold it; flush() applies it after the grace period
        if self._shrink_since is None:
            self._shrink_since = time.monotonic()
        return None

    def drop(self, src: int) -> None:
        """Forget an ended source. Dropping can only shrink the wanted
        set, so arm the grace timer like any other shrink — otherwise the
        survivors would keep paying for event snapshots until an
        unrelated update happened to re-derive the set."""
        if self._per_src.pop(src, None) is None:
            return
        now_state = self._now_state()
        if (
            now_state != (self.hungry_any, self.hungry_types)
            and self._shrink_since is None
        ):
            self._shrink_since = time.monotonic()

    def flush(self, now: float):
        """Apply a held shrink once stable for the grace period; returns a
        broadcast payload or None."""
        if (
            self._shrink_since is None
            or now - self._shrink_since < self.shrink_grace
        ):
            return None
        self._shrink_since = None
        now_any, now_types = self._now_state()
        if (now_any, now_types) == (self.hungry_any, self.hungry_types):
            return None
        return self._apply(now_any, now_types, grew=False)
