"""Incremental running statistics.

Port of the reference's standalone stats library (reference
``examples/stats.c``): values are contributed one at a time and running
min / max / mean / sample standard deviation stay current after every
contribution, using the numerically stable incremental update from Higham,
*Accuracy and Stability of Numerical Algorithms*, pp. 12-13 (the same
algorithm the reference cites, ``examples/stats.c:1-9``). Used by the
coinop workload's worker-side pop-latency accumulation.
"""

from __future__ import annotations

import math


class RunningStats:
    """Streaming min/max/mean/stddev accumulator with an on/off gate.

    Mirrors the reference object: ``statsinit/statson/statsoff/statsreset/
    statsenter`` plus accessors (reference ``examples/stats.c:30-52``).
    Contributions while the gate is off are ignored, as in the reference
    (``examples/stats.c:main`` demonstrates this contract).
    """

    def __init__(self, name: str = "") -> None:
        self.name = name
        self.active = False
        self.reset()

    def reset(self) -> None:
        """Reinitialize without losing the name (reference ``statsreset``).
        Also turns the gate off, matching ``statsinit``'s initial state."""
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._mean = 0.0
        self._q = 0.0  # sum of squared deviations (Higham's running Q)
        self.numvals = 0
        self.active = False

    def on(self) -> None:
        self.active = True

    def off(self) -> None:
        self.active = False

    def enter(self, value: float) -> bool:
        """Contribute one value; returns False if the gate is off."""
        if not self.active:
            return False
        self.numvals += 1
        n = self.numvals
        self._sum += value
        if value < self._min:
            self._min = value
        if value > self._max:
            self._max = value
        delta = value - self._mean
        self._mean += delta / n
        self._q += delta * (value - self._mean)
        return True

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def min(self) -> float:
        return self._min if self.numvals else 0.0

    @property
    def max(self) -> float:
        return self._max if self.numvals else 0.0

    @property
    def mean(self) -> float:
        return self._mean if self.numvals else 0.0

    @property
    def stddev(self) -> float:
        """Sample standard deviation (n-1 denominator, as the reference)."""
        if self.numvals < 2:
            return 0.0
        return math.sqrt(self._q / (self.numvals - 1))

    def dump(self) -> str:
        return (
            f"stats[{self.name}]: n={self.numvals} sum={self._sum:.6g} "
            f"min={self.min:.6g} max={self.max:.6g} mean={self.mean:.6g} "
            f"stddev={self.stddev:.6g} active={int(self.active)}"
        )

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<{self.dump()}>"


_rss_cache: list = [0.0, 0]  # [stamp, value]


def rss_kb(max_age: float = 1.0) -> int:
    """Resident-set size of this process in KiB, from /proc/self/status —
    the reference's get_memusage probe (reference ``src/adlb.c:3347-3369``).
    Cached for ``max_age`` seconds: callers on periodic paths (the qmstat
    entry at 20 Hz) must not pay a /proc read per tick. Returns 0 where
    /proc is unavailable (non-Linux)."""
    import time as _time

    now = _time.monotonic()
    if now - _rss_cache[0] < max_age and _rss_cache[1]:
        return _rss_cache[1]
    val = 0
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    val = int(line.split()[1])
                    break
    except OSError:
        pass
    _rss_cache[0] = now
    _rss_cache[1] = val
    return val
