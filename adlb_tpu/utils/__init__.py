"""Framework utilities (running statistics, small shared helpers)."""

from adlb_tpu.utils.stats import RunningStats

__all__ = ["RunningStats"]
