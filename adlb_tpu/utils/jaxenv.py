"""Force JAX onto a virtual multi-device CPU mesh.

Multi-chip TPU hardware is not available in this environment; sharding
correctness is validated on XLA's host platform with virtual devices
instead (the analogue of testing the reference's multi-rank protocols
under ``mpiexec -n k`` on one host, reference ``examples/nq.c:179-183``).

The ambient environment may have registered a single-chip accelerator
plugin in *every* Python process (via sitecustomize) and pinned
``jax_platforms`` at the config level — overriding env vars — so forcing
the CPU platform requires all three steps below, in order.
"""

from __future__ import annotations

import os


def force_cpu_devices(n_devices: int = 8):
    """Make JAX expose ``n_devices`` virtual CPU devices; returns jax.

    Safe to call whether or not JAX has been imported or initialized:
    sets the env vars (for any backend not yet created), pins the
    platform at the config level (beats ambient config pins), and drops
    any backend an accelerator plugin pre-initialized so the CPU
    backend re-reads ``XLA_FLAGS`` on next use.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")

    from jax._src import xla_bridge as _xb

    if _xb.backends_are_initialized():  # pragma: no cover
        from jax.extend.backend import clear_backends

        clear_backends()
    return jax
