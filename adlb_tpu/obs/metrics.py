"""Per-rank metrics registry: counters, gauges, log-bucket histograms.

One :class:`Registry` per rank, written by every layer that has something
to count — the transport (per-tag message/byte counters, send/recv
latency), the server reactor (puts/reserves/rfrs/pushes, queue-depth
gauges), the balancer engine (round duration, plan age, pairs emitted)
and the client. Reads happen from other threads (the ops endpoint, the
flight recorder), so the design rules are:

* **instrument creation** is locked (get-or-create may race between the
  reactor and transport reader threads);
* **updates** are plain attribute writes/adds — unlocked. CPython's GIL
  makes each individual ``+=`` on the hot path cheap; a torn read by a
  scraper costs at most one sample of skew. A few instruments have two
  writer threads (the reactor and the in-server balancer thread both
  send on one endpoint, so they share per-tag tx counters and the
  ``send_s`` histogram) — an interleaved ``+=`` can drop an increment
  there. That bounded undercount is accepted by design: metrics must
  never serialize the data plane behind a lock.

Histograms use **fixed log buckets** (geometric bounds precomputed at
creation, reference STAT_TIME_ON_Q-style fixed tables) so observation is
one bisect + one integer add, and merging across ranks is elementwise.

A bounded :class:`Timeseries` (ring of ``(t, value)`` samples) backs the
queue-depth timelines the flight recorder dumps — the per-server
wq/rq-depth history that diagnosing a hung or flat-wait world needs.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from collections import deque
from typing import Iterable, Optional

# default latency bucket geometry: 1 us .. ~17 min in x4 steps
_DEF_BASE = 1e-6
_DEF_MULT = 4.0
_DEF_NBUCKETS = 16

# summary-style point quantiles emitted next to the cumulative buckets
_QUANTILES = ("0.5", "0.95", "0.99")


class Counter:
    """Monotone counter. ``inc`` is a plain add — see module docstring."""

    __slots__ = ("v",)

    def __init__(self) -> None:
        self.v = 0

    def inc(self, n: int = 1) -> None:
        self.v += n


class Gauge:
    """Point-in-time value (queue depth, backlog, bytes held)."""

    __slots__ = ("v",)

    def __init__(self) -> None:
        self.v = 0.0

    def set(self, v: float) -> None:
        self.v = v


class Histogram:
    """Fixed-log-bucket histogram: counts[i] = observations <= bounds[i],
    with one overflow bucket; plus sum/count for rate math."""

    __slots__ = ("bounds", "counts", "sum", "n")

    def __init__(
        self,
        base: float = _DEF_BASE,
        mult: float = _DEF_MULT,
        nbuckets: int = _DEF_NBUCKETS,
    ) -> None:
        self.bounds = tuple(base * mult**i for i in range(nbuckets))
        self.counts = [0] * (nbuckets + 1)
        self.sum = 0.0
        self.n = 0

    def observe(self, x: float) -> None:
        # bisect_left: an observation EQUAL to a bound belongs in that
        # bound's bucket (le = <=, Prometheus semantics)
        self.counts[bisect_left(self.bounds, x)] += 1
        self.sum += x
        self.n += 1

    def quantile(self, q: float) -> float:
        """Within-bucket linearly interpolated quantile at ``q`` (0..1)
        — still log-bucket coarse between bucket edges, but sharp enough
        for the point-quantile /metrics lines and the tail-promotion p99
        threshold (Prometheus ``histogram_quantile`` semantics)."""
        return quantile_of(self.bounds, self.counts, self.n, q)


def quantile_of(bounds, counts, n: int, q: float) -> float:
    """Interpolated quantile shared by live Histograms and merged
    snapshot dicts (the fleet /metrics, /jobs stage-latency views, and
    the tail-promotion thresholds): linear within the bucket the target
    rank lands in (lower edge 0 for the first bucket). A quantile in
    the +Inf overflow bucket answers the highest finite bound —
    Prometheus ``histogram_quantile`` convention; ``inf`` would poison
    every threshold compare downstream."""
    if n == 0:
        return 0.0
    target = q * n
    seen = 0.0
    for i, c in enumerate(counts):
        prev = seen
        seen += c
        if seen >= target and c > 0:
            if i >= len(bounds):
                return bounds[-1] if bounds else float("inf")
            lo = bounds[i - 1] if i > 0 else 0.0
            frac = min(max((target - prev) / c, 0.0), 1.0)
            return lo + (bounds[i] - lo) * frac
    return bounds[-1] if bounds else float("inf")


class Timeseries:
    """Bounded ring of (t, value) samples — the queue-depth timeline."""

    __slots__ = ("_ring",)

    def __init__(self, capacity: int = 2048) -> None:
        self._ring: deque[tuple[float, float]] = deque(maxlen=capacity)

    def append(self, t: float, v: float) -> None:
        self._ring.append((t, v))

    def samples(self) -> list[tuple[float, float]]:
        return safe_copy(self._ring)

    def __len__(self) -> int:
        return len(self._ring)


def safe_copy(seq) -> list:
    """Copy a deque/list whose owner thread may be appending concurrently:
    appends are atomic, but iterating a mutating deque raises — retry.
    Shared by the timeline samplers and the flight recorder's ring copy."""
    for _ in range(8):
        try:
            return list(seq)
        except RuntimeError:
            continue
    return []


def _key(name: str, labels: dict) -> tuple:
    return (name, tuple(sorted(labels.items())))


class Registry:
    """One rank's metric store. Instruments are created on first use and
    cached by (name, labels); hot paths should hold the returned object
    instead of re-looking it up per event."""

    def __init__(self, rank: int = -1) -> None:
        self.rank = rank
        self._lock = threading.Lock()
        self._counters: dict[tuple, Counter] = {}
        self._gauges: dict[tuple, Gauge] = {}
        self._hists: dict[tuple, Histogram] = {}
        self._series: dict[str, Timeseries] = {}

    # -- get-or-create ------------------------------------------------------

    def counter(self, name: str, **labels) -> Counter:
        k = _key(name, labels)
        c = self._counters.get(k)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(k, Counter())
        return c

    def gauge(self, name: str, **labels) -> Gauge:
        k = _key(name, labels)
        g = self._gauges.get(k)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(k, Gauge())
        return g

    def histogram(
        self,
        name: str,
        base: float = _DEF_BASE,
        mult: float = _DEF_MULT,
        nbuckets: int = _DEF_NBUCKETS,
        **labels,
    ) -> Histogram:
        k = _key(name, labels)
        h = self._hists.get(k)
        if h is None:
            with self._lock:
                h = self._hists.setdefault(k, Histogram(base, mult, nbuckets))
        return h

    def timeseries(self, name: str, capacity: int = 2048) -> Timeseries:
        s = self._series.get(name)
        if s is None:
            with self._lock:
                s = self._series.setdefault(name, Timeseries(capacity))
        return s

    # -- reads ---------------------------------------------------------------

    def value(self, name: str, **labels) -> float:
        """Current counter (or gauge) value; 0 when never touched."""
        k = _key(name, labels)
        c = self._counters.get(k)
        if c is not None:
            return c.v
        g = self._gauges.get(k)
        return g.v if g is not None else 0

    def sum_counter(self, name: str) -> float:
        """Sum of a counter over all its label sets (e.g. all tags)."""
        with self._lock:  # creation may resize the dict mid-iteration
            items = list(self._counters.items())
        return sum(c.v for (n, _), c in items if n == name)

    def labelled(self, name: str) -> dict[str, float]:
        """One counter family's current values keyed the snapshot way
        (``name{a=b}``; the bare cell keys as ``name``) — the hedge
        trigger's in-window ``leases_expired_by`` growth memo, without
        paying for a full snapshot per scan."""
        with self._lock:
            items = list(self._counters.items())
        out: dict[str, float] = {}
        for (n, labels), c in items:
            if n != name:
                continue
            if labels:
                out[name + "{" + ",".join(
                    f"{a}={b}" for a, b in labels) + "}"] = c.v
            else:
                out[name] = c.v
        return out

    def _stable_items(self) -> tuple[list, list, list, list]:
        """Consistent item lists for cross-thread readers (the ops scrape
        / flight dump): instrument *creation* holds the lock, so copying
        under it guarantees the dicts don't resize mid-iteration. Values
        keep updating — a scrape sees each metric within one update of
        live, which is the contract."""
        with self._lock:
            return (
                list(self._counters.items()),
                list(self._gauges.items()),
                list(self._hists.items()),
                list(self._series.items()),
            )

    def snapshot(self) -> dict:
        """JSON-able dump of everything — the flight recorder's metrics
        section and the cross-rank merge input."""

        def lk(k: tuple) -> str:
            name, labels = k
            if not labels:
                return name
            return name + "{" + ",".join(f"{a}={b}" for a, b in labels) + "}"

        counters, gauges, hists, series = self._stable_items()
        return {
            "rank": self.rank,
            "counters": {lk(k): c.v for k, c in sorted(counters)},
            "gauges": {lk(k): g.v for k, g in sorted(gauges)},
            "histograms": {
                lk(k): {
                    "bounds": list(h.bounds),
                    "counts": list(h.counts),
                    "sum": h.sum,
                    "count": h.n,
                }
                for k, h in sorted(hists)
            },
            "series": {
                name: [[round(t, 6), v] for t, v in s.samples()]
                for name, s in sorted(series)
            },
        }

    @staticmethod
    def merge(snapshots: Iterable[dict]) -> dict:
        """Elementwise merge of :meth:`snapshot` dicts from many ranks:
        counters and histogram cells sum; gauges keep per-rank identity by
        gaining a ``rank=`` label (a summed queue depth across ranks is a
        different metric than each rank's depth)."""
        counters: dict[str, float] = {}
        gauges: dict[str, float] = {}
        hists: dict[str, dict] = {}
        for snap in snapshots:
            r = snap.get("rank", -1)
            for k, v in snap.get("counters", {}).items():
                counters[k] = counters.get(k, 0) + v
            for k, v in snap.get("gauges", {}).items():
                sep = "," if k.endswith("}") else "{"
                base = k[:-1] if k.endswith("}") else k
                gauges[f"{base}{sep}rank={r}}}"] = v
            for k, h in snap.get("histograms", {}).items():
                agg = hists.get(k)
                if agg is None or len(agg["counts"]) != len(h["counts"]):
                    hists[k] = {
                        "bounds": list(h["bounds"]),
                        "counts": list(h["counts"]),
                        "sum": h["sum"],
                        "count": h["count"],
                    }
                else:
                    agg["counts"] = [
                        a + b for a, b in zip(agg["counts"], h["counts"])
                    ]
                    agg["sum"] += h["sum"]
                    agg["count"] += h["count"]
        return {"counters": counters, "gauges": gauges, "histograms": hists}

    # -- text exposition -----------------------------------------------------

    def expose(self, prefix: str = "adlb_") -> str:
        """Prometheus-style text exposition of this registry (the ops
        endpoint's ``/metrics`` body; aggregates are appended by the
        caller). Counter names gain ``_total``; every sample carries a
        ``rank`` label."""
        out: list[str] = []
        base_labels = {"rank": str(self.rank)} if self.rank >= 0 else {}

        def fmt(name: str, labels: dict, v) -> str:
            lab = {**base_labels, **labels}
            ls = ",".join(f'{a}="{b}"' for a, b in sorted(lab.items()))
            return f"{prefix}{name}{{{ls}}} {v}" if ls else f"{prefix}{name} {v}"

        seen_types: set[str] = set()

        def typ(name: str, t: str) -> None:
            if name not in seen_types:
                seen_types.add(name)
                out.append(f"# TYPE {prefix}{name} {t}")

        counters, gauges, hists, _ = self._stable_items()
        for (name, labels), c in sorted(counters):
            typ(name + "_total", "counter")
            out.append(fmt(name + "_total", dict(labels), c.v))
        for (name, labels), g in sorted(gauges):
            typ(name, "gauge")
            out.append(fmt(name, dict(labels), g.v))
        for (name, labels), h in sorted(hists):
            typ(name, "histogram")
            lab = dict(labels)
            cum = 0
            for i, c in enumerate(h.counts):
                cum += c
                le = f"{h.bounds[i]:.9g}" if i < len(h.bounds) else "+Inf"
                out.append(fmt(name + "_bucket", {**lab, "le": le}, cum))
            out.append(fmt(name + "_sum", dict(labels), round(h.sum, 9)))
            out.append(fmt(name + "_count", dict(labels), h.n))
            # point quantiles alongside the cumulative buckets (summary-
            # style compat lines for dashboards that read p50/p95/p99
            # directly; within-bucket interpolated, like
            # Histogram.quantile)
            for q in _QUANTILES:
                out.append(
                    fmt(name, {**lab, "quantile": q},
                        f"{h.quantile(float(q)):.9g}")
                )
        return "\n".join(out) + "\n"

    # -- fleet gossip (delta snapshots) --------------------------------------

    def delta_snapshot(self, last: dict) -> dict:
        """Changed-instruments-only snapshot for the SS_OBS_SYNC gossip:
        ``last`` is the caller-held per-instrument memo of what was last
        shipped (mutated in place). Values are CUMULATIVE — the receiver
        overwrites per-key, so a lost-and-reconnected stream heals on
        the next change rather than drifting. Histograms ship whole on
        any change (cells are elementwise-merged downstream)."""

        def lk(k: tuple) -> str:
            name, labels = k
            if not labels:
                return name
            return name + "{" + ",".join(f"{a}={b}" for a, b in labels) + "}"

        counters, gauges, hists, _ = self._stable_items()
        lc = last.setdefault("c", {})
        lg = last.setdefault("g", {})
        lh = last.setdefault("h", {})
        out: dict = {}
        dc = {}
        for k, c in counters:
            key = lk(k)
            if lc.get(key) != c.v:
                lc[key] = dc[key] = c.v
        if dc:
            out["counters"] = dc
        dg = {}
        for k, g in gauges:
            key = lk(k)
            if lg.get(key) != g.v:
                lg[key] = dg[key] = g.v
        if dg:
            out["gauges"] = dg
        dh = {}
        for k, h in hists:
            key = lk(k)
            if lh.get(key) != h.n:
                lh[key] = h.n
                dh[key] = {
                    "bounds": list(h.bounds),
                    "counts": list(h.counts),
                    "sum": h.sum,
                    "count": h.n,
                }
        if dh:
            out["histograms"] = dh
        return out


class SnapshotRing:
    """Bounded ring of timestamped MERGED registry snapshots — the
    windowed-rate substrate under the SLO engine (obs/slo.py).

    The servers gossip CUMULATIVE counters/histogram cells; a burn-rate
    objective needs *windowed* rates ("errors over the last 30 s", "p99
    of the units closed in the last 5 s"). Appending the master's merged
    view once per evaluation tick makes any window a two-snapshot
    subtraction: the newest entry minus the newest entry at least
    ``window_s`` old. Deltas are clamped at zero because membership
    churn shrinks the merge (a retired server's snapshot is popped, so
    fleet sums can step DOWN without any event having un-happened).

    A young ring answers with the span it actually covers — ``span_s``
    rides every delta so the caller can rate-normalize honestly instead
    of dividing a 3-second delta by a 300-second window."""

    __slots__ = ("_ring",)

    def __init__(self, capacity: int = 600) -> None:
        self._ring: deque[tuple[float, dict]] = deque(maxlen=capacity)

    def __len__(self) -> int:
        return len(self._ring)

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    def grow(self, capacity: int) -> None:
        """Re-bound the ring (a later objective may need a longer
        window); shrinking is refused — a live window must not lose its
        far edge mid-evaluation."""
        if capacity > (self._ring.maxlen or 0):
            self._ring = deque(self._ring, maxlen=capacity)

    def append(self, t: float, merged: dict) -> None:
        self._ring.append((t, merged))

    def latest(self) -> Optional[tuple[float, dict]]:
        return self._ring[-1] if self._ring else None

    def baseline(self, window_s: float, now: float) -> \
            Optional[tuple[float, dict]]:
        """The window's far edge: the NEWEST entry at least ``window_s``
        old, else the oldest available (young ring). None when empty."""
        entries = safe_copy(self._ring)
        if not entries:
            return None
        cut = now - window_s
        best = entries[0]
        for t, snap in entries:
            if t <= cut:
                best = (t, snap)
            else:
                break
        return best

    def counter_delta(self, key: str, window_s: float,
                      now: float) -> tuple[float, float]:
        """(delta, span_s) of one merged-counter key over the window;
        delta clamps at 0 (see class docstring)."""
        cur = self.latest()
        base = self.baseline(window_s, now)
        if cur is None or base is None or cur[0] <= base[0]:
            return 0.0, 0.0
        d = cur[1].get("counters", {}).get(key, 0) - \
            base[1].get("counters", {}).get(key, 0)
        return max(d, 0.0), cur[0] - base[0]

    def hist_delta(self, key: str, window_s: float, now: float) -> \
            Optional[tuple[list, list, int, float]]:
        """(bounds, counts_delta, n_delta, span_s) of one merged
        histogram over the window — the input quantile_of turns into a
        windowed p99. Cells clamp at 0 elementwise; None when the
        histogram never appeared (or changed bucket geometry)."""
        cur = self.latest()
        base = self.baseline(window_s, now)
        if cur is None:
            return None
        h = cur[1].get("histograms", {}).get(key)
        if h is None:
            return None
        span = 0.0
        counts = list(h["counts"])
        n = h["count"]
        if base is not None and base[0] < cur[0]:
            span = cur[0] - base[0]
            hb = base[1].get("histograms", {}).get(key)
            if hb is not None and len(hb["counts"]) == len(counts):
                counts = [max(a - b, 0) for a, b in
                          zip(counts, hb["counts"])]
                n = max(n - hb["count"], 0)
        return list(h["bounds"]), counts, n, span

    def window_delta(self, window_s: float, now: float) -> dict:
        """The full merged-metrics delta over the window (changed
        counters + histograms with closes in-window, latest gauges) —
        the ``metrics_delta`` section of an incident bundle."""
        cur = self.latest()
        base = self.baseline(window_s, now)
        if cur is None:
            return {"span_s": 0.0, "counters": {}, "gauges": {},
                    "histograms": {}}
        bc = base[1].get("counters", {}) if base else {}
        bh = base[1].get("histograms", {}) if base else {}
        counters = {}
        for k, v in cur[1].get("counters", {}).items():
            d = v - bc.get(k, 0)
            if d > 0:
                counters[k] = d
        hists = {}
        for k, h in cur[1].get("histograms", {}).items():
            prev = bh.get(k)
            counts, n = list(h["counts"]), h["count"]
            if prev is not None and len(prev["counts"]) == len(counts):
                counts = [max(a - b, 0) for a, b in
                          zip(counts, prev["counts"])]
                n = max(n - prev["count"], 0)
            if n > 0:
                hists[k] = {"bounds": list(h["bounds"]),
                            "counts": counts, "count": n}
        return {
            "span_s": round(cur[0] - base[0], 3) if base else 0.0,
            "counters": counters,
            "gauges": dict(cur[1].get("gauges", {})),
            "histograms": hists,
        }


def _prom_key(key: str) -> tuple[str, dict]:
    """Split a snapshot label-key (``name{a=b,c=d}`` / ``name``) back
    into (name, labels) for re-exposition."""
    if not key.endswith("}"):
        return key, {}
    name, _, rest = key.partition("{")
    labels = {}
    for pair in rest[:-1].split(","):
        a, _, b = pair.partition("=")
        labels[a] = b
    return name, labels


def expose_merged(merged: dict, prefix: str = "adlb_fleet_") -> str:
    """Prometheus-style exposition of a :meth:`Registry.merge` result —
    the master's FLEET view on ``/metrics``: counters and histogram
    cells are fleet sums, gauges keep the per-rank label merge() gave
    them. Same line shapes as :meth:`Registry.expose` (counters gain
    ``_total``; histograms emit ``_bucket``/``_sum``/``_count`` plus the
    point-quantile compat lines)."""
    out: list[str] = []

    def fmt(name: str, labels: dict, v) -> str:
        if not labels:
            return f"{prefix}{name} {v}"
        ls = ",".join(f'{a}="{b}"' for a, b in sorted(labels.items()))
        return f"{prefix}{name}{{{ls}}} {v}"

    for key, v in sorted(merged.get("counters", {}).items()):
        name, labels = _prom_key(key)
        out.append(fmt(name + "_total", labels, v))
    for key, v in sorted(merged.get("gauges", {}).items()):
        name, labels = _prom_key(key)
        out.append(fmt(name, labels, v))
    for key, h in sorted(merged.get("histograms", {}).items()):
        name, labels = _prom_key(key)
        bounds, counts = h["bounds"], h["counts"]
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            le = f"{bounds[i]:.9g}" if i < len(bounds) else "+Inf"
            out.append(fmt(name + "_bucket", {**labels, "le": le}, cum))
        out.append(fmt(name + "_sum", labels, round(h["sum"], 9)))
        out.append(fmt(name + "_count", labels, h["count"]))
        for q in _QUANTILES:
            out.append(fmt(
                name, {**labels, "quantile": q},
                f"{quantile_of(bounds, counts, h['count'], float(q)):.9g}",
            ))
    return "\n".join(out) + ("\n" if out else "")


def attach(ep, registry: Optional[Registry]) -> None:
    """Point an endpoint's transport instrumentation at ``registry``
    (both the TCP and in-proc endpoints check ``self.metrics``). First
    attachment wins — a Server and a Client never share an endpoint, so
    this only guards double-init."""
    if registry is not None and getattr(ep, "metrics", None) is None:
        ep.metrics = registry
