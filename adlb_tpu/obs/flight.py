"""Crash flight recorder: JSON post-mortem artifacts for dead worlds.

Extends the in-memory circular log (:class:`adlb_tpu.runtime.debug.
FlightRecorder`, the reference's ``cblog``) with a durable JSON artifact:
when a rank dies — abort, watchdog timeout, lost home server — it writes
``flight-rank<R>-<reason>.json`` into the flight directory, carrying the
recent-event ring, a full metrics snapshot (counter totals, per-tag
message counts, the wq/rq depth timelines) and whatever role context the
caller adds. A chaos-soak failure then reads as a post-mortem instead of
demanding a rerun; ``scripts/obs_report.py`` summarizes the artifacts
offline.

Artifacts are opt-in: ``Config(flight_dir=...)`` or the
``ADLB_FLIGHT_DIR`` environment variable (the env var is how CI collects
them from worlds it did not configure). Disabled = the text dump through
the sink still happens, nothing is written.
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Optional

from adlb_tpu.obs.metrics import safe_copy
from adlb_tpu.runtime import debug as _debug

SCHEMA = 1


def _write_json(out_dir: str, filename: str, doc: dict) -> Optional[str]:
    """Atomic artifact write (tmp + rename, readers never see a torn
    file); returns the path, or None on failure — a post-mortem writer
    must never replace the original failure with an error of its own."""
    try:
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, filename)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path
    except (OSError, TypeError, ValueError):
        return None


def resolve_flight_dir(cfg_value: Optional[str] = None) -> Optional[str]:
    """Explicit config wins; else the ADLB_FLIGHT_DIR env contract (how
    CI and the native daemons' Python wrappers opt whole worlds in);
    else disabled."""
    return cfg_value or os.environ.get("ADLB_FLIGHT_DIR") or None


def _slug(reason: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]+", "_", reason).strip("_") or "dump"


class FlightRecorder(_debug.FlightRecorder):
    """The debug-layer ring plus JSON artifact emission.

    ``record()`` stays one deque append; ``dump()`` keeps the sink text
    dump (the reference's abort behaviour, and what the existing tests
    assert) and *additionally* writes the JSON artifact when a flight
    directory is configured. ``metrics`` and ``context`` are attached by
    the owner (server/client) after construction.
    """

    def __init__(
        self,
        rank: int,
        capacity: int = 512,
        out_dir: Optional[str] = None,
        role: str = "server",
    ) -> None:
        super().__init__(rank, capacity)
        self.out_dir = resolve_flight_dir(out_dir)
        self.role = role
        self.metrics = None  # Registry, attached by the owner
        self.context: dict = {}  # static role context (world shape, cfg)
        self.last_artifact: Optional[str] = None

    # -- artifact ------------------------------------------------------------

    def _safe_entries(self) -> list:
        """Ring copy tolerant of a concurrent writer: /dump runs on the
        ops HTTP thread while the reactor keeps record()-ing."""
        return safe_copy(self._ring)

    def snapshot_doc(self, reason: str = "") -> dict:
        """The artifact body, also served live by the ops endpoint's
        ``/dump`` (which must work without a flight directory)."""
        doc = {
            "schema": SCHEMA,
            "rank": self.rank,
            "role": self.role,
            "reason": reason,
            "wall_time": time.time(),
            "monotonic": time.monotonic(),
            "pid": os.getpid(),
            "context": dict(self.context),
            "events": [
                [round(ts, 6), text] for ts, text in self._safe_entries()
            ],
        }
        if self.metrics is not None:
            doc["metrics"] = self.metrics.snapshot()
        return doc

    def dump_json(self, reason: str = "") -> Optional[str]:
        """Write the artifact; returns its path, or None when disabled or
        unwritable (never raises — see _write_json)."""
        if not self.out_dir:
            return None
        # pid in the name: successive worlds sharing one flight dir
        # (a CI suite, a chaos soak) are distinct OS processes per
        # rank, so their post-mortems must not overwrite each other;
        # within ONE process re-dumps of the same reason overwrite,
        # which keeps long soaks bounded
        path = _write_json(
            self.out_dir,
            f"flight-rank{self.rank}-{_slug(reason)}-p{os.getpid()}.json",
            self.snapshot_doc(reason),
        )
        if path is not None:
            self.last_artifact = path
        return path

    def dump(self, reason: str = "") -> None:
        super().dump(reason)  # sink text dump (tests/operators read this)
        self.dump_json(reason)


def write_incident(
    out_dir: Optional[str], name: str, doc: dict
) -> Optional[str]:
    """Live incident bundle writer (the SLO engine's page-severity
    FIRING capture): same atomic write + pid-suffix rule as the
    post-mortem artifacts, but an ``incident-`` prefix so ``/flight``
    and ``obs_report --index`` can tell dead-world post-mortems from
    live captures. Within one process, re-fires of the same alert
    overwrite — a flapping objective cannot fill the disk."""
    out_dir = resolve_flight_dir(out_dir)
    if not out_dir:
        return None
    return _write_json(
        out_dir,
        f"incident-{_slug(name)}-p{os.getpid()}.json",
        {"schema": SCHEMA, **doc},
    )


def write_artifact(
    out_dir: Optional[str], name: str, doc: dict
) -> Optional[str]:
    """One-off artifact writer for roles without a recorder (the debug
    watchdog dumping its aggregates on timeout, the balancer sidecar at
    exit). Same pid-suffix rule as dump_json: successive worlds sharing
    one flight dir must not overwrite each other's post-mortems."""
    out_dir = resolve_flight_dir(out_dir)
    if not out_dir:
        return None
    return _write_json(
        out_dir,
        f"flight-{_slug(name)}-p{os.getpid()}.json",
        {"schema": SCHEMA, "wall_time": time.time(), **doc},
    )
