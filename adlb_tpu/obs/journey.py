"""Unit-lifecycle tracing: per-unit journeys through the fleet.

The SLO sensor layer: a sampled work unit (``Config(trace_sample)``
head-sampling at put — the client mints a ``trace_id`` that rides
``FA_PUT`` as codec field 98) accumulates a span list of
``(stage, rank, t_mono)`` tuples as it moves through the system:

    put_recv -> enqueue -> [wal_commit] -> [migrate | push | expire |
    adopt | replay]* -> match -> [relay] -> deliver -> finalize

The span list lives ON the unit (``WorkUnit.spans``) so every path that
moves a unit moves its history with it: ``SS_PUSH_WORK``,
``SS_MIGRATE_WORK``, the fused-relay ``SS_RFR_RESP``, the replication
stream / WAL (``replica.OP_TRACE``), and failover adoption. A terminal
event — delivery (``finalize``), quarantine, failover loss — closes the
record into a **journey** dict; the closing server feeds per-stage
latency histograms (``unit_stage_s{stage=,job=,type=}``: the time spent
REACHING each stage from the previous one, so queue wait / plan wait /
relay / fetch attribute separately) and, when ``Config(trace=True)``,
emits the journey into the Chrome-trace stream as a flow-event chain
(``ph: s/t/f`` sharing ``id=trace_id``) binding the hops across rank
lanes.

Closed journeys ride the fleet metrics gossip (``SS_OBS_SYNC``) to the
master, whose ops endpoint serves them on ``/trace/units``; summarize
offline with ``scripts/obs_report.py --journeys``.

**Tail-based promotion** (``Config(trace_tail)``, default on when
``ops_port`` is set): head sampling by construction almost never
records the p99/p999 outliers, so under tail mode EVERY put is armed
with spans (server-minted NEGATIVE trace ids — client-minted head ids
are positive, so the wire field 98 and the retention decision never
collide) and the recorder decides *retention* at terminal close:

* head-sampled (``trace_id > 0``) — kept, as before (``why=["head"]``);
* anomalous terminal — ``quarantined`` / ``dropped`` / ``lost``, or a
  delivered journey that crossed a lease ``expire`` hop — ALWAYS kept,
  so chaos events arrive with their full hop history attached;
* slow — total latency exceeds the live per-(job, type) p99 threshold
  the master computes from the merged fleet ``unit_total_s`` cells and
  gossips back on ``SS_OBS_SYNC`` replies. Hysteresis: a threshold
  only arms once its fleet cell holds ``TAIL_MIN_COUNT`` closes, so a
  cold histogram promotes nothing (anomalous terminals still do).

Unretained tail journeys still feed one ``unit_total_s`` observation
(that histogram IS the p99 estimator; the per-stage ``unit_stage_s``
cells stay head-sampled-only — the unbiased baseline) and skip the
journey-dict build — the hot-path cost of tail mode is spans + one
fold, bounded by the ``trace_tail_overhead_ratio`` bench arm. Promoted
journeys carry
``why=[...]`` and route to ``/trace/tails`` on the master (head
journeys keep ``/trace/units``), plus a ``prof_win`` window-id range
binding them to the continuous profiler's clock-aligned windows
(``obs/profile.py``) for the tail↔profile join.

Clock caveat: spans are ``time.monotonic`` stamps, comparable across
processes on ONE host (Linux CLOCK_MONOTONIC is system-wide). Cross-host
journeys carry each host's own clock — per-stage deltas that cross a
host boundary include the clock skew.
"""

from __future__ import annotations

import struct
from bisect import bisect_left
from collections import deque
from time import monotonic as _monotonic
from typing import Optional

from adlb_tpu.obs.profile import window_of

# Stage registry: the codes are the replica/WAL wire form (OP_TRACE),
# the names are the histogram labels and journey entries. Append-only —
# renumbering would corrupt WAL replays of older logs.
STAGES = (
    "put_recv",    # 1  FA_PUT arrived at the home-of-record server
    "enqueue",     # 2  unit admitted to the work queue
    "wal_commit",  # 3  the group commit covering this put fsynced (ack released)
    "match",       # 4  pinned for a requester (local match, plan, or RFR)
    "migrate",     # 5  landed at a migration destination (SS_MIGRATE_WORK)
    "push",        # 6  landed at a memory-pressure push target (SS_PUSH_WORK)
    "relay",       # 7  payload left the holder in a fused SS_RFR_RESP
    "deliver",     # 8  payload handed to the consuming app rank
    "finalize",    # 9  journey closed (terminal)
    "expire",      # 10 lease expired; unit re-enqueued under a fresh attempt
    "adopt",       # 11 adopted by a failover buddy at promotion
    "replay",      # 12 recovered from the WAL at cold restart
    # elastic membership (append-only — renumbering corrupts old WALs):
    "attach",      # 13 shipped to a scale-out shard's bootstrap rebalance
    "drain",       # 14 crossed a detach/scale-in drain (lease drained,
    #                   shard shipped to the buddy, target departed)
    # tail hedging (append-only — renumbering corrupts old WALs):
    "hedge",       # 15 a hedge sibling was launched for this unit (the
    #                   origin stamps it; the sibling's journey inherits
    #                   the origin's history including this hop)
)
STAGE_CODES = {name: i + 1 for i, name in enumerate(STAGES)}
CODE_STAGES = {v: k for k, v in STAGE_CODES.items()}

# per-unit span cap: a unit bouncing through expiry loops must not grow
# an unbounded history (the journey keeps its most recent window)
MAX_SPANS = 64

# tail-promotion hysteresis: the per-(job, type) p99 threshold only
# arms once the fleet's unit_total_s cell has seen this many closes —
# a cold histogram's p99 is noise and would promote everything
TAIL_MIN_COUNT = 64

_SPANHDR = struct.Struct("<qH")  # trace id, span count
_SPAN = struct.Struct("<Bid")    # stage code, rank, t_mono


def pack_spans(trace_id: int, spans) -> bytes:
    """Wire/WAL form of a unit's trace context (replica OP_TRACE body)."""
    spans = spans or []
    return _SPANHDR.pack(trace_id, len(spans)) + b"".join(
        _SPAN.pack(STAGE_CODES.get(stage, 0), rank, t)
        for stage, rank, t in spans
    )


def unpack_spans(body: bytes) -> tuple[int, list]:
    trace_id, n = _SPANHDR.unpack_from(body, 0)
    spans = []
    off = _SPANHDR.size
    for _ in range(n):
        code, rank, t = _SPAN.unpack_from(body, off)
        off += _SPAN.size
        spans.append((CODE_STAGES.get(code, "?"), rank, t))
    return trace_id, spans


class JourneyRecorder:
    """One server's unit-trace bookkeeping.

    ``begin``/``stamp`` are reactor-thread appends on the unit's own
    span list; ``close`` folds the spans into per-stage latency
    histograms and a bounded closed-journey deque (drained by the
    SS_OBS_SYNC gossip toward the master, or read directly on the
    master). ``live`` caps how many traced units this server will track
    at once — past it, new puts simply go untraced (``trace_dropped``
    counter) instead of growing without bound.
    """

    def __init__(self, rank: int, registry, tracer=None,
                 max_live: int = 4096, max_done: int = 1024) -> None:
        self.rank = rank
        self.registry = registry
        self.tracer = tracer
        self.max_live = max_live
        self.live = 0
        self.done: deque = deque(maxlen=max_done)
        # tail-based promotion (Config(trace_tail)): when armed the
        # server begins a journey on EVERY put (begin_tail) and close
        # decides retention; tail_thr is the fleet-fed per-(job, type)
        # p99 map the master computes and gossips (swapped whole, never
        # mutated in place — the reactor reads it mid-close)
        self.tail = False
        self.tail_thr: dict = {}
        self._tail_seq = 0
        self._m_closed = registry.counter("trace_journeys_closed")
        self._m_dropped = registry.counter("trace_dropped")
        self._m_promoted = registry.counter("trace_tail_promoted")
        # instrument cache: close_spans runs on the delivery hot path,
        # and the registry's kwargs/label lookup per observation is the
        # expensive part — hold the histogram objects by plain key
        self._hists: dict = {}
        self._totals: dict = {}
        self._errs: dict = {}

    # -- span lifecycle ------------------------------------------------------

    def begin(self, unit, trace_id: int, t: float) -> None:
        """Arm a freshly-put unit with its trace context (or drop the
        context at the live cap) and stamp ``put_recv``."""
        if self.live >= self.max_live:
            self._m_dropped.inc()
            return
        self.live += 1
        unit.trace_id = trace_id
        unit.spans = [("put_recv", self.rank, t)]

    def begin_tail(self, unit, t: float) -> None:
        """Arm an un-head-sampled unit under tail mode: the server mints
        a NEGATIVE trace id (rank in the high bits, like the client's
        positive head ids) so retention can tell the two apart at close
        without any extra per-unit state."""
        self.begin(unit, self.mint_tail_id(), t)

    def mint_tail_id(self) -> int:
        """A fresh server-minted (negative) trace id — begin_tail's, and
        the hedge launcher's for sibling journeys that carry a copy of
        the origin's span history under their own identity."""
        self._tail_seq += 1
        return -((self.rank << 40) | self._tail_seq)

    def adopt(self, unit, trace_id: int, spans, stage: Optional[str] = None,
              t: Optional[float] = None) -> None:
        """Attach a context that arrived WITH the unit (push, migrate,
        WAL replay, failover adoption), optionally stamping the arrival
        stage. Counts against the live cap like begin()."""
        if not trace_id:
            return
        if self.live >= self.max_live:
            self._m_dropped.inc()
            return
        self.live += 1
        unit.trace_id = trace_id
        unit.spans = list(spans or [])
        if stage is not None:
            self.stamp(unit, stage, t)

    def stamp(self, unit, stage: str, t: Optional[float] = None) -> None:
        spans = unit.spans
        if spans is None:
            return
        if len(spans) >= MAX_SPANS:
            del spans[1:2]  # keep put_recv; shed the oldest middle hop
        spans.append((stage, self.rank,
                      _monotonic() if t is None else t))

    def forget(self, unit) -> None:
        """Release a unit's context without closing (the fused-relay
        handoff: the requester's HOME closed the journey from the copy
        that rode the SS_RFR_RESP; the holder's original is dropped at
        the SS_DELIVERED consume)."""
        if unit.spans is not None:
            unit.spans = None
            unit.trace_id = 0
            self.live = max(0, self.live - 1)

    # -- closing -------------------------------------------------------------

    def deliver_close(self, unit, t: Optional[float] = None) -> None:
        """Fused deliver-stamp + delivered-close — ONE call on the hot
        delivery path (under tail mode it runs for every unit; the
        deliver and finalize stamps share one clock read, since they
        land in the same handler anyway)."""
        spans = unit.spans
        if spans is None:
            return
        tm = _monotonic() if t is None else t
        if len(spans) >= MAX_SPANS:
            del spans[1:2]
        spans.append(("deliver", self.rank, tm))
        tid = unit.trace_id
        if tid > 0:
            # head journeys keep the PR 12 stage set (finalize last);
            # tail journeys end at deliver (same instant, one fold less)
            spans.append(("finalize", self.rank, tm))
        unit.spans = None
        unit.trace_id = 0
        if self.live > 0:
            self.live -= 1
        self.close_spans(tid, unit.job, unit.work_type, "delivered", spans)

    def close(self, unit, end: str, t: Optional[float] = None) -> None:
        """Terminal event on a locally-held unit: finalize-stamp and fold
        the journey. Tail-minted journeys (negative ids — EVERY unit in
        a tail-armed world) skip the finalize stamp when the last hop is
        already this close's own ``deliver``: the two stamps land in the
        same handler microseconds apart, so the hop carries no
        attribution and costs a span + a fold per unit. Terminal closes
        without a deliver hop (quarantine, drop, loss) still stamp."""
        if unit.spans is None:
            return
        if unit.trace_id > 0 or unit.spans[-1][0] != "deliver":
            self.stamp(unit, "finalize", t)
        spans, trace_id = unit.spans, unit.trace_id
        unit.spans = None
        unit.trace_id = 0
        self.live = max(0, self.live - 1)
        self.close_spans(trace_id, unit.job, unit.work_type, end, spans)

    def close_spans(self, trace_id: int, job: int, work_type: int,
                    end: str, spans: list) -> None:
        """Close an explicit span list into a journey (the relay path
        at the requester's home server, and failover-loss closes, hold
        spans without a live local unit).

        Under tail mode this runs for EVERY unit, so the folds split by
        what each estimator actually needs: the p99 promotion threshold
        is a quantile of TOTAL latency, so the tail bulk (negative ids)
        feeds one ``unit_total_s`` observation and nothing else; the
        per-stage ``unit_stage_s`` cells stay head-sampled-only — they
        exist to be an UNBIASED per-stage baseline (the /jobs view and
        the tails excess attribution), and folding the promoted slow
        journeys into them would bias exactly that baseline, while
        promoted journeys already carry their raw spans for exact
        within-journey deltas. Net: the every-unit path costs one
        histogram observation plus the retention check (written for the
        1-core GIL-coupled worst case — each microsecond here is
        client-visible pop latency on a saturated core)."""
        if not spans:
            return
        self._m_closed.v += 1  # counter.inc() inlined: every-unit path
        total = spans[-1][2] - spans[0][2]
        if total < 0.0:
            total = 0.0
        ht = self._totals.get((job, work_type))
        if ht is None:
            ht = self._totals[(job, work_type)] = self.registry.histogram(
                "unit_total_s", job=str(job), type=str(work_type)
            )
        # Histogram.observe inlined (every-unit path): one bisect + adds
        ht.counts[bisect_left(ht.bounds, total)] += 1
        ht.sum += total
        ht.n += 1
        if end != "delivered":
            # the SLO engine's error-rate numerator: anomalous closes
            # per (job, type), with the total histogram's count as the
            # matching denominator (every close folds both)
            ec = self._errs.get((job, work_type))
            if ec is None:
                ec = self._errs[(job, work_type)] = self.registry.counter(
                    "unit_errors", job=str(job), type=str(work_type)
                )
            ec.v += 1  # counter.inc() inlined: every-unit path
        if trace_id > 0:
            # head-sampled: the unbiased per-stage baseline cells
            hists = self._hists
            prev_t = spans[0][2]
            for span in spans[1:]:
                stage = span[0]
                t = span[2]
                h = hists.get((stage, job, work_type))
                if h is None:
                    h = hists[(stage, job, work_type)] = \
                        self.registry.histogram(
                            "unit_stage_s", stage=stage, job=str(job),
                            type=str(work_type),
                        )
                d = t - prev_t
                h.observe(d if d > 0.0 else 0.0)
                prev_t = t
        # ---- retention decision (the head-vs-tail sampling gap fix):
        # the journey dict below is only built for what we keep; the
        # dominant case — tail-armed clean delivery, below threshold —
        # exits with two dict probes and a span scan
        if trace_id < 0 and end == "delivered":
            why = None
            for s in spans:
                st = s[0]
                if st == "expire":
                    why = ["expired_lease"]
                    break
                if st == "hedge":
                    # a hedge race crossed this journey (this copy won
                    # it — losers are forgotten, never closed): always
                    # keep, so every hedge outcome lands in /trace/tails
                    why = ["hedged"]
                    break
                if st == "attach" or st == "drain":
                    # membership churn crossed this journey (scale-out
                    # bootstrap / detach / scale-in drain): always keep,
                    # so churn events are visible in /trace/tails
                    why = ["churn"]
                    break
            if why is None:
                thr = self.tail_thr.get((job, work_type))
                if thr is None or total <= thr:
                    return
                why = ["slow"]
        else:
            why = self._why(trace_id, job, work_type, end, total, spans)
            if not why:
                return
        if why != ["head"]:
            self._m_promoted.inc()
        self.done.append({
            "trace_id": trace_id,
            "job": job,
            "type": work_type,
            "end": end,
            "why": why,
            "t0": round(spans[0][2], 6),
            "total_s": round(total, 6),
            # the profiler window-id range this journey crossed: window
            # ids are clock-aligned (t // WINDOW_S on the shared host
            # CLOCK_MONOTONIC), so no profiler handshake is needed here
            "prof_win": [window_of(spans[0][2]), window_of(spans[-1][2])],
            "spans": [[stage, rank, round(t, 6)] for stage, rank, t in spans],
        })
        tr = self.tracer
        if tr is not None:
            # flow-event chain into the merged Chrome-trace stream: one
            # s/t/.../f sequence sharing id=trace_id, each step on the
            # lane (tid) of the rank that performed the hop, so Perfetto
            # draws the unit's path across server lanes
            last = len(spans) - 1
            for i, (stage, rank, t) in enumerate(spans):
                ev = {
                    "name": "unit",
                    "cat": "unit",
                    "ph": "s" if i == 0 else ("f" if i == last else "t"),
                    "id": trace_id,
                    "ts": t * 1e6,
                    "pid": tr.pid,
                    "tid": rank,
                    "args": {"stage": stage, "job": job,
                             "type": work_type, "end": end},
                }
                if i == last:
                    ev["bp"] = "e"
                tr._emit(ev)

    def _why(self, trace_id: int, job: int, work_type: int, end: str,
             total: float, spans: list) -> list:
        """Retention reasons for a closed journey (empty = drop).

        Head-sampled ids (positive) always keep — the PR 12 behavior is
        unchanged. Under tail mode, anomalous terminals (anything but a
        clean delivery, plus delivered journeys that crossed a lease
        expiry) always promote, and a clean delivery promotes iff it
        blew past the fleet-fed per-(job, type) p99 threshold."""
        why = []
        if trace_id > 0:
            why.append("head")
        if self.tail:
            if end != "delivered":
                why.append(end)
                for s in spans:
                    if s[0] == "hedge":
                        # an anomalous terminal that crossed a hedge
                        # race still tags it, so /trace/tails answers
                        # "was hedging in play?" for every outcome
                        why.append("hedged")
                        break
            else:
                # plain loop, not any(genexpr): this runs per close
                # under tail mode and the generator allocation is a
                # measured slice of the per-journey cost
                mark = None
                for s in spans:
                    st = s[0]
                    if st == "expire":
                        mark = "expired_lease"
                        break
                    if st == "hedge":
                        mark = "hedged"
                        break
                    if st == "attach" or st == "drain":
                        mark = "churn"
                        break
                if mark is not None:
                    why.append(mark)
                else:
                    thr = self.tail_thr.get((job, work_type))
                    if thr is not None and total > thr:
                        why.append("slow")
        return why

    def take_done(self) -> list:
        """Drain closed journeys (the gossip tick toward the master)."""
        out = []
        while self.done:
            try:
                out.append(self.done.popleft())
            except IndexError:  # pragma: no cover — single-consumer today
                break
        return out


def trace_fields(unit) -> Optional[dict]:
    """The one-key wire form a unit's context rides in pickled SS frames
    (push / migrate dicts, the fused-relay response): ``None`` when the
    unit is untraced, so untraced frames stay byte-identical."""
    if not unit.trace_id or unit.spans is None:
        return None
    return {"id": unit.trace_id, "spans": list(unit.spans)}
