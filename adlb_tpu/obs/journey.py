"""Unit-lifecycle tracing: per-unit journeys through the fleet.

The SLO sensor layer: a sampled work unit (``Config(trace_sample)``
head-sampling at put — the client mints a ``trace_id`` that rides
``FA_PUT`` as codec field 98) accumulates a span list of
``(stage, rank, t_mono)`` tuples as it moves through the system:

    put_recv -> enqueue -> [wal_commit] -> [migrate | push | expire |
    adopt | replay]* -> match -> [relay] -> deliver -> finalize

The span list lives ON the unit (``WorkUnit.spans``) so every path that
moves a unit moves its history with it: ``SS_PUSH_WORK``,
``SS_MIGRATE_WORK``, the fused-relay ``SS_RFR_RESP``, the replication
stream / WAL (``replica.OP_TRACE``), and failover adoption. A terminal
event — delivery (``finalize``), quarantine, failover loss — closes the
record into a **journey** dict; the closing server feeds per-stage
latency histograms (``unit_stage_s{stage=,job=,type=}``: the time spent
REACHING each stage from the previous one, so queue wait / plan wait /
relay / fetch attribute separately) and, when ``Config(trace=True)``,
emits the journey into the Chrome-trace stream as a flow-event chain
(``ph: s/t/f`` sharing ``id=trace_id``) binding the hops across rank
lanes.

Closed journeys ride the fleet metrics gossip (``SS_OBS_SYNC``) to the
master, whose ops endpoint serves them on ``/trace/units``; summarize
offline with ``scripts/obs_report.py --journeys``.

Clock caveat: spans are ``time.monotonic`` stamps, comparable across
processes on ONE host (Linux CLOCK_MONOTONIC is system-wide). Cross-host
journeys carry each host's own clock — per-stage deltas that cross a
host boundary include the clock skew.
"""

from __future__ import annotations

import struct
from collections import deque
from time import monotonic as _monotonic
from typing import Optional

# Stage registry: the codes are the replica/WAL wire form (OP_TRACE),
# the names are the histogram labels and journey entries. Append-only —
# renumbering would corrupt WAL replays of older logs.
STAGES = (
    "put_recv",    # 1  FA_PUT arrived at the home-of-record server
    "enqueue",     # 2  unit admitted to the work queue
    "wal_commit",  # 3  the group commit covering this put fsynced (ack released)
    "match",       # 4  pinned for a requester (local match, plan, or RFR)
    "migrate",     # 5  landed at a migration destination (SS_MIGRATE_WORK)
    "push",        # 6  landed at a memory-pressure push target (SS_PUSH_WORK)
    "relay",       # 7  payload left the holder in a fused SS_RFR_RESP
    "deliver",     # 8  payload handed to the consuming app rank
    "finalize",    # 9  journey closed (terminal)
    "expire",      # 10 lease expired; unit re-enqueued under a fresh attempt
    "adopt",       # 11 adopted by a failover buddy at promotion
    "replay",      # 12 recovered from the WAL at cold restart
)
STAGE_CODES = {name: i + 1 for i, name in enumerate(STAGES)}
CODE_STAGES = {v: k for k, v in STAGE_CODES.items()}

# per-unit span cap: a unit bouncing through expiry loops must not grow
# an unbounded history (the journey keeps its most recent window)
MAX_SPANS = 64

_SPANHDR = struct.Struct("<qH")  # trace id, span count
_SPAN = struct.Struct("<Bid")    # stage code, rank, t_mono


def pack_spans(trace_id: int, spans) -> bytes:
    """Wire/WAL form of a unit's trace context (replica OP_TRACE body)."""
    spans = spans or []
    return _SPANHDR.pack(trace_id, len(spans)) + b"".join(
        _SPAN.pack(STAGE_CODES.get(stage, 0), rank, t)
        for stage, rank, t in spans
    )


def unpack_spans(body: bytes) -> tuple[int, list]:
    trace_id, n = _SPANHDR.unpack_from(body, 0)
    spans = []
    off = _SPANHDR.size
    for _ in range(n):
        code, rank, t = _SPAN.unpack_from(body, off)
        off += _SPAN.size
        spans.append((CODE_STAGES.get(code, "?"), rank, t))
    return trace_id, spans


class JourneyRecorder:
    """One server's unit-trace bookkeeping.

    ``begin``/``stamp`` are reactor-thread appends on the unit's own
    span list; ``close`` folds the spans into per-stage latency
    histograms and a bounded closed-journey deque (drained by the
    SS_OBS_SYNC gossip toward the master, or read directly on the
    master). ``live`` caps how many traced units this server will track
    at once — past it, new puts simply go untraced (``trace_dropped``
    counter) instead of growing without bound.
    """

    def __init__(self, rank: int, registry, tracer=None,
                 max_live: int = 4096, max_done: int = 1024) -> None:
        self.rank = rank
        self.registry = registry
        self.tracer = tracer
        self.max_live = max_live
        self.live = 0
        self.done: deque = deque(maxlen=max_done)
        self._m_closed = registry.counter("trace_journeys_closed")
        self._m_dropped = registry.counter("trace_dropped")
        # instrument cache: close_spans runs on the delivery hot path,
        # and the registry's kwargs/label lookup per observation is the
        # expensive part — hold the histogram objects by plain key
        self._hists: dict = {}
        self._totals: dict = {}

    # -- span lifecycle ------------------------------------------------------

    def begin(self, unit, trace_id: int, t: float) -> None:
        """Arm a freshly-put unit with its trace context (or drop the
        context at the live cap) and stamp ``put_recv``."""
        if self.live >= self.max_live:
            self._m_dropped.inc()
            return
        self.live += 1
        unit.trace_id = trace_id
        unit.spans = [("put_recv", self.rank, t)]

    def adopt(self, unit, trace_id: int, spans, stage: Optional[str] = None,
              t: Optional[float] = None) -> None:
        """Attach a context that arrived WITH the unit (push, migrate,
        WAL replay, failover adoption), optionally stamping the arrival
        stage. Counts against the live cap like begin()."""
        if not trace_id:
            return
        if self.live >= self.max_live:
            self._m_dropped.inc()
            return
        self.live += 1
        unit.trace_id = trace_id
        unit.spans = list(spans or [])
        if stage is not None:
            self.stamp(unit, stage, t)

    def stamp(self, unit, stage: str, t: Optional[float] = None) -> None:
        spans = unit.spans
        if spans is None:
            return
        if len(spans) >= MAX_SPANS:
            del spans[1:2]  # keep put_recv; shed the oldest middle hop
        spans.append((stage, self.rank,
                      _monotonic() if t is None else t))

    def forget(self, unit) -> None:
        """Release a unit's context without closing (the fused-relay
        handoff: the requester's HOME closed the journey from the copy
        that rode the SS_RFR_RESP; the holder's original is dropped at
        the SS_DELIVERED consume)."""
        if unit.spans is not None:
            unit.spans = None
            unit.trace_id = 0
            self.live = max(0, self.live - 1)

    # -- closing -------------------------------------------------------------

    def close(self, unit, end: str, t: Optional[float] = None) -> None:
        """Terminal event on a locally-held unit: finalize-stamp and fold
        the journey."""
        if unit.spans is None:
            return
        self.stamp(unit, "finalize", t)
        spans, trace_id = unit.spans, unit.trace_id
        unit.spans = None
        unit.trace_id = 0
        self.live = max(0, self.live - 1)
        self.close_spans(trace_id, unit.job, unit.work_type, end, spans)

    def close_spans(self, trace_id: int, job: int, work_type: int,
                    end: str, spans: list) -> None:
        """Fold an explicit span list into a closed journey (the relay
        path at the requester's home server, and failover-loss closes,
        hold spans without a live local unit)."""
        if not spans:
            return
        reg = self.registry
        prev_t = spans[0][2]
        for stage, _rank, t in spans[1:]:
            h = self._hists.get((stage, job, work_type))
            if h is None:
                h = self._hists[(stage, job, work_type)] = reg.histogram(
                    "unit_stage_s", stage=stage, job=str(job),
                    type=str(work_type),
                )
            h.observe(max(t - prev_t, 0.0))
            prev_t = t
        ht = self._totals.get((job, work_type))
        if ht is None:
            ht = self._totals[(job, work_type)] = reg.histogram(
                "unit_total_s", job=str(job), type=str(work_type)
            )
        ht.observe(max(spans[-1][2] - spans[0][2], 0.0))
        self._m_closed.inc()
        self.done.append({
            "trace_id": trace_id,
            "job": job,
            "type": work_type,
            "end": end,
            "t0": round(spans[0][2], 6),
            "total_s": round(max(spans[-1][2] - spans[0][2], 0.0), 6),
            "spans": [[stage, rank, round(t, 6)] for stage, rank, t in spans],
        })
        tr = self.tracer
        if tr is not None:
            # flow-event chain into the merged Chrome-trace stream: one
            # s/t/.../f sequence sharing id=trace_id, each step on the
            # lane (tid) of the rank that performed the hop, so Perfetto
            # draws the unit's path across server lanes
            last = len(spans) - 1
            for i, (stage, rank, t) in enumerate(spans):
                ev = {
                    "name": "unit",
                    "cat": "unit",
                    "ph": "s" if i == 0 else ("f" if i == last else "t"),
                    "id": trace_id,
                    "ts": t * 1e6,
                    "pid": tr.pid,
                    "tid": rank,
                    "args": {"stage": stage, "job": job,
                             "type": work_type, "end": end},
                }
                if i == last:
                    ev["bp"] = "e"
                tr._emit(ev)

    def take_done(self) -> list:
        """Drain closed journeys (the gossip tick toward the master)."""
        out = []
        while self.done:
            try:
                out.append(self.done.popleft())
            except IndexError:  # pragma: no cover — single-consumer today
                break
        return out


def trace_fields(unit) -> Optional[dict]:
    """The one-key wire form a unit's context rides in pickled SS frames
    (push / migrate dicts, the fused-relay response): ``None`` when the
    unit is untraced, so untraced frames stay byte-identical."""
    if not unit.trace_id or unit.spans is None:
        return None
    return {"id": unit.trace_id, "spans": list(unit.spans)}
