"""Continuous sampling profiler: folded stacks by thread role + phase.

The fleet's "why is the CPU busy" sensor (Google-Wide-Profiling-style):
an always-available, low-overhead sampling thread per process
(``Config(profile_hz)``, default 0 = off; 19 Hz recommended — a prime,
so it cannot phase-lock with the 20 ms balancer tick or the 50 ms
qmstat cadence) walks ``sys._current_frames()`` and folds each thread's
stack into a collapsed-stack counter::

    <role>;[phase:<p>;]<outer frame>;...;<inner frame>  ->  samples

* **role** — threads declare themselves via :func:`register_thread`
  ("reactor", "balancer", "heartbeat", "client", ...); undeclared
  threads fall back to their thread name. Registration is a plain dict
  write, safe to call whether or not a profiler is running.
* **phase** — the server reactor publishes a *phase marker*
  (:meth:`Profiler.set_phase`: ``decode`` / ``handler:<TAG>`` /
  ``wal_fsync`` / ``submit_flush`` / ``periodic``; the balancer thread
  publishes ``balancer_tick``) so each sample lands in the tick phase
  it interrupted. Markers are edge-set (a plain per-thread dict write,
  nanoseconds) — a sample between two edges attributes to the previous
  phase, which at 19 Hz vs sub-ms phases is the usual sampling blur.
* **windows** — besides the cumulative counters, samples also land in
  the current **window**: ``window_id = int(t_mono // WINDOW_S)``,
  i.e. windows are aligned to the host's shared CLOCK_MONOTONIC, so a
  window id computed from a journey span's timestamp on ANY co-located
  rank names the same wall interval (the tail↔profile join needs no
  clock exchange). Sealed windows keep their top stacks only, in a
  bounded ring.

Counters are CUMULATIVE and delta-gossiped over ``SS_OBS_SYNC`` like
registry instruments (changed-stacks-only; a lost frame heals on the
next change). The master serves the merged fleet profile at
``/profile`` (collapsed-stack text, or JSON with ``?format=json``);
render offline with ``scripts/obs_report.py --profile``.

One profiler per PROCESS: in-proc worlds run many server threads in one
interpreter, and ``sys._current_frames()`` sees them all — the first
server to start one owns it (and gossips it); later servers share the
instance for phase markers only, so the fleet view counts each process
exactly once.

Overhead: one ``sys._current_frames()`` + a frame walk per tick. At
19 Hz with ~10 threads x ~30 frames that is well under 0.1% of a core
(the ``profile_overhead`` bench row bounds the end-to-end cost at
<= 1.05x pop latency, same bar as the trace arms).
"""

from __future__ import annotations

import sys
import threading
from collections import deque
from time import monotonic as _monotonic
from typing import Optional

# window geometry: 1 s windows, last 64 kept (≈ a minute of history for
# the tail join), top 40 stacks per sealed window
WINDOW_S = 1.0
MAX_WINDOWS = 64
WINDOW_TOP_STACKS = 40

MAX_DEPTH = 48     # frames kept per stack (outermost dropped beyond it)
MAX_STACKS = 4096  # distinct folded keys; beyond it samples fold into
# a per-role "<overflow>" key instead of growing without bound

# thread ident -> declared role; module-global so threads can register
# before (or without) a profiler existing. Never cleared — idents are
# reused by the OS, but a reused ident belongs to a NEW thread that
# re-registers (or falls back to its thread name).
_roles: dict[int, str] = {}

_lock = threading.Lock()
_active: "Optional[Profiler]" = None


def register_thread(role: str, ident: Optional[int] = None) -> None:
    """Declare the calling thread's role for stack folding. Cheap and
    unconditional — call it whether or not profiling is armed."""
    _roles[threading.get_ident() if ident is None else ident] = role


def start(hz: float, rank: int) -> Optional["Profiler"]:
    """Start the per-process profiler and return it iff the caller now
    OWNS it (first starter wins; later callers get None and should use
    :func:`active` for phase markers only — ownership decides who
    gossips, so a shared process is counted once)."""
    global _active
    if hz <= 0:
        return None
    with _lock:
        if _active is not None:
            return None
        p = Profiler(hz, rank)
        _active = p
    p._start_thread()
    return p


def active() -> Optional["Profiler"]:
    return _active


def stop(p: Optional["Profiler"]) -> None:
    """Stop an owned profiler (no-op for None / a non-owner handle)."""
    global _active
    if p is None:
        return
    p._stop_thread()
    with _lock:
        if _active is p:
            _active = None


def window_of(t_mono: float) -> int:
    """The window id covering a CLOCK_MONOTONIC stamp — shared math
    with the journey side of the tail↔profile join."""
    return int(t_mono // WINDOW_S)


class Profiler:
    """One process's folded-stack sampler. Construct via :func:`start`."""

    def __init__(self, hz: float, rank: int) -> None:
        self.hz = float(hz)
        self.rank = rank
        self.samples = 0
        # folded stack -> cumulative sample count (reader: the ops
        # scrape / gossip delta; writes are GIL-atomic dict ops, same
        # discipline as the metrics registry)
        self.counts: dict[str, int] = {}
        # sealed windows, oldest first: {"id", "t0", "t1", "stacks"}
        self.windows: deque = deque(maxlen=MAX_WINDOWS)
        self._win_id = window_of(_monotonic())
        self._win_counts: dict[str, int] = {}
        self._phases: dict[int, str] = {}     # thread ident -> phase
        self._names: dict[int, str] = {}      # ident -> thread-name cache
        self._code_names: dict = {}           # code object -> display name
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._ident: Optional[int] = None

    # -- lifecycle -----------------------------------------------------------

    def _start_thread(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"adlb-prof-{self.rank}"
        )
        self._thread.start()

    def _stop_thread(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=2.0)

    def _run(self) -> None:
        self._ident = threading.get_ident()
        period = 1.0 / self.hz
        while not self._stop.wait(period):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 — a torn frame walk must
                pass  # never kill the sampler (threads die mid-walk)

    # -- markers -------------------------------------------------------------

    def set_phase(self, phase: str) -> None:
        """Publish the calling thread's current phase (edge-set)."""
        self._phases[threading.get_ident()] = phase

    # -- sampling ------------------------------------------------------------

    def _frame_name(self, code) -> str:
        name = self._code_names.get(code)
        if name is None:
            fn = code.co_filename
            base = fn[fn.rfind("/") + 1:]
            if base.endswith(".py"):
                base = base[:-3]
            name = self._code_names[code] = f"{base}.{code.co_name}"
        return name

    def _role_of(self, ident: int) -> str:
        role = _roles.get(ident)
        if role is not None:
            return role
        name = self._names.get(ident)
        if name is None:
            for t in threading.enumerate():
                if t.ident is not None and t.ident not in self._names:
                    self._names[t.ident] = t.name
            name = self._names.get(ident, f"tid-{ident}")
        return name

    def sample_once(self, now: Optional[float] = None) -> None:
        """One sampling tick: every live thread's stack (except the
        sampler's own) folds into the cumulative and current-window
        counters. Exposed for deterministic tests."""
        t = _monotonic() if now is None else now
        wid = window_of(t)
        if wid != self._win_id:
            self._seal_window()
            self._win_id = wid
        own = self._ident if self._ident is not None \
            else threading.get_ident()
        counts, win = self.counts, self._win_counts
        for ident, frame in sys._current_frames().items():
            if ident == own:
                continue
            parts = []
            f, depth = frame, 0
            while f is not None and depth < MAX_DEPTH:
                parts.append(self._frame_name(f.f_code))
                f = f.f_back
                depth += 1
            parts.reverse()
            head = [self._role_of(ident)]
            phase = self._phases.get(ident)
            if phase is not None:
                head.append(f"phase:{phase}")
            key = ";".join(head + parts)
            if key not in counts and len(counts) >= MAX_STACKS:
                key = f"{head[0]};<overflow>"
            counts[key] = counts.get(key, 0) + 1
            win[key] = win.get(key, 0) + 1
        self.samples += 1

    def _seal_window(self) -> None:
        if self._win_counts:
            top = dict(sorted(
                self._win_counts.items(), key=lambda kv: -kv[1]
            )[:WINDOW_TOP_STACKS])
            self.windows.append({
                "id": self._win_id,
                "t0": round(self._win_id * WINDOW_S, 3),
                "t1": round((self._win_id + 1) * WINDOW_S, 3),
                "stacks": top,
            })
            self._win_counts = {}

    # -- export --------------------------------------------------------------

    def _stable_counts(self) -> list:
        """Item list of the cumulative counters, retried against the
        sampler thread inserting a first-seen stack mid-copy (the same
        discipline as metrics.safe_copy; value updates are GIL-atomic)."""
        for _ in range(8):
            try:
                return list(self.counts.items())
            except RuntimeError:
                continue
        return []

    def snapshot(self) -> dict:
        """Whole-profile view (the master's own live contribution)."""
        return {
            "hz": self.hz,
            "samples": self.samples,
            "stacks": dict(self._stable_counts()),
            "win": _stable_list(self.windows),
        }

    def take_delta(self, last: dict) -> dict:
        """Changed-stacks-only cumulative delta + windows sealed since
        the previous ship — the SS_OBS_SYNC gossip body. ``last`` is
        the caller-held memo, mutated in place (same contract as
        ``Registry.delta_snapshot``)."""
        ls = last.setdefault("s", {})
        out_stacks = {}
        for k, v in self._stable_counts():
            if ls.get(k) != v:
                ls[k] = out_stacks[k] = v
        last_win = last.get("w", -1)
        wins = [w for w in _stable_list(self.windows) if w["id"] > last_win]
        if wins:
            last["w"] = wins[-1]["id"]
        out: dict = {}
        if out_stacks:
            out["stacks"] = out_stacks
        if wins:
            out["win"] = wins
        if out:
            out["hz"] = self.hz
            out["samples"] = self.samples
        return out


def _stable_list(seq) -> list:
    """Copy a deque the sampler thread may be appending to (appends are
    atomic; iteration during a mutation raises — retry)."""
    for _ in range(8):
        try:
            return list(seq)
        except RuntimeError:
            continue
    return []


def merge_stacks(per_rank: dict) -> dict:
    """Elementwise sum of per-rank ``{stack: count}`` dicts — the
    master's merged fleet view on ``/profile``."""
    merged: dict[str, int] = {}
    for stacks in per_rank.values():
        for k, v in stacks.items():
            merged[k] = merged.get(k, 0) + v
    return merged


def collapsed_text(stacks: dict) -> str:
    """Flamegraph-compatible collapsed form: one ``stack count`` line
    per folded stack, heaviest first."""
    lines = [
        f"{k} {v}"
        for k, v in sorted(stacks.items(), key=lambda kv: (-kv[1], kv[0]))
    ]
    return "\n".join(lines) + ("\n" if lines else "")
