"""Live ops endpoint on the master server.

A localhost HTTP surface (``Config(ops_port=...)``) so an operator — or a
scraper — can interrogate a running world without touching the protocol
plane:

* ``GET /healthz`` — liveness + role summary (uptime, wq/rq depth,
  done/aborted flags) plus per-rank snapshot staleness from the
  SS_OBS_SYNC gossip (a wedged server's age grows before it EOFs); JSON.
* ``GET /metrics`` — Prometheus-style text exposition of the master's
  registry (per-tag message counters, queue-depth gauges, latency
  histograms), the ``adlb_fleet_*`` merged-fleet section (the master's
  registry + every gossiped per-rank snapshot through
  ``Registry.merge``) with per-rank seq/age provenance rows, and the
  **world aggregate**: the most recent STAT_APS record the
  periodic-stats ring delivered (enable with
  ``Config(periodic_log_interval=...)``), exposed as
  ``adlb_world_*``/``adlb_server_*`` samples stamped with the ring
  sequence number AND aged (``adlb_stat_aps_age_seconds``) so stale
  data is distinguishable from live.
* ``GET /trace/units`` — the fleet journey store (unit-lifecycle
  tracing, ``Config(trace_sample)``): closed per-unit journeys from
  every rank, summarizable offline with
  ``scripts/obs_report.py --journeys``. Supports ``?job=``, ``?type=``,
  ``?min_ms=`` and ``?limit=`` (newest N) query filters — the bounded
  store holds up to 4096 journeys, which is an unwieldy single body.
* ``GET /trace/tails`` — the TAIL store (``Config(trace_tail)``):
  journeys promoted at close because they blew the live per-(job,type)
  fleet p99 or ended anomalously (quarantined/dropped/lost/expired
  lease). Same query filters as ``/trace/units``. Each journey comes
  annotated with the stage that blew past its fleet-typical p50
  (``slow_stage``/``excess_s``) and, when the continuous profiler is
  armed, the dominant folded stacks active on the responsible rank
  during the window(s) that stage crossed (``stacks``) — the
  tail↔profile join. Render with ``scripts/obs_report.py --tails``.
* ``GET /profile`` — the merged fleet continuous profile
  (``Config(profile_hz)``): collapsed-stack text (flamegraph-ready;
  one ``role;[phase:..;]frames... count`` line per stack), or the full
  JSON document (per-rank stacks + sampling windows) with
  ``?format=json``. Render with ``scripts/obs_report.py --profile``.
* ``GET /dump`` — trigger a flight-record snapshot: returns the JSON doc
  inline and writes the artifact when a flight directory is configured.
* ``GET /deadletter`` — this server's dead-letter quarantine (units that
  exhausted ``Config(max_unit_retries)``): metadata + attempt counts,
  payloads hex-encoded and truncated to ``Config(ops_dump_bytes)``. The
  store is per-server; the ops endpoint runs on the master, so this is
  the master's shard — ``ctx.get_quarantined()`` is the world-wide view.
* ``/fleet`` — elastic membership (adlb_tpu/runtime/membership.py):
  ``GET /fleet`` serves the live topology under the fleet epoch — every
  server with its state (live/joining/draining/drained/dead, extra =
  scale-out shard), every app rank with its home and state (attached =
  joined after bring-up), the detached-rank history, and any parked
  scale request (the autoscaler feed). ``POST /fleet/scale`` with
  ``{"dir": "out"}`` requests a new server shard; ``{"dir": "in"}``
  (optional ``"rank"``) drains one through the zero-loss promote path.
* ``/slo`` + ``/alerts`` + ``/incidents`` + ``/flight`` — the SLO plane
  (adlb_tpu/obs/slo.py): ``POST /slo`` adds a declarative objective to
  the live engine (same schema as ``Config(slo=...)``);
  ``GET /alerts`` serves the per-objective alert rows (state machine
  PENDING→FIRING→RESOLVED, fast/slow burn rates, staleness-degraded
  flag) plus the transition history; ``GET /incidents`` the captured
  live incident bundles (tails + stacks + metrics delta + topology for
  each page-severity FIRING); ``GET /flight`` the flight-directory
  inventory (post-mortem artifacts and incident bundles with rank,
  reason, size, age) so captures are discoverable without shell access.
* ``/jobs`` — the service-mode control plane: ``GET /jobs`` lists the
  job table, ``GET /jobs/<id>`` one job's status, ``POST /jobs`` (JSON
  body ``{"name": ..., "quota_bytes": ...}``) submits a namespace, and
  ``POST /jobs/<id>/drain`` / ``POST /jobs/<id>/kill`` drive its
  lifecycle. Mutations are injected into the reactor thread via
  ``Server.ctl_request`` (the HTTP thread never touches protocol state
  directly) and fan out to the fleet as ``SS_JOB_CTL``.

The GET handlers only read plain attributes of the live ``Server``
object (GIL-consistent snapshots, same discipline as the metrics
registry), so they never block the reactor. Binding is 127.0.0.1-only
by design: this is an operator surface, not a public one.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


def _stable_dict(d: dict) -> dict:
    """Copy a dict the reactor thread may be inserting into (the fleet
    snapshot/staleness ledgers): per-key VALUES are published by swap
    (never mutated in place), so a retried shallow copy of the outer
    dict is a consistent read. Same retry discipline as
    metrics.safe_copy."""
    for _ in range(8):
        try:
            return dict(d)
        except RuntimeError:
            continue
    return {}


def _world_agg_lines(agg: dict) -> list[str]:
    """STAT_APS aggregate -> exposition lines (the 'world-aggregated via
    the existing stats ring' half of /metrics)."""
    out = [
        "# world aggregate from the periodic stats ring (STAT_APS)",
        f"adlb_stat_aps_seq {agg['seq']}",
        f"adlb_stat_aps_trip_seconds {agg['trip_s']}",
        f"adlb_world_nservers {agg['nservers']}",
    ]
    total = agg["total"]
    for k in ("wq", "rq", "puts", "resolved", "nbytes"):
        out.append(f"adlb_world_{k}_total {total[k]}")
    for t, cell in agg["by_type"].items():
        out.append(
            f'adlb_world_wq_depth_by_type{{type="{t}",kind="untargeted"}} '
            f"{cell['untargeted']}"
        )
        out.append(
            f'adlb_world_wq_depth_by_type{{type="{t}",kind="targeted"}} '
            f"{cell['targeted']}"
        )
    for r, e in agg["per_server"].items():
        out.append(f'adlb_server_wq_depth{{rank="{r}"}} {e["wq"]}')
        out.append(f'adlb_server_rq_depth{{rank="{r}"}} {e["rq"]}')
        out.append(f'adlb_server_nbytes{{rank="{r}"}} {e["nbytes"]}')
    return out


def fleet_stage_p50(server) -> dict:
    """(stage, job, type) -> fleet-typical p50 from the merged
    unit_stage_s cells — the baseline each tail journey's per-stage
    deltas are judged against. Module-level so the SLO engine's
    incident builder (obs/slo.py) shares the exact join the
    /trace/tails view uses."""
    from adlb_tpu.obs.metrics import Registry, quantile_of

    s = server
    merged = Registry.merge(
        [s.metrics.snapshot()] + list(_stable_dict(s._fleet_snaps).values())
    )["histograms"]
    out = {}
    for key, h in merged.items():
        if not key.startswith("unit_stage_s{"):
            continue
        lab = dict(
            kv.split("=", 1)
            for kv in key[len("unit_stage_s{"):-1].split(",")
        )
        try:
            out[(lab["stage"], int(lab["job"]), int(lab["type"]))] = \
                quantile_of(h["bounds"], h["counts"], h["count"], 0.5)
        except (KeyError, ValueError):
            continue
    return out


def rank_windows(server, rank: int) -> list:
    """A rank's sealed profiler windows: the master's own live from
    its owned sampler, every other rank's from the gossip ring —
    with an in-proc fallback: a single-interpreter world runs ONE
    process profiler whose samples cover every co-located rank's
    threads but are filed under the owner, so when nothing has ever
    gossiped windows (the profile plane is entirely local) the
    process profiler's windows ARE this rank's windows."""
    from adlb_tpu.obs import profile as _profile
    from adlb_tpu.obs.metrics import safe_copy

    s = server
    wins = s._prof_windows.get(rank)
    if wins is not None:
        return safe_copy(wins)
    if rank == s.rank and s._prof is not None:
        return safe_copy(s._prof.windows)
    if not s._prof_windows:
        p = s._prof or _profile.active()
        if p is not None:
            return safe_copy(p.windows)
    return []


def annotate_tails(server, journeys: list) -> list:
    """Annotate tail journeys with the stage their excess attributes to
    (the stage whose delta most exceeds the fleet-typical p50 —
    ``slow_stage``/``slow_rank``/``excess_s``) and, when the continuous
    profiler runs, the dominant folded stacks active on the responsible
    rank during the window(s) that stage crossed. The body behind
    ``GET /trace/tails``, shared with the incident bundles."""
    from adlb_tpu.obs.profile import window_of

    p50 = fleet_stage_p50(server)
    out = []
    for j in journeys:
        j = dict(j)
        spans = j.get("spans") or []
        best = None  # (excess, stage, rank, t_prev, t)
        prev_t = spans[0][2] if spans else 0.0
        for stage, rank, t in spans[1:]:
            delta = max(t - prev_t, 0.0)
            excess = delta - p50.get(
                (stage, j.get("job", 0), j.get("type", -1)), 0.0
            )
            if best is None or excess > best[0]:
                best = (excess, stage, rank, prev_t, t)
            prev_t = t
        if best is not None and best[0] > 0:
            excess, stage, rank, t_a, t_b = best
            j["slow_stage"] = stage
            j["slow_rank"] = rank
            j["excess_s"] = round(excess, 6)
            # profiler join: sum the responsible rank's window
            # stacks over the window ids the slow interval crossed
            # (window ids are clock-aligned on the shared host
            # CLOCK_MONOTONIC, so span stamps index them directly)
            w0, w1 = window_of(t_a), window_of(t_b)
            stacks: dict = {}
            for w in rank_windows(server, rank):
                if w0 <= w["id"] <= w1:
                    for k, v in w["stacks"].items():
                        stacks[k] = stacks.get(k, 0) + v
            if stacks:
                j["stacks"] = sorted(
                    stacks.items(), key=lambda kv: -kv[1]
                )[:5]
        out.append(j)
    return out


class OpsServer:
    """Threaded HTTP listener owned by the master server's process.

    Started by ``Server.run()`` (master only) when ``cfg.ops_port`` is
    set; stopped in its ``finally``. ``port`` holds the actual bound port
    (``ops_port=0`` binds ephemeral — useful for tests on one host).
    """

    def __init__(self, server, port: int, host: str = "127.0.0.1") -> None:
        self.server = server
        self._t0 = None
        srv = self.server

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # the reactor's stderr is not a
                pass  # request log

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self) -> None:  # noqa: N802 — http.server contract
                from urllib.parse import parse_qs

                path, _, query = self.path.partition("?")
                q = {k: v[-1] for k, v in parse_qs(query).items()}
                try:
                    if path == "/healthz":
                        body = json.dumps(ops._healthz()).encode()
                        self._send(200, body, "application/json")
                    elif path == "/metrics":
                        self._send(
                            200, ops._metrics().encode(),
                            "text/plain; version=0.0.4",
                        )
                    elif path == "/dump":
                        body = json.dumps(ops._dump()).encode()
                        self._send(200, body, "application/json")
                    elif path == "/deadletter":
                        body = json.dumps(ops._deadletter()).encode()
                        self._send(200, body, "application/json")
                    elif path == "/trace/units":
                        body = json.dumps(ops._trace_units(q)).encode()
                        self._send(200, body, "application/json")
                    elif path == "/trace/tails":
                        body = json.dumps(ops._trace_tails(q)).encode()
                        self._send(200, body, "application/json")
                    elif path == "/profile":
                        if q.get("format") == "json":
                            self._send(
                                200,
                                json.dumps(ops._profile_doc()).encode(),
                                "application/json",
                            )
                        else:
                            self._send(200, ops._profile_text().encode(),
                                       "text/plain")
                    elif path == "/fleet":
                        body = json.dumps(srv.fleet_doc()).encode()
                        self._send(200, body, "application/json")
                    elif path == "/alerts":
                        body = json.dumps(ops._alerts()).encode()
                        self._send(200, body, "application/json")
                    elif path == "/incidents":
                        body = json.dumps(ops._incidents(q)).encode()
                        self._send(200, body, "application/json")
                    elif path == "/flight":
                        body = json.dumps(ops._flight_index()).encode()
                        self._send(200, body, "application/json")
                    elif path == "/control":
                        body = json.dumps(ops._control()).encode()
                        self._send(200, body, "application/json")
                    elif path == "/jobs":
                        body = json.dumps(ops._jobs()).encode()
                        self._send(200, body, "application/json")
                    elif path.startswith("/jobs/"):
                        doc = ops._job_one(path.split("/")[2])
                        if doc is None:
                            self._send(404, b"no such job\n", "text/plain")
                        else:
                            self._send(200, json.dumps(doc).encode(),
                                       "application/json")
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except Exception as e:  # noqa: BLE001 — a scrape must
                    # never kill the listener thread
                    self._send(500, repr(e).encode(), "text/plain")

            def do_POST(self) -> None:  # noqa: N802
                path = self.path.split("?", 1)[0]
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    raw = self.rfile.read(n) if n else b""
                    parts = [p for p in path.split("/") if p]
                    if path == "/dump":
                        # historical alias: POST /dump == GET /dump
                        body = json.dumps(ops._dump()).encode()
                        self._send(200, body, "application/json")
                    elif parts[:1] == ["jobs"] and len(parts) <= 3:
                        body = json.dumps(
                            ops._jobs_post(parts[1:], raw)
                        ).encode()
                        self._send(200, body, "application/json")
                    elif parts == ["fleet", "scale"]:
                        body = json.dumps(
                            ops._fleet_scale(raw)
                        ).encode()
                        self._send(200, body, "application/json")
                    elif parts == ["slo"]:
                        body = json.dumps(ops._slo_post(raw)).encode()
                        self._send(200, body, "application/json")
                    elif parts == ["control"]:
                        body = json.dumps(ops._control_post(raw)).encode()
                        self._send(200, body, "application/json")
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except (KeyError, ValueError, IndexError) as e:
                    self._send(400, repr(e).encode(), "text/plain")
                except Exception as e:  # noqa: BLE001
                    self._send(500, repr(e).encode(), "text/plain")

        ops = self
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self.host = host
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.25},
            daemon=True,
            name=f"adlb-ops-{srv.rank}",
        )

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "OpsServer":
        import time

        self._t0 = time.monotonic()
        self._thread.start()
        return self

    def stop(self) -> None:
        try:
            if self._thread.is_alive():
                # shutdown() handshakes with serve_forever — calling it
                # on a never-started listener would block forever
                self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass

    # -- views ---------------------------------------------------------------

    def _healthz(self) -> dict:
        import time

        s = self.server
        now = time.monotonic()
        # per-rank snapshot staleness from the SS_OBS_SYNC gossip: a
        # wedged server stops heartbeating and its age grows — visible
        # here BEFORE its connections EOF. The master is age 0 (its own
        # registry is read live); ranks never heard from report seq 0
        # with age since the endpoint started.
        cadence = getattr(s.cfg, "obs_sync_interval", 0) or 0
        fleet_seen = _stable_dict(s._fleet_seen)
        ranks = {str(s.rank): {"seq": -1, "age_s": 0.0, "stale": False}}
        for r in list(s.world.server_ranks):
            if r == s.rank:
                continue
            if r in s._dead_servers or not s._is_live_member(r):
                # retired (dead/drained) or not-yet-live members must
                # not report stale forever — /fleet keeps the topology
                # history; staleness is a LIVE-member alarm
                continue
            seen = fleet_seen.get(r)
            if seen is None:
                age = round(now - (self._t0 or now), 3)
                seq = 0
            else:
                seq, t_at = seen
                age = round(now - t_at, 3)
            ranks[str(r)] = {
                "seq": seq,
                "age_s": age,
                "stale": bool(cadence) and age > 3.0 * cadence,
            }
        return {
            "ok": not s._aborted,
            "rank": s.rank,
            "role": "master" if s.is_master else "server",
            "uptime_s": round(now - (self._t0 or 0.0), 3),
            "wq": s.wq.count,
            "rq": len(s.rq),
            "nbytes": s.mem.curr,
            "done": s.done,
            "aborted": s._aborted,
            "no_more_work": s.no_more_work,
            "done_by_exhaustion": s.done_by_exhaustion,
            "nservers": s.world.nservers,
            "obs_sync_interval": cadence,
            "ranks": ranks,
            "stale_ranks": sorted(
                int(r) for r, e in ranks.items() if e["stale"]
            ),
        }

    def _metrics(self) -> str:
        import time

        from adlb_tpu.obs.metrics import Registry, expose_merged

        s = self.server
        now = time.monotonic()
        body = s.metrics.expose()
        # ---- fleet view: the master's live registry merged with every
        # gossiped per-rank snapshot (counters/histogram cells sum,
        # gauges keep rank identity) — what Registry.merge computed
        # offline for post-mortems, served live
        fleet = [s.metrics.snapshot()] + list(
            _stable_dict(s._fleet_snaps).values()
        )
        body += "# fleet view: merged across gossiped rank snapshots\n"
        body += expose_merged(Registry.merge(fleet))
        # per-rank snapshot provenance: seq + age, so a scraper can tell
        # live rows from stale ones (the staleness /healthz alarms on)
        for r, (seq, t_at) in sorted(_stable_dict(s._fleet_seen).items()):
            body += (
                f'adlb_obs_snapshot_seq{{rank="{r}"}} {seq}\n'
                f'adlb_obs_snapshot_age_seconds{{rank="{r}"}} '
                f"{max(now - t_at, 0.0):.3f}\n"
            )
        agg = getattr(s, "last_aggregate", None)
        if agg is not None:
            body += "\n".join(_world_agg_lines(agg)) + "\n"
            # age-stamp the aggregate: it is the LAST ring tick's data,
            # and without an age a stalled ring is indistinguishable
            # from a live one
            body += (
                f"adlb_stat_aps_age_seconds "
                f"{max(now - s._last_aggregate_at, 0.0):.3f}\n"
            )
        return body

    @staticmethod
    def _filter_journeys(journeys: list, q: Optional[dict]) -> list:
        """Apply the ``?job= / ?type= / ?min_ms= / ?limit=`` query
        filters (limit keeps the NEWEST n; the stores append newest
        last). Unknown keys are ignored; malformed values raise
        ValueError, which the handler answers as a 500 with the repr."""
        if not q:
            return journeys
        if "job" in q:
            want = int(q["job"])
            journeys = [j for j in journeys if j.get("job", 0) == want]
        if "type" in q:
            want = int(q["type"])
            journeys = [j for j in journeys if j.get("type", -1) == want]
        if "min_ms" in q:
            floor_s = float(q["min_ms"]) / 1e3
            journeys = [
                j for j in journeys if j.get("total_s", 0.0) >= floor_s
            ]
        if "limit" in q:
            n = max(int(q["limit"]), 0)
            # negative-index slice: clamps when n exceeds the store
            # (journeys[len-n:] would wrap and DROP results instead)
            journeys = journeys[-n:] if n else []
        return journeys

    def _trace_units(self, q: Optional[dict] = None) -> dict:
        """The fleet journey store: every closed unit journey that
        reached the master (its own + the SS_OBS_SYNC gossip), newest
        last. Spans are (stage, rank, t_mono) triples; per-stage deltas
        are the same data the unit_stage_s histograms aggregate."""
        from adlb_tpu.obs.metrics import safe_copy

        s = self.server
        journeys = self._filter_journeys(safe_copy(s._journeys_fleet), q)
        return {
            "rank": s.rank,
            "count": len(journeys),
            "journeys": journeys,
        }

    # -- tail store + the tail<->profile join --------------------------------

    def _trace_tails(self, q: Optional[dict] = None) -> dict:
        """The tail store (Config(trace_tail)): promoted journeys
        through :func:`annotate_tails` (slow-stage attribution + the
        tail<->profile window join, shared with the incident bundles)."""
        from adlb_tpu.obs.metrics import safe_copy

        s = self.server
        journeys = annotate_tails(
            s, self._filter_journeys(safe_copy(s._tails_fleet), q)
        )
        return {"rank": s.rank, "count": len(journeys),
                "journeys": journeys}

    # -- continuous profile --------------------------------------------------

    def _profile_doc(self) -> dict:
        """The merged fleet profile: per-rank cumulative folded stacks
        (the master's own read live from its sampler, peers' from the
        SS_OBS_SYNC gossip), their elementwise-summed merge, and the
        per-rank sealed sampling windows (the tail-join inputs)."""
        from adlb_tpu.obs.profile import merge_stacks

        s = self.server
        per_rank: dict[str, dict] = {}
        windows: dict[str, list] = {}
        if s._prof is not None:
            own = s._prof.snapshot()
            per_rank[str(s.rank)] = own["stacks"]
            windows[str(s.rank)] = own["win"]
        from adlb_tpu.obs.metrics import safe_copy

        for r, stacks in sorted(_stable_dict(s._prof_fleet).items()):
            per_rank[str(r)] = dict(stacks)
        for r, wins in sorted(_stable_dict(s._prof_windows).items()):
            windows[str(r)] = safe_copy(wins)
        return {
            "rank": s.rank,
            "hz": getattr(s.cfg, "profile_hz", 0.0),
            "ranks": per_rank,
            "merged": merge_stacks(per_rank),
            "windows": windows,
        }

    def _profile_text(self) -> str:
        """Flamegraph-compatible collapsed-stack text of the merged
        fleet profile (one ``stack count`` line, heaviest first)."""
        from adlb_tpu.obs.profile import collapsed_text

        return collapsed_text(self._profile_doc()["merged"])

    def _deadletter(self) -> dict:
        s = self.server
        cut = getattr(s.cfg, "ops_dump_bytes", 256)
        records = []
        for q in list(getattr(s, "quarantine", ())):
            payload = q.get("payload", b"")
            records.append(
                {
                    "seqno": q["seqno"],
                    "work_type": q["work_type"],
                    "prio": q["prio"],
                    "target_rank": q["target_rank"],
                    "answer_rank": q["answer_rank"],
                    "attempts": q["attempts"],
                    "server_rank": q["server_rank"],
                    "payload_len": len(payload),
                    # bounded hex (Config(ops_dump_bytes)) so a fat
                    # poison unit cannot blow up a scrape; the full
                    # payload stays retrievable in-band via
                    # ctx.get_quarantined()
                    "payload_hex": bytes(payload[:cut]).hex(),
                    # a fused member whose prefix lives on another
                    # server: payload is the suffix alone and the
                    # common handle says where the rest is
                    "suffix_only": bool(q.get("suffix_only")),
                    "common_seqno": q.get("common_seqno", -1),
                    "common_server_rank": q.get("common_server_rank", -1),
                }
            )
        return {"rank": s.rank, "count": len(records), "records": records}

    def _dump(self) -> dict:
        s = self.server
        s.flight.record("ops /dump requested")
        doc = s.flight.snapshot_doc(reason="ops")
        path = s.flight.dump_json(reason="ops")
        return {"artifact": path, "record": doc}

    # -- SLO / alerts / incidents --------------------------------------------

    def _alerts(self) -> dict:
        """The SLO engine's published state: objectives, per-objective
        alert rows (state, burn rates, degraded flag), and the recent
        transition history. All publish-by-swap reads — the engine runs
        on the reactor; this is the HTTP thread."""
        from adlb_tpu.obs.metrics import safe_copy

        s = self.server
        eng = s._slo_engine
        if eng is None:
            return {"rank": s.rank, "enabled": False, "objectives": [],
                    "alerts": [], "firing": 0, "history": []}
        return {
            "rank": s.rank,
            "enabled": True,
            "objectives": list(eng.objectives),
            "alerts": eng.alerts_pub,
            "firing": eng.firing,
            "history": safe_copy(eng.history),
        }

    def _incidents(self, q: Optional[dict] = None) -> dict:
        """Captured live incident bundles, newest last (bounded ring;
        the durable copies live in flight_dir — see /flight).
        ``?limit=`` keeps the newest n."""
        from adlb_tpu.obs.metrics import safe_copy

        s = self.server
        incidents = safe_copy(s._incidents)
        if q and "limit" in q:
            n = max(int(q["limit"]), 0)
            incidents = incidents[-n:] if n else []
        return {"rank": s.rank, "count": len(incidents),
                "incidents": incidents}

    def _flight_index(self) -> dict:
        """Inventory of the flight directory: every post-mortem artifact
        and incident bundle (filename, kind, rank, reason, size, age) so
        CI and operators discover captures without shelling into the
        box. Filenames encode rank/reason/pid (see obs/flight.py); the
        index parses, never re-reads, the JSON bodies."""
        import os
        import re
        import time

        s = self.server
        out_dir = s.flight.out_dir
        entries = []
        if out_dir and os.path.isdir(out_dir):
            now = time.time()
            for fn in sorted(os.listdir(out_dir)):
                m = re.match(
                    r"(flight|incident)-(?:rank(\d+)-)?(.+?)-p(\d+)\.json$",
                    fn,
                )
                if m is None:
                    continue
                kind, rank, slug, pid = m.groups()
                try:
                    st = os.stat(os.path.join(out_dir, fn))
                except OSError:
                    continue  # racing a concurrent atomic replace
                entries.append({
                    "file": fn,
                    "kind": "incident" if kind == "incident" else "flight",
                    "rank": int(rank) if rank is not None else None,
                    "reason": slug,
                    "pid": int(pid),
                    "bytes": st.st_size,
                    "age_s": round(max(now - st.st_mtime, 0.0), 3),
                })
        return {
            "rank": s.rank,
            "flight_dir": out_dir,
            "count": len(entries),
            "artifacts": entries,
        }

    def _slo_post(self, raw: bytes) -> dict:
        """POST /slo — add an objective to the live engine. Validated
        here first (a malformed body answers 400 from the HTTP thread),
        then normalized for real on the reactor, where the engine and
        its evaluation cadence live."""
        from adlb_tpu.obs.slo import parse_objective

        body = json.loads(raw.decode() or "{}")
        parse_objective(body)  # 400 gate only; reactor re-normalizes
        return self.server.ctl_request({"op": "slo", "objective": body})

    # -- /control: the closed-loop controller --------------------------------

    def _control(self) -> dict:
        """The fleet controller's published state (adlb_tpu/control):
        live policy, hold/cooldown status, and the decision history —
        every decision as inputs -> rule -> action -> outcome. All
        publish-by-swap reads (the controller runs on the reactor's obs
        tick; this is the HTTP thread), mirroring /alerts."""
        from adlb_tpu.obs.metrics import safe_copy

        s = self.server
        ctl = getattr(s, "_controller", None)
        if ctl is None:
            return {"rank": s.rank, "enabled": False, "policy": {},
                    "decisions": [], "actions": 0}
        return {
            "rank": s.rank,
            "enabled": True,
            "dry_run": ctl.dry_run,
            "policy": ctl.policy_doc(),
            "status": ctl.status_pub,
            "actions": ctl.actions_total,
            "decisions": safe_copy(ctl.history),
        }

    def _control_post(self, raw: bytes) -> dict:
        """POST /control — live policy tweaks (cooldown, pressure
        thresholds, server bounds, dry_run). Validated and applied on
        the reactor, where the controller lives."""
        from adlb_tpu.control.controller import parse_policy

        if getattr(self.server, "_controller", None) is None:
            raise ValueError(
                "controller not configured (Config(control=True))"
            )
        body = json.loads(raw.decode() or "{}")
        parse_policy(body)  # 400 gate only; reactor merges onto the live base
        return self.server.ctl_request({"op": "control", "policy": body})

    # -- /jobs control plane -------------------------------------------------

    def _jobs(self) -> dict:
        s = self.server
        return {
            "rank": s.rank,
            "jobs": [j.summary() for j in s.jobs.values()],
        }

    def _job_one(self, jid_str: str):
        jid = int(jid_str)
        job = self.server.jobs.get(jid)
        if job is None:
            return None
        doc = job.summary()
        doc.update(self._job_gauges(jid))
        return doc

    def _job_gauges(self, jid: int) -> dict:
        """Live per-job depth/bytes/age + stage-latency quantiles: the
        master's own queues read directly, every other rank's from its
        gossiped snapshot's ``job_*`` gauges and ``unit_stage_s``
        histogram cells (the item-3 autoscaler's sensor row)."""
        from adlb_tpu.obs.metrics import quantile_of

        s = self.server
        import time

        now = time.monotonic()
        part = s.wq.part(jid)
        job = s.jobs.get(jid)
        depth = part.count if part is not None else 0
        nbytes = part.total_bytes if part is not None else 0
        age = max(
            (now - u.time_stamp for u in part.units()), default=0.0
        ) if part is not None else 0.0
        backoffs = job.backoffs if job is not None else 0
        per_rank = {
            str(s.rank): {
                "depth": depth, "bytes": nbytes, "age_s": round(age, 3),
                "backoffs": backoffs,
            }
        }
        jl = f"job={jid}"
        fleet_snaps = _stable_dict(s._fleet_snaps)
        for r, snap in fleet_snaps.items():
            g = snap.get("gauges", {})

            def cell(name: str) -> float:
                # gauge keys carry sorted labels: job_* have only {job=}
                return float(g.get(f"{name}{{{jl}}}", 0.0))

            d = cell("job_wq_depth")
            b = cell("job_wq_bytes")
            a = cell("job_oldest_age_s")
            bk = cell("job_backoffs")
            per_rank[str(r)] = {
                "depth": int(d), "bytes": int(b), "age_s": round(a, 3),
                "backoffs": int(bk),
            }
            depth += int(d)
            nbytes += int(b)
            age = max(age, a)
            backoffs += int(bk)
        # stage latencies: Registry.merge sums the unit_stage_s cells
        # across ranks (per full label set); what remains here is only
        # restricting to this job's label and folding the TYPE label
        # away so /jobs reports one row per stage
        from adlb_tpu.obs.metrics import Registry

        merged = Registry.merge(
            [s.metrics.snapshot()] + list(fleet_snaps.values())
        )["histograms"]
        stages: dict = {}
        for key, h in merged.items():
            if not key.startswith("unit_stage_s{"):
                continue
            labels = key[len("unit_stage_s{"):-1].split(",")
            if jl not in labels:
                continue
            stage = next(
                (x.split("=", 1)[1] for x in labels
                 if x.startswith("stage=")), "?",
            )
            agg = stages.get(stage)
            if agg is None:
                stages[stage] = {
                    "bounds": list(h["bounds"]),
                    "counts": list(h["counts"]),
                    "sum": h["sum"], "count": h["count"],
                }
            elif len(agg["counts"]) == len(h["counts"]):
                agg["counts"] = [
                    a_ + b_ for a_, b_ in zip(agg["counts"], h["counts"])
                ]
                agg["sum"] += h["sum"]
                agg["count"] += h["count"]
        quota = job.quota_bytes if job is not None else 0
        return {
            "queue_depth": depth,
            "queued_bytes": nbytes,
            "oldest_age_s": round(age, 3),
            # quota state (PR 19): the cap is PER SERVER, so pressure is
            # the WORST rank's used/quota — the signal the controller's
            # throttle rules and an operator's eyeball both want
            "quota_bytes": quota,
            "quota_used_frac": round(
                max(
                    (e["bytes"] / quota for e in per_rank.values()),
                    default=0.0,
                ), 4,
            ) if quota > 0 else 0.0,
            "backoffs_fleet": backoffs,
            "per_rank": per_rank,
            "stage_latency_s": {
                stage: {
                    "p50": quantile_of(a["bounds"], a["counts"],
                                       a["count"], 0.5),
                    "p99": quantile_of(a["bounds"], a["counts"],
                                       a["count"], 0.99),
                    "count": a["count"],
                }
                for stage, a in sorted(stages.items())
            },
        }

    def _fleet_scale(self, raw: bytes) -> dict:
        """POST /fleet/scale — elastic membership: ``{"dir": "out"}``
        requests a new server shard (spawned via the registered member
        spawner, or parked as a pending request feeding the autoscaler);
        ``{"dir": "in"}`` (optionally ``{"rank": N}``) drains a server
        through the zero-loss promote path. Serviced on the reactor via
        the same ctl inbox as /jobs."""
        body = json.loads(raw.decode() or "{}")
        direction = body.get("dir") or body.get("direction")
        if direction == "out":
            return self.server.ctl_request({"op": "scale_out"})
        if direction == "in":
            req = {"op": "scale_in"}
            if body.get("rank") is not None:
                req["rank"] = int(body["rank"])
            return self.server.ctl_request(req)
        raise ValueError('scale needs {"dir": "out"|"in"}')

    def _jobs_post(self, parts: list, raw: bytes) -> dict:
        """POST /jobs (submit), POST /jobs/<id> (live update: fair-share
        ``weight``, ``quota_bytes`` with -1 = unlimited), and
        POST /jobs/<id>/{drain,kill}: build a control request and hand
        it to the reactor thread."""
        s = self.server
        if not parts:  # POST /jobs — submit
            body = json.loads(raw.decode() or "{}")
            return s.ctl_request({
                "op": "submit",
                "name": str(body.get("name", "")),
                "quota_bytes": int(body.get("quota_bytes", 0) or 0),
            })
        jid, action = int(parts[0]), (parts[1] if len(parts) > 1 else "")
        if not action:  # POST /jobs/<id> — policy update
            body = json.loads(raw.decode() or "{}")
            req = {"op": "update", "job_id": jid,
                   "quota_bytes": int(body.get("quota_bytes", 0) or 0)}
            if body.get("weight") is not None:
                req["weight"] = float(body["weight"])
            return s.ctl_request(req)
        if action not in ("drain", "kill"):
            raise ValueError(f"unknown job action {action!r}")
        return s.ctl_request({"op": action, "job_id": jid})


def maybe_start(server, cfg, port=None) -> Optional[OpsServer]:
    """Start the ops endpoint iff this server is the master and a port is
    configured. ``port`` overrides ``cfg.ops_port`` — a promoted deputy
    rebinds on an ephemeral port (0) because the dead master's HTTP
    thread may still hold the configured one. Bind failures degrade to a
    warning — observability must never take the data plane down with it."""
    p = cfg.ops_port if port is None else port
    if not server.is_master or p is None:
        return None
    try:
        return OpsServer(server, p).start()
    except OSError as e:
        import sys

        print(
            f"[adlb ops] could not bind ops endpoint on port "
            f"{p}: {e!r}; continuing without it",
            file=sys.stderr,
        )
        return None
