"""SLO engine: burn-rate alerting + live incident capture (ISSUE 16).

The "decide" layer of the sense → decide → act loop. PRs 12/13 gave the
master a merged fleet registry, tail-promoted journeys, and a continuous
profiler; PR 15 gave it actuators. This module evaluates that merged
view against **declarative per-job objectives** (``Config(slo=...)`` or
``POST /slo``) every obs tick, on the master, and turns violations into
a durable alert lifecycle a controller (ROADMAP item 3) can subscribe to
instead of polling raw gauges.

**Objectives** are plain dicts, e.g.::

    {"job": 0, "type": 3, "p99_ms": 50, "error_frac": 0.001,
     "window_s": 300}

``p99_ms`` bounds the windowed p99 of ``unit_total_s`` for that
(job, type); ``error_frac`` bounds the windowed fraction of closes that
ended anomalously (``unit_errors`` / closes). At least one term is
required; ``window_s`` is the SLOW window.

**Multi-window burn rates** (the standard SRE/Prometheus recording-rules
shape): every evaluation appends the merged registry to a bounded
:class:`~adlb_tpu.obs.metrics.SnapshotRing`, so both a FAST window
(default ``window_s / 12``, floored at two evaluation ticks) and the
slow window are two-snapshot subtractions. The fast window catches a
fresh burn within seconds; the slow window refuses to confirm a blip
(one slow unit among a window's thousands moves neither its p99 nor its
error fraction). Fast-only burn = PENDING (about to page); slow-only
burn = a "warn"-severity PENDING (a slow simmer); **both burning,
sustained past ``for_s``, fires** — the no-flapping-on-blips property is
structural, not a tuned threshold.

**Staleness-aware**: the merged registry already carries a stale rank's
last gossiped snapshot (the master never zeroes a rank it stopped
hearing from), so a wedged server's contribution degrades to
"last known value" rather than silently vanishing; every alert row
evaluated while any live member is stale (the ``/healthz`` rule:
age > 3 × ``obs_sync_interval``) is flagged ``degraded`` with the rank
list, so a consumer can tell "fleet is healthy" from "fleet looks
healthy because half of it went quiet".

**Churn hysteresis**: membership epoch bumps (PR 15 attach/detach/
scale) open a grace hold during which alert STATE is frozen — burn
numbers keep updating, but a scale-out's transient cannot flap
PENDING→FIRING→RESOLVED. A cooldown (``cooldown_s`` clear-time before
RESOLVED) bounds flapping on the way down the same way ``for_s`` does on
the way up.

**Alert lifecycle**: OK → PENDING → FIRING → RESOLVED (→ PENDING again
on relapse). Each transition is returned to the caller (the master's
reactor), which records a flight event, updates the ``alerts_firing``
gauge, republishes the compact rows the SS_OBS_SYNC replies carry
fleet-wide, and — on a page-severity FIRING — snapshots a **live
incident bundle**: the violating (job, type)'s tail journeys with the
PR 13 slow-stage/profiler-window annotations, the responsible ranks'
dominant stacks over the firing window, the merged metrics delta over
the burn window, suspect ranks (stale members, slow-stage ranks,
lease-expiry owners), and the epoch-stamped fleet topology — written
atomically into ``flight_dir`` and served at ``GET /incidents``.

Threading: ``evaluate`` runs on the master's reactor thread only; the
ops HTTP thread reads ``alerts_pub`` / ``wire`` / ``history``, which are
republished by swap (never mutated in place), the same discipline as the
fleet snapshot ledgers.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional

from adlb_tpu.obs.metrics import SnapshotRing, quantile_of

# alert states (the lifecycle is append-only vocabulary: consumers
# switch on these strings, so renaming would break mixed-version fleets)
OK = "OK"
PENDING = "PENDING"
FIRING = "FIRING"
RESOLVED = "RESOLVED"

MAX_OBJECTIVES = 64
# ring depth bounds: at least a minute of context, at most ~2k merged
# snapshots (each is a few KiB on a busy fleet)
_RING_MIN = 64
_RING_MAX = 2048


def parse_objective(doc: dict, eval_interval: float = 1.0) -> dict:
    """Validate + normalize one objective dict (Config(slo=...) entries
    and POST /slo bodies go through the same gate). Raises ValueError
    with an operator-readable message — the ops route answers 400."""
    if not isinstance(doc, dict):
        raise ValueError(f"objective must be a dict, got {type(doc).__name__}")
    job = int(doc.get("job", 0))
    typ = int(doc.get("type", -1))
    p99_ms = doc.get("p99_ms")
    error_frac = doc.get("error_frac")
    if p99_ms is None and error_frac is None:
        raise ValueError("objective needs p99_ms and/or error_frac")
    if p99_ms is not None and float(p99_ms) <= 0:
        raise ValueError("p99_ms must be > 0")
    if error_frac is not None and not (0.0 < float(error_frac) <= 1.0):
        raise ValueError("error_frac must be in (0, 1]")
    window_s = float(doc.get("window_s", 0) or 0)
    if window_s <= 0:
        raise ValueError("window_s must be > 0")
    tick = max(eval_interval, 1e-3)
    # fast window: 1/12 of the slow one (the classic 5m/1h pairing's
    # ratio), floored at two evaluation ticks so a single tick's noise
    # cannot page on its own
    fast_s = float(doc.get("fast_s", 0) or 0) or max(window_s / 12.0,
                                                     2.0 * tick)
    fast_s = min(fast_s, window_s)
    severity = str(doc.get("severity", "page"))
    if severity not in ("page", "warn"):
        raise ValueError(f"unknown severity {severity!r}")
    kind = "p99" if p99_ms is not None else "err"
    if p99_ms is not None and error_frac is not None:
        kind = "p99+err"
    name = str(doc.get("name") or f"job{job}-type{typ}-{kind}")
    return {
        "name": name,
        "job": job,
        "type": typ,
        "p99_ms": float(p99_ms) if p99_ms is not None else None,
        "error_frac": float(error_frac) if error_frac is not None else None,
        "window_s": window_s,
        "fast_s": round(fast_s, 6),
        # sustain before firing / clear-time before resolving: both
        # floored at two ticks — one tick of hysteresis each way is the
        # minimum that makes a single noisy evaluation flap-proof
        "for_s": float(doc.get("for_s", 0) or 0) or 2.0 * tick,
        "cooldown_s": float(doc.get("cooldown_s", 0) or 0) or max(
            fast_s, 2.0 * tick),
        "severity": severity,
        "min_count": int(doc.get("min_count", 1) or 1),
    }


def _cell_key(name: str, job: int, typ: int) -> str:
    # merged-snapshot keys carry sorted labels: job before type
    return f"{name}{{job={job},type={typ}}}"


class SloEngine:
    """Master-side objective evaluator. One instance per master server;
    created at init when ``Config(slo=...)`` is set, or lazily by the
    first ``POST /slo``."""

    def __init__(self, eval_interval: float = 1.0,
                 now: Optional[float] = None) -> None:
        self.eval_interval = max(eval_interval, 1e-3)
        self.started_at = time.monotonic() if now is None else now
        self.objectives: list[dict] = []
        self.ring = SnapshotRing(_RING_MIN)
        self._alerts: dict[str, dict] = {}  # name -> live state (reactor)
        # published views (swapped whole; the ops HTTP thread and the
        # gossip reply path read these)
        self.alerts_pub: list[dict] = []
        self.wire: list = []
        self.history: deque = deque(maxlen=256)
        self.firing = 0
        # churn grace: epoch bumps freeze state transitions until this
        self._epoch: Optional[int] = None
        self._hold_until = 0.0

    # -- objectives ----------------------------------------------------------

    def add(self, doc: dict) -> dict:
        if len(self.objectives) >= MAX_OBJECTIVES:
            raise ValueError(f"at most {MAX_OBJECTIVES} objectives")
        o = parse_objective(doc, self.eval_interval)
        if any(x["name"] == o["name"] for x in self.objectives):
            raise ValueError(f"duplicate objective {o['name']!r}")
        self.objectives.append(o)
        # the ring must reach back one slow window (+ slack for the
        # baseline search landing between ticks)
        need = int(o["window_s"] / self.eval_interval) + 8
        self.ring.grow(max(_RING_MIN, min(need, _RING_MAX)))
        return o

    # -- churn hysteresis ----------------------------------------------------

    def note_epoch(self, epoch: int, now: float) -> None:
        """Membership change: freeze state transitions for a grace
        period so attach/detach/scale transients cannot flap alerts.
        Burn numbers keep updating — only the lifecycle holds."""
        if self._epoch is not None and epoch != self._epoch:
            self._hold_until = now + max(4.0 * self.eval_interval, 2.0)
        self._epoch = epoch

    # -- evaluation ----------------------------------------------------------

    def _burn(self, o: dict, window_s: float, now: float) -> tuple:
        """(burn, violating, detail) for one objective over one window.
        Burn is the worst term's ratio to its bound (>= 1.0 violates);
        p99 needs ``min_count`` in-window closes to arm (a cold window
        proves nothing)."""
        job, typ = o["job"], o["type"]
        burn = 0.0
        detail: dict = {}
        hd = self.ring.hist_delta(
            _cell_key("unit_total_s", job, typ), window_s, now)
        closes = 0
        if hd is not None:
            bounds, counts, n, span = hd
            closes = n
            detail["closes"] = n
            detail["span_s"] = round(span, 3)
            if o["p99_ms"] is not None and n >= o["min_count"]:
                p99_s = quantile_of(bounds, counts, n, 0.99)
                detail["p99_ms"] = round(p99_s * 1e3, 3)
                burn = max(burn, p99_s * 1e3 / o["p99_ms"])
        if o["error_frac"] is not None:
            errs, _span = self.ring.counter_delta(
                _cell_key("unit_errors", job, typ), window_s, now)
            if errs:
                # errored closes observe unit_total_s too, so closes is
                # the honest denominator; errors with zero recorded
                # closes (clock skew between the two folds) saturate
                frac = errs / closes if closes else 1.0
                detail["errors"] = int(errs)
                detail["error_frac"] = round(frac, 6)
                burn = max(burn, frac / o["error_frac"])
        return burn, burn >= 1.0, detail

    def evaluate(self, now: float, merged: dict,
                 stale_ranks: Optional[list] = None) -> list[dict]:
        """One evaluation tick: append ``merged`` to the ring, advance
        every objective's alert state machine, republish the HTTP/wire
        views, and return the transitions that happened this tick."""
        self.ring.append(now, merged)
        stale = sorted(stale_ranks or [])
        held = now < self._hold_until
        transitions: list[dict] = []
        firing = 0
        pub: list[dict] = []
        wire: list = []
        for o in self.objectives:
            st = self._alerts.get(o["name"])
            if st is None:
                st = self._alerts[o["name"]] = {
                    "state": OK, "since": now, "fired_at": None,
                    "clear_since": None, "fire_count": 0,
                }
            burn_f, viol_f, det_f = self._burn(o, o["fast_s"], now)
            burn_s, viol_s, det_s = self._burn(o, o["window_s"], now)
            prev = st["state"]
            nxt = prev
            if prev in (OK, RESOLVED):
                if viol_f or viol_s:
                    nxt = PENDING
            elif prev == PENDING:
                if not (viol_f or viol_s):
                    if not held:
                        nxt = OK
                elif viol_f and viol_s and not held and \
                        now - st["since"] >= o["for_s"]:
                    nxt = FIRING
            elif prev == FIRING:
                if viol_f or viol_s:
                    st["clear_since"] = None
                else:
                    if st["clear_since"] is None:
                        st["clear_since"] = now
                    if not held and \
                            now - st["clear_since"] >= o["cooldown_s"]:
                        nxt = RESOLVED
            if nxt != prev:
                st["state"] = nxt
                st["since"] = now
                if nxt == FIRING:
                    st["fired_at"] = now
                    st["fire_count"] += 1
                if nxt != FIRING:
                    st["clear_since"] = None
                tr = {
                    "name": o["name"], "from": prev, "to": nxt,
                    "at": now, "severity": o["severity"],
                    "job": o["job"], "type": o["type"],
                    "burn_fast": round(burn_f, 3),
                    "burn_slow": round(burn_s, 3),
                    "degraded": bool(stale),
                }
                transitions.append(tr)
                self.history.append(tr)
            if st["state"] == FIRING:
                firing += 1
            # row severity: both windows burning carries the
            # objective's severity (page by default); a single-window
            # burn is a warn — "fast pages, slow warns, both fire"
            row_sev = o["severity"] if (viol_f and viol_s) else (
                "warn" if (viol_f or viol_s) else o["severity"])
            pub.append({
                "name": o["name"], "state": st["state"],
                "severity": row_sev,
                "job": o["job"], "type": o["type"],
                "since": round(st["since"], 3),
                "fired_at": round(st["fired_at"], 3)
                if st["fired_at"] is not None else None,
                "fire_count": st["fire_count"],
                "burn_fast": round(burn_f, 3),
                "burn_slow": round(burn_s, 3),
                "fast": det_f, "slow": det_s,
                "window_s": o["window_s"], "fast_s": o["fast_s"],
                "degraded": bool(stale),
                "stale_ranks": stale,
                "held": held,
            })
            wire.append([o["name"], st["state"], row_sev,
                         round(burn_f, 3), round(burn_s, 3)])
        # publish-by-swap for the HTTP thread / gossip replies
        self.alerts_pub = pub
        self.wire = wire
        self.firing = firing
        return transitions


# ---------------------------------------------------------------- suspects

# the owner-labelled lease-expiry counter's snapshot key prefix (see
# Server._expire_lease): the window-delta of these cells names the
# stalled worker directly
LEASE_EXPIRY_PREFIX = "leases_expired_by{owner="


def suspect_ranks(stale_ranks, tails, counter_deltas) -> set[int]:
    """The stall-signature heuristic, shared by the incident builder
    below and the hedge trigger (``runtime/server.py::_hedge_suspects``):
    ranks the evidence points at — members that went quiet (the
    ``/healthz`` staleness rule), ranks a promoted tail's excess
    attributes to (``slow_rank`` annotations), and lease-expiry owners
    whose ``leases_expired_by{owner=}`` cell grew inside the window
    (the stalled worker itself). Inputs are all optional — each caller
    feeds what its window actually has."""
    suspects: set[int] = set()
    for r in stale_ranks or ():
        suspects.add(int(r))
    for j in tails or ():
        if "slow_rank" in j:
            suspects.add(j["slow_rank"])
    for key, v in (counter_deltas or {}).items():
        if key.startswith(LEASE_EXPIRY_PREFIX) and v > 0:
            try:
                suspects.add(int(key[len(LEASE_EXPIRY_PREFIX):-1]))
            except ValueError:
                pass
    return suspects


# ---------------------------------------------------------------- incidents


def build_incident(server, engine: SloEngine, transition: dict,
                   now: float) -> dict:
    """Snapshot the evidence for a page-severity FIRING, on the master's
    reactor: the violating (job, type)'s tail journeys (with the PR 13
    slow-stage + profiler-window annotations), the responsible ranks'
    dominant stacks over the firing window, the merged metrics delta
    over the burn window, suspect ranks, and the epoch-stamped fleet
    topology. Pure read — the caller writes it via flight.py."""
    from adlb_tpu.obs.metrics import safe_copy
    from adlb_tpu.obs.ops_server import annotate_tails
    from adlb_tpu.obs.profile import window_of

    name = transition["name"]
    o = next((x for x in engine.objectives if x["name"] == name), {})
    job, typ = transition.get("job", 0), transition.get("type", -1)
    tails = [
        j for j in safe_copy(server._tails_fleet)
        if j.get("job", 0) == job and j.get("type", -1) == typ
    ]
    tails = annotate_tails(server, tails[-16:])  # bounded, newest last
    # suspect ranks: where the evidence points (the shared heuristic —
    # the hedge trigger consumes the same function per scan window)
    alert_row = next(
        (a for a in engine.alerts_pub if a["name"] == name), {})
    window_s = float(o.get("window_s") or 60.0)
    delta = engine.ring.window_delta(window_s, now)
    suspects = suspect_ranks(
        alert_row.get("stale_ranks"), tails, delta.get("counters")
    )
    # profiler join: each responsible rank's dominant stacks over the
    # monotonic windows the firing interval crossed (windows are
    # clock-aligned, so alert timestamps index them directly — the same
    # join /trace/tails does per journey, driven by an alert instead)
    fast_s = float(o.get("fast_s") or 2.0)
    fired_at = transition.get("at", now)
    w0, w1 = window_of(fired_at - fast_s), window_of(now)
    from adlb_tpu.obs.ops_server import rank_windows

    span_ranks = {s[1] for j in tails for s in j.get("spans") or ()}
    stacks: dict[str, list] = {}
    for r in sorted(span_ranks | suspects | {server.rank}):
        agg: dict = {}
        for w in rank_windows(server, r):
            if w0 <= w["id"] <= w1:
                for k, v in w["stacks"].items():
                    agg[k] = agg.get(k, 0) + v
        if agg:
            stacks[str(r)] = sorted(
                agg.items(), key=lambda kv: -kv[1])[:5]
    return {
        "incident": name,
        "at": round(now, 6),
        "wall_time": time.time(),
        "job": job,
        "type": typ,
        "severity": transition.get("severity", "page"),
        "transition": dict(transition),
        "objective": dict(o),
        "alert": dict(alert_row),
        "suspect_ranks": sorted(suspects),
        "tails": tails,
        "stacks": stacks,
        "metrics_delta": delta,
        # burn-window hedge activity (launched/won/fenced/vetoed cells):
        # a page should show at a glance whether tail hedging was
        # already absorbing the straggler before the alert fired
        "hedges": {
            k: v for k, v in delta.get("counters", {}).items()
            if k.startswith("hedges_")
        },
        "epoch": server.world.epoch,
        "fleet": server.fleet_doc(),
    }
