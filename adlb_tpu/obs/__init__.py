"""Unified observability: metrics registry, flight recorder, ops endpoint.

The reference scatters its observability across compile-time layers — MPE
spans (``src/adlb_prof.c``), the STAT_APS periodic ring, the debug server's
11-counter heartbeat, and the cblog circular buffer. The rebuild reproduced
each piece in isolation; this package unifies them around one per-rank
:class:`~adlb_tpu.obs.metrics.Registry` that every layer (transport, server
reactor, balancer engine, client) writes into, one JSON
:class:`~adlb_tpu.obs.flight.FlightRecorder` artifact emitted when a world
dies, and one live HTTP surface
(:class:`~adlb_tpu.obs.ops_server.OpsServer`) on the master server —
plus the tail-aware layer: unit journeys with tail-based promotion
(:mod:`~adlb_tpu.obs.journey`) and the continuous sampling profiler
(:mod:`~adlb_tpu.obs.profile`), both riding the same gossip plane.
"""

from adlb_tpu.obs.flight import FlightRecorder, resolve_flight_dir
from adlb_tpu.obs.journey import JourneyRecorder
from adlb_tpu.obs.profile import Profiler
from adlb_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    expose_merged,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "FlightRecorder",
    "JourneyRecorder",
    "Profiler",
    "expose_merged",
    "resolve_flight_dir",
]
