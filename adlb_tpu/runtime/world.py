"""World topology and run configuration.

Role layout matches the reference (reference ``src/adlb.c:238-283``): given W
ranks and S servers, ranks ``0..W-S-1`` (minus an optional trailing debug
server) are app ranks, the next S are servers, and the optional last rank is
the debug-server watchdog. Each app rank has a static *home server*
``num_app_ranks + (rank % nservers)`` (reference ``src/adlb.c:257``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence


@dataclasses.dataclass(frozen=True)
class WorldSpec:
    nranks: int
    nservers: int
    types: tuple[int, ...]
    use_debug_server: bool = False

    def __post_init__(self) -> None:
        if self.nservers < 1:
            raise ValueError("need at least one server rank")
        extra = 1 if self.use_debug_server else 0
        if self.nranks < self.nservers + extra + 1:
            raise ValueError("need at least one app rank")
        if len(set(self.types)) != len(self.types):
            raise ValueError("duplicate work types")

    @property
    def num_app_ranks(self) -> int:
        return self.nranks - self.nservers - (1 if self.use_debug_server else 0)

    @property
    def master_server_rank(self) -> int:
        return self.num_app_ranks

    @property
    def server_ranks(self) -> range:
        return range(self.num_app_ranks, self.num_app_ranks + self.nservers)

    @property
    def app_ranks(self) -> range:
        return range(self.num_app_ranks)

    @property
    def debug_server_rank(self) -> Optional[int]:
        return self.nranks - 1 if self.use_debug_server else None

    def is_server(self, rank: int) -> bool:
        return rank in self.server_ranks

    def is_app(self, rank: int) -> bool:
        return rank < self.num_app_ranks

    def home_server(self, app_rank: int) -> int:
        return self.num_app_ranks + (app_rank % self.nservers)

    def local_apps(self, server_rank: int) -> list[int]:
        """App ranks homed at this server."""
        return [r for r in self.app_ranks if self.home_server(r) == server_rank]

    def ring_next(self, server_rank: int) -> int:
        """Server ring successor (reference rhs_rank, ``src/adlb.c:272-283``),
        used by the termination/exhaustion token passes — and, under
        ``on_server_failure="failover"``, the replication **buddy**: each
        server streams its pool-mutation log to its ring successor."""
        i = server_rank - self.num_app_ranks
        return self.num_app_ranks + (i + 1) % self.nservers

    def validate_type(self, work_type: int) -> bool:
        return work_type in self.types


@dataclasses.dataclass
class Config:
    """Run-time knobs. The reference exposes these as ADLB_Init/Server
    arguments and compile-time constants (reference ``src/adlb.c:93-96,165``;
    ``USERGUIDE.txt:96-130``)."""

    # "steal" = reference-style heuristics (qmstat gossip + RFR pull + memory
    # push); "tpu" = periodic batched global assignment solve in JAX.
    balancer: str = "steal"

    max_malloc_per_server: float = 0.0  # 0 = unlimited (reference hi_malloc)
    qmstat_interval: float = 0.05  # reference 0.1 s (src/adlb.c:165)
    # qmstat propagation: "broadcast" sends each server's entry directly to
    # every peer each interval (this framework's improvement); "ring" is the
    # reference-faithful store-and-forward token pass — the master kicks one
    # token per interval, each server overwrites the table except its own
    # entry and forwards (reference src/adlb.c:806-822,1705-1757), so the
    # k-th hop sees k-hop-stale state. Use "ring" + 0.1 s to reproduce
    # upstream's behavior as a baseline.
    qmstat_mode: str = "broadcast"
    # steal/broadcast mode only: when an untargeted put makes a type's
    # advertised inventory go empty->nonempty, broadcast a fresh qmstat
    # immediately (rate-limited to one event broadcast per this many
    # seconds) instead of waiting out the periodic tick — the trickle
    # dispatch-latency fix. 0 disables the event path. Ring mode stays
    # upstream-faithful (interval-only) regardless.
    qmstat_event_gap: float = 0.005
    balancer_interval: float = 0.02  # TPU-mode snapshot->solve->plan period
    # min gap between event-driven solves (a park triggers an immediate
    # snapshot+solve; this bounds solve rate under churn)
    balancer_min_gap: float = 0.002
    # the balancer worker is event-gated: it sleeps on its doorbell
    # (armed by puts, requester parks and qmstat deltas) and only falls
    # back to this slow insurance tick when no work signal arrives —
    # an idle world pays ~4 ticks/s instead of 50 (the 20 ms tick was
    # 8.3% of single-core samples on the tsp parity bench). 0 disables
    # the insurance tick entirely (pure event-driven; not recommended)
    balancer_idle_interval: float = 0.25
    # untargeted put routing: "round_robin" spreads over servers (reference
    # src/adlb.c:2771-2773); "home" keeps work at the putter's home server
    # (data locality; relies on the balancer to redistribute)
    put_routing: str = "round_robin"
    exhaust_check_interval: float = 0.25  # reference 5 s (src/adlb.c:754-785)
    periodic_log_interval: float = 0.0  # 0 = off
    debug_log_interval: float = 1.0  # DS_LOG cadence (src/adlb.c:842-854)
    debug_server_timeout: float = 30.0
    # debug server's aggregate-print cadence (the reference prints per
    # minute, src/adlb.c:2569-2610); 0 disables the prints
    debug_print_interval: float = 60.0
    put_max_retries: int = 10  # reference retry loop (src/adlb.c:2779-2796)
    # retry pacing: capped exponential backoff with decorrelated jitter
    # (replacing the reference's fixed-interval spin, src/adlb.c:2779-2796):
    # sleep_k ~ U(put_retry_sleep, 3*sleep_{k-1}), capped at put_retry_cap
    put_retry_sleep: float = 0.002  # backoff base (first retry's floor)
    put_retry_cap: float = 0.25  # backoff ceiling per attempt
    # bounded client-side send retries when a peer connection breaks
    # mid-run (network churn): the endpoint already retries once; beyond
    # that the client backs off and re-sends instead of dying on the
    # first OSError. 0 = fail fast (pre-reclaim behaviour).
    reconnect_attempts: int = 4
    # client-side batch-common prefix cache (LRU over (common_server,
    # common_seqno) -> bytes): members of a batch inline only their
    # suffix and the prefix is fetched once per client instead of once
    # per unit; cache hits send an SS_COMMON_FORFEIT accounting note so
    # server refcounts (and prefix GC) stay exact. 0 disables caching
    # (every prefixed unit pays the fetch, as the reference does).
    prefix_cache_bytes: int = 16 << 20
    # worker (app rank) failure policy: "abort" preserves the reference's
    # rank-death-kills-job semantics (MPI_Abort paths, src/adlb.c:2508-2526);
    # "reclaim" survives it — the home server fans out SS_RANK_DEAD, every
    # server re-enqueues the dead rank's leased-but-unfetched units, drops
    # its rq entries and targeted work (refcount-correct common release),
    # and termination counting excludes the rank. Server death aborts
    # under both policies (checkpoint/restore is the recovery path).
    on_worker_failure: str = "abort"
    # server failure policy: "abort" preserves the reference's
    # server-death-kills-world semantics; "failover" survives the death of
    # a NON-master server — every server asynchronously streams a
    # replication log of its pool mutations to its ring-successor buddy
    # (adlb_tpu/runtime/replica.py, SS_REPL frames in the checkpoint.py
    # unit wire format); on a server's EOF the survivors fan out
    # SS_SERVER_DEAD, the buddy replays the log into its own queues and
    # takes over home-server duty for the dead server's app ranks, and
    # clients learn the epoch-stamped remap via TA_HOME_TAKEOVER.
    # Replication-lag losses are bounded and counted (failover_lost /
    # InfoKey.FAILOVER_LOST). The MASTER is covered too: its ring buddy
    # is a standing deputy — the master streams its brain (job table,
    # membership snapshot + fleet epoch, live SLO objectives, control
    # policy, parked scale requests, per-job weights) over the same
    # replication plane, and on the master's death the deputy promotes
    # under a bumped epoch, fans SS_MASTER_TAKEOVER behind an ack
    # barrier, rebinds the ops endpoint, and resumes termination duty
    # with exact unit accounting. A buddy dying before its promotion
    # completes (the double failure) still aborts. Requires
    # server_impl="python"; inert when nservers == 1.
    on_server_failure: str = "abort"
    # how long a client waits for the buddy's TA_HOME_TAKEOVER after
    # losing a server connection before declaring the world dead
    # (failover policy only)
    failover_client_wait: float = 15.0
    # gray-failure detection: a lease (reserved-but-unfetched unit) whose
    # owner has neither sent traffic nor heartbeated for this long is
    # EXPIRED — the unit re-enqueues under a fresh attempt and the old
    # owner is FENCED for it (its late Get_reserved answers ADLB_FENCED;
    # clients map that onto the ADLB_RETRY path). Clients arm a liveness
    # heartbeat (FA_HEARTBEAT at timeout/3 cadence to every server) while
    # this is set; a rank silent for 2x the timeout is declared hung by
    # its home server (declared dead under "reclaim", world abort under
    # "abort" — bounded detection either way; a SIGSTOP'd worker EOFs
    # nothing, so without this the world hangs forever). 0 = off
    # (reference semantics: a hung owner holds its leases forever).
    # CAVEAT: armed expiry makes delivery at-least-once for exactly the
    # expired-lease window (the fenced owner may have fetched the
    # payload before stalling); fencing guarantees no double-SETTLE, not
    # no double-execution. Python clients only (the C client does not
    # heartbeat — a busy native rank would be misread as hung).
    lease_timeout_s: float = 0.0
    # retry budget per unit: a unit whose delivery failed (owner death
    # reclaim, lease expiry, undeliverable response) more than this many
    # times is moved to the per-server dead-letter QUARANTINE instead of
    # the queue — bounded blast radius for a poison unit that crashes
    # every worker it touches. Counted exactly-once
    # (InfoKey.QUARANTINED / WorldResult.quarantined, surviving
    # failover), settled for exhaustion voting, retrievable via
    # ctx.get_quarantined() and the ops endpoint /deadletter.
    # 0 = unlimited retries (reference-faithful: reclaim re-enqueues
    # forever).
    max_unit_retries: int = 0
    # tail hedging (runtime/hedge.py): when > 0 the home server
    # speculatively re-dispatches a leased-but-unfetched unit whose age
    # crossed the live per-(job, type) p99 threshold the master gossips
    # (SS_OBS_SYNC `thr`) — or whose lease holder shows a stall
    # signature (the shared obs/slo.py suspect heuristic) — to a parked
    # requester on a DIFFERENT rank. First terminal wins and closes the
    # books exactly once; every losing sibling is fenced through the
    # (seqno, owner) machinery, so the at-least-once window stays
    # exactly the documented lease-expiry one. The value doubles as the
    # per-job token-bucket refill per delivered unit: launches are
    # bounded by ~frac x deliveries (+ a small burst) by construction,
    # and any backpressure signal (memory watermark, job quota,
    # allocation failure) vetoes a launch stickily — hedging always
    # yields to overload. Requires lease_timeout_s > 0 (the trigger
    # scans the lease table; fencing IS the lease machinery). 0 = off:
    # frame-identical to an unhedged world.
    hedge_budget_frac: float = 0.0
    # age floor (ms) below which a unit is never hedged regardless of
    # threshold or suspicion — cold-start p99 noise must not burn the
    # budget on units that are not stragglers yet
    hedge_min_age_ms: float = 100.0
    # memory watermarks (fractions of max_malloc_per_server): above SOFT
    # the server engages memory-pressure pushes (the reference's
    # THRESHOLD_TO_START_PUSH, src/adlb.c:93 — 0.95 there and here) and
    # reports the mem_pressure gauge; above HARD with no peer believed to
    # have room, puts answer ADLB_BACKOFF with a retry-after hint that
    # feeds the client's decorrelated-jitter backoff (not burning its
    # retry budget), so an overloaded fleet sheds load instead of
    # aborting producers on malloc exhaustion. mem_hard_frac 0 = off
    # (reference behavior: ADLB_PUT_REJECTED hopping until retries
    # exhaust).
    mem_soft_frac: float = 0.95
    mem_hard_frac: float = 0.0
    # seeded deterministic fault injection (adlb_tpu/runtime/faults.py):
    # a plain-data spec dict {seed, drop, delay, delay_s, duplicate,
    # disconnect_at: {rank: frame}, kill_at_frame: {rank: frame},
    # kill_at: {rank: seconds}, ranks: [..], log_dir}. None = off.
    fault_spec: Optional[dict] = None
    # Max queued tasks & waiting requesters per server in one balancer
    # snapshot (fixed shapes for the jitted solve).
    balancer_max_tasks: int = 256
    balancer_max_requesters: int = 64
    # ---- multi-job planning (balancer/jobdim.py) ----
    # how many job namespaces the tpu balancer plans: 1 (default)
    # reproduces the historical job-0-only planner exactly — same
    # shapes, same compiled programs, same pairs — with non-default
    # jobs riding the qmstat RFR fallback; > 1 widens the solver's
    # type axis to max_jobs * len(types) composite (job, type) slots
    # so every namespace below the cap is planned (jobs at or above
    # the cap keep the fallback). Auto-raised to cover job_weights.
    balancer_max_jobs: int = 1
    # per-job weights/shares folded into the assignment score as an
    # int32-safe priority bias (eff_prio = clip(prio) + (w-1)*1e6,
    # see balancer/jobdim.py): {job_id: weight}, 1.0 = neutral. A
    # heavier tenant outranks a lighter one at equal native priority
    # without letting priorities cross job isolation — weights are
    # shares, priorities stay the intra-job ordering. Live updates
    # ride POST /jobs/<id> {"weight": w}. None = all jobs neutral.
    job_weights: Optional[dict] = None
    # Adaptive migration-pump knobs (balancer/engine.py): a server holding
    # >= lookahead ready units per local consumer is never
    # migration-deficient; a destination that re-triggers its deficit
    # within grow_window seconds of the last shipped batch has its
    # per-consumer window doubled (capped at look_max); in-flight batch
    # credits survive at least inflow_min_age seconds and at most
    # inflow_ttl. None = engine defaults.
    balancer_lookahead: "Optional[int]" = None
    balancer_look_max: "Optional[int]" = None
    balancer_grow_window: "Optional[float]" = None
    balancer_inflow_ttl: "Optional[float]" = None
    balancer_inflow_min_age: "Optional[float]" = None
    # device solve implementation: "auto" = Pallas sweep kernel on TPU, XLA
    # scan elsewhere; explicit "xla"/"pallas" force one
    solver_backend: str = "auto"
    # parked-requester count below which the solve stays on the numpy host
    # path (a device dispatch round-trip would dominate); None = solver
    # default. Set very high when the balancer host has no local
    # accelerator (e.g. a CPU-only sidecar).
    solver_host_threshold: "Optional[int]" = None
    # "auto" = when more than one accelerator device is visible, shard the
    # balancer's task table over a jax.sharding.Mesh (one shard per device,
    # balancer/distributed.py); "off" = single-device solve
    balancer_mesh: str = "off"
    # auction tier of the sharded solver (balancer/distributed.py):
    # "device" runs merge + auction rounds + commit threshold as one
    # jitted shard_map program (no per-round host merge of the gather);
    # "host" is the retained reference twin the device tier is
    # fuzz-proven exactly equal to. Only consulted when the mesh
    # solver is active (balancer_mesh="auto" on a multi-device host)
    balancer_auction: str = "device"
    # host tier of the plan engine (balancer/ledger.py): "array" keeps
    # parked requesters / snapshot tasks resident in numpy columns so
    # round admission costs O(changed rows); "py" is the pure-Python
    # twin (exact reference semantics, fuzz-proven identical — an
    # escape hatch, not a feature switch)
    host_ledger: str = "array"
    trace: bool = False  # event tracing hooks (reference MPE shims);
    # since the obs unification this traces BOTH sides: client API spans
    # (pid 0) and server handler / balancer-round spans (pid 1) into one
    # merged Chrome-trace stream
    # unit-lifecycle tracing (adlb_tpu/obs/journey.py): head-sampling
    # probability at put — a sampled unit's FA_PUT carries a trace id
    # (codec field 98) and every server it crosses appends
    # (stage, rank, t) spans until a terminal event closes the journey
    # (per-stage latency histograms + /trace/units on the master's ops
    # endpoint). 0 disables it entirely: no wire field, no allocations
    # on the put path — trace_sample=0 worlds are frame-identical to
    # pre-trace builds. Sampling decisions come from a dedicated
    # per-rank seeded RNG, so they are reproducible and never perturb
    # the retry-jitter stream.
    trace_sample: float = 0.01
    # tail-based journey promotion (the head-vs-tail sampling gap fix,
    # obs/journey.py): "auto" arms it whenever the ops endpoint is
    # configured (ops_port is not None) — an observed world captures its
    # p99 by construction; "on"/"off" force it. Armed, EVERY put
    # accumulates spans (server-minted negative trace ids; the put wire
    # stays byte-identical — nothing new rides FA_PUT) and the terminal
    # close decides retention: head-sampled as before, anomalous
    # terminals (quarantined/dropped/lost/expired-lease) always, and
    # clean deliveries only when their total latency exceeds the live
    # fleet per-(job,type) p99 (threshold gossiped back on SS_OBS_SYNC
    # replies; hysteresis: arms at TAIL_MIN_COUNT closes per cell).
    # Promoted journeys serve on the master's /trace/tails.
    trace_tail: str = "auto"
    # continuous sampling profiler (obs/profile.py): per-process
    # folded-stack sampler at this many Hz walking sys._current_frames()
    # into role/phase-keyed collapsed stacks, delta-gossiped over
    # SS_OBS_SYNC; the master serves the merged fleet profile at
    # /profile. 0 = off (no thread at all); 19 Hz recommended (prime —
    # cannot phase-lock the balancer/qmstat cadences).
    profile_hz: float = 0.0
    # fleet metrics plane: non-master servers gossip delta-encoded
    # registry snapshots (changed counters/gauges/histograms, cumulative
    # values) plus their closed journeys to the master every this many
    # seconds, so the master's /metrics serves a merged FLEET view and
    # /healthz exposes per-rank snapshot staleness. Armed only when the
    # ops endpoint is configured (ops_port is not None) — worlds without
    # an observer pay zero gossip traffic. 0 disables the plane.
    obs_sync_interval: float = 1.0
    # Flight-recorder JSON artifacts: directory for per-rank post-mortem
    # dumps on abort / watchdog timeout / lost home server. None defers
    # to the ADLB_FLIGHT_DIR env var; unset = text dumps only
    # (adlb_tpu/obs/flight.py; summarize with scripts/obs_report.py).
    flight_dir: Optional[str] = None
    # Declarative SLO objectives (obs/slo.py), evaluated by the MASTER
    # each obs tick against the merged fleet registry: a tuple of dicts,
    # each e.g. {"job": 0, "type": 3, "p99_ms": 50, "error_frac": 0.001,
    # "window_s": 300} (at least one of p99_ms / error_frac; window_s is
    # the slow burn window — the fast one defaults to window_s/12).
    # None/empty = no evaluation; objectives can also be added to a live
    # world via POST /slo. Requires ops_port (the alert surfaces are
    # ops routes) and obs_sync_interval > 0 (the merged view is the
    # gossip plane's product).
    slo: Optional[tuple] = None
    # SLO evaluation cadence in seconds; 0 (default) evaluates on every
    # obs-sync tick — the natural cadence, since that is when fresh
    # fleet snapshots arrive.
    slo_eval_interval: float = 0.0
    # ---- closed-loop controller (adlb_tpu/control/) ----
    # the fleet brain: a MASTER-side policy loop riding the obs tick
    # (like the SLO engine) that watches the merged registry + alert
    # table (mem_pressure, put_backoff, per-job depth/age, FIRING
    # alerts) and drives the existing actuators — server scale-out/in
    # through the membership plane and per-tenant throttling through
    # job quotas — under explicit hysteresis (per-action cooldowns,
    # min/max bounds, epoch-churn hold). False = no controller thread,
    # no counters, frame-identical to a pre-controller world. Requires
    # obs_sync_interval > 0 (the merged view is the gossip plane's
    # product) and server_impl="python".
    control: bool = False
    # controller evaluation cadence; 0 = every obs-sync tick
    control_interval: float = 0.0
    # log decisions (visible at GET /control) without acting
    control_dry_run: bool = False
    # fleet-size bounds the controller must respect; max 0 = unbounded
    control_min_servers: int = 1
    control_max_servers: int = 0
    # per-action cooldown: after the controller acts (scale/throttle),
    # the same action class is held for this long — a flapping metric
    # produces at most one action per window
    control_cooldown_s: float = 10.0
    # fleet max mem_pressure above which the controller requests a
    # scale-out (and considers throttling the heaviest non-default
    # tenant), and below which — held for a full cooldown window with
    # idle queues — it drains the newest shard back in
    control_scaleout_pressure: float = 0.85
    control_scalein_pressure: float = 0.30
    # Live ops endpoint on the MASTER server: serves /metrics (registry
    # exposition + last STAT_APS world aggregate), /healthz, and /dump
    # (flight-record snapshot) on 127.0.0.1:<ops_port>. None = off;
    # 0 = ephemeral port (the bound port is aprintf-logged and exposed
    # as Server.ops.port). Enable periodic_log_interval for the
    # world-aggregated rows.
    ops_port: Optional[int] = None
    # ops-endpoint rendezvous directory: when set, the serving master
    # atomically writes <dir>/ops_endpoint.json ({"host","port","master",
    # "epoch"}) at startup AND after a master failover rebinds the
    # endpoint on an ephemeral port — external scrapers re-discover the
    # promoted deputy's /metrics without parsing logs. None = off.
    ops_announce_dir: Optional[str] = None
    # restore pool state from checkpoint shards written by ctx.checkpoint()
    # (no reference analogue — SURVEY §5: checkpoint/resume absent there);
    # requires the same world shape the checkpoint was taken with
    restore_path: Optional[str] = None
    # ---- durable service mode (adlb_tpu/runtime/wal.py) ----
    # per-server write-ahead log directory: every pool mutation (the
    # replica op stream, OP_PUT..OP_JOB) is teed to an append-only
    # crc-framed log at <wal_dir>/server.<rank>.log; put acks are held
    # for the group commit that makes their entries durable, so an
    # ACKED put always survives a cold restart (shard-load + replay at
    # server init). None = off (reference semantics: a dead fleet loses
    # the pool). Python servers only.
    wal_dir: Optional[str] = None
    # group-commit window in milliseconds: fsync at most once per
    # window, releasing the put acks the commit covers. 0 = fsync every
    # reactor flush (strict, per-batch durability at per-batch fsync
    # cost). Durability/latency trade-off table in USERGUIDE §10.
    wal_fsync_ms: float = 5.0
    # compaction threshold: when the live segment outgrows this, the
    # server snapshots its pool into the ACK2 checkpoint shard format
    # and starts a fresh segment headed by the seqno manifest. 0 = never
    # compact (the log grows for the fleet's lifetime).
    wal_max_bytes: int = 64 << 20
    # legacy ACK1 (pre-header) checkpoint shards: WAL compaction writes
    # ACK2 only, and silently accepting a headerless shard means
    # silently skipping the world-shape check that keeps targeted units
    # routable — so ACK1 reads now fail LOUDLY unless this flag opts
    # back in (old native daemons' shards; serverd.cpp still writes and
    # validates ACK2 itself).
    allow_legacy_shards: bool = False
    # ops endpoint payload truncation: how many payload bytes /deadletter
    # (and other ops views) hex-encode per record before cutting off.
    # The full payload stays retrievable in-band via ctx.get_quarantined().
    ops_dump_bytes: int = 256
    aprintf_flag: bool = False  # stamped debug prints (src/adlb.c:3395-3417)
    # queue-depth gauge / timeline sampling cadence on the reactor tick
    # (floored at the state-sync interval): decoupled from the 20 ms
    # tpu-mode balancer tick, whose per-tick gauge walk was a measured
    # slice of the r01->r05 tpu pop-latency drift
    gauge_interval: float = 0.25
    selfdiag_interval: float = 30.0  # server health dumps; 0 = off
    # (src/adlb.c:558-710; the reference hard-codes 30 s)
    selfdiag_stuck_after: float = 5.0  # rq age that counts as "stuck"
    # server work-queue implementation: "auto" uses the C++ core when it
    # builds, falling back to the pure-Python queues; "on" requires it
    native_queues: str = "auto"
    # process-world transport fabric (spawn_world / launch.py / joined
    # clients; in-proc thread worlds always use the queue fabric):
    # "auto" upgrades same-host rank pairs to the shared-memory ring
    # fabric (adlb_tpu/runtime/transport_shm.py) whenever the host can
    # run it (honoring the ADLB_FABRIC env override — the CI shm leg's
    # hook), with cross-host pairs staying on TCP; "shm" forces the ring
    # fabric (same-host pairs only — others still fall back to TCP);
    # "tcp" disables the upgrade entirely.
    fabric: str = "auto"
    # per-direction ring capacity per connected pair; frames larger than
    # the ring stream through it, so this bounds /dev/shm footprint
    # (pairs x 2 x this), not payload size. 1 MiB keeps a 2 MiB payload
    # to two backpressure cycles while a 16-app/4-server world still
    # maps under 150 MiB of (reclaimable) tmpfs
    shm_ring_bytes: int = 1 << 20
    # ---- disk spill tier (adlb_tpu/runtime/spill.py) ----
    # directory for the per-server payload spill file: above the spill
    # watermark, cold/large parked payloads move to disk (crc-framed,
    # the WAL's record format) and fault back in transparently at
    # delivery time — memory pressure degrades to slower-fetch instead
    # of ADLB_BACKOFF/ADLB_PUT_REJECTED. None = off (reference
    # semantics). Python servers only.
    spill_dir: Optional[str] = None
    # fraction of max_malloc_per_server above which spilling engages;
    # 0 = track mem_soft_frac (the PR 5 soft watermark)
    spill_watermark_frac: float = 0.0
    # wire-codec implementation for TLV frames (native peers, shm rings,
    # mux'd channels): "auto" uses the compiled C core
    # (adlb_tpu/native/codec.cpp) whenever it builds, falling back to
    # the pure-Python twin; "c" requires it (no silent fallback); "py"
    # forces the Python twin. Selected per-process at world start; the
    # ADLB_CODEC env var sets the import-time default the same way.
    codec: str = "auto"
    # ---- multiplexed cross-host channels (adlb_tpu/runtime/channel.py) ----
    # "auto" rides per-pair TCP today (single-host worlds lose latency
    # on the mux's two hops; engaging it automatically for multi-host
    # fleets — the O(hosts^2)-not-O(ranks^2) socket regime — awaits the
    # launcher's broker publication, ROADMAP item 5); "on" forces the
    # channel plane and requires a harness that runs a broker
    # (spawn_world today; the rendezvous launcher / join_world reject
    # it loudly rather than silently running per-pair) — also
    # forceable via ADLB_TCP_MUX=1 (the CI leg's hook); "off" pins
    # per-pair TCP.
    tcp_mux: str = "auto"
    # compress DATA-envelope bodies at least this large on the channel
    # plane (zlib level 1, flag bit 0 of the envelope header; the
    # receiver inflates before frame decode). 0 = off.
    compress_min_bytes: int = 0
    # elastic scale-out trigger (adlb_tpu/runtime/membership.py):
    # "auto" lets the MASTER request a new server shard when any live
    # server crosses the soft memory watermark — capacity is added
    # BEFORE the spill tier or ADLB_BACKOFF backpressure engage (needs
    # max_malloc_per_server > 0 and a registered member spawner; without
    # a spawner the request parks, visible at /fleet, feeding the
    # future autoscaler). "off" = manual scale only (ops POST
    # /fleet/scale or the harness verbs). Attach/detach and manual
    # scaling are always available on python servers regardless.
    elastic_scaleout: str = "off"
    # cooldown between watermark-triggered scale-out requests
    elastic_cooldown_s: float = 10.0
    # server reactor implementation (spawn_world / TCP worlds only):
    # "python" runs adlb_tpu.runtime.server.Server per server rank; "native"
    # runs the C++ daemon (adlb_tpu/native/serverd.cpp) — the reference's
    # all-native data plane (SURVEY §7 language split). With
    # balancer="tpu", native servers stream snapshots to a Python/JAX
    # balancer sidecar process (adlb_tpu/balancer/sidecar.py) and enact its
    # plan; with "steal" they run the heuristics natively.
    server_impl: str = "python"

    def __post_init__(self) -> None:
        if self.balancer not in ("steal", "tpu"):
            raise ValueError(f"unknown balancer mode {self.balancer!r}")
        if self.put_routing not in ("round_robin", "home"):
            raise ValueError(f"unknown put routing {self.put_routing!r}")
        if self.native_queues not in ("auto", "on", "off"):
            raise ValueError(f"unknown native_queues {self.native_queues!r}")
        if self.solver_backend not in ("auto", "xla", "pallas"):
            raise ValueError(f"unknown solver_backend {self.solver_backend!r}")
        if self.host_ledger not in ("array", "py"):
            raise ValueError(f"unknown host_ledger {self.host_ledger!r}")
        if self.server_impl not in ("python", "native"):
            raise ValueError(f"unknown server_impl {self.server_impl!r}")
        if self.elastic_scaleout not in ("off", "auto"):
            raise ValueError(
                f"unknown elastic_scaleout {self.elastic_scaleout!r}"
            )
        if self.elastic_scaleout == "auto" and self.server_impl == "native":
            # the C++ daemon keeps the reference's fixed-at-init world
            raise ValueError(
                "elastic_scaleout='auto' requires server_impl='python'"
            )
        if self.elastic_cooldown_s < 0:
            raise ValueError("elastic_cooldown_s must be >= 0")
        if self.qmstat_mode not in ("broadcast", "ring"):
            raise ValueError(f"unknown qmstat_mode {self.qmstat_mode!r}")
        if self.fabric not in ("auto", "shm", "tcp"):
            raise ValueError(f"unknown fabric {self.fabric!r}")
        if self.codec not in ("auto", "c", "py"):
            raise ValueError(f"unknown codec {self.codec!r}")
        if self.tcp_mux not in ("auto", "on", "off"):
            raise ValueError(f"unknown tcp_mux {self.tcp_mux!r}")
        if self.compress_min_bytes < 0:
            raise ValueError("compress_min_bytes must be >= 0")
        if self.shm_ring_bytes < 4096:
            raise ValueError("shm_ring_bytes must be >= 4096")
        if not (0.0 <= self.spill_watermark_frac <= 1.0):
            raise ValueError("spill_watermark_frac must be in [0, 1]")
        if self.spill_dir is not None and self.server_impl == "native":
            # the C++ daemon has no spill store; its capacity story is
            # the reference admission control only
            raise ValueError("spill_dir requires server_impl='python'")
        if self.spill_dir is not None and self.native_queues == "on":
            # the spill tier swaps payload residency in place, which the
            # C++ queue core cannot express; an explicit 'on' must fail
            # loudly rather than silently losing the native core
            raise ValueError(
                "spill_dir requires the Python work queue "
                "(native_queues='auto' or 'off')"
            )
        if self.on_worker_failure not in ("abort", "reclaim"):
            raise ValueError(
                f"unknown on_worker_failure {self.on_worker_failure!r}"
            )
        if self.on_server_failure not in ("abort", "failover"):
            raise ValueError(
                f"unknown on_server_failure {self.on_server_failure!r}"
            )
        if self.on_worker_failure == "reclaim" and self.server_impl == "native":
            # the C++ daemon implements the reference fault model only;
            # failing here beats a world that silently aborts anyway
            raise ValueError(
                "on_worker_failure='reclaim' requires server_impl='python'"
            )
        if self.on_server_failure == "failover" and self.server_impl == "native":
            # the C++ daemon has no replication stream or takeover protocol
            raise ValueError(
                "on_server_failure='failover' requires server_impl='python'"
            )
        if self.failover_client_wait <= 0:
            raise ValueError("failover_client_wait must be > 0")
        if self.lease_timeout_s < 0:
            raise ValueError("lease_timeout_s must be >= 0")
        if self.lease_timeout_s > 0 and self.server_impl == "native":
            # the C++ daemon has no lease table, heartbeat intake, or
            # fence bookkeeping
            raise ValueError(
                "lease_timeout_s > 0 requires server_impl='python'"
            )
        if self.max_unit_retries < 0:
            raise ValueError("max_unit_retries must be >= 0")
        if self.max_unit_retries > 0 and self.server_impl == "native":
            raise ValueError(
                "max_unit_retries > 0 requires server_impl='python'"
            )
        if not (0.0 <= self.hedge_budget_frac <= 1.0):
            raise ValueError("hedge_budget_frac must be in [0, 1]")
        if self.hedge_min_age_ms < 0:
            raise ValueError("hedge_min_age_ms must be >= 0")
        if self.hedge_budget_frac > 0 and self.server_impl == "native":
            # the C++ daemon has no lease table or hedge bookkeeping
            raise ValueError(
                "hedge_budget_frac > 0 requires server_impl='python'"
            )
        if self.hedge_budget_frac > 0 and self.lease_timeout_s <= 0:
            # the trigger scans the lease table and the loser's fence
            # is the lease-expiry fence — unarmed leases mean neither
            raise ValueError(
                "hedge_budget_frac > 0 requires lease_timeout_s > 0"
            )
        if not (0.0 < self.mem_soft_frac <= 1.0):
            raise ValueError("mem_soft_frac must be in (0, 1]")
        if not (0.0 <= self.mem_hard_frac <= 1.0):
            raise ValueError("mem_hard_frac must be in [0, 1]")
        if self.mem_hard_frac > 0 and self.mem_hard_frac < self.mem_soft_frac:
            raise ValueError(
                "mem_hard_frac, when armed, must be >= mem_soft_frac"
            )
        if self.mem_hard_frac > 0 and self.server_impl == "native":
            # the C++ daemon answers capacity with ADLB_PUT_REJECTED only
            raise ValueError(
                "mem_hard_frac > 0 requires server_impl='python'"
            )
        if self.put_retry_cap < self.put_retry_sleep:
            raise ValueError("put_retry_cap must be >= put_retry_sleep")
        if self.reconnect_attempts < 0:
            raise ValueError("reconnect_attempts must be >= 0")
        if self.prefix_cache_bytes < 0:
            raise ValueError("prefix_cache_bytes must be >= 0")
        if self.qmstat_event_gap < 0:
            raise ValueError("qmstat_event_gap must be >= 0")
        if self.ops_port is not None and not (0 <= self.ops_port <= 65535):
            raise ValueError("ops_port must be None or in 0..65535")
        if not (0.0 <= self.trace_sample <= 1.0):
            raise ValueError("trace_sample must be in [0, 1]")
        if self.trace_tail not in ("auto", "on", "off"):
            raise ValueError(f"unknown trace_tail {self.trace_tail!r}")
        if self.profile_hz < 0:
            raise ValueError("profile_hz must be >= 0")
        if self.obs_sync_interval < 0:
            raise ValueError("obs_sync_interval must be >= 0")
        if self.slo_eval_interval < 0:
            raise ValueError("slo_eval_interval must be >= 0")
        if self.slo:
            # structural gate only (cheap, import-free): full
            # normalization happens in obs/slo.py parse_objective at
            # engine creation, where errors carry the objective name
            for o in self.slo:
                if not isinstance(o, dict):
                    raise ValueError("slo entries must be dicts")
                if o.get("p99_ms") is None and o.get("error_frac") is None:
                    raise ValueError(
                        "each slo entry needs p99_ms and/or error_frac")
                if float(o.get("window_s", 0) or 0) <= 0:
                    raise ValueError("each slo entry needs window_s > 0")
        if self.wal_dir is not None and self.server_impl == "native":
            # the C++ daemon has no WAL writer; its durability story is
            # the explicit checkpoint ring only
            raise ValueError("wal_dir requires server_impl='python'")
        if self.wal_dir is not None and self.restore_path is not None:
            # two competing sources of restored pool state would apply
            # in an arbitrary-looking order; pick one
            raise ValueError(
                "wal_dir and restore_path are mutually exclusive (WAL "
                "recovery IS a restore)"
            )
        if self.wal_fsync_ms < 0:
            raise ValueError("wal_fsync_ms must be >= 0")
        if self.wal_max_bytes < 0:
            raise ValueError("wal_max_bytes must be >= 0")
        if self.ops_dump_bytes < 0:
            raise ValueError("ops_dump_bytes must be >= 0")
        # snapshot lists are flattened into binary-codec list fields whose
        # element count is a u16 (4 entries per task, 3+ntypes per
        # requester); keep a wide safety margin under 65535
        for knob in ("balancer_lookahead", "balancer_look_max",
                     "balancer_grow_window", "balancer_inflow_ttl",
                     "balancer_inflow_min_age"):
            v = getattr(self, knob)
            if v is not None and v < 0:
                raise ValueError(f"{knob} must be >= 0")
        # the engine cannot honor a transit floor above the credit TTL
        # (TTL expiry would silently override the min-age guarantee);
        # literals = the engine defaults (balancer/engine.py INFLOW_TTL /
        # INFLOW_MIN_AGE), not imported here to keep Config import-light
        # look_max below the lookahead floor would let _touch_window decay
        # a destination's window under its own floor — with look_max=0 the
        # window (and thus need) pins to 0 and migrations to that
        # destination are silently disabled forever
        look = 8 if self.balancer_lookahead is None \
            else self.balancer_lookahead
        lmax = 512 if self.balancer_look_max is None \
            else self.balancer_look_max
        if lmax < max(1, look):
            raise ValueError(
                "balancer_look_max must be >= max(1, balancer_lookahead)"
            )
        ttl = 2.0 if self.balancer_inflow_ttl is None \
            else self.balancer_inflow_ttl
        age = 0.05 if self.balancer_inflow_min_age is None \
            else self.balancer_inflow_min_age
        if age > ttl:
            raise ValueError(
                "balancer_inflow_min_age must be <= balancer_inflow_ttl"
            )
        if not (0 < self.balancer_max_jobs <= 16):
            # the composite type axis is max_jobs * len(types) solver
            # columns; 16 namespaces keeps the widened axis far from
            # the u16 wire limits and the one-compile shape reasonable
            raise ValueError("balancer_max_jobs must be in 1..16")
        if self.job_weights is not None:
            for j, w in self.job_weights.items():
                if int(j) < 0:
                    raise ValueError("job_weights keys must be >= 0")
                if not (float(w) > 0.0):
                    raise ValueError("job_weights values must be > 0")
            # weights on jobs the planner cannot see would silently do
            # nothing — widen the planning axis to cover them
            hi = max((int(j) for j in self.job_weights), default=0)
            if hi + 1 > self.balancer_max_jobs:
                if hi + 1 > 16:
                    raise ValueError(
                        "job_weights names a job beyond the planner's "
                        "16-namespace cap"
                    )
                self.balancer_max_jobs = hi + 1
        if self.control:
            if self.server_impl != "python":
                raise ValueError("control=True requires server_impl='python'")
            if self.obs_sync_interval <= 0:
                # the controller's inputs are the merged obs registry
                # and alert table — products of the gossip plane
                raise ValueError("control=True requires obs_sync_interval > 0")
        if self.control_interval < 0:
            raise ValueError("control_interval must be >= 0")
        if self.control_cooldown_s < 0:
            raise ValueError("control_cooldown_s must be >= 0")
        if self.control_min_servers < 1:
            raise ValueError("control_min_servers must be >= 1")
        if self.control_max_servers < 0:
            raise ValueError("control_max_servers must be >= 0")
        if self.control_max_servers and \
                self.control_max_servers < self.control_min_servers:
            raise ValueError(
                "control_max_servers, when bounded, must be >= "
                "control_min_servers"
            )
        if not (0.0 < self.control_scaleout_pressure <= 1.0):
            raise ValueError("control_scaleout_pressure must be in (0, 1]")
        if not (0.0 <= self.control_scalein_pressure
                < self.control_scaleout_pressure):
            raise ValueError(
                "control_scalein_pressure must be in "
                "[0, control_scaleout_pressure)"
            )
        if not (0 < self.balancer_max_tasks <= 8192):
            raise ValueError("balancer_max_tasks must be in 1..8192")
        if not (0 < self.balancer_max_requesters <= 2048):
            raise ValueError("balancer_max_requesters must be in 1..2048")
        if self.balancer_mesh not in ("off", "auto"):
            raise ValueError(f"unknown balancer_mesh {self.balancer_mesh!r}")
        if self.balancer_auction not in ("device", "host"):
            raise ValueError(
                f"unknown balancer_auction {self.balancer_auction!r}"
            )
        if self.balancer_idle_interval < 0:
            raise ValueError("balancer_idle_interval must be >= 0")


def normalize_req_types(
    req_types: Optional[Sequence[int]], valid: Sequence[int]
) -> Optional[frozenset[int]]:
    """Validate a Reserve request vector; None / [-1] means any type
    (reference ADLB_RESERVE_REQUEST_ANY). Raises on unregistered types
    (reference aborts, ``src/adlb.c:2893-2902``)."""
    from adlb_tpu.types import ADLB_RESERVE_REQUEST_ANY, REQ_TYPE_VECT_SZ, AdlbError

    if req_types is None:
        return None
    kept = []
    for t in req_types:
        if t == ADLB_RESERVE_REQUEST_ANY:
            return None
        kept.append(t)
    if not kept:
        return None
    if len(kept) > REQ_TYPE_VECT_SZ:
        raise AdlbError(f"reserve requests at most {REQ_TYPE_VECT_SZ} types")
    for t in kept:
        if t not in valid:
            raise AdlbError(f"unregistered work type {t}")
    return frozenset(kept)
