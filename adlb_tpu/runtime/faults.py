"""Deterministic fault-injection transport shim.

The chaos soak finds failure modes with wall-clock randomness; this module
makes every failure path *reproducible*. A :class:`FaultPlan` is seeded
plain data (picklable, so it crosses ``spawn_world``'s process boundary
inside ``Config``); a :class:`FaultyEndpoint` wraps any transport endpoint
(the in-proc fabric or the TCP fabric — both expose ``send``/``recv``) and
injects faults on the **send side**, where decisions can be a pure
function of ``(seed, rank, outbound frame number)``:

* ``drop`` — the frame silently never leaves this rank;
* ``delay`` — the frame is held ``delay_s`` seconds before leaving;
* ``duplicate`` — the frame is sent twice back-to-back;
* ``disconnect_at`` — at outbound frame N this rank's connectivity dies:
  further sends raise ``OSError`` and peers observe EOF (the TCP wrapper
  closes the real endpoint; the in-proc wrapper synthesizes ``PEER_EOF``
  frames, which the in-proc fabric otherwise never produces);
* ``kill_at_frame`` — at outbound frame N the whole process dies with
  SIGKILL (``os._exit`` fallback) — the byte-deterministic analogue of a
  preempted worker, pinned to an exact protocol point;
* ``kill_at`` — the wall-clock variant (seconds after the endpoint is
  wrapped), for soak-style adversities where determinism is not the goal;
* ``stall_at_frame`` / ``stall_at`` — GRAY failure: at outbound frame N
  (or after N wall-clock seconds) the endpoint freezes — outbound frames
  buffer instead of leaving, inbound recv goes silent — while the
  process stays alive, so peers observe no EOF, only silence. After
  ``stall_for_s`` seconds (0 = forever) the endpoint resumes and the
  buffered frames flush in order, modelling a SIGCONT'd process's
  kernel buffers draining: the late-traffic burst that lease fencing
  must reject. For spawned (real-process) worlds :func:`sigstop_self`
  is the non-simulated variant — the whole process, heartbeat threads
  included, really stops;
* ``poison_types`` — a worker receiving a reservation for a unit of a
  marked work type dies on the spot (SIGKILL), the deterministic
  poison-unit: the reserve leaves a lease behind, reclaim re-enqueues
  the unit, and it serially kills every worker that touches it until a
  retry budget (``Config(max_unit_retries)``) quarantines it;
* ``partition`` — ASYMMETRIC one-way partition: frames from ``src`` to
  ``dst`` on each listed ``(src, dst)`` pair are silently dropped while
  the reverse direction (and every connection) stays up — the gray link
  where A can hear B but B never hears A, which ack barriers and death
  ladders must survive without a raced verdict. Schedulable at a frame
  (``at_frame``), at a wall-clock offset (``at``), immediately (neither),
  or mid-run via :meth:`FaultPlan.partition_now` / ``heal_now``; bounded
  by ``for_s`` (0 = until healed).

Probabilistic faults (drop/delay/duplicate) draw from a per-rank
``random.Random`` in frame order, so the injected-event log — a list of
``(frame, action, tag, dest)`` tuples — is identical across runs whenever
the rank's outbound frame sequence is (tests drive a scripted sequence;
live worlds get per-frame determinism relative to each rank's own send
order). The log is exposed at :attr:`FaultPlan.events` and optionally
written as JSON per rank (``ADLB_FAULT_LOG_DIR`` or ``spec["log_dir"]``)
so multi-process runs can be compared offline.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Optional

from adlb_tpu.runtime.messages import Msg, Tag
from adlb_tpu.types import ADLB_SUCCESS

# actions recorded in the injected-event log
DROP = "drop"
DELAY = "delay"
DUP = "duplicate"
DISCONNECT = "disconnect"
KILL = "kill"
STALL = "stall"
RESUME = "resume"
POISON = "poison"
PARTITION = "partition"
HEAL = "heal"


def _mix(seed: int, rank: int) -> int:
    """Stable per-rank stream seed (splitmix-style constant; must not
    depend on PYTHONHASHSEED, so no hash())."""
    return (seed * 0x9E3779B97F4A7C15 + rank * 0xBF58476D1CE4E5B9) & (
        (1 << 63) - 1
    )


class FaultPlan:
    """One rank's seeded fault schedule + injected-event log."""

    def __init__(self, spec: dict, rank: int) -> None:
        self.spec = dict(spec)
        self.rank = rank
        self.seed = int(spec.get("seed", 0))
        self.p_drop = float(spec.get("drop", 0.0))
        self.p_delay = float(spec.get("delay", 0.0))
        self.delay_s = float(spec.get("delay_s", 0.001))
        self.p_dup = float(spec.get("duplicate", 0.0))
        ranks = spec.get("ranks")
        self.active = ranks is None or rank in set(ranks)
        self.disconnect_at = int(
            dict(spec.get("disconnect_at") or {}).get(rank, 0) or 0
        )
        self.kill_at_frame = int(
            dict(spec.get("kill_at_frame") or {}).get(rank, 0) or 0
        )
        self.kill_at = float(dict(spec.get("kill_at") or {}).get(rank, 0.0)
                             or 0.0)
        self.stall_at_frame = int(
            dict(spec.get("stall_at_frame") or {}).get(rank, 0) or 0
        )
        self.stall_at = float(
            dict(spec.get("stall_at") or {}).get(rank, 0.0) or 0.0
        )
        # stall duration; 0 = stalled forever (the never-resuming hang)
        self.stall_for_s = float(spec.get("stall_for_s", 0.0) or 0.0)
        self.poison_types = frozenset(spec.get("poison_types") or ())
        # asymmetric one-way partition: {"pairs": [[src, dst], ...],
        # "at_frame": N | "at": seconds | neither (immediate),
        # "for_s": duration (0 = until healed)}. Only this rank's
        # OUTBOUND legs matter to this plan — the reverse direction is
        # the other rank's plan (or flows freely: that is the asymmetry).
        part = dict(spec.get("partition") or {})
        self._part_sched = [
            (int(p[0]), int(p[1]))
            for p in (part.get("pairs") or ())
            if int(p[0]) == rank
        ]
        self.part_at_frame = int(part.get("at_frame", 0) or 0)
        self.part_at = float(part.get("at", 0.0) or 0.0)
        self.part_for_s = float(part.get("for_s", 0.0) or 0.0)
        self.log_dir = spec.get("log_dir") or os.environ.get(
            "ADLB_FAULT_LOG_DIR"
        )
        self._rng = random.Random(_mix(self.seed, rank))
        self._lock = threading.Lock()
        self.frame = 0  # outbound frames observed (post-increment)
        self.events: list[tuple[int, str, str, int]] = []
        self.disconnected = False
        # gray-failure stall window: None = not stalled, inf = forever,
        # else the monotonic time at which the endpoint resumes; a stall
        # fires at most once per plan (a resumed endpoint must not
        # re-stall on its next frame)
        self.stalled_until: Optional[float] = None
        self._stall_done = False
        # active one-way drops (src is always this rank) + expiry; the
        # frame/timer trigger fires once, explicit partition_now re-arms
        self._part_pairs: set[tuple[int, int]] = set()
        self._part_until: Optional[float] = None
        self._part_done = False
        if self._part_sched and not self.part_at_frame and not self.part_at:
            # no trigger given: the partition exists from frame one
            self._begin_partition_locked(0)

    # -- decisions -----------------------------------------------------------

    def on_send(self, m: Msg, dest: int) -> str:
        """Account one outbound frame and decide its fate. Returns one of
        the action constants or "" (pass through). Called under the lock
        so the frame counter, the RNG draw order, and the event log stay
        mutually consistent even with multiple sender threads."""
        with self._lock:
            self.frame += 1
            n = self.frame
            if self.disconnected:
                return DISCONNECT
            if self.kill_at_frame and n >= self.kill_at_frame:
                self.events.append((n, KILL, m.tag.name, dest))
                self._flush_log()
                return KILL
            if self.disconnect_at and n >= self.disconnect_at:
                self.disconnected = True
                self.events.append((n, DISCONNECT, m.tag.name, dest))
                self._flush_log()
                return DISCONNECT
            if self._stalled_locked(n, m.tag.name, dest):
                return STALL
            if (
                self.stall_at_frame
                and n >= self.stall_at_frame
                and not self._stall_done
            ):
                self._begin_stall_locked(n, m.tag.name, dest)
                return STALL
            if (
                self.part_at_frame
                and n >= self.part_at_frame
                and not self._part_done
            ):
                self._begin_partition_locked(n)
            if self._partitioned_locked(n, m.tag.name, dest):
                return PARTITION
            if not self.active:
                return ""
            # one draw per probabilistic knob per frame, in fixed order:
            # the decision stream is then a pure function of (seed, rank,
            # frame), independent of which knobs are enabled downstream
            r_drop = self._rng.random()
            r_delay = self._rng.random()
            r_dup = self._rng.random()
            if self.p_drop and r_drop < self.p_drop:
                self.events.append((n, DROP, m.tag.name, dest))
                return DROP
            if self.p_delay and r_delay < self.p_delay:
                self.events.append((n, DELAY, m.tag.name, dest))
                return DELAY
            if self.p_dup and r_dup < self.p_dup:
                self.events.append((n, DUP, m.tag.name, dest))
                return DUP
            return ""

    # -- stall (gray failure) ------------------------------------------------

    def _begin_stall_locked(self, frame: int, tag: str, dest: int) -> None:
        self.stalled_until = (
            time.monotonic() + self.stall_for_s
            if self.stall_for_s > 0
            else float("inf")
        )
        self._stall_done = True
        self.events.append((frame, STALL, tag, dest))
        self._flush_log()

    def _stalled_locked(self, frame: int, tag: str, dest: int) -> bool:
        """Inside the stall window? Clears the window (recording RESUME)
        the first time it is consulted past its end."""
        if self.stalled_until is None:
            return False
        if time.monotonic() < self.stalled_until:
            return True
        self.stalled_until = None
        self.events.append((frame, RESUME, tag, dest))
        return False

    def stall_now(self) -> None:
        """Begin a stall immediately (the wall-clock ``stall_at`` timer's
        entry point, and the deterministic in-proc trigger for tests).
        Unlike the frame-count trigger, explicit calls RE-ARM: a test
        driving repeated gray failures (e.g. stalling the same owner
        until its unit's retry budget quarantines it) stalls once per
        call."""
        with self._lock:
            if self.stalled_until is None:
                self._begin_stall_locked(self.frame, "<timer>", -1)

    def stalled(self) -> bool:
        """Inside the stall window right now? (recv-side check)"""
        if self.stalled_until is None:
            return False  # lock-free: every non-stalled recv lands here
        with self._lock:
            return self._stalled_locked(self.frame, "<recv>", -1)

    # -- asymmetric partition (one-way gray link) ----------------------------

    def _begin_partition_locked(self, frame: int) -> None:
        self._part_pairs = set(self._part_sched)
        self._part_until = (
            time.monotonic() + self.part_for_s
            if self.part_for_s > 0
            else float("inf")
        )
        self._part_done = True
        self.events.append((frame, PARTITION, "<engage>", -1))
        self._flush_log()

    def _partitioned_locked(self, frame: int, tag: str, dest: int) -> bool:
        """Is the (self.rank -> dest) leg inside an active one-way drop?
        Heals (recording HEAL) the first time it is consulted past a
        bounded window's end — the reverse direction was never touched,
        so only the send-side decision needs the check."""
        if not self._part_pairs:
            return False
        if (
            self._part_until is not None
            and time.monotonic() >= self._part_until
        ):
            self._part_pairs = set()
            self._part_until = None
            self.events.append((frame, HEAL, tag, dest))
            return False
        if (self.rank, dest) in self._part_pairs:
            self.events.append((frame, PARTITION, tag, dest))
            return True
        return False

    def partition_now(self, pairs=None) -> None:
        """Engage (or extend) a one-way partition immediately: outbound
        frames on each ``(src, dst)`` pair are silently dropped while
        every connection stays up — peers observe no EOF, only one-way
        silence. ``pairs`` defaults to the spec's schedule; explicit
        calls RE-ARM and may swap the pair set, so a test can drive a
        partition mid-run (e.g. isolate the deputy from the master's
        acks during a takeover barrier) and later :meth:`heal_now` it."""
        with self._lock:
            add = (
                self._part_sched
                if pairs is None
                else [(int(p[0]), int(p[1])) for p in pairs]
            )
            self._part_pairs |= {p for p in add if p[0] == self.rank}
            self._part_until = (
                time.monotonic() + self.part_for_s
                if self.part_for_s > 0
                else float("inf")
            )
            self.events.append((self.frame, PARTITION, "<engage>", -1))
            self._flush_log()

    def heal_now(self) -> None:
        """Drop every active one-way partition leg: subsequent frames
        flow again (nothing buffered — a partitioned frame is LOST, as
        on a real lossy link, unlike a stall's kernel-buffer flush)."""
        with self._lock:
            if self._part_pairs:
                self._part_pairs = set()
                self._part_until = None
                self.events.append((self.frame, HEAL, "<heal>", -1))
                self._flush_log()

    # -- log -----------------------------------------------------------------

    def event_log(self) -> list[tuple[int, str, str, int]]:
        with self._lock:
            return list(self.events)

    def _flush_log(self) -> None:
        """Best-effort durable log (called before a kill/disconnect — the
        process may be about to die, so write NOW, atomically)."""
        if not self.log_dir:
            return
        try:
            os.makedirs(self.log_dir, exist_ok=True)
            path = os.path.join(self.log_dir, f"faults-rank{self.rank}.json")
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump({"rank": self.rank, "seed": self.seed,
                           "events": self.events}, f)
            os.replace(tmp, path)
        except OSError:
            pass

    def flush(self) -> None:
        with self._lock:
            self._flush_log()


class FaultyEndpoint:
    """Endpoint wrapper applying a :class:`FaultPlan` to outbound frames.

    Everything except ``send``/``recv`` (attribute reads AND writes —
    ``attach()`` assigns ``ep.metrics``) is forwarded to the wrapped
    endpoint, so roles and harnesses cannot tell the difference.
    """

    _OWN = ("_ep", "plan", "rank", "_contacted", "_killer", "_staller",
            "_stall_buf", "_parter")

    def __init__(self, ep, plan: FaultPlan) -> None:
        object.__setattr__(self, "_ep", ep)
        object.__setattr__(self, "plan", plan)
        object.__setattr__(self, "rank", ep.rank)
        object.__setattr__(self, "_contacted", set())
        object.__setattr__(self, "_killer", None)
        object.__setattr__(self, "_staller", None)
        object.__setattr__(self, "_stall_buf", [])
        if plan.kill_at > 0:
            t = threading.Timer(plan.kill_at, self._kill_now)
            t.daemon = True
            object.__setattr__(self, "_killer", t)
            t.start()
        if plan.stall_at > 0:
            t = threading.Timer(plan.stall_at, plan.stall_now)
            t.daemon = True
            object.__setattr__(self, "_staller", t)
            t.start()
        object.__setattr__(self, "_parter", None)
        if plan.part_at > 0 and plan._part_sched:
            t = threading.Timer(plan.part_at, plan.partition_now)
            t.daemon = True
            object.__setattr__(self, "_parter", t)
            t.start()

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_ep"), name)

    def __setattr__(self, name, value):
        if name in FaultyEndpoint._OWN:
            object.__setattr__(self, name, value)
        else:
            setattr(object.__getattribute__(self, "_ep"), name, value)

    # -- fault enactment -----------------------------------------------------

    def _kill_now(self) -> None:
        with self.plan._lock:
            self.plan.events.append((self.plan.frame, KILL, "<timer>", -1))
            self.plan._flush_log()
        try:
            import signal

            os.kill(os.getpid(), signal.SIGKILL)
        except (OSError, ValueError):
            pass
        os._exit(137)

    def _enact_disconnect(self) -> None:
        """Make the death observable: TCP peers see real EOFs when the
        endpoint closes; in-proc peers get synthetic PEER_EOF frames
        (the in-proc fabric has no connections to EOF)."""
        fabric = getattr(self._ep, "_fabric", None)
        if fabric is not None:
            # every rank, not just contacted ones: a TCP death closes all
            # listeners at once, and the home server must learn even about
            # a rank that died before its first frame reached it
            for peer in list(fabric.endpoints.values()):
                if peer.rank == self.rank:
                    continue
                try:
                    peer.inbox.put(Msg(tag=Tag.PEER_EOF, src=self.rank))
                except AttributeError:
                    pass
        else:
            try:
                self._ep.close()
            except OSError:
                pass

    def _flush_stalled(self) -> None:
        """Drain frames buffered during a stall — the SIGCONT'd process's
        kernel buffers finally going out, in order. The buffer swap holds
        the plan lock: the app thread and the client's heartbeat thread
        can resume concurrently, and a racing double-flush would
        duplicate (or drop) the buffered tail."""
        if not self._stall_buf:
            # lock-free hot-path exit (every non-stalled frame lands
            # here): the truthiness read is GIL-atomic, and a frame a
            # racing stall appends right after it flushes on the next
            # call — the same tolerance the buffer swap already has
            return
        with self.plan._lock:
            buf = self._stall_buf
            if not buf:
                return
            object.__setattr__(self, "_stall_buf", [])
        for dest, m, kw in buf:
            try:
                self._ep.send(dest, m, **kw)
            except OSError:
                pass

    def _maybe_poison(self, m: Msg) -> None:
        """The poison-unit fault: receiving a reservation for a marked
        work type kills this worker on the spot (the lease it just took
        survives it — reclaim's retry budget is what bounds the blast
        radius)."""
        if (
            m.tag is Tag.TA_RESERVE_RESP
            and m.data.get("rc") == ADLB_SUCCESS
            and m.data.get("work_type") in self.plan.poison_types
        ):
            with self.plan._lock:
                self.plan.events.append(
                    (self.plan.frame, POISON, m.tag.name, m.src)
                )
                self.plan._flush_log()
            self._kill_now()

    def send(self, dest: int, m: Msg, **kw) -> None:
        act = self.plan.on_send(m, dest)
        if act == KILL:
            self._kill_now()
            return  # unreachable except under test monkeypatching
        if act == DISCONNECT:
            if not self.plan.disconnected:
                self.plan.disconnected = True
            self._enact_disconnect()
            raise OSError(
                f"fault injection: rank {self.rank} disconnected at frame "
                f"{self.plan.frame}"
            )
        if act == STALL:
            with self.plan._lock:  # vs a concurrent resume's buffer swap
                self._stall_buf.append((dest, m, kw))
            return
        if act == PARTITION:
            return  # one-way lost frame: connection alive, no buffering
        self._flush_stalled()  # a resume flushes before new traffic
        if act == DROP:
            return
        if act == DELAY:
            time.sleep(self.plan.delay_s)
        self._contacted.add(dest)
        self._ep.send(dest, m, **kw)
        if act == DUP:
            self._ep.send(dest, m, **kw)

    def recv(self, timeout: Optional[float] = None) -> Optional[Msg]:
        if self.plan.disconnected:
            # a dead rank hears nothing further; burn the poll budget so
            # reactors don't spin
            if timeout:
                time.sleep(min(timeout, 0.05))
            return None
        if self.plan.stalled():
            # frozen endpoint: inbound traffic waits in the transport
            # (like a stopped process's socket buffers); burn the poll
            if timeout:
                time.sleep(min(timeout, 0.05))
            return None
        self._flush_stalled()
        m = self._ep.recv(timeout=timeout)
        if m is not None and self.plan.poison_types:
            self._maybe_poison(m)
        return m


def resolve_spec(spec: dict, world) -> dict:
    """Expand server-targeted kill/stall specs into world-rank form.

    ``kill_server_at_frame`` / ``kill_server_at`` / ``disconnect_server_at``
    / ``stall_server_at_frame`` / ``stall_server_at``
    are keyed by SERVER INDEX (0 = the master, i = the i-th server rank)
    so a spec need not hard-code the world shape; with a ``world`` they
    translate into the corresponding ``kill_at_frame`` / ``kill_at`` /
    ``disconnect_at`` world-rank entries. A ``partition`` spec's
    ``server_pairs`` translate the same way into world-rank ``pairs``
    (one-way: ``[0, 1]`` drops master->server1 only). Idempotent and
    copy-on-write — the input spec is never mutated."""
    if world is None or not spec:
        return spec
    pairs = (
        ("kill_server_at_frame", "kill_at_frame"),
        ("kill_server_at", "kill_at"),
        ("disconnect_server_at", "disconnect_at"),
        ("stall_server_at_frame", "stall_at_frame"),
        ("stall_server_at", "stall_at"),
    )
    part_srv = (dict(spec.get("partition") or {})).get("server_pairs")
    if not any(spec.get(sk) for sk, _ in pairs) and not part_srv:
        return spec
    out = dict(spec)
    if part_srv:
        part = dict(out["partition"])
        rank_pairs = [list(p) for p in (part.get("pairs") or ())]
        for a, b in part.pop("server_pairs"):
            for i in (int(a), int(b)):
                if not (0 <= i < world.nservers):
                    raise ValueError(
                        f"partition server_pairs: server index {i} "
                        f"outside 0..{world.nservers - 1}"
                    )
            rank_pairs.append([
                world.num_app_ranks + int(a), world.num_app_ranks + int(b),
            ])
        part["pairs"] = rank_pairs
        out["partition"] = part
    for srv_key, rank_key in pairs:
        by_idx = out.pop(srv_key, None)
        if not by_idx:
            continue
        merged = dict(out.get(rank_key) or {})
        for idx, v in dict(by_idx).items():
            i = int(idx)
            if not (0 <= i < world.nservers):
                raise ValueError(
                    f"{srv_key}: server index {i} outside 0.."
                    f"{world.nservers - 1}"
                )
            merged[world.num_app_ranks + i] = v
        out[rank_key] = merged
    return out


def sigstop_self(resume_after_s: float) -> None:
    """SIGSTOP the calling process — the REAL gray failure for spawned
    worlds: every thread (heartbeats included) and socket freezes with no
    EOF for peers to observe — after forking a watchdog child that
    SIGCONTs us ``resume_after_s`` seconds later (a stopped process
    cannot resume itself). Execution continues here after the resume, so
    the caller's next protocol op is exactly the "late settle from a
    fenced owner" that lease expiry must reject."""
    import signal

    pid = os.getpid()
    child = os.fork()
    if child == 0:
        # watchdog: nothing but sleep-and-resume, then vanish without
        # running the parent's atexit/harness teardown
        try:
            time.sleep(resume_after_s)
            os.kill(pid, signal.SIGCONT)
        finally:
            os._exit(0)
    os.kill(pid, signal.SIGSTOP)
    # ---- stopped until the watchdog's SIGCONT ----
    try:
        os.waitpid(child, 0)
    except (OSError, ChildProcessError):
        pass


def maybe_wrap(ep, cfg, world=None):
    """Wrap ``ep`` when ``cfg.fault_spec`` is set (else return it
    unchanged) — the single hook every world harness (run_world,
    spawn_world, launch.py, join_world) calls. ``world`` enables
    server-index kill specs (kill-server-at-frame / -at-time)."""
    spec = getattr(cfg, "fault_spec", None)
    if not spec:
        return ep
    return FaultyEndpoint(ep, FaultPlan(resolve_spec(spec, world), ep.rank))
