"""Runtime: message protocol, transports, server reactor, client engine."""
