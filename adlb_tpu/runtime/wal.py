"""Per-server write-ahead log: durable service mode.

The PR 4 replication stream is already *write-ahead for put acks* — an
accepted put's log entry leaves for the ring buddy before the client
sees the ack — but the buddy's mirror lives in memory, so a whole-fleet
crash (power loss, OOM-killer sweep, deliberate restart) still loses
every queued unit, exactly the reference's no-pool-serialization gap
(SURVEY §5). This module tees the same op stream (``replica.OP_*``) to
an append-only on-disk log under ``Config(wal_dir)``:

* **Group-commit fsync** (``Config(wal_fsync_ms)``): entries buffer in
  memory and hit the OS file on every reactor pass, but ``fsync`` runs
  at most once per window — and *put acks are held until the fsync that
  covers them*, so the write-ahead invariant (an acked put is durable)
  holds at amortized, not per-op, fsync cost. ``wal_fsync_ms=0`` fsyncs
  on every flush (strictest, slowest).
* **Record framing**: each entry is wrapped ``<II`` (crc32, length) so
  a torn tail — the crash landing mid-``write`` — is detected, not
  replayed: recovery stops at the first record whose length or CRC does
  not check out and truncates the log there. Everything before it is
  the durable prefix.
* **Compaction** (``Config(wal_max_bytes)``): when the log outgrows the
  threshold, the server snapshots its pool into the existing **ACK2
  checkpoint shard format** (``checkpoint.save_shard``) and starts a
  fresh log segment whose head record is a snapshot *manifest* — the
  shard's units' seqnos/jobs/attempt counts in shard order (the ACK2
  format deliberately carries no seqnos; the manifest restores the
  correlation so the log tail's consume/pin entries resolve exactly).
  Segment and shard swap in atomically (write-new + ``os.replace``),
  and the previous generation's shard is kept until the new segment is
  live.
* **Recovery** reuses the :class:`replica.ReplicaMirror` replay
  machinery rather than a second applier: the log replays into a
  mirror (shard units installed at the manifest record), and the
  server adopts the mirror's pool — units unpinned (their owners died
  with the old fleet), batch-common entries under their original
  seqnos, quarantine records, put-dedup windows, and the job table.
  Cold restart of a server (or the whole fleet) is shard-load + replay.

Loss model: everything fsynced is recovered; the tail after the last
group commit is lost *except that no put in it was ever acked* — the
conservation contract (completed / re-executed / counted lost, zero
silent loss) extends across process death.
"""

from __future__ import annotations

import os
import struct
import time
import zlib
from typing import Iterable, Optional

from adlb_tpu.runtime.replica import (
    _HDR,
    ReplicaMirror,
    ReplicationLog,
)

# on-disk record framing: crc32 of the entry bytes, then entry length.
# The entry itself is the replica wire form (op byte + body length +
# body), so the mirror replays it unchanged.
_REC = struct.Struct("<II")

# WAL-private ops (replica ops are 1..63; these never cross a socket)
WAL_OP_SNAPSHOT = 200
_SNAPHDR = struct.Struct("<qI")    # shard generation, unit count
_SNAPROW = struct.Struct("<qqi")   # unit seqno, job, attempts

# group-commit backstop: never hold more than this many acks for one
# fsync window, whatever the timer says
MAX_PENDING_ACKS = 256


def log_path(wal_dir: str, rank: int) -> str:
    return os.path.join(wal_dir, f"server.{rank}.log")


def snap_prefix(wal_dir: str, rank: int, generation: int) -> str:
    """Checkpoint-shard prefix for one compaction generation; the shard
    itself lands at ``<prefix>.<rank>.ckpt`` (checkpoint.shard_path)."""
    return os.path.join(wal_dir, f"server.{rank}.g{generation}")


class WriteAheadLog(ReplicationLog):
    """Disk sink with the ReplicationLog append surface.

    Inherits every ``log_*`` method (the tee hands the server ONE call
    shape for both sinks); ``tick()`` moves the buffered entries to the
    file and runs the group commit. Never sends anything — ``buddy`` is
    a vestigial -1.
    """

    def __init__(self, wal_dir: str, rank: int, world=None,
                 fsync_ms: float = 5.0, max_bytes: int = 64 << 20,
                 allow_legacy: bool = False) -> None:
        super().__init__(buddy=-1)
        self.dir = wal_dir
        self.rank = rank
        self.world = world
        self.fsync_ms = fsync_ms
        self.max_bytes = max_bytes
        self.allow_legacy = allow_legacy
        self.path = log_path(wal_dir, rank)
        os.makedirs(wal_dir, exist_ok=True)
        self._f = None
        self.size = 0              # bytes in the current segment
        self.generation = 0        # last compaction's shard generation
        self._unsynced = 0         # entries written but not yet fsynced
        self._first_unsynced_t: Optional[float] = None
        # put acks held for the write-ahead invariant: released by the
        # fsync that covers their entries. (app_rank, Msg) pairs.
        self.pending_acks: list = []
        self.entries_synced = 0
        self.syncs = 0
        self.compactions = 0
        self.recovered_torn = False

    # -- write path ----------------------------------------------------------

    def _open(self) -> None:
        if self._f is None:
            self._f = open(self.path, "ab")
            self.size = self._f.tell()

    def defer_ack(self, app: int, resp) -> None:
        """Hold a put ack until its entry is durable."""
        self.pending_acks.append((app, resp))

    @property
    def depth(self) -> int:
        """Entries not yet durable (buffered + written-unsynced)."""
        return len(self._buf) + self._unsynced

    def fsync_lag_ms(self, now: float) -> float:
        t0 = self._first_unsynced_t
        return 0.0 if t0 is None else (now - t0) * 1e3

    def next_deadline(self, default: float) -> float:
        """When the reactor must wake to run the group commit."""
        if not (self._buf or self._unsynced or self.pending_acks):
            return default
        if self.fsync_ms <= 0:
            return 0.0
        t0 = self._first_unsynced_t
        base = time.monotonic() if t0 is None else t0
        return base + self.fsync_ms / 1e3

    def _write_out(self) -> None:
        """Buffered entries -> OS file (no fsync)."""
        if not self._buf:
            return
        self._open()
        recs = []
        for entry in self._buf:
            recs.append(_REC.pack(zlib.crc32(entry), len(entry)))
            recs.append(entry)
        blob = b"".join(recs)
        self._f.write(blob)
        self.size += len(blob)
        self._unsynced += len(self._buf)
        if self._first_unsynced_t is None:
            self._first_unsynced_t = time.monotonic()
        self._buf.clear()

    def _sync(self) -> list:
        """fsync the segment; returns the acks the commit releases."""
        if self._f is not None and self._unsynced:
            self._f.flush()
            os.fsync(self._f.fileno())
        self.entries_synced += self._unsynced
        self.syncs += 1
        self._unsynced = 0
        self._first_unsynced_t = None
        acks, self.pending_acks = self.pending_acks, []
        return acks

    def tick(self, now: float, force: bool = False) -> list:
        """One reactor pass: write out, group-commit when due. Returns
        the (app, Msg) acks released by a commit (empty otherwise)."""
        self._write_out()
        if not (self._unsynced or self.pending_acks):
            return []
        due = (
            force
            or self.fsync_ms <= 0
            or len(self.pending_acks) >= MAX_PENDING_ACKS
            or (
                self._first_unsynced_t is not None
                and now >= self._first_unsynced_t + self.fsync_ms / 1e3
            )
        )
        return self._sync() if due else []

    def close(self) -> None:
        try:
            self.tick(time.monotonic(), force=True)
        finally:
            if self._f is not None:
                self._f.close()
                self._f = None

    # -- compaction ----------------------------------------------------------

    def maybe_compact(self, server) -> bool:
        if self.max_bytes <= 0 or self.size < self.max_bytes:
            return False
        self.compact(server)
        return True

    def compact(self, server) -> None:
        """Snapshot the live pool into an ACK2 shard + fresh segment.

        The snapshot captures everything the old segment's entries
        produced (the wq/cq ARE that state), so the old segment and the
        previous generation's shard retire together. Held put acks
        release after the new segment is durable — their units are in
        the shard, which is stricter than the fsync they were waiting
        for."""
        from adlb_tpu.runtime import checkpoint

        gen = self.generation + 1
        # spill tier: the snapshot shard serializes payload bytes, so
        # any spilled payloads must be resident first
        fault_in = getattr(server, "_spill_fault_in_all", None)
        if fault_in is not None:
            fault_in()
        units = list(server.wq.units())
        checkpoint.save_shard(
            snap_prefix(self.dir, self.rank, gen), self.rank, units,
            server.cq, world=server.world,
        )
        # fresh segment: manifest first (ACK2 carries no seqnos — this
        # row list restores the correlation for the tail's entries),
        # then the durable non-pool state the shard format cannot hold
        seed = ReplicationLog(buddy=-1)
        body = _SNAPHDR.pack(gen, len(units)) + b"".join(
            _SNAPROW.pack(u.seqno, getattr(u, "job", 0),
                          getattr(u, "attempts", 0))
            for u in units
        )
        entries = [_HDR.pack(WAL_OP_SNAPSHOT, len(body)) + body]
        server._wal_seed(seed)
        entries.extend(seed._buf)
        newpath = self.path + ".new"
        with open(newpath, "wb") as nf:
            for entry in entries:
                nf.write(_REC.pack(zlib.crc32(entry), len(entry)))
                nf.write(entry)
            nf.flush()
            os.fsync(nf.fileno())
            newsize = nf.tell()
        if self._f is not None:
            self._f.close()
        os.replace(newpath, self.path)
        self._f = open(self.path, "ab")
        self.size = newsize
        old_gen, self.generation = self.generation, gen
        self.compactions += 1
        # old generation's shard only retires once the new segment is
        # the live one (a crash between the two replaces leaves both on
        # disk; the manifest names the right generation)
        if old_gen:
            try:
                os.remove(checkpoint.shard_path(
                    snap_prefix(self.dir, self.rank, old_gen), self.rank
                ))
            except OSError:
                pass
        # entries buffered for the old segment are superseded by the
        # snapshot; their acks release now (durable via the shard)
        self._buf.clear()
        self._unsynced = 0
        self._first_unsynced_t = None
        acks, self.pending_acks = self.pending_acks, []
        self._released_by_compact = acks

    def take_compact_acks(self) -> list:
        acks = getattr(self, "_released_by_compact", [])
        self._released_by_compact = []
        return acks

    # -- recovery ------------------------------------------------------------

    def recover(self) -> Optional[ReplicaMirror]:
        """Replay an existing log into a fresh mirror; truncate any torn
        tail; position the writer at the durable end. Returns None when
        no prior log exists (cold start of a brand-new fleet)."""
        if not os.path.exists(self.path):
            self._open()
            return None
        with open(self.path, "rb") as f:
            data = f.read()
        mirror = ReplicaMirror(self.rank)
        off = 0
        n = len(data)
        while off + _REC.size <= n:
            crc, ln = _REC.unpack_from(data, off)
            start = off + _REC.size
            if start + ln > n:
                break  # torn tail: record body cut mid-write
            entry = data[start:start + ln]
            if zlib.crc32(entry) != crc:
                break  # torn tail: record body corrupt
            op, blen = _HDR.unpack_from(entry, 0)
            body = entry[_HDR.size:_HDR.size + blen]
            if op == WAL_OP_SNAPSHOT:
                self._load_snapshot(mirror, body)
            else:
                mirror.apply_entry(op, body)
            off = start + ln
        if off < n:
            self.recovered_torn = True
            os.truncate(self.path, off)
        self._f = open(self.path, "ab")
        self.size = off
        return mirror

    def _load_snapshot(self, mirror: ReplicaMirror, body: bytes) -> None:
        from adlb_tpu.runtime import checkpoint

        gen, count = _SNAPHDR.unpack_from(body, 0)
        rows = [
            _SNAPROW.unpack_from(body, _SNAPHDR.size + i * _SNAPROW.size)
            for i in range(count)
        ]
        units, centries = checkpoint.load_shard(
            snap_prefix(self.dir, self.rank, gen), self.rank, self.world,
            allow_legacy=self.allow_legacy,
        )
        if len(units) != count:
            raise ValueError(
                f"WAL snapshot manifest names {count} units but shard "
                f"generation {gen} holds {len(units)}"
            )
        for (seqno, job, attempts), fields in zip(rows, units):
            fields = dict(fields)
            fields["job"] = job
            fields["attempts"] = attempts
            mirror.units[seqno] = fields
        for seqno, refcnt, ngets, buf in centries:
            mirror.commons[seqno] = [buf, refcnt, ngets, 0]
        self.generation = gen


class TeeLog:
    """Fan one ``log_*`` call out to several sinks (the network
    replication log and the WAL). The server mutates through ONE handle
    so no path can forget a sink."""

    def __init__(self, sinks: Iterable) -> None:
        self.sinks = [s for s in sinks if s is not None]


def _tee(name: str):
    def fan(self, *a, **kw):
        for s in self.sinks:
            getattr(s, name)(*a, **kw)
    fan.__name__ = name
    return fan


for _name in [m for m in dir(ReplicationLog) if m.startswith("log_")]:
    setattr(TeeLog, _name, _tee(_name))


def make_wlog(repl, wal):
    """The server's single mutation-log handle: None, the lone sink, or
    a tee over both."""
    sinks = [s for s in (repl, wal) if s is not None]
    if not sinks:
        return None
    if len(sinks) == 1:
        return sinks[0]
    return TeeLog(sinks)


def scan_records(path: str) -> tuple[list[tuple[int, bytes]], bool]:
    """Diagnostic/test helper: (durable (op, body) list, torn?)."""
    with open(path, "rb") as f:
        data = f.read()
    out = []
    off = 0
    n = len(data)
    while off + _REC.size <= n:
        crc, ln = _REC.unpack_from(data, off)
        start = off + _REC.size
        if start + ln > n or zlib.crc32(data[start:start + ln]) != crc:
            return out, True
        entry = data[start:start + ln]
        op, blen = _HDR.unpack_from(entry, 0)
        out.append((op, entry[_HDR.size:_HDR.size + blen]))
        off = start + ln
    return out, off < n
