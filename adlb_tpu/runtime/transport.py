"""Transports: how ranks exchange protocol messages.

The reference's substrate is MPI point-to-point with Iprobe polling
(reference ``src/adlb.c:856-868``). Here a `Transport` is a per-rank endpoint
with ``send(dest, msg)`` and ``recv(timeout)``; the server reactor stays a
single-threaded poll loop, as in the reference.

* `InProcFabric` — ranks are threads in one process, inboxes are queues.
  This is the testing substrate (the reference's analogue is ``mpiexec -n k``
  on one host, SURVEY §4) and the low-latency single-host runtime.
* `TcpFabric` (transport_tcp.py) — ranks are processes, possibly on many
  hosts, length-prefixed msgpack-ish frames over sockets.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional, Protocol

from adlb_tpu.runtime.messages import Msg


class Endpoint(Protocol):
    rank: int

    def send(self, dest: int, m: Msg) -> None: ...

    def recv(self, timeout: Optional[float]) -> Optional[Msg]: ...


class InProcEndpoint:
    def __init__(self, fabric: "InProcFabric", rank: int) -> None:
        self._fabric = fabric
        self.rank = rank
        self.inbox: "queue.SimpleQueue[Msg]" = queue.SimpleQueue()
        self.bytes_sent = 0
        self.msgs_sent = 0
        # observability: owning role attaches its metrics Registry
        # (adlb_tpu.obs.metrics.attach). In-proc delivery is one queue
        # put — there is no wire/decode layer — so only the tx side is
        # instrumented (a rank's rx IS its peers' tx, readable from
        # their registries); rx_*/send_s/recv_wait_s exist on the TCP
        # endpoint where they measure something real
        self.metrics = None
        self._tx_stats: dict = {}

    def submit_begin(self) -> None:
        """Submission batching is a wire-transport concern (deferred
        doorbells / coalesced channel gathers); in-proc delivery is one
        queue put, so the batch surface is a no-op here — kept so role
        code can bracket bursts transport-agnostically."""

    def submit_flush(self) -> None:
        pass

    def close(self) -> None:
        """Dynamically attached ranks (elastic membership) close their
        endpoint on exit, exactly like a TCP joiner: the fabric forgets
        the inbox, so a late frame toward this rank raises OSError at
        the sender — the in-proc analogue of connection refused."""
        self._fabric.remove_endpoint(self)

    def send(self, dest: int, m: Msg, connect_grace: float = 0.0) -> None:
        # connect_grace is a TCP-endpoint knob; accepted (and ignored)
        # here so role code can pass it transport-agnostically
        self.msgs_sent += 1
        payload = m.data.get("payload")
        nbytes = (
            len(payload) if isinstance(payload, (bytes, bytearray)) else 0
        )
        self.bytes_sent += nbytes
        reg = self.metrics
        if reg is not None:
            st = self._tx_stats.get(m.tag)
            if st is None:
                st = self._tx_stats[m.tag] = (
                    reg.counter("tx_msgs", tag=m.tag.name),
                    reg.counter("tx_bytes", tag=m.tag.name),
                )
            st[0].inc()
            st[1].inc(nbytes)
        try:
            peer = self._fabric.endpoints[dest]
        except KeyError:
            # elastic membership: no endpoint (yet/anymore) for this
            # rank — surface it like TCP's connection refused, which
            # every sender path already tolerates
            raise OSError(f"no endpoint for rank {dest}") from None
        peer.inbox.put(m)

    def recv(self, timeout: Optional[float] = None) -> Optional[Msg]:
        try:
            if timeout is None:
                return self.inbox.get()
            if timeout <= 0.0:
                # never SimpleQueue.get(timeout=0.0): on this host class a
                # freshly forked child's zero-timeout timed get can park
                # forever in the lock (kernel-level; ~1/10 TCP worlds
                # wedged in the client's first recv). get_nowait() checks
                # the list without touching the lock and cannot hang.
                return self.inbox.get_nowait()
            return self.inbox.get(timeout=timeout)
        except queue.Empty:
            return None

    def backlog(self) -> int:
        """Received-but-unhandled frames — the TCP-era analogue of the
        reference's MPI unexpected-message-queue depth probe (reference
        ``src/adlb.c:3645-3719``)."""
        return self.inbox.qsize()


class InProcFabric:
    """All ranks in one process; message passing via thread-safe queues.

    Endpoints live in a dict so elastic membership can add ranks to a
    RUNNING fabric (attach/scale-out); a send to a rank with no endpoint
    raises OSError, the in-proc analogue of connection refused."""

    def __init__(self, nranks: int) -> None:
        self.nranks = nranks
        self.endpoints: dict[int, InProcEndpoint] = {
            r: InProcEndpoint(self, r) for r in range(nranks)
        }
        self.abort_event = threading.Event()

    def endpoint(self, rank: int) -> InProcEndpoint:
        return self.endpoints[rank]

    def add_endpoint(self, rank: int) -> InProcEndpoint:
        """Elastic membership: an inbox for a newly attached rank (dict
        assignment is atomic under the GIL, so concurrent senders see
        either no endpoint — OSError, retried — or the live one)."""
        ep = InProcEndpoint(self, rank)
        self.endpoints[rank] = ep
        return ep

    def remove_endpoint(self, ep: InProcEndpoint) -> None:
        """The in-proc analogue of closing a TCP listener: subsequent
        sends toward the rank raise OSError (connection refused). Only
        dynamically attached ranks close their endpoints; base ranks
        live for the world."""
        if self.endpoints.get(ep.rank) is ep:
            del self.endpoints[ep.rank]
