"""Debug plumbing: stamped logging, flight recorder, self-diagnosis.

The rebuild of the reference's L0 debug layer:

* :func:`aprintf` — rank/line/time-stamped stderr prints gated by a flag
  (reference ``aprintf``/``adlbp_dbgprintf``, ``src/adlb.c:3395-3417``);
* :class:`FlightRecorder` — fixed-size circular in-memory log, dumpable on
  abort or by the self-diagnosis pass (reference ``cblog``,
  ``src/adlb.c:176-179,3371-3393``);
* :func:`self_diagnosis` — the server's periodic health dump: requesters
  stuck on the rq, work-queue age by type, message-tag frequency (reference
  the 30-second ``DBG1..DBG9`` dumps, ``src/adlb.c:558-710``).

Like the stats module, output flows through a swappable sink so tests (and
embedding applications) can capture it.
"""

from __future__ import annotations

import sys
import time
from collections import deque

from adlb_tpu.runtime.sink import Sink

_SINK = Sink()
set_sink = _SINK.set
_emit = _SINK.emit


def aprintf(enabled: bool, rank: int, text: str) -> None:
    """Rank/caller/time-stamped debug print, gated by the init-time flag the
    reference threads through ``ADLB_Init`` (reference ``src/adlb.c:3395``)."""
    if not enabled:
        return
    frame = sys._getframe(1)
    where = f"{frame.f_code.co_filename.rsplit('/', 1)[-1]}:{frame.f_lineno}"
    _emit(f"[rank {rank} {where} @ {time.monotonic():.6f}] {text}")


class FlightRecorder:
    """Circular in-memory log: cheap enough to leave on, dumped only when
    something goes wrong (reference ``cblog``, ``src/adlb.c:3371-3393``)."""

    def __init__(self, rank: int, capacity: int = 512) -> None:
        self.rank = rank
        self._ring: deque[tuple[float, str]] = deque(maxlen=capacity)

    def record(self, text: str) -> None:
        self._ring.append((time.monotonic(), text))

    def __len__(self) -> int:
        return len(self._ring)

    def entries(self) -> list[tuple[float, str]]:
        return list(self._ring)

    def dump(self, reason: str = "") -> None:
        header = f"FLIGHT_RECORDER rank {self.rank}"
        if reason:
            header += f" ({reason})"
        _emit(f"{header}: {len(self._ring)} entries")
        for ts, text in self._ring:
            _emit(f"  [{ts:.6f}] {text}")


def self_diagnosis(server, now: float, stuck_after: float = 5.0) -> list[str]:
    """One periodic health dump for a server — the reference's DBG1..DBG9
    block (reference ``src/adlb.c:558-710``). Returns the emitted lines."""
    lines: list[str] = [
        f"SELFDIAG rank {server.rank}: wq={server.wq.count} "
        f"rq={len(server.rq)} bytes={server.mem.curr} "
        f"loops={server._loops} activity={server.activity}"
    ]
    # peer memory picture from the qmstat table: accountant bytes next to
    # each peer's /proc RSS (the reference prints its memusage probe in
    # the same diagnostics block, src/adlb.c:3347-3369)
    peers = getattr(server, "peers", None)
    if peers:
        mem = " ".join(
            f"s{s}:{st.nbytes}B/{st.rss_kb}kB"
            for s, st in sorted(peers.items())
        )
        lines.append(f"SELFDIAG rank {server.rank}: peer mem {mem}")
    # prefetch (get_work_stream) parks of a BUSY rank are long-lived by
    # design — the consumer is computing while its slots wait — so only
    # blocking reserves and idle-reported streams count as "stuck"
    idle = getattr(server, "_stream_idle", ())
    stuck = [
        (e.world_rank, round(now - e.time_stamp, 3))
        for e in server.rq.entries()
        if now - e.time_stamp > stuck_after
        and (not e.prefetch or e.world_rank in idle)
    ]
    if stuck:
        lines.append(
            f"SELFDIAG rank {server.rank}: stuck requesters "
            + " ".join(f"rank{r}:{age}s" for r, age in stuck)
        )
    # work-queue age by type (reference DBG4: oldest unit per type)
    oldest: dict[int, float] = {}
    for u in server.wq.units():
        age = now - u.time_stamp
        if age > oldest.get(u.work_type, 0.0):
            oldest[u.work_type] = age
    if oldest:
        lines.append(
            f"SELFDIAG rank {server.rank}: wq age by type "
            + " ".join(f"t{t}:{a:.3f}s" for t, a in sorted(oldest.items()))
        )
    # message-tag frequency since the last dump (reference DBG9)
    if server.tag_freq:
        top = sorted(server.tag_freq.items(), key=lambda kv: -kv[1])[:8]
        lines.append(
            f"SELFDIAG rank {server.rank}: tags "
            + " ".join(f"{t.name}:{n}" for t, n in top)
        )
        server.tag_freq.clear()
    for line in lines:
        _emit(line)
    return lines
