"""Server failover: replicated pool shards and home-server takeover.

The reference has no server fault tolerance at all — the servers *are*
the work pool, there is no pool serialization, and a dead rank kills the
job (SURVEY §5; ``MPI_Abort`` paths, reference ``src/adlb.c:2508-2526``).
PR 2 made *worker* death a policy; this module does the same for server
death, composing ingredients that already exist in-tree:

* every server **asynchronously streams a replication log** of its pool
  mutations (put, fetch/delivery consume, pin/unpin, batch-common
  refcount ops, app finalize/death) to its **ring-successor buddy**
  server, as ``SS_REPL`` frames of packed entries reusing the
  ``checkpoint.py`` unit wire format (:data:`_UNIT`);
* the buddy maintains a passive :class:`ReplicaMirror` — the
  predecessor's wq/cq shard reconstructed entry by entry;
* on the predecessor's death (EOF / ``SS_SERVER_DEAD`` fan-out) the
  buddy **replays the mirror into its own queues and takes over
  home-server duty** for the dead server's app ranks: pinned units stay
  pinned under their original leases (live clients fetch them through a
  seqno translation), unpinned units re-enqueue, batch-common prefixes
  re-home with their refcount state, and clients learn the new mapping
  via an epoch-stamped ``TA_HOME_TAKEOVER`` remap.

Loss model: replication is asynchronous, so mutations the dead server
made after its last flushed ``SS_REPL`` frame are gone. The lag is
bounded (flush on every reactor pass and at ``MAX_BUFFER`` entries),
observable (``repl_lag`` gauge at the primary), and the losses are
counted where they become observable: a client fetching a handle whose
unit's consume tombstone replayed (the response died with the server)
gets ``ADLB_RETRY`` and the buddy counts ``failover_lost``. At-most-once
execution is preserved exactly as in PR 2/PR 3 — a consume in the log
means the payload may already have landed, so the unit is never
re-enqueued (the delivered-at-death rule).
"""

from __future__ import annotations

import struct
from collections import deque
from typing import Optional

from adlb_tpu.runtime.checkpoint import _UNIT  # unit metadata wire format

# entry opcodes (1 byte on the wire)
OP_PUT = 1        # unit added to the wq
OP_PIN = 2        # unit pinned (lease granted)
OP_UNPIN = 3      # unit unpinned (lease released, still queued)
OP_CONSUME = 4    # unit fetched/delivered (removed; tombstoned)
OP_REMOVE = 5     # unit removed without delivery (migrate/push/drop)
OP_COMMON_PUT = 6     # batch-common prefix stored
OP_COMMON_REFCNT = 7  # End_batch_put shipped the final refcount
OP_COMMON_GET = 8     # one get accounted against the prefix
OP_COMMON_FORFEIT = 9
OP_COMMON_CREDIT = 10
OP_COMMON_GC = 11     # prefix GC'd (refcount satisfied)
OP_APP_DONE = 12      # local app finalized
OP_RANK_DEAD = 13     # app rank declared dead (reclaim policy)
OP_COMMON_STATE = 14  # full refcount state (re-bootstrap after buddy death)
OP_SEEN_PUTS = 15     # a sender's accepted-put dedup window (re-bootstrap)
# gray-failure state (lease expiry / quarantine) — attempt counts and
# fences must survive failover or a takeover would reset a poison unit's
# retry budget and un-fence a stalled owner
OP_ATTEMPTS = 16      # unit's failure-attempt count changed
OP_FENCE = 17         # (seqno, owner) fenced by lease expiry
OP_QUARANTINE = 18    # unit moved to the dead-letter quarantine
# job-namespace control (service mode): a job's state/quota changed —
# rides the stream (and the per-server WAL that tees it) so job
# membership and lifecycle survive failover and cold restart
OP_JOB = 19
# unit-lifecycle trace context (obs/journey.py): a traced unit's
# (trace_id, span list) — logged right behind its OP_PUT so the journey
# survives failover adoption and WAL cold-restart replay
OP_TRACE = 20
# tail hedging (runtime/hedge.py): marks a unit as a speculative hedge
# SIBLING of an origin unit (body: sibling seqno, origin seqno), logged
# right behind the sibling's OP_PUT. Failover adoption and WAL replay
# DISCARD marked siblings and adopt only origins — re-running the
# origin falls inside the documented lease-expiry at-least-once window,
# while adopting both copies would put two live duplicates into open
# matching with nobody left to fence the loser. A fresh OP_PUT of the
# same seqno supersedes the mark (the race dissolved and the survivor
# became an ordinary unit). Append-only, as above.
OP_HEDGE = 21
# master brain state (master failover): the master-only durable control
# plane streams to the master's ring buddy — the standing DEPUTY — over
# the same plane, so promotion rebuilds a fully functioning brain
# without a cold start. Append-only like everything above; a non-master
# primary never emits these, so unconfigured worlds stay frame-identical.
OP_MEMBER = 22     # membership snapshot: epoch, master rank, provisional
#                    watermark, retired srv-route map, addrs, live/ready/
#                    dead/drained sets, ops-armed flag (newest wins)
OP_SLO = 23        # one live SLO objective doc (POST /slo; keyed by name)
OP_CONTROL = 24    # controller policy doc (POST /control; newest wins)
OP_SCALE = 25      # parked scale request, or its clearing (newest wins)
OP_JOB_WEIGHT = 26  # a job's fair-share weight changed (job id + f64)

_HDR = struct.Struct("<BI")       # op, body length
_SEQ = struct.Struct("<q")        # one seqno
_SEQ2 = struct.Struct("<qq")      # seqno + arg (pin rank, refcnt, ...)
_SEQ3 = struct.Struct("<qqq")     # seqno + src + request id (common ops)
# seqno, src, put_id, pinned(pin_rank|-1), attempts, job
_PUTHDR = struct.Struct("<qqqiii")
_JOBHDR = struct.Struct("<qqB")   # job id, quota bytes, state code
_JOBW = struct.Struct("<qd")      # job id, fair-share weight

# flush the buffered log at this many entries even mid-pass
MAX_BUFFER = 256
# bounded tombstone memory at the mirror (consumed seqnos kept so a
# post-takeover fetch of a consumed unit is distinguishable from an
# invalid handle)
MAX_TOMBSTONES = 65536


def _pack_unit(u) -> bytes:
    """Unit metadata + payload in the checkpoint shard layout
    (``_UNIT`` + common_len + payload_len + payload)."""
    return b"".join((
        _UNIT.pack(u.work_type, u.target_rank, u.answer_rank, u.prio,
                   u.common_server_rank, u.common_seqno),
        struct.pack("<II", u.common_len, len(u.payload)),
        u.payload,
    ))


def _unpack_unit(body: bytes, off: int) -> tuple[dict, int]:
    wt, target, answer, prio, cserver, cseqno = _UNIT.unpack_from(body, off)
    off += _UNIT.size
    clen, plen = struct.unpack_from("<II", body, off)
    off += 8
    payload = body[off:off + plen]
    off += plen
    return dict(work_type=wt, target_rank=target, answer_rank=answer,
                prio=prio, common_server_rank=cserver, common_seqno=cseqno,
                common_len=clen, payload=payload), off


class ReplicationLog:
    """Primary side: buffer mutation entries, flush them to the buddy as
    ``SS_REPL`` frames. Append is O(entry); the flush is one endpoint
    send (fire-and-forget — the buddy never acks; TCP's per-pair FIFO is
    the ordering guarantee)."""

    def __init__(self, buddy: int) -> None:
        self.buddy = buddy
        self._buf: list[bytes] = []
        self.seq = 0          # frames flushed
        self.entries_total = 0

    # -- appends -------------------------------------------------------------

    def _append(self, op: int, body: bytes) -> None:
        self._buf.append(_HDR.pack(op, len(body)) + body)
        self.entries_total += 1

    def log_put(self, unit, src: int, put_id) -> None:
        pid = -1 if put_id is None else int(put_id)
        body = _PUTHDR.pack(unit.seqno, src, pid,
                            unit.pin_rank if unit.pinned else -1,
                            getattr(unit, "attempts", 0),
                            getattr(unit, "job", 0))
        self._append(OP_PUT, body + _pack_unit(unit))
        if getattr(unit, "trace_id", 0) and \
                getattr(unit, "spans", None) is not None:
            # the trace context travels with the unit through EVERY
            # log_put site (put intake, push/migrate re-log, promote
            # re-log, WAL recovery re-log) by construction
            self.log_trace(unit.seqno, unit.trace_id, unit.spans)

    def log_trace(self, seqno: int, trace_id: int, spans) -> None:
        from adlb_tpu.obs.journey import pack_spans

        self._append(OP_TRACE,
                     _SEQ.pack(seqno) + pack_spans(trace_id, spans))

    def log_pin(self, seqno: int, rank: int) -> None:
        self._append(OP_PIN, _SEQ2.pack(seqno, rank))

    def log_unpin(self, seqno: int) -> None:
        self._append(OP_UNPIN, _SEQ.pack(seqno))

    def log_consume(self, seqno: int) -> None:
        self._append(OP_CONSUME, _SEQ.pack(seqno))

    def log_remove(self, seqno: int) -> None:
        self._append(OP_REMOVE, _SEQ.pack(seqno))

    def log_common_put(self, seqno: int, buf: bytes) -> None:
        self._append(OP_COMMON_PUT, _SEQ.pack(seqno) + buf)

    def log_common_refcnt(self, seqno: int, refcnt: int) -> None:
        self._append(OP_COMMON_REFCNT, _SEQ2.pack(seqno, refcnt))

    def log_common_op(self, seqno: int, op: str, src: int = -1,
                      op_id: int = -1) -> None:
        """``src``/``op_id`` carry the requester's dedup identity for
        client-driven gets/forfeits, so the buddy's replay windows absorb
        a request re-sent across the takeover (seqno=-1 with src>=0 is a
        pure window entry — the re-bootstrap path — with no accounting)."""
        code = {"get": OP_COMMON_GET, "forfeit": OP_COMMON_FORFEIT,
                "credit": OP_COMMON_CREDIT, "gc": OP_COMMON_GC}[op]
        self._append(code, _SEQ3.pack(seqno, src, op_id))

    def log_common_state(self, seqno: int, refcnt: int, ngets: int,
                         credits: int) -> None:
        self._append(OP_COMMON_STATE,
                     struct.pack("<qqqq", seqno, refcnt, ngets, credits))

    def log_attempts(self, seqno: int, attempts: int) -> None:
        self._append(OP_ATTEMPTS, _SEQ2.pack(seqno, attempts))

    def log_fence(self, seqno: int, owner: int, origin: int = -1) -> None:
        """``origin`` is the server whose numbering ``seqno`` belongs to:
        -1 for this primary's own fences, a rank for fences it ADOPTED in
        an earlier takeover (a doubly-rerouted late fetch still stamps
        the ORIGINAL home in fo_from, so the key must survive chains)."""
        self._append(OP_FENCE, _SEQ3.pack(seqno, owner, origin))

    def log_quarantine(self, seqno: int) -> None:
        self._append(OP_QUARANTINE, _SEQ.pack(seqno))

    def log_hedge(self, sib_seqno: int, origin_seqno: int) -> None:
        """Mark ``sib_seqno`` as a hedge sibling of ``origin_seqno``
        (logged right behind the sibling's OP_PUT, like OP_TRACE)."""
        self._append(OP_HEDGE, _SEQ2.pack(sib_seqno, origin_seqno))

    def log_job(self, job_id: int, state_code: int, quota_bytes: int,
                name: str = "") -> None:
        """Job lifecycle entry (service mode): state codes are
        jobs.STATE_CODES (running/draining/done/killed)."""
        self._append(
            OP_JOB,
            _JOBHDR.pack(job_id, quota_bytes, state_code)
            + name.encode("utf-8", "replace"),
        )

    def log_app_done(self, rank: int) -> None:
        self._append(OP_APP_DONE, _SEQ.pack(rank))

    def log_rank_dead(self, rank: int) -> None:
        self._append(OP_RANK_DEAD, _SEQ.pack(rank))

    # -- master brain state (deputy stream) ----------------------------------
    # Bodies are pickled dicts: these are rare control-plane events (a
    # membership change, an operator POST), not per-unit hot-path ops,
    # and SS_REPL bodies are opaque blobs end to end.

    def log_member(self, doc: dict) -> None:
        """Full membership/brain snapshot, newest wins (epoch, master
        rank, provisional-id watermark, retired-route map, addrs,
        live/ready/dead/drained sets, ops-armed flag)."""
        import pickle

        self._append(OP_MEMBER, pickle.dumps(doc, protocol=4))

    def log_slo(self, doc: dict) -> None:
        """One live SLO objective (the POST /slo body after engine
        normalization), keyed by name at the mirror."""
        import pickle

        self._append(OP_SLO, pickle.dumps(doc, protocol=4))

    def log_control(self, policy: dict) -> None:
        """The controller policy doc (POST /control), newest wins."""
        import pickle

        self._append(OP_CONTROL, pickle.dumps(policy, protocol=4))

    def log_scale(self, parked) -> None:
        """The parked scale request (spawnerless scale-out), or None
        when the park is serviced/cleared. Newest wins."""
        import pickle

        self._append(OP_SCALE, pickle.dumps(parked, protocol=4))

    def log_job_weight(self, job_id: int, weight: float) -> None:
        self._append(OP_JOB_WEIGHT, _JOBW.pack(job_id, weight))

    def log_seen_puts(self, src: int, put_ids) -> None:
        """Re-bootstrap: ship a sender's whole accepted-put window so a
        put acked by THIS server and re-sent after its death is answered
        idempotently by the new buddy (without this, a buddy-death-then-
        primary-death chain would admit the duplicate and run it twice)."""
        ids = list(put_ids)
        self._append(OP_SEEN_PUTS,
                     _SEQ2.pack(src, len(ids))
                     + struct.pack(f"<{len(ids)}q", *ids))

    # -- flush ---------------------------------------------------------------

    @property
    def pending(self) -> int:
        return len(self._buf)

    def take(self) -> Optional[bytes]:
        """Drain the buffer into one frame body, or None when empty."""
        if not self._buf:
            return None
        blob = b"".join(self._buf)
        self._buf.clear()
        self.seq += 1
        return blob


class ReplicaMirror:
    """Buddy side: the predecessor's pool shard, reconstructed from its
    replication stream. Plain dicts — the mirror is passive until a
    takeover replays it into the buddy's live queues."""

    def __init__(self, primary: int) -> None:
        self.primary = primary
        self.units: dict[int, dict] = {}       # seqno -> unit fields
        self.pins: dict[int, int] = {}         # seqno -> pin_rank
        self.commons: dict[int, list] = {}     # seqno -> [buf, refcnt, ngets,
        #                                        credits]
        self.tombstones: set[int] = set()      # consumed seqnos
        self._tomb_order: deque[int] = deque()
        self.seen_puts: dict[int, list[int]] = {}  # src -> put ids (ordered)
        # per-requester dedup identities for the common-prefix control
        # plane: the last fetch id per src (the primary's _last_common)
        # and the forfeit-note window — merged at promotion so a request
        # the dead server already accounted is absorbed, not re-counted
        self.last_common: dict[int, int] = {}      # src -> last get_id
        self.forfeit_ids: dict[int, list[int]] = {}  # src -> note ids
        # gray-failure state: fences (seqno, owner, origin) from lease
        # expiry at the primary (origin -1 = the primary's own
        # numbering, else the server an earlier takeover adopted them
        # from), and units it moved to its dead-letter quarantine (the
        # takeover adopts both, so attempt budgets and fencing survive
        # the failover)
        self.fences: set[tuple[int, int, int]] = set()
        self.quarantined: dict[int, dict] = {}     # seqno -> unit fields
        # hedge siblings (OP_HEDGE): sibling seqno -> origin seqno.
        # Promotion / WAL replay discard marked units (see the opcode
        # comment); any terminal op on the sibling pops its mark.
        self.hedges: dict[int, int] = {}
        self.finalized: set[int] = set()
        self.dead_ranks: set[int] = set()
        # job-namespace lifecycle: job id -> (state_code, quota, name);
        # replayed into the taker-over's / restarted server's job table
        self.jobs_meta: dict[int, tuple[int, int, str]] = {}
        # master brain state (deputy stream): only populated when the
        # primary is the master under failover. ``brain`` is the newest
        # OP_MEMBER snapshot; slo docs are keyed by objective name;
        # weights by job id; policy / scale_pending are newest-wins.
        self.brain: Optional[dict] = None
        self.slo_docs: dict[str, dict] = {}
        self.control_policy: Optional[dict] = None
        self.scale_pending = None
        self.job_weights: dict[int, float] = {}
        self.entries_applied = 0
        self.frames_applied = 0
        self.sealed = False

    def _tombstone(self, seqno: int) -> None:
        self.tombstones.add(seqno)
        self._tomb_order.append(seqno)
        if len(self._tomb_order) > MAX_TOMBSTONES:
            self.tombstones.discard(self._tomb_order.popleft())

    def apply(self, blob: bytes) -> None:
        if self.sealed:
            return  # late frame after promotion: the shard already replayed
        off = 0
        n = len(blob)
        while off < n:
            op, blen = _HDR.unpack_from(blob, off)
            off += _HDR.size
            body = blob[off:off + blen]
            off += blen
            self._apply_one(op, body)
            self.entries_applied += 1
        self.frames_applied += 1

    def apply_entry(self, op: int, body: bytes) -> None:
        """Apply ONE already-unframed entry — the WAL replay path (the
        on-disk log wraps each entry in its own CRC record, so the
        torn-tail scan unframes record by record)."""
        self._apply_one(op, body)
        self.entries_applied += 1

    def _apply_one(self, op: int, body: bytes) -> None:
        if op == OP_PUT:
            seqno, src, pid, pin_rank, attempts, job = _PUTHDR.unpack_from(
                body, 0
            )
            fields, _ = _unpack_unit(body, _PUTHDR.size)
            fields["attempts"] = attempts
            fields["job"] = job
            self.units[seqno] = fields
            # a re-put of a marked sibling means its race dissolved and
            # it is an ordinary unit now (see OP_HEDGE comment)
            self.hedges.pop(seqno, None)
            if pin_rank >= 0:
                self.pins[seqno] = pin_rank
            if pid >= 0:
                ids = self.seen_puts.setdefault(src, [])
                ids.append(pid)
                if len(ids) > 512:
                    del ids[0]
        elif op == OP_PIN:
            seqno, rank = _SEQ2.unpack(body)
            if seqno in self.units:
                self.pins[seqno] = rank
        elif op == OP_UNPIN:
            (seqno,) = _SEQ.unpack(body)
            self.pins.pop(seqno, None)
        elif op == OP_CONSUME:
            (seqno,) = _SEQ.unpack(body)
            self.units.pop(seqno, None)
            self.pins.pop(seqno, None)
            self.hedges.pop(seqno, None)
            self._tombstone(seqno)
        elif op == OP_REMOVE:
            (seqno,) = _SEQ.unpack(body)
            self.units.pop(seqno, None)
            self.pins.pop(seqno, None)
            self.hedges.pop(seqno, None)
        elif op == OP_COMMON_PUT:
            (seqno,) = _SEQ.unpack_from(body, 0)
            self.commons[seqno] = [body[_SEQ.size:], -1, 0, 0]
        elif op == OP_COMMON_REFCNT:
            seqno, refcnt = _SEQ2.unpack(body)
            e = self.commons.get(seqno)
            if e is not None:
                e[1] = refcnt + e[3]
                e[3] = 0
        elif op in (OP_COMMON_GET, OP_COMMON_FORFEIT):
            seqno, src, op_id = _SEQ3.unpack(body)
            if src >= 0 and op_id >= 0:
                if op == OP_COMMON_GET:
                    self.last_common[src] = max(
                        self.last_common.get(src, -1), op_id
                    )
                else:
                    ids = self.forfeit_ids.setdefault(src, [])
                    ids.append(op_id)
                    if len(ids) > 512:
                        del ids[0]
            e = self.commons.get(seqno)
            if e is not None:
                e[2] += 1
        elif op == OP_COMMON_CREDIT:
            seqno, _src, _id = _SEQ3.unpack(body)
            e = self.commons.get(seqno)
            if e is not None:
                if e[1] >= 0:
                    e[1] += 1
                else:
                    e[3] += 1
        elif op == OP_COMMON_GC:
            seqno, _src, _id = _SEQ3.unpack(body)
            self.commons.pop(seqno, None)
        elif op == OP_COMMON_STATE:
            seqno, refcnt, ngets, credits = struct.unpack("<qqqq", body)
            e = self.commons.get(seqno)
            if e is not None:
                e[1], e[2], e[3] = refcnt, ngets, credits
        elif op == OP_ATTEMPTS:
            seqno, attempts = _SEQ2.unpack(body)
            f = self.units.get(seqno)
            if f is not None:
                f["attempts"] = attempts
        elif op == OP_FENCE:
            seqno, owner, origin = _SEQ3.unpack(body)
            self.fences.add((seqno, owner, origin))
        elif op == OP_QUARANTINE:
            (seqno,) = _SEQ.unpack(body)
            f = self.units.pop(seqno, None)
            self.pins.pop(seqno, None)
            self.hedges.pop(seqno, None)
            if f is not None:
                self.quarantined[seqno] = f
        elif op == OP_APP_DONE:
            (rank,) = _SEQ.unpack(body)
            self.finalized.add(rank)
        elif op == OP_RANK_DEAD:
            (rank,) = _SEQ.unpack(body)
            self.dead_ranks.add(rank)
            self.finalized.add(rank)
        elif op == OP_SEEN_PUTS:
            src, n = _SEQ2.unpack_from(body, 0)
            new = struct.unpack_from(f"<{n}q", body, _SEQ2.size)
            ids = self.seen_puts.setdefault(src, [])
            ids.extend(new)
            if len(ids) > 512:
                del ids[:len(ids) - 512]
        elif op == OP_JOB:
            job_id, quota, state_code = _JOBHDR.unpack_from(body, 0)
            name = body[_JOBHDR.size:].decode("utf-8", "replace")
            self.jobs_meta[job_id] = (state_code, quota, name)
        elif op == OP_TRACE:
            from adlb_tpu.obs.journey import unpack_spans

            (seqno,) = _SEQ.unpack_from(body, 0)
            f = self.units.get(seqno)
            if f is not None:
                tid, spans = unpack_spans(body[_SEQ.size:])
                f["trace_id"] = tid
                f["spans"] = spans
        elif op == OP_HEDGE:
            sib, origin = _SEQ2.unpack(body)
            if sib in self.units:
                self.hedges[sib] = origin
        elif op == OP_MEMBER:
            import pickle

            self.brain = pickle.loads(body)
        elif op == OP_SLO:
            import pickle

            doc = pickle.loads(body)
            name = str(doc.get("name", ""))
            if name:
                self.slo_docs[name] = doc
        elif op == OP_CONTROL:
            import pickle

            self.control_policy = pickle.loads(body)
        elif op == OP_SCALE:
            import pickle

            self.scale_pending = pickle.loads(body)
        elif op == OP_JOB_WEIGHT:
            job_id, weight = _JOBW.unpack(body)
            self.job_weights[job_id] = weight
        # unknown ops are skipped by construction (op byte + length frame)

    def seal(self) -> None:
        self.sealed = True


def buddy_of(world, dead: int, dead_servers=()) -> int:
    """The server expected to hold ``dead``'s replica: its next LIVE ring
    successor. With no intermediate deaths that is the original
    ``ring_next`` the replication stream targeted; after an intermediate
    death the primary re-bootstrapped its stream to the next live
    successor (see ``Server._rebootstrap_repl``). If the walk comes back
    to ``dead`` there is no live peer at all. The buddy may still hold no
    mirror (the double failure: primary and its buddy died back to back,
    before any re-bootstrap) — promotion detects that and aborts."""
    b = world.ring_next(dead)
    while b != dead and b in dead_servers:
        b = world.ring_next(b)
    return b
