"""Client-side protocol engine.

Equivalent of the reference's L4 layer — ``ADLBP_Put`` / ``adlbp_Reserve`` /
``adlbp_Get_reserved_timed`` / batch puts (reference ``src/adlb.c:2638-3176``)
— over a Transport endpoint instead of tagged MPI sends.

Behavioral contract kept from the reference:

* targeted Puts are routed to the *target's* home server; untargeted Puts
  round-robin over servers (reference ``src/adlb.c:2767-2773``);
* rejected Puts retry at the server hinted by the rejecting server (the
  least-loaded one it knows of), with bounded retries and a short sleep, then
  return ADLB_PUT_REJECTED (reference ``src/adlb.c:2779-2796``);
* a targeted Put accepted off the target's home server notifies the home
  server so its targeted-work directory stays accurate (reference
  ``src/adlb.c:2845-2852``);
* Reserve blocks until work or a termination code; Ireserve returns
  ADLB_NO_CURRENT_WORK immediately (reference ``src/adlb.c:2868-2957``);
* Get_reserved fetches the batch-common prefix (possibly from a different
  server) before the unique payload bytes (reference ``src/adlb.c:2976-3025``).
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from collections import OrderedDict, deque
from typing import Iterator, Optional, Sequence

from contextlib import nullcontext

from adlb_tpu.obs.flight import FlightRecorder
from adlb_tpu.obs.metrics import Registry, attach
from adlb_tpu.runtime.messages import Msg, Tag, msg
from adlb_tpu.runtime.trace import PID_APP, Tracer
from adlb_tpu.runtime.transport import Endpoint
from adlb_tpu.runtime.world import Config, WorldSpec, normalize_req_types
from adlb_tpu.types import (
    ADLB_BACKOFF,
    ADLB_FENCED,
    ADLB_NO_CURRENT_WORK,
    ADLB_NO_MORE_WORK,
    ADLB_PUT_REJECTED,
    ADLB_RETRY,
    ADLB_SUCCESS,
    AdlbAborted,
    AdlbError,
    GotWork,
    HomeServerLostError,
    ReserveResult,
    WorkHandle,
)


@dataclasses.dataclass
class _BatchState:
    common_server: int
    common_seqno: int
    common_len: int
    refcnt: int = 0


class Client:
    def __init__(
        self, world: WorldSpec, cfg: Config, ep: Endpoint, abort_event=None
    ) -> None:
        self.world = world
        self.cfg = cfg
        self.ep = ep
        self.rank = ep.rank
        self.home = world.home_server(self.rank)
        self._rr = self.rank % world.nservers  # round-robin cursor
        self._batch: Optional[_BatchState] = None
        # job namespace this rank is attached to (service mode): 0 = the
        # default/legacy namespace; attach() binds another and every
        # subsequent put/reserve rides in it (frames omit the field when
        # 0, so single-job traffic stays byte-identical)
        self.job = 0
        self._rqseqno = 0
        self._abort_event = abort_event
        self.aborted = False
        # MPE-equivalent event tracing (reference src/adlb_prof.c:46-74),
        # a run-time flag here instead of a compile-time one
        self.tracer: Optional[Tracer] = (
            Tracer(self.rank, pid=PID_APP, process_name="apps")
            if cfg.trace
            else None
        )
        # observability: per-rank metrics registry wired into the
        # transport (per-tag msgs/bytes, send/recv latency) + a flight
        # recorder dumped when this rank dies (abort, lost home server)
        self.metrics = Registry(self.rank)
        attach(ep, self.metrics)
        self.flight = FlightRecorder(
            self.rank, out_dir=cfg.flight_dir, role="app"
        )
        self.flight.metrics = self.metrics
        self.flight.context = {"home": self.home}
        self._reserved_types: dict[tuple[int, int], int] = {}  # (holder, seqno) -> type
        # app<->app messages that arrived while waiting for a protocol
        # response (the reference's app_comm traffic is a separate MPI
        # communicator, so it can never be confused with ADLB's tags; here
        # one fabric carries both, so AM_APP frames are stashed)
        self._app_inbox: list[Msg] = []
        # pipelined puts (iput): put_id -> request args, awaiting a
        # TA_PUT_RESP that may arrive out of band
        self._next_put_id = 1
        self._pending_puts: dict[int, dict] = {}
        self._failed_puts = 0
        self._failed_nmw = False
        # retry/backoff state: capped exponential backoff with
        # decorrelated jitter (sleep_k ~ U(base, 3*sleep_{k-1}), capped)
        # replaces the fixed put_retry_sleep spin — under contention the
        # fixed interval synchronized whole worker pools into retry
        # convoys. Seeded per rank: reproducible, and ranks decorrelate.
        self._retry_rng = random.Random(0xADB0 + 7919 * self.rank)
        # unit-lifecycle head sampling (Config(trace_sample)): its OWN
        # seeded RNG, so arming/raising the sample rate never perturbs
        # the retry-jitter stream (and sampling is reproducible per
        # rank). trace_sample=0 never draws — the put path is
        # allocation-identical to a pre-trace build.
        self._trace_rng = random.Random(0x7ACE ^ (104729 * self.rank))
        self._trace_seq = 0
        self._m_traced_puts = self.metrics.counter("traced_puts")
        self._m_put_retries = self.metrics.counter("put_retries")
        self._m_reserve_retries = self.metrics.counter("reserve_retries")
        self._m_reconnects = self.metrics.counter("reconnects")
        # client-side batch-common prefix cache (bounded LRU keyed by
        # (common_server, common_seqno)): members of a batch inline only
        # their suffix; the prefix is fetched once per client and cache
        # hits ship an SS_COMMON_FORFEIT accounting note instead of
        # bytes, keeping server refcounts (and prefix GC) exact
        self._prefix_cache: Optional[OrderedDict[tuple[int, int], bytes]] = (
            OrderedDict() if cfg.prefix_cache_bytes > 0 else None
        )
        self._prefix_cache_bytes = 0
        self._m_prefix_hits = self.metrics.counter("prefix_cache_hits")
        self._m_prefix_misses = self.metrics.counter("prefix_cache_misses")
        # at most one get_work_stream at a time: a concurrent blocking
        # reserve's _wait would race the stream's passive routing for
        # the same response tag
        self._active_stream: Optional[WorkStream] = None
        # server-failover routing (Config(on_server_failure="failover")):
        # dead server -> buddy, learned from epoch-stamped
        # TA_HOME_TAKEOVER notes; every server-bound send resolves
        # through it (stamping fo_from so content-addressed seqnos
        # translate at the buddy). _lost_at tracks when a server's
        # connection was observed gone, bounding how long a blocked wait
        # holds out for the takeover note.
        self._srv_route: dict[int, int] = {}
        self._fo_epoch = 0
        # master succession: TA_HOME_TAKEOVER notes for a dead MASTER
        # carry new_master (the promoted deputy); job control and detach
        # re-point through _master(). None = the spec's static master.
        # Per-instance on purpose — in-proc clients SHARE the WorldSpec.
        self._master_rank: Optional[int] = None
        # elastic membership: True once this rank cleanly detached (a
        # detached rank's finalize is a no-op); attached_member marks a
        # rank that JOINED a running world (membership.attach_app)
        self._detached = False
        self.attached_member = False
        self._lost_at: dict[int, float] = {}
        self._m_failovers = self.metrics.counter("home_takeovers")
        # frames _await_takeover pulled off the endpoint that belong to
        # an OUTER blocking wait (that wait can run nested inside _wait
        # via _apply_takeover's re-sends): queued here and consumed by
        # _recv before the endpoint, never dropped
        self._redeliver: deque = deque()
        # gray-failure surface (Config(lease_timeout_s) > 0): a liveness
        # heartbeat thread — protocol traffic piggybacks liveness, this
        # covers the idle-but-computing gaps so a BUSY rank is never
        # misread as hung while a SIGSTOP'd one (the thread freezes with
        # the process) is detected within the timeout
        self._m_fenced = self.metrics.counter("fenced_fetches")
        self._m_put_backoffs = self.metrics.counter("put_backoffs")
        # continuous-profiler role tag (obs/profile.py): a plain dict
        # write — in-proc worlds share the interpreter with the servers'
        # sampler, so app-rank stacks fold under "client" instead of a
        # raw thread name; a no-op when nothing ever profiles
        from adlb_tpu.obs import profile as _profile

        _profile.register_thread("client")
        self._hb_stop: Optional[threading.Event] = None
        if cfg.lease_timeout_s > 0:
            self._hb_stop = threading.Event()
            threading.Thread(
                target=self._heartbeat_loop,
                daemon=True,
                name=f"adlb-hb-{self.rank}",
            ).start()

    def _heartbeat_loop(self) -> None:
        """FA_HEARTBEAT to every (routed) server at timeout/3 cadence.
        Endpoint sends are thread-safe; a peer that refuses is left to
        the protocol plane's own retry/failover machinery. Beacons are
        best-effort and periodic, so a dead destination gets only a
        short connect grace — the default 15 s grace would stall the
        whole round behind one dead server (the takeover remap happens
        on the main thread) and starve the beacons that keep healthy
        servers from declaring this rank hung."""
        from adlb_tpu.obs import profile as _profile

        _profile.register_thread("heartbeat")
        interval = max(self.cfg.lease_timeout_s / 3.0, 0.005)
        while not self._hb_stop.wait(interval):
            for dest in {self._route(s) for s in self.world.server_ranks}:
                try:
                    self.ep.send(
                        dest, msg(Tag.FA_HEARTBEAT, self.rank),
                        connect_grace=0.25,
                    )
                except OSError:
                    pass

    def _stop_heartbeat(self) -> None:
        if self._hb_stop is not None:
            self._hb_stop.set()

    def _recv(self, timeout):
        """Endpoint recv that drains takeover-deferred frames first."""
        if self._redeliver:
            return self._redeliver.popleft()
        return self.ep.recv(timeout=timeout)

    def _span(self, name: str, **args):
        """API-call trace span + user-state inference boundary."""
        if self.tracer is None:
            return nullcontext()
        self.tracer.api_entry()
        return self.tracer.span(name, **args)

    def _sample_trace(self):
        """Head-sampling decision for one put: a minted trace id (rank
        in the high bits, per-rank sequence below — unique world-wide)
        or None. The id rides FA_PUT as codec field 98 and the unit's
        journey is recorded server-side (obs/journey.py)."""
        rate = self.cfg.trace_sample
        if not rate or self._trace_rng.random() >= rate:
            return None
        self._trace_seq += 1
        self._m_traced_puts.inc()
        return ((self.rank + 1) << 32) | (self._trace_seq & 0xFFFFFFFF)

    # -- plumbing ------------------------------------------------------------

    def _next_server(self) -> int:
        # indexed through server_ranks, not rank arithmetic: under
        # elastic membership scale-out server ids are not contiguous
        # with the base range (a plain WorldSpec's range indexes the
        # same way)
        servers = self.world.server_ranks
        s = servers[self._rr % len(servers)]
        self._rr = (self._rr + 1) % len(servers)
        return s

    def _route_put(self, target_rank: int) -> int:
        """Initial server for a put (reference src/adlb.c:2767-2773)."""
        if target_rank >= 0:
            try:
                return self.world.home_server(target_rank)
            except KeyError:
                # an attached rank this client's membership view has not
                # learned: route via our own home — the receiving server
                # announces the inventory to the target's real home
                # (off-home TargetedDirectory redirection)
                return self.home
        if self.cfg.put_routing == "home":
            return self.home
        return self._next_server()

    def _retry_server(self, hint) -> int:
        """Where a rejected put retries: the rejecting server's least-loaded
        hint, else round-robin (reference src/adlb.c:2779-2796)."""
        return hint if hint is not None and hint >= 0 else self._next_server()

    def _backoff_sleep(self, prev: float, cap: Optional[float] = None) -> float:
        """Sleep one capped decorrelated-jitter step and return it (feed it
        back in as ``prev`` for the next attempt). ``cap`` overrides
        ``put_retry_cap`` for paths that must stay short."""
        base = self.cfg.put_retry_sleep
        s = min(
            self.cfg.put_retry_cap if cap is None else cap,
            self._retry_rng.uniform(base, max(base, prev * 3.0)),
        )
        time.sleep(s)
        return s

    def _jitter_hint(self, hint_s: float, cap: float) -> float:
        """Decorrelate a server-carried retry-after hint. The server's
        ``retry_after_ms`` is deterministic (the same constant on every
        ADLB_BACKOFF), so honoring it verbatim re-synchronizes every
        backpressured client into a retry convoy exactly one hint
        later. Bounded multiplicative jitter [1.0, 1.5) drawn from this
        client's own seeded retry RNG (never a shared stream) spreads
        the wave without ever undercutting the server's ask; the site's
        cap still wins."""
        return min(cap, hint_s * (1.0 + 0.5 * self._retry_rng.random()))

    def _route(self, dest: int) -> int:
        """Resolve a server destination through the failover map (chains
        of takeovers resolve to the final live buddy)."""
        seen = set()
        while dest in self._srv_route and dest not in seen:
            seen.add(dest)
            dest = self._srv_route[dest]
        return dest

    def _failover_policy(self) -> bool:
        return self.cfg.on_server_failure == "failover"

    def _send_retry(self, dest: int, m: Msg) -> None:
        """Protocol send that survives peer-connection churn: the endpoint
        already retries the socket once; past that the client backs off
        and re-sends up to ``cfg.reconnect_attempts`` times instead of
        dying on the first OSError. Under ``on_server_failure="failover"``
        a server destination additionally resolves through the takeover
        map (stamped ``fo_from`` so the buddy translates content
        addresses), and exhausted retries wait out one takeover window
        before giving up; otherwise an unreachable peer is terminal."""
        attempts = self.cfg.reconnect_attempts
        if dest in getattr(self.ep, "binary_peers", ()):
            # native servers implement none of the duplicate-request
            # dedup (put ids, rqseqno, at-most-once get cache) the
            # re-send protocol relies on — fail fast rather than risk a
            # double-stored put or a double-consumed fetch
            attempts = 0
        waited_takeover = False
        sleep = 0.0
        attempt = 0
        while True:
            routed = self._route(dest)
            if routed != dest and self.world.is_server(dest):
                m.data["fo_from"] = dest
            try:
                self.ep.send(routed, m)
                return
            except OSError as e:
                attempt += 1
                if attempt > attempts:
                    if (
                        self._failover_policy()
                        and self.world.is_server(routed)
                        and not waited_takeover
                        and self._await_takeover(routed)
                    ):
                        # buddy announced itself: restart the retry
                        # budget toward the new destination
                        waited_takeover = True
                        attempt = 0
                        continue
                    # a permanently unreachable protocol peer ends this
                    # client — raise the conn-lost error the harnesses
                    # classify (abort collateral / casualty), never a
                    # bare OSError that would read as an application bug
                    self.aborted = True
                    self.flight.record(
                        f"peer {routed} unreachable after "
                        f"{attempt} send attempts: {e!r}"
                    )
                    self.flight.dump_json("home_server_lost")
                    raise HomeServerLostError(
                        f"rank {self.rank}: protocol peer {routed} "
                        f"unreachable ({e!r})"
                    ) from e
                self._m_reconnects.inc()
                self.flight.record(
                    f"reconnect dest={routed} attempt={attempt} ({e!r})"
                )
                sleep = self._backoff_sleep(max(sleep, 0.01))

    def _await_takeover(self, lost: int) -> bool:
        """Block (reading only control frames; everything else stays in
        the endpoint queue order via a bounded drain-and-redeliver) until
        a TA_HOME_TAKEOVER covers ``lost`` or the failover window
        expires. Returns True when the route changed."""
        self._lost_at.setdefault(lost, time.monotonic())
        deadline = (
            self._lost_at[lost] + self.cfg.failover_client_wait
        )
        while time.monotonic() < deadline:
            if self._abort_event is not None and self._abort_event.is_set():
                self.aborted = True
                raise AdlbAborted(-1)
            if self._route(lost) != lost:
                return True
            m = self.ep.recv(timeout=0.2)
            if m is None:
                continue
            if m.tag is Tag.TA_HOME_TAKEOVER:
                self._apply_takeover(m)
                continue
            if m.tag is Tag.TA_ABORT:
                self._dispatch_passive(m)  # raises AdlbAborted
            if (
                m.tag in (Tag.AM_APP, Tag.PEER_EOF)
                or (m.tag is Tag.TA_PUT_RESP
                    and m.data.get("put_id") in self._pending_puts)
                or (m.tag is Tag.TA_RESERVE_RESP
                    and self._active_stream is not None)
            ):
                # stash / settle / bank through the normal passive
                # dispatch — these have a home regardless of context
                try:
                    self._dispatch_passive(m)
                except AdlbError:
                    # an unexpected-for-this-context frame must not turn
                    # the takeover wait into a protocol error
                    self.flight.record(
                        f"frame {m.tag.name} deferred during takeover wait"
                    )
                continue
            # anything else may be the very response an OUTER wait is
            # parked on (this wait can run nested inside _wait via
            # _apply_takeover's re-sends): dispatching here would DROP it
            # as a stray and deadlock the outer wait against a healthy
            # server — queue it for redelivery to the next _recv instead
            self._redeliver.append(m)
        return self._route(lost) != lost

    def _check_failover_resend(self, sent_to, dest, m_req):
        """While blocked on a response from ``sent_to``: a takeover that
        remapped the destination re-sends the request to the buddy (same
        ids — the replicated dedup windows and fo_from translation make
        that safe); a destination lost past the failover window (or
        under the abort policy) is terminal."""
        if dest is None or not self._failover_policy():
            return sent_to
        routed = self._route(dest)
        if routed != sent_to:
            self.flight.record(
                f"re-sending {m_req.tag.name} to {routed} after takeover"
            )
            self._send_retry(dest, m_req)  # resolves + stamps fo_from
            return routed
        lost = self._lost_at.get(sent_to)
        if (
            lost is not None
            and time.monotonic() - lost > self.cfg.failover_client_wait
        ):
            self.aborted = True
            self.flight.record(
                f"server {sent_to} lost and no takeover within "
                f"{self.cfg.failover_client_wait}s"
            )
            self.flight.dump_json("home_server_lost")
            raise HomeServerLostError(
                f"rank {self.rank}: server {sent_to} lost; no takeover"
            )
        return sent_to

    def _wait_put(self, put_id: int, dest=None, m_req=None) -> Msg:
        """Wait for THIS put's response, matched by id: a frame re-sent
        after a send error can be acked twice, and the stale duplicate
        ack must not be mistaken for a later put's answer."""
        sent_to = self._route(dest) if dest is not None else None
        while True:
            if self._abort_event is not None and self._abort_event.is_set():
                self.aborted = True
                self.flight.record("abort event observed waiting put resp")
                self.flight.dump_json("abort_event")
                raise AdlbAborted(-1)
            m = self._recv(timeout=0.5)
            if m is None:
                sent_to = self._check_failover_resend(sent_to, dest, m_req)
                continue
            if m.tag is Tag.TA_PUT_RESP and m.data.get("put_id") == put_id:
                return m
            self._dispatch_passive(m, waiting=Tag.TA_PUT_RESP)
            sent_to = self._check_failover_resend(sent_to, dest, m_req)

    def _wait(self, want: Tag, dest=None, m_req=None) -> Msg:
        sent_to = self._route(dest) if dest is not None else None
        while True:
            if self._abort_event is not None and self._abort_event.is_set():
                self.aborted = True
                self.flight.record(f"abort event observed waiting {want}")
                self.flight.dump_json("abort_event")
                raise AdlbAborted(-1)
            m = self._recv(timeout=0.5)
            if m is None:
                sent_to = self._check_failover_resend(sent_to, dest, m_req)
                continue
            if m.tag is want and not (
                m.tag is Tag.TA_PUT_RESP
                and m.data.get("put_id") in self._pending_puts
            ):
                # (the guard keeps an out-of-band pipelined-put response
                # from answering a synchronous put)
                return m
            # A late RESERVE_RESP can cross a termination flush only if the
            # origin server double-responded, which the rq discipline forbids.
            self._dispatch_passive(m, waiting=want)
            sent_to = self._check_failover_resend(sent_to, dest, m_req)

    # -- Put family ----------------------------------------------------------

    def put(
        self,
        payload: bytes,
        work_type: int,
        work_prio: int = 0,
        target_rank: int = -1,
        answer_rank: int = -1,
    ) -> int:
        with self._span(
            "adlb:put", work_type=work_type, prio=work_prio, len=len(payload)
        ):
            return self._put(payload, work_type, work_prio, target_rank, answer_rank)

    def _validate_target(self, target_rank: int) -> None:
        """Targeted-put destination check. Ranks ABOVE the base world
        (and the sidecar pseudo-rank) may be dynamically attached
        members this client's — possibly static — view has not learned:
        those pass through, and the SERVERS, which hold the
        authoritative membership, answer an unknown target loudly
        (elastic membership, adlb_tpu/runtime/membership.py). In-range
        non-app ranks are always a caller bug."""
        if target_rank < 0 or self.world.is_app(target_rank):
            return
        from adlb_tpu.runtime.membership import is_provisional

        if target_rank <= self.world.nranks or is_provisional(target_rank):
            raise AdlbError(
                f"target rank {target_rank} is not an app rank"
            )

    def _put(
        self,
        payload: bytes,
        work_type: int,
        work_prio: int,
        target_rank: int,
        answer_rank: int,
    ) -> int:
        if not self.world.validate_type(work_type):
            raise AdlbError(f"unregistered work type {work_type}")
        self._validate_target(target_rank)
        common = self._batch
        if common is not None:
            common.refcnt += 1

        server = self._route_put(target_rank)
        attempts = 0
        sleep = 0.0
        # synchronous puts carry an id too (same counter as iput): a
        # send retried across an OSError may have been delivered the
        # first time, and the server's per-sender dedup window turns the
        # re-send into an idempotent ack instead of a duplicated unit
        put_id = self._next_put_id
        self._next_put_id += 1
        trace_id = self._sample_trace()  # one decision per logical put:
        # retries/re-routes keep the id (the server dedup window keeps
        # re-sends from double-tracing a unit)
        while True:
            pm = msg(
                Tag.FA_PUT,
                self.rank,
                payload=bytes(payload),
                work_type=work_type,
                prio=work_prio,
                target_rank=target_rank,
                answer_rank=answer_rank,
                common_len=common.common_len if common else 0,
                common_server=common.common_server if common else -1,
                common_seqno=common.common_seqno if common else -1,
                put_id=put_id,
            )
            if self.job:
                pm.data["job_id"] = self.job
            if trace_id is not None:
                pm.data["trace_id"] = trace_id
            self._send_retry(server, pm)
            resp = self._wait_put(put_id, dest=server, m_req=pm)
            rc = resp.rc
            if rc == ADLB_BACKOFF:
                # overload backpressure: the server (and, it believes,
                # every peer) is above the hard watermark — hopping
                # would not help. Retry the SAME server after the
                # carried retry-after hint fed into the decorrelated-
                # jitter backoff, WITHOUT burning the retry budget:
                # shedding load, not failing the put.
                self._m_put_backoffs.inc()
                hint_s = self._jitter_hint(
                    float(resp.data.get("retry_after_ms", 25) or 25) / 1e3,
                    self.cfg.put_retry_cap,
                )
                self.flight.record(
                    f"put_backoff server={server} retry_after_s={hint_s}"
                )
                sleep = self._backoff_sleep(max(sleep, hint_s))
                continue
            if rc not in (ADLB_PUT_REJECTED, ADLB_RETRY):
                break
            attempts += 1
            if attempts > self.cfg.put_max_retries:
                if common is not None:
                    common.refcnt -= 1
                # the documented contract for retries-exhausted puts is
                # ADLB_PUT_REJECTED, whatever the last transient rc was
                return ADLB_PUT_REJECTED
            if rc == ADLB_PUT_REJECTED:
                # capacity: try the hinted (least-loaded) server;
                # ADLB_RETRY is transient at THIS server — same target
                server = self._retry_server(resp.data.get("hint"))
            self._m_put_retries.inc()
            sleep = self._backoff_sleep(sleep)
        if rc != ADLB_SUCCESS and common is not None:
            common.refcnt -= 1  # unit never stored; keep prefix GC reachable
        try:
            t_home = (
                self.world.home_server(target_rank)
                if target_rank >= 0 else -1
            )
        except KeyError:
            # an attached member this view has not learned: the
            # receiving server's own off-home announce covers it
            t_home = server
        if (
            rc == ADLB_SUCCESS
            and target_rank >= 0
            and server != t_home
        ):
            self._send_retry(
                t_home,
                msg(
                    Tag.FA_DID_PUT_AT_REMOTE,
                    self.rank,
                    target_rank=target_rank,
                    work_type=work_type,
                    server_rank=server,
                ),
            )
        return rc

    def begin_batch_put(self, common_buf: bytes) -> int:
        """Store a shared prefix once; subsequent puts reference it
        (reference ``src/adlb.c:2638-2722``)."""
        if self._batch is not None:
            raise AdlbError("nested Begin_batch_put")
        ctx = self._span("adlb:begin_batch_put", len=len(common_buf))
        with ctx:
            return self._begin_batch_put(common_buf)

    def _begin_batch_put(self, common_buf: bytes) -> int:
        if len(common_buf) == 0:
            # NULL/empty prefix (the reference allows it, src/adlb.c:2638):
            # batch bracketing with nothing to share — no server round trip,
            # nothing for the server to store or GC
            self._batch = _BatchState(common_server=-1, common_seqno=-1,
                                      common_len=0)
            return ADLB_SUCCESS
        server = self._next_server()
        pm = msg(Tag.FA_PUT_COMMON, self.rank, payload=bytes(common_buf))
        self._send_retry(server, pm)
        resp = self._wait(Tag.TA_PUT_COMMON_RESP, dest=server, m_req=pm)
        if resp.rc != ADLB_SUCCESS:
            return resp.rc
        self._batch = _BatchState(
            common_server=server,
            common_seqno=resp.common_seqno,
            common_len=len(common_buf),
        )
        return ADLB_SUCCESS

    def end_batch_put(self) -> int:
        """Ship the final refcount so the server can GC the prefix once every
        member has been fetched (reference ``src/adlb.c:2724-2751``)."""
        if self._batch is None:
            raise AdlbError("End_batch_put without Begin_batch_put")
        b = self._batch
        self._batch = None
        if b.common_server < 0:  # empty-prefix batch: nothing stored
            return ADLB_SUCCESS
        with self._span("adlb:end_batch_put"):
            self._send_retry(
                b.common_server,
                msg(
                    Tag.FA_BATCH_DONE,
                    self.rank,
                    common_seqno=b.common_seqno,
                    refcnt=b.refcnt,
                ),
            )
        return ADLB_SUCCESS

    # -- Reserve / Get family ------------------------------------------------

    def _reserve_rpc(self, **fields) -> Msg:
        """One FA_RESERVE round trip, retried with backoff on ADLB_RETRY
        (a transient server-side condition, e.g. this rank reconnecting
        while its rank-death fan-out settles). Every retry is a fresh
        rqseqno — the previous request is dead at the server."""
        if self._active_stream is not None:
            # reservation responses carry no request id, so a blocking
            # reserve could not tell its answer from a stream delivery
            raise AdlbError(
                "reserve/get_work while a get_work_stream is open; close "
                "the stream first"
            )
        sleep = 0.0
        while True:
            self._rqseqno += 1
            pm = msg(Tag.FA_RESERVE, self.rank, rqseqno=self._rqseqno,
                     **fields)
            if self.job:
                pm.data["job_id"] = self.job
            self._send_retry(self.home, pm)
            resp = self._wait(Tag.TA_RESERVE_RESP, dest=self.home, m_req=pm)
            if resp.rc != ADLB_RETRY:
                return resp
            self._m_reserve_retries.inc()
            sleep = self._backoff_sleep(sleep)

    def _reserve(
        self, req_types: Optional[Sequence[int]], hang: bool
    ) -> tuple[int, Optional[ReserveResult]]:
        types = normalize_req_types(req_types, self.world.types)
        resp = self._reserve_rpc(
            req_types=None if types is None else sorted(types),
            hang=hang,
        )
        if resp.rc != ADLB_SUCCESS:
            return resp.rc, None
        result = ReserveResult(
            work_type=resp.work_type,
            work_prio=resp.prio,
            handle=WorkHandle.from_ints(resp.handle),
            work_len=resp.work_len,
            answer_rank=resp.answer_rank,
        )
        if self.tracer is not None:
            # remembered so get_reserved can start the inferred user-state
            # span with the unit's type (reference src/adlb_prof.c:185-236);
            # keyed by (holder, seqno) — seqnos are per-server counters
            key = (result.handle.server_rank, result.handle.seqno)
            self._reserved_types[key] = result.work_type
        return ADLB_SUCCESS, result

    def reserve(
        self, req_types: Optional[Sequence[int]] = None
    ) -> tuple[int, Optional[ReserveResult]]:
        """Blocking reserve: returns only with work or a termination code."""
        with self._span("adlb:reserve"):
            return self._reserve(req_types, hang=True)

    def ireserve(
        self, req_types: Optional[Sequence[int]] = None
    ) -> tuple[int, Optional[ReserveResult]]:
        """Non-blocking reserve: ADLB_NO_CURRENT_WORK if nothing matches now."""
        with self._span("adlb:ireserve"):
            rc, res = self._reserve(req_types, hang=False)
        if rc == ADLB_NO_CURRENT_WORK:
            return rc, None
        return rc, res

    def get_reserved_timed(
        self, handle: WorkHandle
    ) -> tuple[int, Optional[bytes], float]:
        with self._span("adlb:get_reserved"):
            rc, buf, t = self._get_reserved_timed(handle)
        if self.tracer is not None:
            wt = self._reserved_types.pop(
                (handle.server_rank, handle.seqno), -1
            )
            if rc == ADLB_SUCCESS:
                self.tracer.got_work(wt)
        return rc, buf, t

    def _fetch_prefix(
        self, common_server: int, common_seqno: int
    ) -> tuple[int, bytes]:
        """Batch-common prefix bytes, through the client LRU cache.

        A hit serves locally and ships an SS_COMMON_FORFEIT accounting
        note (``op="forfeit"`` = count one get without re-sending bytes)
        so the server's refcount — and thus prefix GC — stays exact: one
        accounting event per batch member, fetched or cached. Native
        common servers bypass the cache entirely (their frame decoder
        rejects the forfeit tag), paying the fetch as before."""
        key = (common_server, common_seqno)
        cache = self._prefix_cache
        if common_server in getattr(self.ep, "binary_peers", ()):
            cache = None
        if cache is not None:
            buf = cache.get(key)
            if buf is not None:
                cache.move_to_end(key)
                self._m_prefix_hits.inc()
                # get_id (same counter as put ids): a forfeit re-sent
                # across connection churn must not be applied twice —
                # an over-forfeit would GC the prefix one get early and
                # drop a live member
                fid = self._next_put_id
                self._next_put_id += 1
                self._send_retry(
                    common_server,
                    msg(Tag.SS_COMMON_FORFEIT, self.rank,
                        common_seqno=common_seqno, op="forfeit",
                        get_id=fid),
                )
                return ADLB_SUCCESS, buf
        # get_id (same per-client counter as put ids) lets the server
        # tell a re-sent duplicate from a legitimate second fetch of
        # the same prefix (one fetch per batch member is normal)
        get_id = self._next_put_id
        self._next_put_id += 1
        pm = msg(Tag.FA_GET_COMMON, self.rank,
                 common_seqno=common_seqno, get_id=get_id)
        self._send_retry(common_server, pm)
        resp = self._wait(Tag.TA_GET_COMMON_RESP, dest=common_server,
                          m_req=pm)
        if resp.rc != ADLB_SUCCESS:
            return resp.rc, b""
        self._m_prefix_misses.inc()
        buf = resp.payload
        if cache is not None and len(buf) <= self.cfg.prefix_cache_bytes:
            cache[key] = buf
            self._prefix_cache_bytes += len(buf)
            while self._prefix_cache_bytes > self.cfg.prefix_cache_bytes:
                _, old = cache.popitem(last=False)
                self._prefix_cache_bytes -= len(old)
        return ADLB_SUCCESS, buf

    def _get_reserved_timed(
        self, handle: WorkHandle
    ) -> tuple[int, Optional[bytes], float]:
        prefix = b""
        if handle.common_len > 0:
            rc, prefix = self._fetch_prefix(
                handle.common_server_rank, handle.common_seqno
            )
            if rc == ADLB_RETRY:
                # prefix lost to a server failover (a counted loss): the
                # suffix alone is not the unit, but the reservation must
                # still drain — consume and discard it, then let the
                # caller re-reserve. Returning without the fetch would
                # leak the pin and hang exhaustion on a unit nobody can
                # ever complete.
                pm = msg(Tag.FA_GET_RESERVED, self.rank, seqno=handle.seqno)
                self._send_retry(handle.server_rank, pm)
                self._wait(Tag.TA_GET_RESERVED_RESP,
                           dest=handle.server_rank, m_req=pm)
                return ADLB_RETRY, None, 0.0
            if rc != ADLB_SUCCESS:
                # prefix no longer exists (reclaim edge): surface the
                # error; a truncated payload must never look like success
                return rc, None, 0.0
        pm = msg(Tag.FA_GET_RESERVED, self.rank, seqno=handle.seqno)
        self._send_retry(handle.server_rank, pm)
        resp = self._wait(Tag.TA_GET_RESERVED_RESP, dest=handle.server_rank,
                          m_req=pm)
        if resp.rc == ADLB_FENCED:
            # our lease on this unit EXPIRED (this rank went silent past
            # lease_timeout_s — e.g. it was SIGSTOP'd and resumed): the
            # unit was re-enqueued under a new attempt and this settle
            # is rejected. Mapped onto the existing ADLB_RETRY path —
            # drop the handle and re-reserve — so every retry loop
            # (get_work, streams, app-level PR 2 handling) absorbs it
            # unchanged.
            self._m_fenced.inc()
            self.flight.record(
                f"fenced fetch seqno={handle.seqno} -> retry"
            )
            return ADLB_RETRY, None, 0.0
        if resp.rc != ADLB_SUCCESS:
            return resp.rc, None, 0.0
        return ADLB_SUCCESS, prefix + resp.payload, resp.time_on_q

    def get_reserved(self, handle: WorkHandle) -> tuple[int, Optional[bytes]]:
        rc, buf, _ = self.get_reserved_timed(handle)
        return rc, buf

    def get_work(
        self, req_types: Optional[Sequence[int]] = None
    ) -> tuple[int, Optional[GotWork]]:
        """Fused blocking reserve+get (no reference analogue — upstream
        always pays a second round trip for the payload, reference
        ``src/adlb.c:2976-3025``). When the matched unit is local to the
        responding server and has no batch-common prefix, the payload rides
        the reservation response; otherwise this transparently falls back
        to the handle + Get_reserved path (remote holders, prefixed
        units)."""
        with self._span("adlb:get_work"):
            types = normalize_req_types(req_types, self.world.types)
            sleep = 0.0
            while True:
                resp = self._reserve_rpc(
                    req_types=None if types is None else sorted(types),
                    hang=True,
                    fetch=True,
                )
                if resp.rc != ADLB_SUCCESS:
                    return resp.rc, None
                rc, got = self._decode_single_got(resp)
                if rc != ADLB_RETRY:
                    return rc, got
                # void handle (failover tombstone / reclaim resurrect):
                # the unit is gone — re-reserve rather than surface a
                # transient code as termination
                self._m_reserve_retries.inc()
                sleep = self._backoff_sleep(sleep)

    def _decode_single_got(self, resp) -> tuple[int, Optional[GotWork]]:
        """Decode a successful single-unit TA_RESERVE_RESP: fused (payload
        inline — whole for prefix-free units, suffix + common handle for
        batch-common ones) or handle fallback (e.g. a native server that
        predates the remote fuse)."""
        if "payload" in resp.data:  # fused: already consumed
            payload = resp.payload
            if resp.data.get("common_len", 0) > 0:
                rc, prefix = self._fetch_prefix(
                    resp.common_server, resp.common_seqno
                )
                if rc != ADLB_SUCCESS:
                    # prefix gone (reclaim edge): a truncated payload
                    # must never look like success
                    return rc, None
                payload = prefix + payload
            got = GotWork(
                work_type=resp.work_type,
                work_prio=resp.prio,
                payload=payload,
                answer_rank=resp.answer_rank,
                time_on_q=resp.data.get("time_on_q", 0.0),
            )
            if self.tracer is not None:
                self.tracer.got_work(got.work_type)
            return ADLB_SUCCESS, got
        handle = WorkHandle.from_ints(resp.handle)
        rc, buf, t_q = self._get_reserved_timed(handle)
        if rc != ADLB_SUCCESS:
            return rc, None
        if self.tracer is not None:
            self.tracer.got_work(resp.work_type)
        return ADLB_SUCCESS, GotWork(
            work_type=resp.work_type,
            work_prio=resp.prio,
            payload=buf,
            answer_rank=resp.answer_rank,
            time_on_q=t_q,
        )

    def get_work_batch(
        self,
        req_types: Optional[Sequence[int]] = None,
        max_units: int = 8,
    ) -> tuple[int, list[GotWork]]:
        """Blocking fused reserve+get of up to ``max_units`` units in ONE
        round trip (no reference analogue). The responding server inlines
        as many LOCAL prefix-free matches as it holds (capped at
        ``max_units``); remote holders and prefixed units fall back to the
        single-unit path, so a batch never costs extra round trips — it
        only amortizes them when the balancer has pre-positioned local
        inventory. Returns ``(ADLB_SUCCESS, [GotWork, ...])`` (at least
        one), or ``(rc, [])`` on termination."""
        if max_units < 1:
            raise AdlbError("get_work_batch: max_units must be >= 1")
        with self._span("adlb:get_work_batch"):
            types = normalize_req_types(req_types, self.world.types)
            sleep = 0.0
            while True:
                resp = self._reserve_rpc(
                    req_types=None if types is None else sorted(types),
                    hang=True,
                    fetch=True,
                    fetch_max=max_units,
                )
                if resp.rc != ADLB_SUCCESS:
                    return resp.rc, []
                if "payloads" in resp.data:  # batch-fused: already consumed
                    out = []
                    d = resp.data
                    for i, payload in enumerate(d["payloads"]):
                        out.append(GotWork(
                            work_type=d["work_types"][i],
                            work_prio=d["prios"][i],
                            payload=payload,
                            answer_rank=d["answer_ranks"][i],
                            time_on_q=d["times_on_q"][i],
                        ))
                        if self.tracer is not None:
                            self.tracer.got_work(d["work_types"][i])
                    return ADLB_SUCCESS, out
                # single-unit response (a park wake-up, a remote/prefixed
                # fallback, or a server that ignores fetch_max)
                rc, got = self._decode_single_got(resp)
                if rc != ADLB_RETRY:
                    return rc, [got] if got is not None else []
                # void handle (failover tombstone / reclaim resurrect):
                # re-reserve with backoff, as get_work does
                self._m_reserve_retries.inc()
                sleep = self._backoff_sleep(sleep)

    # -- prefetch pipeline (get_work_stream) ----------------------------------

    def get_work_stream(
        self, req_types: Optional[Sequence[int]] = None, depth: int = 2
    ) -> "WorkStream":
        """Iterator of :class:`GotWork` that keeps up to ``depth`` fused
        reserves in flight so the next unit's delivery overlaps the
        current unit's compute (no reference analogue — upstream's
        consumer loop serializes Reserve and Get_reserved round trips
        against the work itself). Ends cleanly at NO_MORE_WORK /
        DONE_BY_EXHAUSTION (the termination code is left in ``.rc``);
        ADLB_RETRY deliveries (reclaim-mode resurrection) re-arm the
        slot with backoff. Toward a native home server — which has no
        multi-entry reserve queue — the stream degrades to repeated
        fused ``get_work`` calls."""
        types = normalize_req_types(req_types, self.world.types)
        if self.home in getattr(self.ep, "binary_peers", ()):
            return _SerialStream(self, req_types)
        if self._active_stream is not None:
            raise AdlbError("only one get_work_stream may be open at a time")
        stream = WorkStream(self, types, depth)
        self._active_stream = stream
        return stream

    # -- app <-> app messaging (the reference's app_comm) ---------------------
    #
    # ADLB_Init returns an app-ranks-only communicator on which applications
    # exchange ordinary point-to-point messages alongside ADLB calls — e.g.
    # c1.c ships B/C answers rank-to-rank with MPI_Send/Iprobe/Recv on
    # app_comm (reference src/adlb.c:256,318; examples/c1.c). Here the same
    # fabric carries those messages under the AM_APP tag with a user tag
    # inside; app rank numbering coincides with world rank numbering for
    # ranks < num_app_ranks, as in the reference (src/adlb.c:252-257).

    def app_send(self, dest_app_rank: int, payload, apptag: int = 0) -> None:
        """Point-to-point message to another app rank (MPI_Send on app_comm)."""
        if not (0 <= dest_app_rank < self.world.num_app_ranks):
            raise AdlbError(f"app_send: {dest_app_rank} is not an app rank")
        self.ep.send(
            dest_app_rank,
            msg(Tag.AM_APP, self.rank, payload=payload, apptag=int(apptag)),
        )

    def _match_app(self, apptag: Optional[int], src: Optional[int]) -> Optional[int]:
        for i, m in enumerate(self._app_inbox):
            if apptag is not None and m.apptag != apptag:
                continue
            if src is not None and m.src != src:
                continue
            return i
        return None

    def app_iprobe(
        self, apptag: Optional[int] = None, src: Optional[int] = None
    ) -> bool:
        """Non-blocking check for a pending app message (MPI_Iprobe)."""
        self._drain_inbox()
        return self._match_app(apptag, src) is not None

    def app_recv(
        self,
        apptag: Optional[int] = None,
        src: Optional[int] = None,
        timeout: Optional[float] = None,
    ):
        """Receive an app message; returns (payload, src_rank, apptag).

        Blocks until a matching message arrives (MPI_Recv), or returns None
        on timeout.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            # drain already-delivered frames first so a zero/expired timeout
            # still sees messages sitting in the endpoint queue
            self._drain_inbox()
            i = self._match_app(apptag, src)
            if i is not None:
                m = self._app_inbox.pop(i)
                return m.payload, m.src, m.apptag
            if self._abort_event is not None and self._abort_event.is_set():
                self.aborted = True
                raise AdlbAborted(-1)
            remaining = 0.2
            if deadline is not None:
                remaining = min(remaining, deadline - time.monotonic())
                if remaining <= 0:
                    return None
            m = self._recv(timeout=remaining)
            if m is None:
                continue
            self._dispatch_passive(m)

    def _drain_inbox(self) -> None:
        """Pull everything already delivered without blocking."""
        while True:
            m = self._recv(timeout=0.0)
            if m is None:
                return
            self._dispatch_passive(m)

    def _dispatch_passive(self, m: Msg, waiting: Optional[Tag] = None) -> None:
        """Handle a message that is not the awaited response: abort frames
        raise, app messages are stashed, pipelined-put responses are
        settled, anything else is a protocol error."""
        if m.tag is Tag.TA_ABORT:
            self.aborted = True
            code = m.data.get("code", -1)
            self.flight.record(f"TA_ABORT code={code} from {m.src}")
            self.flight.dump_json("abort")
            raise AdlbAborted(code)
        if m.tag is Tag.AM_APP:
            self._app_inbox.append(m)
            return
        if m.tag is Tag.TA_HOME_TAKEOVER:
            self._apply_takeover(m)
            return
        if (
            m.tag is Tag.TA_PUT_RESP
            and m.data.get("put_id") in self._pending_puts
        ):
            self._settle_put(m)
            return
        if m.tag is Tag.TA_PUT_RESP and m.data.get("put_id") is not None:
            # stale duplicate ack of an already-settled re-sent put
            return
        if (
            m.tag is Tag.TA_RESERVE_RESP
            and self._active_stream is not None
        ):
            # a stream delivery arriving while the client is inside some
            # other wait (a put settle, a prefix fetch, an app_recv):
            # banked raw — decode (which may itself do nested RPCs)
            # happens in stream context, never here
            self._active_stream._on_resp(m)
            return
        if m.tag in (
            Tag.TA_RESERVE_RESP,
            Tag.TA_GET_RESERVED_RESP,
            Tag.TA_GET_COMMON_RESP,
            # a late/duplicate stream-cancel ack (the close() drain
            # already settled, or a re-sent cancel was acked twice)
            Tag.TA_STREAM_CANCEL_RESP,
            # a duplicated dead-letter listing (re-sent across churn)
            Tag.TA_QUARANTINED_RESP,
            # a duplicated membership verdict (detach re-sent across
            # churn; the first response already settled the call)
            Tag.TA_MEMBER_RESP,
        ):
            # stray replay: a request re-sent across connection churn can
            # be answered twice (the server replays its at-most-once
            # cache); the first response already settled the call
            self.flight.record(f"dropped stray {m.tag.name} from {m.src}")
            return
        if m.tag is Tag.PEER_EOF:
            if self._failover_policy() and self.world.is_server(m.src):
                # a server died but the world may survive it: note the
                # loss (bounding the takeover wait) and keep going — the
                # buddy's TA_HOME_TAKEOVER remaps us, and the blocking
                # waits re-send toward it (see _wait)
                self._lost_at.setdefault(m.src, time.monotonic())
                self.flight.record(
                    f"server {m.src} connection lost; awaiting takeover"
                )
                return
            if m.src == self._route(self.home):
                # the lifeline is gone: error out instead of hanging in the
                # next blocking wait (reference: rank failure kills the job)
                self.aborted = True
                self.flight.record(f"home server {m.src} connection lost")
                self.flight.dump_json("home_server_lost")
                raise HomeServerLostError(
                    f"rank {self.rank}: home server {m.src} connection lost"
                )
            return  # other peers closing is normal at termination
        ctx = f" while waiting {waiting}" if waiting is not None else ""
        raise AdlbError(f"rank {self.rank}: unexpected {m.tag}{ctx}")

    # -- server failover ------------------------------------------------------

    def _apply_takeover(self, m: Msg) -> None:
        """An epoch-stamped TA_HOME_TAKEOVER from the buddy that adopted
        a dead server: install the remap, re-point home if it was the
        casualty, re-send pipelined puts that were awaiting the dead
        server's ack (the buddy's replicated dedup window absorbs
        duplicates), and re-arm an open stream's in-flight reserves."""
        dead, buddy, epoch = m.dead, m.src, m.data.get("epoch", 0)
        if self._srv_route.get(dead) == buddy:
            return  # duplicate note
        old_home = self._route(self.home)
        self._fo_epoch = max(self._fo_epoch, epoch)
        self._srv_route[dead] = buddy
        self._lost_at.pop(dead, None)
        self._m_failovers.inc()
        self.flight.record(
            f"home_takeover dead={dead} buddy={buddy} epoch={epoch}"
        )
        home_moved = self._route(self.home) != old_home
        # master succession rides the same note: the promoted deputy
        # stamps new_master so job control / detach re-point to it
        nm = m.data.get("new_master")
        if nm is not None:
            self._master_rank = int(nm)
        # pipelined puts parked on the dead server's ack: re-send (same
        # put_id — the replicated per-sender window makes this idempotent
        # when the original was accepted before the death)
        for put_id, req in list(self._pending_puts.items()):
            if self._route(req["server"]) != req["server"]:
                req["server"] = self._route(req["server"])
                self._send_iput(put_id, req)
        if home_moved and self._active_stream is not None:
            self._active_stream._on_takeover()

    def _check_lost_servers(self) -> None:
        """Raise when a lost server's takeover window expired with no
        buddy announcement (double failure / master death): blocked
        loops must not wait forever."""
        if not self._lost_at:
            return
        now = time.monotonic()
        for srv, t0 in list(self._lost_at.items()):
            if self._route(srv) != srv:
                self._lost_at.pop(srv, None)
                continue
            if now - t0 > self.cfg.failover_client_wait:
                self.aborted = True
                self.flight.record(
                    f"server {srv} lost; no takeover within "
                    f"{self.cfg.failover_client_wait}s"
                )
                self.flight.dump_json("home_server_lost")
                raise HomeServerLostError(
                    f"rank {self.rank}: server {srv} lost; no takeover"
                )

    # -- pipelined puts -------------------------------------------------------
    #
    # No reference analogue: upstream's Put is a synchronous two-phase
    # exchange per unit (reference src/adlb.c:2811-2843), which caps a
    # producer at one network round trip per unit. iput() streams requests
    # with a client-chosen put_id echoed in the response; flush_puts()
    # settles them, replaying rejects at the hinted server like the
    # synchronous retry loop.

    def iput(
        self,
        payload: bytes,
        work_type: int,
        work_prio: int = 0,
        target_rank: int = -1,
        answer_rank: int = -1,
    ) -> int:
        """Asynchronous put: returns ADLB_SUCCESS when queued locally; the
        accept/reject outcome settles at :meth:`flush_puts`. Not usable
        inside a batch-common region (the prefix refcount must be exact)."""
        if self._batch is not None:
            raise AdlbError("iput inside begin_batch_put is not supported")
        if not self.world.validate_type(work_type):
            raise AdlbError(f"unregistered work type {work_type}")
        self._validate_target(target_rank)
        # opportunistically settle responses already delivered, so a pure
        # producer loop's pending map (payload copies!) and the transport
        # queue stay bounded by in-flight work, not the whole stream
        while True:
            m = self._recv(timeout=0.0)
            if m is None:
                break
            self._dispatch_passive(m)
        server = self._route_put(target_rank)
        put_id = self._next_put_id
        self._next_put_id += 1
        req = dict(
            payload=bytes(payload), work_type=work_type, prio=work_prio,
            target_rank=target_rank, answer_rank=answer_rank,
            attempts=0, server=server, job=self.job,
            trace=self._sample_trace(),
        )
        self._pending_puts[put_id] = req
        self._send_iput(put_id, req)
        return ADLB_SUCCESS

    def _send_iput(self, put_id: int, req: dict) -> None:
        pm = msg(
            Tag.FA_PUT,
            self.rank,
            payload=req["payload"],
            work_type=req["work_type"],
            prio=req["prio"],
            target_rank=req["target_rank"],
            answer_rank=req["answer_rank"],
            common_len=0,
            common_server=-1,
            common_seqno=-1,
            put_id=put_id,
        )
        if req.get("job"):
            pm.data["job_id"] = req["job"]
        if req.get("trace"):
            pm.data["trace_id"] = req["trace"]
        self._send_retry(req["server"], pm)

    def _settle_put(self, m: Msg) -> None:
        put_id = m.put_id
        req = self._pending_puts[put_id]
        rc = m.rc
        if rc == ADLB_BACKOFF:
            # backpressured pipelined put: re-send after a pause floored
            # at the server's retry-after hint, without burning the
            # retry budget — replaying at the reject pace would hit the
            # saturated server ~12x faster than it asked. Still capped:
            # settles run inline in whatever recv loop the client is
            # blocked in, so one backpressured put must not stall it.
            self._m_put_backoffs.inc()
            hint_s = self._jitter_hint(
                (m.data.get("retry_after_ms") or 0) / 1e3, 0.05
            )
            slept = self._backoff_sleep(req.get("sleep", 0.0), cap=0.05)
            if hint_s > slept:
                time.sleep(hint_s - slept)
                slept = hint_s
            req["sleep"] = slept
            self._send_iput(put_id, req)
            return
        if rc in (ADLB_PUT_REJECTED, ADLB_RETRY):
            req["attempts"] += 1
            if req["attempts"] <= self.cfg.put_max_retries:
                if rc == ADLB_PUT_REJECTED:
                    req["server"] = self._retry_server(m.data.get("hint"))
                # pacing like the synchronous retry loop (backoff +
                # jitter): without it all retries burn in a few RTTs while
                # consumers are still draining the full servers. Tightly
                # capped: settles run inline in whatever recv loop the
                # client is blocked in (a reserve must not stall 250 ms
                # because an unrelated pipelined put got rejected).
                self._m_put_retries.inc()
                req["sleep"] = self._backoff_sleep(
                    req.get("sleep", 0.0), cap=0.02
                )
                self._send_iput(put_id, req)
                return
        del self._pending_puts[put_id]
        if rc != ADLB_SUCCESS:
            self._failed_puts += 1
            if rc == ADLB_NO_MORE_WORK:
                # termination, not capacity: the producer must see it
                self._failed_nmw = True
            return
        target = req["target_rank"]
        if target >= 0 and req["server"] != self.world.home_server(target):
            self._send_retry(
                self.world.home_server(target),
                msg(
                    Tag.FA_DID_PUT_AT_REMOTE,
                    self.rank,
                    target_rank=target,
                    work_type=req["work_type"],
                    server_rank=req["server"],
                ),
            )

    def flush_puts(self) -> int:
        """Settle every outstanding iput. Returns ADLB_SUCCESS when all were
        accepted; ADLB_NO_MORE_WORK when any failed because the world
        terminated (the producer's stop signal, like the synchronous put's
        rc); else ADLB_PUT_REJECTED for capacity failures after retries."""
        while self._pending_puts:
            if self._abort_event is not None and self._abort_event.is_set():
                self.aborted = True
                raise AdlbAborted(-1)
            self._check_lost_servers()
            m = self._recv(timeout=0.5)
            if m is None:
                continue
            self._dispatch_passive(m)
        failed, self._failed_puts = self._failed_puts, 0
        nmw, self._failed_nmw = self._failed_nmw, False
        if nmw:
            return ADLB_NO_MORE_WORK
        return ADLB_PUT_REJECTED if failed else ADLB_SUCCESS

    # -- control -------------------------------------------------------------

    def set_problem_done(self) -> int:
        """Explicit termination (reference ADLB_Set_problem_done,
        ``src/adlb.c:3054-3062``). Attached to a non-default job, this
        terminates the JOB (drain), not the world — the fleet keeps
        serving every other namespace."""
        if self.job:
            rc, _state = self.drain_job(self.job)
            return rc
        with self._span("adlb:set_problem_done"):
            self._send_retry(self.home, msg(Tag.FA_NO_MORE_WORK, self.rank))
        return ADLB_SUCCESS

    # -- job control plane (service mode) ------------------------------------

    def _master(self) -> int:
        """The CURRENT master: the promoted deputy once a
        TA_HOME_TAKEOVER note stamped new_master, else the spec's."""
        if self._master_rank is not None:
            return self._master_rank
        return self.world.master_server_rank

    def _job_ctl(self, op: str, job_id: int = 0, name: str = "",
                 quota_bytes: int = 0, dest=None) -> Msg:
        """One FA_JOB_CTL round trip: attach goes to the HOME server
        (which owns this rank's exhaustion vote); submit/drain/kill/
        status go to the MASTER (which owns the job table and fan-out)."""
        dest = self._master() if dest is None else dest
        fields = dict(op=op, job_id=job_id)
        if name:
            fields["job_name"] = name
        if quota_bytes:
            fields["quota"] = quota_bytes
        pm = msg(Tag.FA_JOB_CTL, self.rank, **fields)
        self._send_retry(dest, pm)
        return self._wait(Tag.TA_JOB_CTL_RESP, dest=dest, m_req=pm)

    def detach(self) -> int:
        """Cleanly LEAVE the world (elastic membership): settle every
        pipelined put, then ask the MASTER to drop this rank from
        membership. The master fans the change to every server (ack-
        barriered), so exhaustion/END counting and /healthz forget this
        rank before the reply lands. After a successful detach,
        finalize() is a no-op and the endpoint can simply close.

        Returns ADLB_SUCCESS, or ADLB_NO_MORE_WORK when termination was
        already underway — then a plain finalize() is the right exit
        (and this client does NOT mark itself detached)."""
        with self._span("adlb:detach"):
            if self._active_stream is not None:
                try:
                    self._active_stream.close()
                except Exception:  # teardown races: best-effort
                    self._active_stream = None
            if self._pending_puts:
                self.flush_puts()
            master = self._master()
            pm = msg(Tag.FA_MEMBER, self.rank, mop="detach")
            self._send_retry(master, pm)
            resp = self._wait(Tag.TA_MEMBER_RESP, dest=master, m_req=pm)
        rc = resp.data.get("rc", -1)
        if rc == ADLB_SUCCESS:
            self._detached = True
            self._stop_heartbeat()
            self.flight.record("detached from world")
        return rc

    def attach(self, job_id: int) -> int:
        """Bind this rank to a job namespace on the running fleet: every
        subsequent put/reserve/stream rides in it, and this rank's
        parked-ness counts toward THAT job's exhaustion. attach(0)
        returns to the default namespace."""
        with self._span("adlb:attach", job=job_id):
            resp = self._job_ctl("attach", job_id, dest=self.home)
        if resp.rc == ADLB_SUCCESS:
            self.job = job_id
        return resp.rc

    def submit_job(self, name: str = "",
                   quota_bytes: int = 0) -> tuple[int, int]:
        """Create a namespace on the fleet (master allocates the id and
        fans it out). Returns (rc, job_id). ``quota_bytes`` bounds the
        job's queued bytes PER SERVER; 0 = unlimited."""
        with self._span("adlb:submit_job"):
            resp = self._job_ctl("submit", name=name,
                                 quota_bytes=quota_bytes)
        return resp.rc, resp.data.get("job_id", -1)

    def drain_job(self, job_id: int) -> tuple[int, int]:
        """No new puts for the job; queued work completes, then the
        per-job exhaustion ring marks it done. Returns (rc, job_id)."""
        with self._span("adlb:drain_job", job=job_id):
            resp = self._job_ctl("drain", job_id)
        return resp.rc, resp.data.get("job_id", job_id)

    def kill_job(self, job_id: int) -> tuple[int, int]:
        """Drop the job's queued work everywhere and flush its parked
        requesters with ADLB_NO_MORE_WORK. Returns (rc, job_id)."""
        with self._span("adlb:kill_job", job=job_id):
            resp = self._job_ctl("kill", job_id)
        return resp.rc, resp.data.get("job_id", job_id)

    def job_status(self, job_id: int) -> tuple[int, Optional[dict]]:
        """The master's view of a job (state, quota, counters)."""
        resp = self._job_ctl("status", job_id)
        return resp.rc, resp.data.get("status")

    def checkpoint(self, path_prefix: str) -> tuple[int, int]:
        """Snapshot the whole pool to ``<path_prefix>.<server>.ckpt`` shards
        (no reference analogue — upstream loses all queued work on exit).
        Returns (rc, units captured). Units pinned mid-handoff are captured
        too (a restore rolls the pool back to the snapshot, so work consumed
        after it is re-executed — the standard crash-recovery contract);
        restore with ``Config(restore_path=path_prefix)`` on an identical
        world shape."""
        # native servers take the path over the binary codec (bytes);
        # Python servers take the str through the pickled frame — both
        # write the same ACK1 shards, so either plane restores the other's
        path = (
            path_prefix.encode()
            if self.cfg.server_impl == "native" else path_prefix
        )
        with self._span("adlb:checkpoint"):
            pm = msg(Tag.FA_CHECKPOINT, self.rank, path=path)
            self._send_retry(self.home, pm)
            resp = self._wait(Tag.TA_CHECKPOINT_RESP, dest=self.home,
                              m_req=pm)
        return resp.rc, resp.count

    def info_get(self, key: int) -> tuple[int, float]:
        """One live stats value from this rank's home server (reference
        ADLB_Info_get, ``src/adlb.c:3072-3141``)."""
        pm = msg(Tag.FA_INFO_GET, self.rank, key=int(key))
        self._send_retry(self.home, pm)
        resp = self._wait(Tag.TA_INFO_GET_RESP, dest=self.home, m_req=pm)
        return resp.rc, resp.value

    def info_num_work_units(self, work_type: int) -> tuple[int, int, int, int]:
        """(rc, count, total bytes, max wq count) at the home server
        (reference ``src/adlb.c:3027-3046``)."""
        pm = msg(Tag.FA_INFO_NUM_WORK_UNITS, self.rank,
                 work_type=work_type)
        self._send_retry(self.home, pm)
        resp = self._wait(Tag.TA_INFO_NUM_RESP, dest=self.home, m_req=pm)
        return resp.rc, resp.count, resp.nbytes, resp.max_wq

    def extend_lease(self, handle: WorkHandle) -> int:
        """Explicitly renew this rank's lease on a reserved-but-unfetched
        unit (Config(lease_timeout_s) > 0): a unit whose decode/compute
        legitimately outlives the timeout opts out of expiry without
        raising the whole rank's timeout. Fire-and-forget toward the
        holding server (liveness piggybacks on the frame either way); a
        lease already expired stays expired — the eventual fetch answers
        ADLB_FENCED and the caller re-reserves."""
        with self._span("adlb:extend_lease", seqno=handle.seqno):
            self._send_retry(
                handle.server_rank,
                msg(Tag.FA_HEARTBEAT, self.rank, seqno=handle.seqno),
            )
        return ADLB_SUCCESS

    def get_quarantined(self) -> tuple[int, list[dict]]:
        """Retrieve the dead-letter quarantine: every unit the world
        moved aside after it exhausted Config(max_unit_retries), as
        plain dicts (payload + metadata + attempt count + the holding
        server). Aggregated across live Python servers; native servers
        hold no quarantine (the policy requires server_impl='python')."""
        records: list[dict] = []
        with self._span("adlb:get_quarantined"):
            seen: set[int] = set()
            for srv in self.world.server_ranks:
                dest = self._route(srv)
                if dest in seen:
                    continue  # failed-over: its buddy holds the store
                seen.add(dest)
                if dest in getattr(self.ep, "binary_peers", ()):
                    continue
                pm = msg(Tag.FA_GET_QUARANTINED, self.rank)
                self._send_retry(dest, pm)
                resp = self._wait(Tag.TA_QUARANTINED_RESP, dest=dest,
                                  m_req=pm)
                d = resp.data
                suffix_onlys = d.get("suffix_onlys") or ()
                for i, seqno in enumerate(d.get("seqnos") or ()):
                    records.append(
                        {
                            "seqno": seqno,
                            "work_type": d["work_types"][i],
                            "prio": d["prios"][i],
                            "target_rank": d["target_ranks"][i],
                            "answer_rank": d["answer_ranks"][i],
                            "attempts": d["attempts_list"][i],
                            "payload": d["payloads"][i],
                            "server_rank": resp.src,
                            # payload is a fused member's suffix whose
                            # prefix did not survive on the answering
                            # server
                            "suffix_only": bool(
                                suffix_onlys[i] if i < len(suffix_onlys)
                                else 0
                            ),
                        }
                    )
        return ADLB_SUCCESS, records

    def finalize(self) -> int:
        if self._detached:
            # the rank already left membership: there is no home-server
            # accounting left to settle (FA_LOCAL_APP_DONE from a
            # non-member would be noise)
            return ADLB_SUCCESS
        if self.tracer is not None:
            self.tracer.api_entry()  # close any open inferred user span
        self._stop_heartbeat()
        rc = ADLB_SUCCESS
        if not self.aborted:
            if self._active_stream is not None:
                # an abandoned stream's parked reserves must be cancelled
                # (and any banked deliveries handed back to the pool)
                # before LOCAL_APP_DONE, or the server would keep
                # matching work to a rank that will never read it
                try:
                    self._active_stream.close()
                except Exception:  # teardown races: cancel best-effort
                    self._active_stream = None
            if self._pending_puts:
                # un-settled pipelined puts must land before LOCAL_APP_DONE
                # or the shutdown ring could outrun them; a terminal failure
                # here must not vanish silently
                rc = self.flush_puts()
                if rc not in (ADLB_SUCCESS, ADLB_NO_MORE_WORK):
                    import sys

                    print(
                        f"[adlb rank {self.rank}] finalize: pipelined puts "
                        f"terminally rejected (rc={rc})",
                        file=sys.stderr,
                    )
            self._send_retry(self.home, msg(Tag.FA_LOCAL_APP_DONE,
                                            self.rank))
        return rc

    def abort(self, code: int) -> None:
        """Bring the whole world down (reference ADLB_Abort,
        ``src/adlb.c:3165-3176``)."""
        self.aborted = True
        self._stop_heartbeat()
        self.flight.record(f"this rank called abort({code})")
        self.flight.dump_json("abort_initiated")
        try:
            self.ep.send(self._route(self.home),
                         msg(Tag.FA_ABORT, self.rank, code=code))
        except OSError:
            pass  # the abort_event still propagates in-harness
        if self._abort_event is not None:
            self._abort_event.set()
        raise AdlbAborted(code)


class WorkStream:
    """Client half of the prefetch pipeline (``get_work_stream``).

    Keeps up to ``depth`` fused prefetch reserves in flight at the home
    server; deliveries are banked raw (:class:`Msg`) by whatever recv
    loop sees them and decoded — including prefix-cache assembly and the
    handle fallback's fetch — only in stream context, so no nested RPC
    ever runs inside a passive dispatch. Exhaustion safety: prefetch
    parks only count as idle after this client reports an empty bank
    (FA_STREAM_IDLE), so work banked here can still put descendants
    before the world is allowed to declare exhaustion.
    """

    def __init__(self, client: Client, types, depth: int) -> None:
        self._c = client
        self._types = types  # normalized frozenset or None
        self._depth = max(1, int(depth))
        self._bank: deque[Msg] = deque()
        # outstanding reserve ids: responses echo rqseqno, so matching
        # by id both accounts the slots exactly and dedups duplicated
        # responses (a frame re-sent across reconnect) for free
        self._outstanding: set[int] = set()
        self._retry = 0
        self._retry_sleep = 0.0
        self._idle_sent = False
        self._idle_sent_at = 0.0
        self._closed = False
        self.rc: Optional[int] = None  # termination code once observed

    # re-announce idleness at this cadence while blocked: a note lost to
    # churn (or voided server-side — count mismatch, reclaim sweep) must
    # not wedge the exhaustion vote forever, and the swept-stream re-arm
    # (ADLB_RETRY per phantom slot) is triggered by exactly this re-send
    IDLE_REANNOUNCE_S = 1.0

    def __iter__(self) -> Iterator[GotWork]:
        return self

    # -- wiring --------------------------------------------------------------

    def _send_one(self) -> None:
        c = self._c
        c._rqseqno += 1
        self._outstanding.add(c._rqseqno)
        pm = msg(
            Tag.FA_RESERVE,
            c.rank,
            rqseqno=c._rqseqno,
            req_types=None if self._types is None
            else sorted(self._types),
            hang=True,
            fetch=True,
            prefetch=True,
        )
        if c.job:
            pm.data["job_id"] = c.job
        c._send_retry(c.home, pm)

    def _pump(self) -> None:
        if self.rc is not None or self._closed:
            return
        while len(self._outstanding) + len(self._bank) < self._depth:
            self._send_one()

    def _on_takeover(self) -> None:
        """The home server failed over: every reserve parked at the dead
        server is void — re-arm each slot toward the buddy (the retry
        path sends fresh rqseqnos with backoff, in stream context)."""
        n = len(self._outstanding)
        if n == 0:
            return
        self._c.flight.record(
            f"stream: re-arming {n} in-flight reserves after takeover"
        )
        self._outstanding.clear()
        self._retry += n
        self._idle_sent = False

    def _on_resp(self, m: Msg) -> None:
        """Bank one reservation response (called from the client's
        dispatch — NO decoding, no nested RPCs here). Matched by the
        echoed rqseqno: a response whose id is not outstanding is a
        duplicate (re-sent across reconnect) or a stray — processing it
        would run a unit twice, so it is dropped."""
        rid = m.data.get("rqseqno")
        if rid is None or rid not in self._outstanding:
            self._c.flight.record(
                f"stream: dropped stray/duplicate delivery (rqseqno={rid})"
            )
            return
        self._outstanding.discard(rid)
        rc = m.rc
        if rc == ADLB_SUCCESS:
            self._bank.append(m)
            # the delivery un-idled us server-side; re-announce next
            # time the bank runs dry
            self._idle_sent = False
        elif rc == ADLB_RETRY:
            # reclaim-mode resurrection: this rank reconnected while its
            # death fan-out settled — re-arm the slot (with backoff, in
            # stream context). Re-announce idleness afterwards: a note
            # voided server-side (count mismatch) would otherwise never
            # be re-sent, and the exhaustion vote could wait forever.
            self._retry += 1
            self._idle_sent = False
        else:
            self.rc = rc  # NO_MORE_WORK / DONE_BY_EXHAUSTION

    def _decode(self, m: Msg) -> Optional[GotWork]:
        """Decode a banked delivery in stream context: prefix-cache
        assembly for suffix-only payloads, Get_reserved for the handle
        fallback (native servers). Returns None when the unit vanished
        in a reclaim race (recorded, stream continues)."""
        c = self._c
        if "payload" not in m.data and "handle" not in m.data:
            c.flight.record("stream: malformed delivery dropped")
            return None
        rc, got = c._decode_single_got(m)
        if rc != ADLB_SUCCESS or got is None:
            c.flight.record(f"stream: delivery decode failed rc={rc}")
            return None
        return got

    # -- iteration -----------------------------------------------------------

    def __next__(self) -> GotWork:
        c = self._c
        self._pump()
        while True:
            if self._closed and not self._bank:
                # close() cancelled the parked reserves WITHOUT answering
                # them, so the outstanding set never drains — iterating
                # past a close must stop here, not spin on a recv forever
                if c._active_stream is self:
                    c._active_stream = None
                raise StopIteration
            if self._bank:
                m = self._bank.popleft()
                self._pump()
                got = self._decode(m)
                if got is None:
                    continue
                return got
            if self._retry and self.rc is None:
                self._retry -= 1
                c._m_reserve_retries.inc()
                self._retry_sleep = c._backoff_sleep(self._retry_sleep)
                self._send_one()
                continue
            if not self._outstanding:
                # nothing banked, nothing in flight: terminated (or
                # closed mid-iteration)
                if c._active_stream is self:
                    c._active_stream = None
                self._closed = True
                raise StopIteration
            if c._abort_event is not None and c._abort_event.is_set():
                c.aborted = True
                c.flight.record("abort event observed in get_work_stream")
                c.flight.dump_json("abort_event")
                raise AdlbAborted(-1)
            now = time.monotonic()
            if self.rc is None and (
                not self._idle_sent
                or now - self._idle_sent_at >= self.IDLE_REANNOUNCE_S
            ):
                # the bank is dry and we are (still) blocked: tell the
                # home server this rank is genuinely idle, making its
                # prefetch parks eligible for the exhaustion vote. The
                # in-flight count lets the server void a note that
                # crossed a delivery on the wire (see _on_stream_idle);
                # the periodic re-announce repairs voided/lost notes and
                # triggers the swept-stream re-arm after reclaim churn.
                c._send_retry(
                    c.home,
                    msg(Tag.FA_STREAM_IDLE, c.rank,
                        slots=sorted(self._outstanding)),
                )
                self._idle_sent = True
                self._idle_sent_at = now
            c._check_lost_servers()
            m = c._recv(timeout=0.5)
            if m is not None:
                c._dispatch_passive(m)

    # -- teardown ------------------------------------------------------------

    def close(self) -> None:
        """End the stream early: cancel parked prefetch reserves at the
        server, then hand back anything already matched to us —
        handle-shaped deliveries are UNRESERVEd at their holder (the
        unit unpins and re-matches, targeting intact), fused payloads
        are re-put untargeted (their unit was already consumed). Safe to
        call after normal exhaustion too (no-op then)."""
        if self._closed:
            if self._c._active_stream is self:
                self._c._active_stream = None
            return
        self._closed = True
        c = self._c
        try:
            if self.rc is None and self._outstanding:
                c._send_retry(c.home, msg(Tag.FA_STREAM_CANCEL, c.rank))
                # deliveries that raced the cancel arrive BEFORE the ack
                # (per-peer FIFO with the home server)
                deadline = time.monotonic() + 10.0
                while time.monotonic() < deadline:
                    m = c._recv(timeout=0.2)
                    if m is None:
                        continue
                    if m.tag is Tag.TA_STREAM_CANCEL_RESP:
                        break
                    c._dispatch_passive(m)
            while self._bank:
                m = self._bank.popleft()
                if "handle" in m.data and "payload" not in m.data:
                    h = WorkHandle.from_ints(m.handle)
                    c._send_retry(
                        h.server_rank,
                        msg(Tag.SS_UNRESERVE, c.rank, seqno=h.seqno,
                            for_rank=c.rank),
                    )
                    continue
                got = self._decode(m)
                if got is not None:
                    # fused responses carry the unit's target_rank (if
                    # any) precisely so this re-put can preserve the
                    # only-the-target-may-run-it contract
                    c._put(got.payload, got.work_type, got.work_prio,
                           int(m.data.get("target_rank", -1)),
                           got.answer_rank)
        finally:
            if c._active_stream is self:
                c._active_stream = None

    def __enter__(self) -> "WorkStream":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


class _SerialStream:
    """Degraded stream toward a native home server (no multi-entry
    reserve queue there): repeated fused ``get_work`` calls — still one
    round trip per unit, just no overlap."""

    def __init__(self, client: Client, req_types) -> None:
        self._c = client
        self._types = req_types
        self.rc: Optional[int] = None

    def __iter__(self):
        return self

    def __next__(self) -> GotWork:
        if self.rc is not None:
            raise StopIteration
        rc, got = self._c.get_work(self._types)
        if rc != ADLB_SUCCESS or got is None:
            self.rc = rc
            raise StopIteration
        return got

    def close(self) -> None:
        pass

    def __enter__(self) -> "_SerialStream":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()
