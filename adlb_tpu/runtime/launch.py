"""Multi-host world launcher — the ``mpiexec -n k`` replacement.

The reference's deployment story is MPI's launcher (reference
``examples/README-batcher.txt:57``: ``mpiexec -n <k>``); this framework's
worlds span hosts over TCP, so the launcher's job is the rendezvous. Run
one launcher per host with that host's rank range:

    host A:  python -m adlb_tpu.runtime.launch --rendezvous /shared/w1 \
                 --nranks 8 --nservers 2 --types 1,2 --ranks 0-3 -- prog...
    host B:  python -m adlb_tpu.runtime.launch --rendezvous /shared/w1 \
                 --nranks 8 --nservers 2 --types 1,2 --ranks 4-7 -- prog...

Per rank, the launcher publishes ``<dir>/<rank>.addr`` on the shared
rendezvous directory and waits for all ``nranks`` files. Server ranks bind
first and publish their real ports (Python reactors in-launcher, native
daemons as subprocesses); app-rank ports are pre-allocated, and the app
program is exec'd with ``ADLB_RENDEZVOUS``/``ADLB_RANK``/
``ADLB_NUM_SERVERS`` set — the C client's env contract, and the one
:func:`adlb_tpu.api.join_world` reads for Python apps.

With ``--server-impl native --balancer tpu`` the JAX sidecar runs on the
master server's host, bound to that host's ``--host`` address so servers
anywhere can stream snapshots to it.

**Channel plane (multiplexed host-pair sockets).** Each launcher runs
one :class:`~adlb_tpu.runtime.channel.ChannelBroker` for its ranks and
publishes ``broker.<host>.<pid>.addr`` (address + the rank list it
serves) in the rendezvous directory; after the rendezvous every broker
learns the full rank->broker routing, so the fleet's python<->python
data plane is O(ranks + hosts^2) sockets instead of O(ranks^2).
``tcp_mux="auto"`` turns the plane ON exactly where that explosion
lives — when this launcher owns a strict subset of the world (a real
multi-launcher fleet) — and stays per-pair for single-launcher worlds
(``ADLB_TCP_MUX=1`` still forces it, the CI hook). App programs inherit
the local broker through ``ADLB_BROKER_ADDR``/``ADLB_MUX_RANKS``.

**Elastic membership** (``adlb_tpu/runtime/membership.py``): a running
world grows without restart. ``--attach N`` execs N copies of the app
program against an ALREADY-RUNNING world's rendezvous directory — each
sets ``ADLB_ATTACH=1`` so :func:`adlb_tpu.api.join_world` negotiates a
fresh rank id + home server from the master instead of reading
``ADLB_RANK``. Attached ranks ride per-pair TCP (brokers route the
static world; the ``mux_ranks`` bound keeps joiners off them).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import threading
import time


def _parse_ranks(spec: str) -> list[int]:
    out: list[int] = []
    for part in spec.split(","):
        if "-" in part:
            a, b = part.split("-")
            out.extend(range(int(a), int(b) + 1))
        else:
            out.append(int(part))
    return sorted(set(out))


def _publish(dirpath: str, rank: int, host: str, port: int) -> None:
    os.makedirs(dirpath, exist_ok=True)
    tmp = os.path.join(dirpath, f".{rank}.addr.tmp")
    with open(tmp, "w") as f:
        f.write(f"{host} {port}\n")
    os.replace(tmp, os.path.join(dirpath, f"{rank}.addr"))


def _await_all(dirpath: str, nranks: int, timeout: float) -> dict:
    deadline = time.monotonic() + timeout
    addr_map: dict[int, tuple[str, int]] = {}
    while len(addr_map) < nranks:
        if time.monotonic() > deadline:
            missing = sorted(set(range(nranks)) - set(addr_map))
            raise TimeoutError(
                f"rendezvous incomplete after {timeout}s: waiting for ranks "
                f"{missing[:10]}{'...' if len(missing) > 10 else ''}"
            )
        for r in range(nranks):
            if r in addr_map:
                continue
            try:
                with open(os.path.join(dirpath, f"{r}.addr")) as f:
                    h, p = f.read().split()
                addr_map[r] = (h, int(p))
            except (OSError, ValueError):
                continue
        if len(addr_map) < nranks:
            time.sleep(0.05)
    return addr_map


def _publish_broker(dirpath: str, addr: tuple, ranks) -> None:
    """Publish this launcher's channel broker: address + the world ranks
    it serves (named per launcher, so same-host launchers coexist)."""
    os.makedirs(dirpath, exist_ok=True)
    name = f"broker.{addr[0]}.{os.getpid()}.addr"
    tmp = os.path.join(dirpath, f".{name}.tmp")
    with open(tmp, "w") as f:
        f.write(f"{addr[0]} {addr[1]}\n")
        f.write(",".join(str(r) for r in sorted(ranks)) + "\n")
    os.replace(tmp, os.path.join(dirpath, name))


def _await_brokers(dirpath: str, nranks: int,
                   timeout: float) -> tuple[dict, dict]:
    """Wait until every world rank is covered by some launcher's broker
    publication; returns (rank -> hostkey, hostkey -> broker addr) for
    :meth:`ChannelBroker.set_routes`. Mixed-config fleets (one launcher
    muxed, another not) time out loudly here instead of wedging later."""
    deadline = time.monotonic() + timeout
    while True:
        rank_host: dict[int, str] = {}
        broker_addrs: dict[str, tuple[str, int]] = {}
        try:
            names = os.listdir(dirpath)
        except OSError:
            names = []
        for fn in names:
            if not (fn.startswith("broker.") and fn.endswith(".addr")):
                continue
            try:
                with open(os.path.join(dirpath, fn)) as f:
                    addr_line, ranks_line = f.read().split("\n")[:2]
                h, p = addr_line.split()
                hostkey = f"{h}:{int(p)}"
                broker_addrs[hostkey] = (h, int(p))
                for r in ranks_line.split(","):
                    if r:
                        rank_host[int(r)] = hostkey
            except (OSError, ValueError):
                continue
        if set(range(nranks)) <= set(rank_host):
            return rank_host, broker_addrs
        if time.monotonic() > deadline:
            missing = sorted(set(range(nranks)) - set(rank_host))
            raise TimeoutError(
                f"broker rendezvous incomplete after {timeout}s: no "
                f"broker covers ranks {missing[:10]} — is every "
                f"launcher running with the same tcp_mux setting?"
            )
        time.sleep(0.05)


def _attach_main(args) -> int:
    """``--attach N``: exec N copies of the app program against an
    ALREADY-RUNNING world (elastic membership). Each process negotiates
    a fresh rank id + home server from the master via join_world's
    ``ADLB_ATTACH`` contract — no restart, no rank-range bookkeeping."""
    merged = os.path.join(args.rendezvous, "world.addr")
    deadline = time.monotonic() + args.timeout
    while not os.path.exists(merged):
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"--attach: no running world at {merged} (the launcher "
                f"writes it after its rendezvous completes)"
            )
        time.sleep(0.1)
    if not args.prog:
        print("[adlb_launch] --attach needs an app program",
              file=sys.stderr)
        return 2
    procs = []
    for _ in range(args.attach):
        env = dict(os.environ)
        env["ADLB_RENDEZVOUS"] = merged
        env["ADLB_ATTACH"] = "1"
        env["ADLB_NUM_SERVERS"] = str(args.nservers)
        env.pop("ADLB_RANK", None)  # attached ranks are ALLOCATED
        if args.flight_dir:
            env["ADLB_FLIGHT_DIR"] = args.flight_dir
        procs.append(subprocess.Popen(args.prog, env=env))
    rc_final = 0
    for p in procs:
        try:
            p.wait(timeout=args.timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            rc_final = rc_final or 1
        rc_final = rc_final or (p.returncode or 0)
    return rc_final


def _check_port_clash(addr_map: dict) -> None:
    """Fail fast if two ranks published the same (host, port).

    Concurrent same-host launchers probe with closed sockets and then sit
    in the rendezvous for up to --timeout, so overlapping probe subranges
    can (rarely) hand two ranks one port; the second bind would die
    mid-world and the failure-detection abort would take everything with
    it, minutes later and with a misleading message. Every launcher sees
    the full map here, so they all fail loudly and immediately instead —
    a relaunch redraws the PID-staggered ranges."""
    owners: dict[tuple, list] = {}
    for r, a in sorted(addr_map.items()):
        owners.setdefault(tuple(a), []).append(r)
    clash = {a: rs for a, rs in owners.items() if len(rs) > 1}
    if clash:
        raise RuntimeError(
            f"rendezvous published duplicate addresses {clash}; "
            f"relaunch the world"
        )


def write_rendezvous_file(path: str, addr_map: dict) -> None:
    """The single-file format the C client reads (rank host port lines)."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        for r, (h, p) in sorted(addr_map.items()):
            f.write(f"{r} {h} {p}\n")
    os.replace(tmp, path)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Launch this host's share of an adlb-tpu world."
    )
    ap.add_argument("--rendezvous", required=True,
                    help="shared directory for the world's rendezvous")
    ap.add_argument("--nranks", type=int, default=None)
    ap.add_argument("--nservers", type=int, required=True)
    ap.add_argument("--types", required=True,
                    help="comma-separated work types, e.g. 1,2,3")
    ap.add_argument("--ranks", default=None,
                    help="this host's world ranks, e.g. 0-3 or 0,2,5")
    ap.add_argument("--attach", type=int, default=0, metavar="N",
                    help="elastic membership: attach N NEW app ranks to "
                         "an ALREADY-RUNNING world on this rendezvous "
                         "directory and exec the program once per rank "
                         "(ADLB_ATTACH=1 — join_world negotiates rank "
                         "ids + home servers from the master; no "
                         "restart). --nranks/--ranks are not used; "
                         "python servers only")
    ap.add_argument("--host", default="127.0.0.1",
                    help="address other hosts reach this one at")
    ap.add_argument("--server-impl", default="python",
                    choices=["python", "native"])
    ap.add_argument("--balancer", default="steal", choices=["steal", "tpu"])
    ap.add_argument("--fabric", default="auto",
                    choices=["auto", "shm", "tcp"],
                    help="process-world transport: 'auto' upgrades "
                         "same-host rank pairs to the shared-memory ring "
                         "fabric when the host can run it (cross-host "
                         "pairs stay TCP); 'tcp' disables the upgrade "
                         "(exported to app programs as ADLB_FABRIC / "
                         "ADLB_SHM_KEY)")
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument("--flight-dir", default=None,
                    help="directory for per-rank flight-record JSON "
                         "artifacts on abort/timeout (exported to app "
                         "programs as ADLB_FLIGHT_DIR)")
    ap.add_argument("--ops-port", type=int, default=None,
                    help="serve /metrics, /healthz, /dump on "
                         "127.0.0.1:<port> of the master server's host "
                         "(0 = ephemeral)")
    ap.add_argument("--on-worker-failure", default="abort",
                    choices=["abort", "reclaim"],
                    help="worker (app rank) death policy: 'abort' kills "
                         "the world (reference semantics); 'reclaim' "
                         "re-enqueues the dead rank's leased work and the "
                         "world keeps running")
    ap.add_argument("--on-server-failure", default="abort",
                    choices=["abort", "failover"],
                    help="server death policy: 'abort' kills the world "
                         "(reference semantics); 'failover' replays the "
                         "dead server's replicated pool shard at its "
                         "ring-successor buddy, which takes over its app "
                         "ranks (python servers only)")
    ap.add_argument("--lease-timeout-s", type=float, default=0.0,
                    help="gray-failure detection: expire (and fence) a "
                         "lease whose owner has been silent this long, "
                         "re-enqueueing its unit; 0 = off (python servers "
                         "only; exported to app programs as "
                         "ADLB_LEASE_TIMEOUT_S so clients heartbeat)")
    ap.add_argument("--max-unit-retries", type=int, default=0,
                    help="retry budget per unit: more failed deliveries "
                         "than this moves the unit to the dead-letter "
                         "quarantine instead of the queue; 0 = unlimited "
                         "(python servers only)")
    ap.add_argument("--mem-hard-frac", type=float, default=0.0,
                    help="overload backpressure: above this fraction of "
                         "max-malloc-per-server with no peer believed to "
                         "have room, puts answer ADLB_BACKOFF with a "
                         "retry-after hint; 0 = off (python servers only)")
    ap.add_argument("--mem-soft-frac", type=float, default=0.95,
                    help="memory-pressure push threshold as a fraction of "
                         "max-malloc-per-server (the reference's 0.95); "
                         "lower it together with --mem-hard-frac to leave "
                         "pushes headroom before backpressure bites "
                         "(validation requires hard >= soft when armed)")
    ap.add_argument("--wal-dir", default=None,
                    help="durable service mode: per-server write-ahead "
                         "log directory — pool mutations are teed to "
                         "<dir>/server.<rank>.log with group-commit "
                         "fsync, and a restarted launcher on the same "
                         "directory replays the pool (python servers "
                         "only; see USERGUIDE §10 for the restart "
                         "runbook)")
    ap.add_argument("--wal-fsync-ms", type=float, default=5.0,
                    help="WAL group-commit window: put acks are held "
                         "for the fsync that makes them durable; 0 = "
                         "fsync every flush (strictest)")
    ap.add_argument("--fault-spec", default=None,
                    help="JSON fault-injection spec "
                         "(adlb_tpu/runtime/faults.py), e.g. "
                         '\'{"seed": 7, "delay": 0.01}\'; applied to the '
                         "server endpoints this launcher runs and exported "
                         "to app programs as ADLB_FAULT_SPEC")
    ap.add_argument("prog", nargs="*",
                    help="app program (exec'd per app rank with "
                         "ADLB_RENDEZVOUS/ADLB_RANK set)")
    args = ap.parse_args(argv)

    if args.attach:
        return _attach_main(args)
    if args.nranks is None or args.ranks is None:
        ap.error("--nranks and --ranks are required (unless --attach)")

    from adlb_tpu.runtime.world import Config, WorldSpec

    types = [int(t) for t in args.types.split(",")]
    world = WorldSpec(nranks=args.nranks, nservers=args.nservers,
                      types=tuple(types))
    fault_spec = None
    if args.fault_spec:
        import json

        fault_spec = json.loads(args.fault_spec)
    cfg = Config(balancer=args.balancer, server_impl=args.server_impl,
                 fabric=args.fabric,
                 flight_dir=args.flight_dir, ops_port=args.ops_port,
                 on_worker_failure=args.on_worker_failure,
                 on_server_failure=args.on_server_failure,
                 lease_timeout_s=args.lease_timeout_s,
                 max_unit_retries=args.max_unit_retries,
                 mem_hard_frac=args.mem_hard_frac,
                 mem_soft_frac=args.mem_soft_frac,
                 wal_dir=args.wal_dir,
                 wal_fsync_ms=args.wal_fsync_ms,
                 fault_spec=fault_spec)
    # per-process wire-codec selection (ADLB_CODEC env is the exec'd
    # app ranks' hook; in-launcher server reactors select here)
    from adlb_tpu.runtime.codec import select_codec

    select_codec(cfg.codec)
    my_ranks = _parse_ranks(args.ranks)
    host = args.host
    rdv = args.rendezvous
    # channel plane: one broker per launcher, published through the
    # rendezvous dir. "auto" turns ON exactly where the per-pair socket
    # explosion lives — a launcher owning a strict subset of the world
    # is a multi-launcher fleet — and stays per-pair for single-launcher
    # worlds (ADLB_TCP_MUX=1 still forces it, the CI hook)
    from adlb_tpu.runtime.channel import ChannelBroker, resolve_tcp_mux

    mux_on = cfg.tcp_mux == "on" or (
        cfg.tcp_mux == "auto"
        and (len(my_ranks) < args.nranks or resolve_tcp_mux(cfg))
    )
    broker = ChannelBroker(host=host) if mux_on else None
    if broker is not None:
        _publish_broker(rdv, broker.addr, my_ranks)
    # fabric negotiation: every launcher (and joined client) of this
    # world derives the SAME shm namespace from the rendezvous
    # directory, so same-host pairs find each other's rings while
    # cross-host pairs silently stay on TCP
    from adlb_tpu.runtime.transport_shm import (
        cleanup_world,
        key_for_rendezvous,
        resolve_fabric,
    )

    shm_key = (
        key_for_rendezvous(rdv) if resolve_fabric(cfg) == "shm" else None
    )
    failures: list[str] = []
    threads: list[threading.Thread] = []
    server_eps = {}   # rank -> TcpEndpoint (python impl)
    daemons = {}      # rank -> Popen (native impl)

    # 1. servers bind first and publish REAL ports
    sidecar = None
    for rank in my_ranks:
        if not world.is_server(rank):
            continue
        if args.server_impl == "native":
            from adlb_tpu.native import daemon

            proc = daemon.spawn_daemon(world, cfg, rank)
            daemons[rank] = proc
            _publish(rdv, rank, host, daemon.read_hello(proc, rank))
        else:
            from adlb_tpu.runtime.faults import maybe_wrap
            from adlb_tpu.runtime.transport_shm import maybe_shm
            from adlb_tpu.runtime.transport_tcp import TcpEndpoint

            # shm wrapper inside, fault shim outside (faults must apply
            # to ring traffic exactly as to TCP traffic); the mux bound
            # keeps dynamically attached ranks on per-pair sockets
            ep = maybe_wrap(
                maybe_shm(
                    TcpEndpoint(
                        rank, {rank: (host, 0)},
                        mux=broker.addr if broker is not None else None,
                        mux_ranks=world.nranks,
                        compress_min=cfg.compress_min_bytes,
                    ),
                    cfg, shm_key),
                cfg, world)
            server_eps[rank] = ep
            _publish(rdv, rank, host, ep.port)
    if (args.server_impl == "native" and args.balancer == "tpu"
            and world.master_server_rank in my_ranks):
        from adlb_tpu.balancer.sidecar import start_sidecar

        sidecar = start_sidecar(world, cfg, None, host=host)
        _publish(rdv, world.nranks, host, sidecar[0].port)

    # 2. app ranks publish pre-allocated ports — from the staggered
    # below-ephemeral range (probe_free_ports), NOT per-rank bind(0):
    # an ephemeral-range port released here can be re-issued by the
    # kernel as some outbound connection's source port before the app
    # process rebinds it, which killed the rank on bind (the same flake
    # the single-host harness fixed for 100-rank spawn storms)
    from adlb_tpu.runtime.transport_tcp import probe_free_ports

    app_ranks = [r for r in my_ranks if world.is_app(r)]
    for rank, port in zip(app_ranks, probe_free_ports(len(app_ranks), host)):
        _publish(rdv, rank, host, port)

    # 3. global rendezvous
    addr_map = _await_all(rdv, world.nranks, args.timeout)
    try:
        with open(os.path.join(rdv, f"{world.nranks}.addr")) as f:
            h, p = f.read().split()
        addr_map[world.nranks] = (h, int(p))
    except OSError:
        pass
    _check_port_clash(addr_map)
    merged = os.path.join(rdv, "world.addr")
    write_rendezvous_file(
        merged, {r: a for r, a in addr_map.items() if r < world.nranks}
    )
    if broker is not None:
        # every launcher published a broker: teach ours the fleet's
        # rank -> broker routing so cross-host envelopes bridge
        rank_host, broker_addrs = _await_brokers(
            rdv, world.nranks, args.timeout
        )
        broker.set_routes(rank_host, broker_addrs)

    # 4. run servers
    if sidecar is not None:
        sidecar[0].addr_map.update(addr_map)
        sidecar[1].start()
    for rank, proc in daemons.items():
        from adlb_tpu.native import daemon

        daemon.send_addrs(proc, addr_map)

        def wait_daemon(rank=rank, proc=proc):
            from adlb_tpu.native import daemon as dm

            stats, abort_code, rc = dm.collect_stats(proc, timeout=10**9)
            if stats is None and abort_code is None:
                failures.append(f"native server rank {rank} exited {rc}")

        t = threading.Thread(target=wait_daemon, daemon=True)
        threads.append(t)
        t.start()
    for rank, ep in server_eps.items():
        ep.addr_map.update(addr_map)

        def run_server(rank=rank, ep=ep):
            from adlb_tpu.runtime.server import Server

            try:
                Server(world, cfg, ep).run()
            except Exception as e:  # noqa: BLE001
                failures.append(f"server rank {rank}: {e!r}")
            finally:
                ep.close()

        t = threading.Thread(target=run_server, daemon=True)
        threads.append(t)
        t.start()

    # 5. exec app programs
    procs: list[subprocess.Popen] = []
    for rank in my_ranks:
        if world.is_app(rank):
            if not args.prog:
                failures.append(f"app rank {rank}: no program given")
                continue
            env = dict(os.environ)
            env["ADLB_RENDEZVOUS"] = merged
            env["ADLB_RANK"] = str(rank)
            env["ADLB_NUM_SERVERS"] = str(world.nservers)
            if args.flight_dir:
                # app programs (Python join_world or C clients' Python
                # wrappers) opt into flight artifacts via the env contract
                env["ADLB_FLIGHT_DIR"] = args.flight_dir
            if args.fault_spec:
                env["ADLB_FAULT_SPEC"] = args.fault_spec
            if shm_key:
                # joined clients upgrade their same-host pairs too
                env["ADLB_FABRIC"] = "shm"
                env["ADLB_SHM_KEY"] = shm_key
            elif args.fabric == "tcp":
                env["ADLB_FABRIC"] = "tcp"
            if broker is not None:
                # joined clients attach to this host's broker (one
                # data-plane socket each); the bound keeps them off it
                # for dynamically attached ranks
                env["ADLB_BROKER_ADDR"] = (
                    f"{broker.addr[0]}:{broker.addr[1]}"
                )
                env["ADLB_MUX_RANKS"] = str(world.nranks)
            if args.on_worker_failure != "abort":
                env["ADLB_ON_WORKER_FAILURE"] = args.on_worker_failure
            if args.on_server_failure != "abort":
                env["ADLB_ON_SERVER_FAILURE"] = args.on_server_failure
            if args.lease_timeout_s > 0:
                # joined clients arm the liveness heartbeat from this
                env["ADLB_LEASE_TIMEOUT_S"] = str(args.lease_timeout_s)
            if args.server_impl == "native":
                env["ADLB_SERVER_IMPL"] = "native"
            procs.append(subprocess.Popen(args.prog, env=env))

    # apps must not outlive a failed server: without this, a dead server
    # leaves every app blocked in reserve and the launcher waiting forever
    rc_final = 0
    while any(p.poll() is None for p in procs):
        if failures:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            break
        time.sleep(0.2)
    for p in procs:
        try:
            p.wait(timeout=args.timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            failures.append("app process killed after timeout")
        if p.returncode:
            rc_final = p.returncode
    for t in threads:
        t.join(timeout=args.timeout)
        if t.is_alive():
            failures.append("a server did not terminate (hung shutdown?)")
    if sidecar is not None:
        from adlb_tpu.balancer.sidecar import stop_sidecar

        stop_sidecar(*sidecar)
    if broker is not None:
        broker.close()
    # best-effort sweep of this world's ring segments/FIFOs: ranks that
    # died without unlinking (SIGKILL chaos) would otherwise leak them.
    # Exactly ONE party sweeps — the launcher hosting the master server —
    # so a same-host sibling launcher still finalizing its ranks never
    # has live rings unlinked from under it (others' strays are replaced
    # at create time by the next incarnation anyway).
    if world.master_server_rank in my_ranks:
        cleanup_world(shm_key)
    for f in failures:
        print(f"[adlb_launch] {f}", file=sys.stderr)
    return rc_final if not failures else (rc_final or 1)


if __name__ == "__main__":
    sys.exit(main())
