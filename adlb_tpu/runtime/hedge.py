"""Tail hedging: budgeted speculative re-dispatch of p99 stragglers.

The "act" half of the tail story (ROADMAP item 2, Dean/Barroso "The
Tail at Scale"): PR 13 lets a server *see* that a leased unit's age
crossed the live per-(job, type) p99 threshold the master gossips
(``SS_OBS_SYNC`` ``thr``), and PR 16 *names* stalled lease holders
(``leases_expired_by`` growth / staleness — the shared
:func:`adlb_tpu.obs.slo.suspect_ranks` heuristic). This module lets the
home server do something about it: mint a **hedge sibling** — a copy of
the straggling unit — and hand it to an already-parked requester on a
DIFFERENT rank. First terminal wins and closes the books exactly once;
every losing sibling is fenced through the PR 5 (seqno, owner)
machinery, so the loser's late fetch answers ``ADLB_FENCED`` exactly
like a lease-expired owner's would. The at-least-once window is the one
already documented for lease expiry — hedging adds no new one.

Two structural properties the server hooks rely on:

* **Budgeted** — a per-job token bucket refilled by deliveries
  (``Config(hedge_budget_frac)`` tokens per delivered unit, small
  burst cap): launches are bounded by ``~frac x deliveries + burst``
  by construction, not by a tuned rate limit.
* **Backpressure-subordinate** — any overload signal at launch time
  (memory watermark, per-job quota, allocation failure) vetoes the
  hedge STICKILY for that straggler: a vetoed origin can never launch
  later ("zero vetoed-then-launched", proven under the put-storm
  bench). Budget and no-parked-taker vetoes are transient — the next
  scan may retry them.

The manager is pure bookkeeping (groups, buckets, veto set); all queue
/ lease / WAL side effects live in ``runtime/server.py`` so the hedge
state can never disagree with the reactor's.
"""

from __future__ import annotations

from collections import deque
from typing import Iterator, Optional

# a fresh job's bucket: one immediate hedge allowed, then paced by the
# per-delivery refill
INITIAL_TOKENS = 1.0
# bucket cap: bounds the burst after an idle stretch (deliveries keep
# crediting while nothing straggles)
BURST_TOKENS = 4.0
# sticky-veto memory bound, same policy as the server's fence set
MAX_VETOED = 65536


def should_hedge(age_s: float, thr_s: Optional[float],
                 owner_suspect: bool, min_age_s: float) -> bool:
    """The trigger predicate, separated for direct unit testing: hedge
    when the unit's age crossed the fleet-fed p99 threshold for its
    (job, type) — or its lease holder shows a stall signature — but
    never below the ``hedge_min_age_ms`` floor (cold-start thresholds
    are noise and a young unit is not a straggler)."""
    if age_s < min_age_s:
        return False
    if thr_s is not None and age_s > thr_s:
        return True
    return owner_suspect


class HedgeGroup:
    """One straggler's race: the origin unit plus its hedge siblings
    (today exactly one sibling per origin — the server never re-hedges
    an existing member)."""

    __slots__ = ("origin", "members", "job")

    def __init__(self, origin: int, job: int) -> None:
        self.origin = origin
        self.members: set[int] = {origin}
        self.job = job


class HedgeManager:
    """Per-server hedge bookkeeping: open groups, per-job budget
    buckets, and the sticky backpressure-veto set. Reactor-thread only,
    like the queues it annotates."""

    def __init__(self, budget_frac: float,
                 burst: float = BURST_TOKENS) -> None:
        self.budget_frac = budget_frac
        self.burst = burst
        self._tokens: dict[int, float] = {}     # job -> tokens
        self.groups: dict[int, HedgeGroup] = {}  # origin seqno -> group
        self.by_seqno: dict[int, int] = {}       # member -> origin seqno
        self._vetoed: set[int] = set()           # sticky: origin seqnos
        self._veto_order: deque = deque()
        self.launched = 0

    # -- budget --------------------------------------------------------------

    def tokens(self, job: int) -> float:
        return self._tokens.get(job, INITIAL_TOKENS)

    def credit(self, job: int) -> None:
        """One delivered unit funds its job's bucket."""
        self._tokens[job] = min(
            self._tokens.get(job, INITIAL_TOKENS) + self.budget_frac,
            self.burst,
        )

    def try_debit(self, job: int) -> bool:
        t = self._tokens.get(job, INITIAL_TOKENS)
        if t < 1.0:
            return False
        self._tokens[job] = t - 1.0
        return True

    def refund(self, job: int) -> None:
        """Return a debited token (the launch aborted after the debit —
        no taker parked, allocation failed)."""
        self._tokens[job] = min(
            self._tokens.get(job, INITIAL_TOKENS) + 1.0, self.burst
        )

    # -- sticky backpressure veto -------------------------------------------

    def veto(self, origin_seqno: int) -> None:
        """Backpressure said no: this straggler never hedges. Sticky by
        design — overload is exactly when a later retry would be the
        start of a hedge storm."""
        if origin_seqno in self._vetoed:
            return
        self._vetoed.add(origin_seqno)
        self._veto_order.append(origin_seqno)
        if len(self._veto_order) > MAX_VETOED:
            self._vetoed.discard(self._veto_order.popleft())

    def is_vetoed(self, seqno: int) -> bool:
        return seqno in self._vetoed

    # -- group lifecycle -----------------------------------------------------

    def open(self, origin_seqno: int, sib_seqno: int, job: int) -> None:
        g = self.groups.get(origin_seqno)
        if g is None:
            g = self.groups[origin_seqno] = HedgeGroup(origin_seqno, job)
            self.by_seqno[origin_seqno] = origin_seqno
        g.members.add(sib_seqno)
        self.by_seqno[sib_seqno] = origin_seqno
        self.launched += 1

    def group_of(self, seqno: int) -> Optional[HedgeGroup]:
        origin = self.by_seqno.get(seqno)
        return None if origin is None else self.groups.get(origin)

    def is_member(self, seqno: int) -> bool:
        return seqno in self.by_seqno

    def settle(self, seqno: int) -> Optional[tuple[int, list[int]]]:
        """First terminal among a group's members: dissolve the race and
        return ``(origin_seqno, losers)`` — every OTHER member, for the
        server to fence and retire. ``None`` when ``seqno`` is not
        racing (the overwhelmingly common case: one dict probe)."""
        origin = self.by_seqno.get(seqno)
        if origin is None:
            return None
        g = self.groups.pop(origin, None)
        if g is None:  # pragma: no cover — by_seqno implies a group
            self.by_seqno.pop(seqno, None)
            return None
        for m in g.members:
            self.by_seqno.pop(m, None)
        return origin, [m for m in g.members if m != seqno]

    def drop(self, seqno: int) -> None:
        """A member retired WITHOUT terminating (lease expiry /
        unreserve / rank-death while a sibling still races). When only
        one member remains the race is over — the group dissolves and
        the survivor is an ordinary unit again (the server re-logs its
        OP_PUT so recovery stops treating it as a discardable
        sibling)."""
        origin = self.by_seqno.pop(seqno, None)
        if origin is None:
            return
        g = self.groups.get(origin)
        if g is None:
            return
        g.members.discard(seqno)
        if len(g.members) <= 1:
            del self.groups[origin]
            for m in g.members:
                self.by_seqno.pop(m, None)

    def live_siblings(self) -> Iterator[tuple[int, int]]:
        """(sibling seqno, origin seqno) for every open group — the WAL
        compaction seed re-logs these as OP_HEDGE so a cold restart
        still knows which copies are speculative."""
        for origin, g in self.groups.items():
            for m in g.members:
                if m != origin:
                    yield m, origin

    def survivors_of(self, seqno: int) -> list[int]:
        """Other members of ``seqno``'s group (empty when not racing) —
        the member-unpin hook asks this before deciding whether the
        unpinned copy may retire or must re-enqueue (work is never lost
        to hedging: the LAST live copy always stays in service)."""
        g = self.group_of(seqno)
        if g is None:
            return []
        return [m for m in g.members if m != seqno]
