"""TCP transport: ranks as processes, possibly on many hosts.

The multi-process analogue of the reference's MPI substrate (reference
``src/adlb.c:44-83`` tag protocol over ``MPI_Send/Irecv``): every rank runs a
tiny acceptor thread; messages are length-prefixed pickled frames over
persistent sockets, delivered into the same inbox interface the in-process
fabric uses, so the server reactor and client engine are transport-agnostic.

Bootstrap mirrors ``jax.distributed``-style initialization: a rendezvous
file or coordinator address maps rank -> (host, port). For single-host
multi-process use, :func:`spawn_world` forks one process per rank.
"""

from __future__ import annotations

import pickle
import queue
import socket
import struct
import threading
from typing import Optional

from adlb_tpu.runtime.messages import Msg

_HDR = struct.Struct("<I")


class TcpEndpoint:
    """One rank's endpoint: an acceptor thread feeding an inbox, plus lazily
    opened persistent outbound connections to peers."""

    def __init__(self, rank: int, addr_map: dict[int, tuple[str, int]]) -> None:
        self.rank = rank
        self.addr_map = dict(addr_map)
        self.inbox: "queue.SimpleQueue[Msg]" = queue.SimpleQueue()
        self._out: dict[int, socket.socket] = {}
        self._out_lock = threading.Lock()
        self._closed = False

        host, port = self.addr_map[rank]
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        # rebind may have picked an ephemeral port
        self.addr_map[rank] = self._listener.getsockname()
        self._acceptor = threading.Thread(
            target=self._accept_loop, daemon=True, name=f"adlb-tcp-acceptor-{rank}"
        )
        self._acceptor.start()

    @property
    def port(self) -> int:
        return self.addr_map[self.rank][1]

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._reader, args=(conn,), daemon=True
            ).start()

    def _reader(self, conn: socket.socket) -> None:
        try:
            while True:
                hdr = self._read_exact(conn, _HDR.size)
                if hdr is None:
                    return
                (n,) = _HDR.unpack(hdr)
                body = self._read_exact(conn, n)
                if body is None:
                    return
                self.inbox.put(pickle.loads(body))
        except OSError:
            return
        finally:
            conn.close()

    @staticmethod
    def _read_exact(conn: socket.socket, n: int) -> Optional[bytes]:
        buf = bytearray()
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf.extend(chunk)
        return bytes(buf)

    def send(self, dest: int, m: Msg) -> None:
        body = pickle.dumps(m, protocol=pickle.HIGHEST_PROTOCOL)
        frame = _HDR.pack(len(body)) + body
        with self._out_lock:
            sock = self._out.get(dest)
            if sock is None:
                sock = socket.create_connection(self.addr_map[dest], timeout=30)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._out[dest] = sock
            try:
                sock.sendall(frame)
            except OSError:
                # one reconnect attempt; beyond that the watchdog handles it
                sock = socket.create_connection(self.addr_map[dest], timeout=30)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._out[dest] = sock
                sock.sendall(frame)

    def recv(self, timeout: Optional[float] = None) -> Optional[Msg]:
        try:
            if timeout is None:
                return self.inbox.get()
            return self.inbox.get(timeout=max(timeout, 0.0))
        except queue.Empty:
            return None

    def close(self) -> None:
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass
        with self._out_lock:
            for s in self._out.values():
                try:
                    s.close()
                except OSError:
                    pass
            self._out.clear()


def local_addr_map(nranks: int, host: str = "127.0.0.1") -> dict[int, tuple[str, int]]:
    """Pick nranks free ports on one host (rendezvous for tests/single-host)."""
    addr_map = {}
    socks = []
    for r in range(nranks):
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        socks.append(s)
        addr_map[r] = (host, s.getsockname()[1])
    for s in socks:
        s.close()
    return addr_map
