"""TCP transport: ranks as processes, possibly on many hosts.

The multi-process analogue of the reference's MPI substrate (reference
``src/adlb.c:44-83`` tag protocol over ``MPI_Send/Irecv``): every rank runs a
tiny acceptor thread; messages are length-prefixed pickled frames over
persistent sockets, delivered into the same inbox interface the in-process
fabric uses, so the server reactor and client engine are transport-agnostic.

Bootstrap mirrors ``jax.distributed``-style initialization: a rendezvous
file or coordinator address maps rank -> (host, port). For single-host
multi-process use, :func:`spawn_world` forks one process per rank.
"""

from __future__ import annotations

import itertools
import pickle
import queue
import socket
import struct
import threading
import time
from typing import Optional

from adlb_tpu.runtime.channel import data_envelope as _data_envelope
from adlb_tpu.runtime.codec import (
    decode_binary,
    encodable,
    encode_binary_iov,
    loads_restricted,
    wire_native_ok,
)
from adlb_tpu.runtime.messages import Msg, Tag

_HDR = struct.Struct("<I")

# sentinel: _deliver_body refused a frame in a way that must close a
# per-pair connection (hostile pickle); the channel plane drops instead
_REFUSED = object()


class _SubmitBatch(threading.local):
    """Per-thread submit-batch state (see TcpEndpoint.submit_begin):
    channel-plane envelopes accumulated between begin/flush so a burst
    of N frames costs one gather syscall, not N."""

    depth = 0
    envs: Optional[list] = None
    saved = 0

# staggers the rendezvous-port probe start for successive worlds created
# by the same process (see local_addr_map)
# atomic per-process probe counter (itertools.count.__next__ is a single
# C-level op, so concurrent world creation from multiple threads cannot
# read-modify-write the same value and collapse onto one probe start)
_PORT_PROBE_CALLS = itertools.count()


class TcpEndpoint:
    """One rank's endpoint: an acceptor thread feeding an inbox, plus lazily
    opened persistent outbound connections to peers."""

    def __init__(
        self,
        rank: int,
        addr_map: dict[int, tuple[str, int]],
        binary_peers: Optional[set[int]] = None,
        mux: Optional[tuple[str, int]] = None,
        compress_min: int = 0,
        mux_ranks: Optional[int] = None,
    ) -> None:
        self.rank = rank
        self.addr_map = dict(addr_map)
        self.inbox: "queue.SimpleQueue[Msg]" = queue.SimpleQueue()
        self._out: dict[int, socket.socket] = {}
        self._out_lock = threading.Lock()  # guards the maps only
        self._dest_locks: dict[int, threading.Lock] = {}
        self._closed = False
        # ranks that speak the binary TLV codec (native C/Fortran clients).
        # Learned automatically from inbound frames — clients always send
        # first (FA_*) — or declared upfront via the rendezvous.
        self.binary_peers: set[int] = set(binary_peers or ())
        # observability: the owning role (Server/Client) attaches its
        # metrics Registry here (adlb_tpu.obs.metrics.attach); per-tag
        # counter objects are cached so the per-message cost is one
        # None-check when detached and two dict hits when attached
        self.metrics = None
        self._tx_stats: dict = {}
        self._rx_stats: dict = {}
        self._h_send = None  # send_s / recv_wait_s histograms, cached on
        self._h_recv = None  # first use (hot path: no per-message lookup)
        # shm-fabric hooks (transport_shm.py): ``notify`` fires after
        # every inbox delivery so a recv blocked on the shm doorbell
        # wakes for TCP traffic too; ``shm_ctl`` receives the swallowed
        # SHM_HELLO frames (ring-attach announcements). Both None when
        # no shm wrapper is stacked on this endpoint.
        self.notify = None
        self.shm_ctl = None
        # multiplexed channel plane (adlb_tpu/runtime/channel.py): when a
        # broker address is given, python<->python traffic rides (src,
        # dst, frame) envelopes over ONE socket to the host's broker —
        # O(hosts^2) fleet sockets — while native peers (binary TLV,
        # no envelope support) keep direct per-pair connections, which
        # is also why the listener below stays up under the mux.
        self._mux = None
        # elastic membership: brokers are wired for the STATIC world at
        # launch (rank -> host routes from the rendezvous), so only
        # dests BELOW this bound ride the mux — dynamically attached
        # ranks (ids above the base world) keep per-pair sockets both
        # ways. None = every python peer rides the broker.
        self._mux_ranks = mux_ranks
        self._compress_min = int(compress_min)
        self._submit = _SubmitBatch()
        self._g_ch = None       # tcp_channels_open gauge, cached
        self._c_coal = None     # frames_coalesced counter, cached
        self._c_comp = None     # bytes_compressed counter, cached
        self._h_enc = None      # codec_encode_us histogram, cached

        host, port = self.addr_map[rank]
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        # rebind may have picked an ephemeral port
        self.addr_map[rank] = self._listener.getsockname()
        self._acceptor = threading.Thread(
            target=self._accept_loop, daemon=True, name=f"adlb-tcp-acceptor-{rank}"
        )
        self._acceptor.start()
        if mux is not None:
            from adlb_tpu.runtime.channel import ChannelClient

            self._mux = ChannelClient(self, mux, compress_min)

    @property
    def port(self) -> int:
        return self.addr_map[self.rank][1]

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._reader, args=(conn,), daemon=True
            ).start()

    def _deliver_body(self, body, learn_binary: bool = True):
        """Decode one frame body (first-byte pickle/TLV discrimination)
        and deliver it: rx accounting, SHM_HELLO swallowing, inbox put,
        notify. Shared by the per-pair reader threads and the channel
        plane's client. Returns the Msg, None for a dropped binary
        frame or a swallowed HELLO, or ``_REFUSED`` for a frame whose
        unpickle was refused (the per-pair reader closes on it; the
        channel plane drops and keeps the shared channel up)."""
        if body[:1] == b"\x01":
            try:
                m = decode_binary(body)
            except Exception as e:  # noqa: BLE001 — stale C peer
                # A malformed frame (e.g. a native client built against
                # stale codec tables) must be diagnosable, not a silent
                # reader-thread death + peer hang.
                import sys

                print(
                    f"[adlb tcp rank {self.rank}] dropping "
                    f"undecodable binary frame ({len(body)}B): {e!r}",
                    file=sys.stderr,
                )
                return None
            if learn_binary:
                # inbound TLV on a DIRECT connection marks a native
                # client; TLV over the channel plane is just a python
                # peer's wire-native frame and must not re-route our
                # replies off the mux
                self.binary_peers.add(m.src)
        else:
            try:
                m = loads_restricted(body)
                if not isinstance(m, Msg):
                    raise pickle.UnpicklingError(
                        f"frame unpickled to "
                        f"{type(m).__name__}, not Msg"
                    )
            except Exception as e:  # noqa: BLE001 — hostile bytes
                import sys

                print(
                    f"[adlb tcp rank {self.rank}] refusing "
                    f"unpicklable frame ({len(body)}B): {e!r}",
                    file=sys.stderr,
                )
                return _REFUSED
        if m.tag is Tag.SHM_HELLO:
            # ring-attach announcement: hand the frame to the shm
            # wrapper instead of the role's inbox (the connection — or
            # channel attachment — it rode is the pair's death sentinel)
            ctl = self.shm_ctl
            if ctl is not None:
                ctl(m)
            return m
        reg = self.metrics
        if reg is not None:
            st = self._rx_stats.get(m.tag)
            if st is None:
                st = self._rx_stats[m.tag] = (
                    reg.counter("rx_msgs", tag=m.tag.name),
                    reg.counter("rx_bytes", tag=m.tag.name),
                )
            st[0].inc()
            # header included, so a rank's rx_bytes reconciles
            # with its peers' tx_bytes (which count the frame)
            st[1].inc(_HDR.size + len(body))
        self.inbox.put(m)
        cb = self.notify
        if cb is not None:
            cb()
        return m

    def _reader(self, conn: socket.socket) -> None:
        last_src: Optional[int] = None
        try:
            while True:
                hdr = self._read_exact(conn, _HDR.size)
                if hdr is None:
                    return
                (n,) = _HDR.unpack(hdr)
                body = self._read_exact(conn, n)
                if body is None:
                    return
                m = self._deliver_body(body)
                if m is _REFUSED:
                    # close the connection: for a never-established
                    # stray connection (last_src is None) nothing else
                    # happens; for an established peer stream the
                    # finally below synthesizes PEER_EOF — the
                    # rank-death fail-fast — rather than silently
                    # dropping a frame someone awaits
                    return
                if m is not None:
                    last_src = m.src
        except OSError:
            return
        finally:
            # EOF after the peer's frames: a synthetic in-order signal so
            # role logic can tell a finalized peer from a dead one (the
            # reference's failure model is rank-death-kills-job,
            # src/adlb.c:2508-2526; a silent EOF here would hang instead)
            if last_src is not None and not self._closed:
                self.inbox.put(Msg(tag=Tag.PEER_EOF, src=last_src))
                cb = self.notify
                if cb is not None:
                    cb()
            conn.close()

    @staticmethod
    def _read_exact(conn: socket.socket, n: int) -> Optional[bytes]:
        buf = bytearray()
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf.extend(chunk)
        return bytes(buf)

    def _connect(self, dest: int, grace: float = 15.0) -> socket.socket:
        """Connect to a peer, tolerating a listener that is still coming up
        (ranks bind at different times in thread/process worlds); ``grace``
        bounds how long refusals are retried — senders that know their
        peers are already up (e.g. the balancer sidecar, whose peers
        snapshot only after binding) pass a short grace so a dead peer
        fails fast instead of stalling the loop 15 s."""
        deadline = time.monotonic() + grace
        while True:
            try:
                sock = socket.create_connection(self.addr_map[dest], timeout=30)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                return sock
            except ConnectionRefusedError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.05)

    def send(self, dest: int, m: Msg, connect_grace: float = 15.0) -> None:
        reg = self.metrics
        # channel-plane routing: python peers ride the broker; native
        # peers (binary TLV, no envelope support) and self keep direct
        # per-pair sockets
        mux = self._mux
        if mux is not None and (
            dest == self.rank
            or dest in self.binary_peers
            or (self._mux_ranks is not None and dest >= self._mux_ranks)
        ):
            mux = None
        if mux is not None and dest in mux.dead:
            # sends to a dead peer must fail like a refused reconnect,
            # not vanish into a dropped envelope
            raise OSError(
                f"channel plane: rank {dest} is dead (DETACH seen)"
            )
        # serialization (pickle/TLV encode) runs OUTSIDE the send lock:
        # only socket I/O is serialized per destination
        t_enc = time.monotonic() if reg is not None else 0.0
        tlv = False
        if dest in self.binary_peers:
            if not encodable(m):
                raise ValueError(
                    f"message {m.tag} carries fields outside the binary "
                    f"codec but rank {dest} is a native (non-pickle) client"
                )
            # scatter-gather encode: the payload views ride the iovec
            # straight into sendmsg — no body-concat copy on the hot path
            parts = encode_binary_iov(m)
            tlv = True
        elif mux is not None and wire_native_ok(m):
            # the channel plane carries TLV for the wire-native hot path
            # (same body rule as the shm rings), pickle for the rest
            parts = encode_binary_iov(m)
            tlv = True
        else:
            parts = [pickle.dumps(m, protocol=pickle.HIGHEST_PROTOCOL)]
        nbody = sum(len(p) for p in parts)
        t0 = time.monotonic() if reg is not None else 0.0
        if reg is not None and tlv:
            if self._h_enc is None:
                self._h_enc = reg.histogram("codec_encode_us")
            self._h_enc.observe((t0 - t_enc) * 1e6)
        if mux is not None:
            env, saved = _data_envelope(self.rank, dest, parts, nbody,
                                        self._compress_min)
            if saved and reg is not None:
                if self._c_comp is None:
                    self._c_comp = reg.counter("bytes_compressed")
                self._c_comp.inc(saved)
            st_b = self._submit
            if st_b.depth > 0 and st_b.envs is not None:
                st_b.envs.append(env)  # one gather at submit_flush
            else:
                mux.send_batch([env])
        else:
            frame = [_HDR.pack(nbody), *parts]
            # per-destination serialization: a slow/dead peer (15 s
            # connect retry) must not stall sends to every other rank
            with self._out_lock:
                dlock = self._dest_locks.setdefault(dest, threading.Lock())
            with dlock:
                with self._out_lock:
                    sock = self._out.get(dest)
                if sock is None:
                    sock = self._connect(dest, connect_grace)
                    with self._out_lock:
                        self._out[dest] = sock
                try:
                    self._send_iov(sock, frame)
                except OSError:
                    # one reconnect attempt (a FRESH stream, so
                    # restarting the frame from its first byte is safe);
                    # beyond that the watchdog handles it
                    sock = self._connect(dest, connect_grace)
                    with self._out_lock:
                        self._out[dest] = sock
                    self._send_iov(sock, frame)
        if reg is not None:
            st = self._tx_stats.get(m.tag)
            if st is None:
                st = self._tx_stats[m.tag] = (
                    reg.counter("tx_msgs", tag=m.tag.name),
                    reg.counter("tx_bytes", tag=m.tag.name),
                )
            st[0].inc()
            st[1].inc(_HDR.size + nbody)
            # whole-path send latency: serialization wait + (re)connect +
            # kernel buffer admission — the "how backed up is this peer"
            # signal the reference reads off MPI's unexpected queue
            if self._h_send is None:
                self._h_send = reg.histogram("send_s")
            self._h_send.observe(time.monotonic() - t0)
            # data-plane socket census: direct per-pair sockets plus the
            # one channel to the broker (the O(1)-per-host-pair claim,
            # scraped off /metrics as tcp_channels_open)
            if self._g_ch is None:
                self._g_ch = reg.gauge("tcp_channels_open")
            self._g_ch.set(len(self._out) + (1 if self._mux else 0))

    # -- submit batching ------------------------------------------------------

    def submit_begin(self) -> None:
        """Enter a per-thread submission batch: channel-plane sends
        accumulate and go out as ONE gather at :meth:`submit_flush` (a
        reactor tick's burst of N responses costs O(1) syscalls and
        wakeups). Per-pair sockets stay synchronous — their error
        surface (reconnect-at-caller) must not move to the flush point.
        Nests; only the outermost flush submits."""
        st = self._submit
        st.depth += 1
        if st.envs is None:
            st.envs = []

    def submit_flush(self) -> None:
        st = self._submit
        if st.depth > 0:
            st.depth -= 1
        if st.depth > 0:
            return
        envs, st.envs = st.envs, None
        if not envs:
            return
        mux = self._mux
        if mux is None:  # closed mid-batch
            return
        mux.send_batch(envs)
        if len(envs) > 1:
            reg = self.metrics
            if reg is not None:
                if self._c_coal is None:
                    self._c_coal = reg.counter("frames_coalesced")
                self._c_coal.inc(len(envs) - 1)

    @staticmethod
    def _send_iov(sock: socket.socket, parts: list) -> None:
        """Write one frame as a gather (writev-style) send over an
        arbitrary iovec instead of materializing a concatenated body —
        the old concat copied every payload once more per hop, a
        measurable tax on the work-delivery data plane. A short write
        (kernel buffer full) RESUMES the iovec at the unsent offset:
        the remainder re-gathers into the next sendmsg, so large frames
        never fall back to a concat copy either."""
        # Linux IOV_MAX is 1024 segments; a batched fused fetch can carry
        # more payload views than that — split into sequential gathers
        # (the caller holds the per-destination lock, so the frame stays
        # contiguous on the stream)
        while len(parts) > 1000:
            head, parts = parts[:1000], parts[1000:]
            TcpEndpoint._send_iov(sock, head)
        try:
            sent = sock.sendmsg(parts)
        except InterruptedError:
            # EINTR surfaced by a raising signal handler: nothing was
            # written, resume the same gather (PEP 475 auto-retries the
            # silent case; this covers the loud one)
            sent = 0
        except (AttributeError, NotImplementedError):  # platform without
            for p in parts:  # sendmsg: plain per-segment writes
                sock.sendall(p)
            return
        total = sum(len(p) for p in parts)
        while sent < total:
            total -= sent
            rest = []
            for p in parts:
                if sent >= len(p):
                    sent -= len(p)
                    continue
                rest.append(memoryview(p)[sent:] if sent else p)
                sent = 0
            parts = rest
            try:
                sent = sock.sendmsg(parts)
            except InterruptedError:
                sent = 0

    def backlog(self) -> int:
        """Received-but-unhandled frames — the TCP-era analogue of the
        reference's MPI unexpected-message-queue depth probe (reference
        ``src/adlb.c:3645-3719``)."""
        return self.inbox.qsize()

    def recv(self, timeout: Optional[float] = None) -> Optional[Msg]:
        reg = self.metrics
        t0 = time.monotonic() if reg is not None else 0.0
        try:
            if timeout is None:
                m = self.inbox.get()
            elif timeout <= 0.0:
                # never SimpleQueue.get(timeout=0.0): on this host class a
                # freshly forked child's zero-timeout timed get can park
                # forever in the lock (kernel-level; ~1/10 TCP worlds
                # wedged in the client's first recv — minimal repro is
                # fork + fresh SimpleQueue + get(timeout=0.0); nonblocking
                # gets and positive timeouts are unaffected). get_nowait()
                # checks the list without touching the lock.
                m = self.inbox.get_nowait()
            else:
                m = self.inbox.get(timeout=timeout)
        except queue.Empty:
            return None
        if reg is not None:
            # wait-for-message latency (observed only when a message
            # arrived: empty timeouts measure the poll deadline, not
            # the transport)
            if self._h_recv is None:
                self._h_recv = reg.histogram("recv_wait_s")
            self._h_recv.observe(time.monotonic() - t0)
        return m

    def close(self) -> None:
        self._closed = True
        mux, self._mux = self._mux, None
        if mux is not None:
            # FIN after our queued envelopes: the broker forwards them,
            # then fans out our DETACH — peers see our last frames
            # before the PEER_EOF, exactly like the per-pair plane
            mux.close()
        with self._out_lock:
            for s in self._out.values():
                # Outbound sockets are unidirectional (replies arrive on the
                # peer's own connection to our listener), so they never hold
                # unread inbound data and close() can't RST away buffered
                # frames; shutdown(SHUT_WR) makes the FIN-after-data explicit.
                try:
                    s.shutdown(socket.SHUT_WR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass
            self._out.clear()
        try:
            self._listener.close()
        except OSError:
            pass


def probe_free_ports(count: int, host: str = "127.0.0.1") -> list[int]:
    """Pick ``count`` free ports on one host for ranks that bind later.

    Ports come from BELOW the kernel's ephemeral range (see
    /proc/sys/net/ipv4/ip_local_port_range, typically 32768+): the ports
    are handed to child processes that bind later, and in a 100+-rank
    spawn storm an OUTBOUND connection's ephemeral port can otherwise
    land on a rank's not-yet-bound listener port — that rank then dies on
    bind and the failure-detection abort takes the whole world with it
    (observed at 64-128 ranks as a few-percent flake; the multi-host
    launcher had the same flake from per-rank ephemeral bind(0) probes).
    The probe start is derived from the PID (plus a per-process call
    counter), so concurrent worlds — distinct processes by
    construction — probe well-separated subranges instead of relying on
    lucky random draws; the bind check still skips any port someone else
    actually holds.
    """
    import os

    # the actual ephemeral floor is tunable; read it so the guarantee
    # holds on hosts with a lowered range (fall back to the Linux default)
    floor = 32768
    try:
        with open("/proc/sys/net/ipv4/ip_local_port_range") as f:
            floor = int(f.read().split()[0])
    except (OSError, ValueError, IndexError):
        pass
    if floor < 13000 + 2 * count:
        # no usable static range below the ephemeral floor: fall back to
        # kernel-assigned ports (the pre-fix behaviour, collision risk
        # and all — there is nowhere safe to allocate from)
        ports = []
        socks = []
        for _r in range(count):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind((host, 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
        for s in socks:
            s.close()
        return ports

    lo = max(1024, floor - 12000)
    hi = floor - 100
    ports = []
    socks = []
    span = hi - lo
    # Knuth-hash the PID so adjacent PIDs (concurrently spawned worlds)
    # land far apart in the range; successive worlds from the SAME
    # process are staggered by the call counter
    start = lo + (os.getpid() * 40503 + next(_PORT_PROBE_CALLS) * 1013) % span
    port = start
    probed = 0
    while len(ports) < count:
        port += 1
        if port >= hi:
            port = lo  # wrap: free ports below the start stay usable
        probed += 1
        if probed > span:
            raise OSError(f"no free rendezvous ports in [{lo},{hi})")
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            s.bind((host, port))
        except OSError:
            s.close()
            continue
        socks.append(s)
        ports.append(port)
    for s in socks:
        s.close()
    return ports


def local_addr_map(nranks: int, host: str = "127.0.0.1") -> dict[int, tuple[str, int]]:
    """Pick nranks free ports on one host (rendezvous for tests/single-host);
    see :func:`probe_free_ports` for the ephemeral-range rationale."""
    return {
        r: (host, p) for r, p in enumerate(probe_free_ports(nranks, host))
    }


# --------------------------------------------------------------- spawn_world


def _native_server_main(rank, world, cfg, port_q, conn, result_q, abort_event):
    """Wrapper for a native C++ server rank: launch adlb_serverd, relay the
    rendezvous (PORT line out, addr map in), parse the final STATS line.

    The daemon speaks the same binary TLV protocol as the native C client;
    Python app ranks are told to use binary frames toward server ranks (see
    ``binary_peers`` in :func:`_child_main`)."""
    from adlb_tpu.native import daemon

    proc = daemon.spawn_daemon(world, cfg, rank)
    reported = False

    def report(kind, value):
        nonlocal reported
        if not reported:
            reported = True
            result_q.put((kind, rank, value))

    try:
        port_q.put((rank, daemon.read_hello(proc, rank)))
        daemon.send_addrs(proc, conn.recv())

        # kill the daemon if the world aborts around it (an app rank died)
        def watch_abort():
            while proc.poll() is None:
                if abort_event.wait(timeout=0.25):
                    proc.terminate()
                    return

        threading.Thread(target=watch_abort, daemon=True).start()

        stats, abort_code = daemon.drain_output(proc)
        if abort_code is not None:
            abort_event.set()
        proc.wait(timeout=30.0)
        if abort_code is not None:
            # parity with the Python-server path: the abort code must be
            # recoverable from WorldResult, not just the aborted flag
            report("aborted", abort_code)
        elif stats is None:
            if abort_event.is_set():
                report("server", {})  # killed by watch_abort: not this
                # rank's failure; the erroring rank reports the cause
            else:
                # daemon died without printing STATS: attribute the failure
                # instead of reporting a clean empty-stats server
                raise RuntimeError(
                    f"native server rank {rank} exited {proc.returncode} "
                    f"without STATS"
                )
        else:
            report("server", stats)
    except BaseException as e:  # noqa: BLE001 — surfaced to the parent
        abort_event.set()
        proc.terminate()
        report("error", repr(e))


def _child_main(rank, world, cfg, app_fn, port_q, conn, result_q, abort_event,
                shm_key=None, mux_addr=None):
    """One rank's process body: bind, rendezvous, run role, report result.

    Exactly one message goes on result_q per rank — the parent counts ranks,
    so a success followed by a teardown error must not report twice.
    """
    if cfg.server_impl == "native" and world.is_server(rank):
        _native_server_main(
            rank, world, cfg, port_q, conn, result_q, abort_event
        )
        return

    reported = False

    def report(kind, value):
        nonlocal reported
        if not reported:
            reported = True
            result_q.put((kind, rank, value))

    # per-process codec selection (Config(codec) beats the import-time
    # env default; "c" is strict — an explicit ask must not silently
    # fall back to the Python twin)
    from adlb_tpu.runtime.codec import select_codec

    select_codec(cfg.codec)

    # with native servers, Python ranks must speak the binary codec toward
    # every server rank (the daemon cannot read pickle frames)
    binary_peers = (
        set(world.server_ranks) if cfg.server_impl == "native" else None
    )
    ep = TcpEndpoint(rank, {rank: ("127.0.0.1", 0)},
                     binary_peers=binary_peers, mux=mux_addr,
                     compress_min=cfg.compress_min_bytes,
                     mux_ranks=world.nranks)
    if shm_key:
        # same-host ranks upgrade to the shared-memory ring fabric; the
        # fault shim stacks OUTSIDE it, so injected faults apply to ring
        # traffic exactly as to TCP traffic
        from adlb_tpu.runtime.transport_shm import ShmEndpoint

        ep = ShmEndpoint(ep, shm_key, ring_bytes=cfg.shm_ring_bytes)
    if cfg.fault_spec:
        from adlb_tpu.runtime.faults import maybe_wrap

        ep = maybe_wrap(ep, cfg, world)
    try:
        port_q.put((rank, ep.port))
        ep.addr_map.update(conn.recv())  # full rank -> (host, port) map
        if world.is_app(rank):
            from adlb_tpu.api import AdlbContext
            from adlb_tpu.runtime.client import Client

            client = Client(world, cfg, ep, abort_event)
            try:
                report("app", app_fn(AdlbContext(client)))
            finally:
                try:
                    client.finalize()
                except Exception:  # home server already gone: benign
                    pass
        elif world.is_server(rank):
            from adlb_tpu.runtime.server import Server

            server = Server(world, cfg, ep, abort_event)
            server.run()
            if server.died:
                # fault-injected connectivity death absorbed by
                # on_server_failure="failover" (a SIGKILLed server never
                # reports at all; the parent classifies that case)
                report("server_dead", None)
            else:
                report("server", server.finalize_stats())
        else:
            from adlb_tpu.runtime.debug_server import DebugServer

            DebugServer(world, cfg, ep, abort_event).run()
            report("debug", None)
    except BaseException as e:  # noqa: BLE001 — surfaced to the parent
        try:
            from adlb_tpu.types import AdlbAborted, HomeServerLostError

            if isinstance(e, AdlbAborted):
                report("aborted", e.code)
            elif isinstance(e, HomeServerLostError):
                # distinct kind: the parent decides whether this is abort
                # collateral (server closed before the TA_ABORT landed),
                # a reclaim casualty, or a genuine server crash. Under
                # "reclaim" the rest of the world must keep running, so
                # only the abort policy escalates to the shared event.
                if cfg.on_worker_failure != "reclaim":
                    abort_event.set()
                report("conn_lost", repr(e))
            else:
                abort_event.set()
                report("error", repr(e))
        except Exception:  # pragma: no cover
            pass
    finally:
        ep.close()


def spawn_world(
    num_app_ranks: int,
    nservers: int,
    types,
    app_fn,
    cfg=None,
    use_debug_server: bool = False,
    timeout: float = 120.0,
    start_method: str = "fork",
):
    """Run a world with one OS process per rank over TCP — the analogue of
    ``mpiexec -n k`` for the reference's examples (reference
    ``examples/README-batcher.txt:57``), and the building block for
    multi-host deployment (replace the port rendezvous with a shared file).

    Returns :class:`adlb_tpu.api.WorldResult`. With ``start_method="spawn"``
    the ``app_fn`` must be picklable (module-level).
    """
    import multiprocessing as mp

    from adlb_tpu.api import WorldResult
    from adlb_tpu.runtime.world import Config, WorldSpec

    cfg = cfg or Config()
    if cfg.server_impl == "native":
        from adlb_tpu.native.build import ensure_serverd

        ensure_serverd()  # build once up front, not per server rank
    world = WorldSpec(
        nranks=num_app_ranks + nservers + (1 if use_debug_server else 0),
        nservers=nservers,
        types=tuple(types),
        use_debug_server=use_debug_server,
    )
    # fabric negotiation: spawn_world ranks are same-host by
    # construction, so the resolved "shm" fabric upgrades every
    # python<->python pair to rings under one fresh world key (native
    # daemon ranks negotiate down to TCP per pair inside the endpoint)
    from adlb_tpu.runtime.transport_shm import (
        cleanup_world,
        new_world_key,
        resolve_fabric,
    )

    shm_key = new_world_key() if resolve_fabric(cfg) == "shm" else None

    # channel plane (Config(tcp_mux) / ADLB_TCP_MUX): one broker for
    # this single-host world, running in the parent like the balancer
    # sidecar; ranks hold ONE data-plane socket each instead of one per
    # peer. Native server worlds keep direct sockets toward the daemons
    # (binary peers route around the mux inside the endpoint).
    from adlb_tpu.runtime.channel import ChannelBroker, resolve_tcp_mux

    broker = ChannelBroker() if resolve_tcp_mux(cfg) else None
    mux_addr = broker.addr if broker is not None else None

    ctx = mp.get_context(start_method)
    port_q = ctx.Queue()
    result_q = ctx.Queue()
    abort_event = ctx.Event()
    pipes = {}
    procs = {}
    for rank in range(world.nranks):
        parent_end, child_end = ctx.Pipe()
        pipes[rank] = parent_end
        p = ctx.Process(
            target=_child_main,
            args=(rank, world, cfg, app_fn, port_q, child_end, result_q,
                  abort_event, shm_key, mux_addr),
            name=f"adlb-rank-{rank}",
        )
        procs[rank] = p
        p.start()

    # native + tpu: the JAX balancer brain runs as a sidecar thread in the
    # parent at pseudo-rank world.nranks; servers stream snapshots to it
    sidecar_ep = None
    sidecar_thread = None
    if cfg.server_impl == "native" and cfg.balancer == "tpu":
        from adlb_tpu.balancer.sidecar import start_sidecar

        sidecar_ep, sidecar_thread = start_sidecar(world, cfg, abort_event)

    deadline = time.monotonic() + timeout
    addr_map = {}
    try:
        while len(addr_map) < world.nranks:
            try:
                rank, port = port_q.get(timeout=0.25)
            except queue.Empty:
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        "spawn_world: rendezvous did not complete"
                    ) from None
                dead = [r for r, p in procs.items()
                        if not p.is_alive() and r not in addr_map]
                if dead:
                    # surface the child's real startup error if it reported one
                    detail = ""
                    try:
                        kind, r, value = result_q.get(timeout=0.25)
                        if kind == "error":
                            detail = f": rank {r}: {value}"
                    except queue.Empty:
                        pass
                    raise RuntimeError(
                        f"spawn_world: rank(s) {dead} died before "
                        f"rendezvous{detail}"
                    )
                continue
            addr_map[rank] = ("127.0.0.1", port)
        if sidecar_ep is not None:
            addr_map[world.nranks] = ("127.0.0.1", sidecar_ep.port)
            sidecar_ep.addr_map.update(addr_map)
            sidecar_thread.start()
        for conn in pipes.values():
            conn.send(addr_map)
    except Exception:
        abort_event.set()
        for p in procs.values():
            p.terminate()
        if sidecar_ep is not None:
            from adlb_tpu.balancer.sidecar import stop_sidecar

            stop_sidecar(sidecar_ep, sidecar_thread, abort_event)
        if broker is not None:
            broker.close()
        cleanup_world(shm_key)
        raise

    app_results, server_stats = {}, {}
    errors: list[str] = []
    conn_lost: list[str] = []
    casualties: list[int] = []
    server_casualties: list[int] = []
    aborted_code = None
    real_abort = False
    reported: set[int] = set()
    while len(reported) < world.nranks:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            abort_event.set()
            errors.append(f"world did not finish within {timeout}s")
            break
        try:
            kind, rank, value = result_q.get(timeout=min(remaining, 1.0))
        except queue.Empty:
            if all(not p.is_alive() for p in procs.values()):
                missing = sorted(set(procs) - reported)
                if cfg.on_worker_failure == "reclaim":
                    # app ranks that died without reporting are the
                    # casualties the reclaim policy absorbed; the world
                    # completing around them is the success criterion.
                    casualties.extend(
                        r for r in missing if world.is_app(r)
                    )
                    missing = [r for r in missing if not world.is_app(r)]
                if cfg.on_server_failure == "failover":
                    # servers that died without reporting are the
                    # failover casualties (SIGKILLed mid-run); their
                    # buddies completed the world around them — the
                    # MASTER included: its ring buddy is the standing
                    # deputy and promotes (see server._promote_master)
                    server_casualties.extend(
                        r for r in missing if world.is_server(r)
                    )
                    missing = [r for r in missing if not world.is_server(r)]
                if missing:
                    errors.append(
                        f"rank(s) {missing} died without reporting a result"
                    )
                break
            continue
        reported.add(rank)
        if kind == "app":
            app_results[rank] = value
        elif kind == "server":
            server_stats[rank] = value
        elif kind == "server_dead":
            server_casualties.append(rank)
        elif kind == "error":
            errors.append(f"rank {rank}: {value}")
        elif kind == "conn_lost":
            conn_lost.append((rank, f"rank {rank}: {value}"))
        elif kind == "aborted":
            aborted_code = value
            # -1 is the abort_event sentinel (AdlbAborted(-1) raised when
            # a sibling set the event), NOT proof a rank called Abort:
            # a conn_lost child sets the event too, so collateral -1
            # reports must not launder a genuine server failure into a
            # clean abort. A real abort always yields a non-sentinel
            # report — Client.abort raises AdlbAborted(code) in the
            # aborting rank itself.
            if value != -1:
                real_abort = True

    for p in procs.values():
        p.join(timeout=max(deadline - time.monotonic(), 1.0))
        if p.is_alive():
            p.terminate()
            p.join(timeout=5.0)
    if sidecar_thread is not None:
        from adlb_tpu.balancer.sidecar import stop_sidecar

        stop_sidecar(sidecar_ep, sidecar_thread, abort_event)
    if broker is not None:
        broker.close()
    # every child is gone: sweep ring segments/FIFOs whose owners died
    # without unlinking (SIGKILL chaos legs would otherwise leak them)
    cleanup_world(shm_key)

    # a rank losing its home server is abort COLLATERAL when some rank
    # REALLY aborted the world (the server may close its listener before
    # every TA_ABORT frame lands); under the reclaim policy an app rank's
    # lost connectivity is a CASUALTY the world completed around (e.g. a
    # fault-injected disconnect — the client process survives to report
    # conn_lost, the servers reclaim its work); otherwise it is a genuine
    # failure
    if conn_lost and not real_abort:
        if cfg.on_worker_failure == "reclaim":
            casualties.extend(r for r, _ in conn_lost if world.is_app(r))
            errors.extend(s for r, s in conn_lost if not world.is_app(r))
        else:
            errors.extend(s for _, s in conn_lost)
    if errors:
        raise RuntimeError("; ".join(errors))
    from adlb_tpu.types import InfoKey

    return WorldResult(
        app_results=app_results,
        server_stats=server_stats,
        aborted=abort_event.is_set() or aborted_code is not None,
        exception=None,
        casualties=sorted(casualties),
        server_casualties=sorted(server_casualties),
        quarantined=int(sum(
            s.get(int(InfoKey.QUARANTINED), 0)
            for s in server_stats.values()
        )),
    )
