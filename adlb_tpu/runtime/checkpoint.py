"""Pool-state checkpoint shards.

The reference has **no** pool serialization (SURVEY §5: checkpoint/resume
absent entirely — killing a run loses every queued unit). This framework
adds it: on ``ctx.checkpoint(prefix)`` a ring token makes every server
write its queue shard to ``<prefix>.<server_rank>.ckpt``; a new world
started with ``Config(restore_path=prefix)`` reloads each server's shard at
init. Restore assumes the same world shape (rank numbering), since targeted
units and batch-common references name ranks.

Semantics: the shard is the pool at token-arrival time — pinned units
(reserved but not yet fetched) are captured too, so a restore rolls the
pool back to the snapshot and work consumed after it is re-executed, the
standard crash-recovery contract; it also keeps batch-common refcounts
consistent. Each server holds the token until its in-flight migration
batches are acked, closing the tracked in-transit window; a unit that
migrates INTO an already-checkpointed server while the token is still
circulating is live in the world but absent from the checkpoint — take
checkpoints at quiescent points (e.g. between phases) for exact capture.

Shard format (little-endian): magic ``ACK2``, then a header ``<III``
(format version, world nranks, world nservers), u32 unit count, per unit
``<iiiqqq`` (work_type, target_rank, answer_rank, prio as q, common_server,
common_seqno) + u32 common_len + u32 payload_len + payload bytes; then u32
common-entry count, per entry ``<qqq`` (seqno, refcnt, ngets) + u32 len +
buf.

Restores validate the header's world shape **loudly**: targeted units and
batch-common references name ranks, so loading a shard into a different
shape would silently misroute them. ``ACK1`` shards (pre-header, written
by earlier builds and by older native daemons) carry no shape to check —
loading one silently skips that validation, so since the WAL began
compacting into ACK2-only snapshots the legacy read path is **gated**:
an ACK1 shard raises unless the caller opts in via
``Config(allow_legacy_shards=True)`` (the native daemon, serverd.cpp,
writes and validates ACK2 itself and is unaffected).
"""

from __future__ import annotations

import os
import struct
from typing import Iterable, Optional

_MAGIC = b"ACK2"
_MAGIC_V1 = b"ACK1"
_VERSION = 2
_SHAPE = struct.Struct("<III")  # version, nranks, nservers
_UNIT = struct.Struct("<iiiqqq")
_U32 = struct.Struct("<I")
_CQE = struct.Struct("<qqq")


class ShardShapeError(ValueError):
    """Restore-time world shape (or format version) mismatch — failing
    loudly beats silently misrouting every targeted unit."""


def shard_path(prefix: str, server_rank: int) -> str:
    return f"{prefix}.{server_rank}.ckpt"


def save_shard(prefix: str, server_rank: int, units: Iterable, cq,
               world=None) -> int:
    """Write one server's shard; returns the number of units captured.
    ``world`` (a WorldSpec, optional for bare callers) stamps the shape
    header so a mismatched restore fails loudly."""
    n = 0
    body = []
    for u in units:
        body.append(
            _UNIT.pack(u.work_type, u.target_rank, u.answer_rank,
                       u.prio, u.common_server_rank, u.common_seqno)
        )
        body.append(_U32.pack(u.common_len))
        body.append(_U32.pack(len(u.payload)))
        body.append(u.payload)
        n += 1
    centries = list(cq.entries()) if cq is not None else []
    nranks = world.nranks if world is not None else 0
    nservers = world.nservers if world is not None else 0
    out = [_MAGIC, _SHAPE.pack(_VERSION, nranks, nservers), _U32.pack(n)]
    out.extend(body)
    out.append(_U32.pack(len(centries)))
    for e in centries:
        out.append(_CQE.pack(e.seqno, e.refcnt, e.ngets))
        out.append(_U32.pack(len(e.buf)))
        out.append(e.buf)
    tmp = f"{shard_path(prefix, server_rank)}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        f.write(b"".join(out))
    os.replace(tmp, shard_path(prefix, server_rank))
    return n


def existing_shard_ranks(prefix: str) -> list[int]:
    """Server ranks that have shards on disk for this prefix."""
    import glob
    import re

    out = []
    for path in glob.glob(f"{prefix}.*.ckpt"):
        m = re.match(re.escape(prefix) + r"\.(\d+)\.ckpt$", path)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def load_shard(prefix: str, server_rank: int, world=None,
               allow_legacy: bool = False):
    """Read one server's shard; returns (units, common_entries) where units
    are dicts of constructor fields (seqnos are assigned by the server) and
    common_entries are (seqno, refcnt, ngets, buf) tuples. Missing shard =
    loud (a server with no queued work writes one anyway). With ``world``
    given, an ACK2 header naming a different world shape raises
    :class:`ShardShapeError` instead of silently misrouting targeted
    units. ACK1 shards carry no shape header, so they can never pass
    that check — reading one is refused unless ``allow_legacy``
    (Config(allow_legacy_shards)) explicitly opts into the unvalidated
    path."""
    path = shard_path(prefix, server_rank)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"checkpoint shard missing: {path} (was the checkpoint taken "
            f"with the same world shape?)"
        )
    with open(path, "rb") as f:
        data = f.read()
    magic = data[:4]
    off = 4
    if magic == _MAGIC:
        version, nranks, nservers = _SHAPE.unpack_from(data, off)
        off += _SHAPE.size
        if version > _VERSION:
            raise ShardShapeError(
                f"{path}: shard format version {version} is newer than this "
                f"build understands ({_VERSION})"
            )
        if world is not None and nranks and (
            nranks != world.nranks or nservers != world.nservers
        ):
            raise ShardShapeError(
                f"{path}: checkpoint was taken with nranks={nranks}/"
                f"nservers={nservers} but this world is "
                f"nranks={world.nranks}/nservers={world.nservers}; restore "
                f"with the same world shape"
            )
    elif magic == _MAGIC_V1:
        if not allow_legacy:
            raise ShardShapeError(
                f"{path}: legacy ACK1 shard (no world-shape header to "
                f"validate); re-checkpoint with a current build, or opt "
                f"into the unvalidated read with "
                f"Config(allow_legacy_shards=True)"
            )
    else:
        raise ValueError(f"{path}: bad shard magic")
    (n,) = _U32.unpack_from(data, off)
    off += 4
    units = []
    for _ in range(n):
        wt, target, answer, prio, cserver, cseqno = _UNIT.unpack_from(
            data, off
        )
        off += _UNIT.size
        (clen,) = _U32.unpack_from(data, off)
        off += 4
        (plen,) = _U32.unpack_from(data, off)
        off += 4
        payload = data[off:off + plen]
        off += plen
        units.append(
            dict(work_type=wt, target_rank=target, answer_rank=answer,
                 prio=prio, common_server_rank=cserver, common_seqno=cseqno,
                 common_len=clen, payload=payload)
        )
    (nc,) = _U32.unpack_from(data, off)
    off += 4
    centries = []
    for _ in range(nc):
        seqno, refcnt, ngets = _CQE.unpack_from(data, off)
        off += _CQE.size
        (blen,) = _U32.unpack_from(data, off)
        off += 4
        centries.append((seqno, refcnt, ngets, data[off:off + blen]))
        off += blen
    return units, centries
