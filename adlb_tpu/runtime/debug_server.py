"""Debug-server watchdog.

Equivalent of the reference's optional extra rank (``ADLBP_Debug_server``,
reference ``src/adlb.c:2528-2635``): servers ship periodic counter summaries
(DS_LOG); the watchdog aggregates them and **aborts the whole world if no
message arrives within the timeout** — turning hangs into bounded-time
failures with state dumps, which the reference's docs recommend as the soak-
test harness (reference ``USERGUIDE.txt:60-80``).
"""

from __future__ import annotations

import sys
import time

from adlb_tpu.runtime.messages import Tag, msg
from adlb_tpu.runtime.transport import Endpoint
from adlb_tpu.runtime.world import Config, WorldSpec


class DebugServer:
    def __init__(
        self, world: WorldSpec, cfg: Config, ep: Endpoint, abort_event=None
    ) -> None:
        self.world = world
        self.cfg = cfg
        self.ep = ep
        self._abort_event = abort_event
        self.aggregates: dict[int, dict] = {}
        self.timed_out = False

    def run(self) -> None:
        ended: set[int] = set()
        last_msg = time.monotonic()
        while len(ended) < self.world.nservers:
            if self._abort_event is not None and self._abort_event.is_set():
                return
            m = self.ep.recv(timeout=min(self.cfg.debug_server_timeout / 4, 0.25))
            now = time.monotonic()
            if m is None:
                if now - last_msg > self.cfg.debug_server_timeout:
                    self.timed_out = True
                    print(
                        f"[adlb debug server] no server heartbeat for "
                        f"{self.cfg.debug_server_timeout:.1f}s — aborting world",
                        file=sys.stderr,
                    )
                    for s in self.world.server_ranks:
                        self.ep.send(s, msg(Tag.SS_ABORT, self.ep.rank, code=-2))
                    for a in self.world.app_ranks:
                        self.ep.send(a, msg(Tag.TA_ABORT, self.ep.rank, code=-2))
                    if self._abort_event is not None:
                        self._abort_event.set()
                    return
                continue
            last_msg = now
            if m.tag is Tag.DS_END:
                ended.add(m.src)
            elif m.tag is Tag.DS_LOG:
                agg = self.aggregates.setdefault(
                    m.src, {"wq_count": 0, "rq_count": 0, "nbytes": 0, "n": 0}
                )
                agg["wq_count"] = m.wq_count
                agg["rq_count"] = m.rq_count
                agg["nbytes"] = m.nbytes
                agg["n"] += 1
