"""Debug-server watchdog.

Equivalent of the reference's optional extra rank (``ADLBP_Debug_server``,
reference ``src/adlb.c:2528-2635``): servers ship periodic counter summaries
(DS_LOG); the watchdog aggregates them and **aborts the whole world if no
message arrives within the timeout** — turning hangs into bounded-time
failures with state dumps, which the reference's docs recommend as the soak-
test harness (reference ``USERGUIDE.txt:60-80``).
"""

from __future__ import annotations

import sys
import time

from adlb_tpu.runtime.messages import Tag, msg
from adlb_tpu.runtime.transport import Endpoint
from adlb_tpu.runtime.world import Config, WorldSpec


class DebugServer:
    def __init__(
        self, world: WorldSpec, cfg: Config, ep: Endpoint, abort_event=None
    ) -> None:
        self.world = world
        self.cfg = cfg
        self.ep = ep
        self._abort_event = abort_event
        self.aggregates: dict[int, dict] = {}
        self.timed_out = False
        # per-interval aggregation of the 11-counter heartbeats, printed
        # the way the reference's debug server does per minute (reference
        # ``src/adlb.c:2539-2551,2569-2610``)
        self.printed_lines: list[str] = []
        self._window: dict[str, float] = {}
        self._window_n = 0

    # DS_LOG fields aggregated per print window (sums of the since-last-log
    # counters; averages of the point-in-time depths)
    _SUM_FIELDS = ("events", "reserves", "reserves_immed", "reserves_parked",
                   "rfr_failed", "ss_msgs")
    _AVG_FIELDS = ("wq_targeted", "wq_count", "rq_count", "backlog",
                   "rss_kb", "nbytes")

    def _print_window(self, span: float) -> None:
        if not self._window_n:
            return
        w = self._window
        navg = max(self._window_n, 1)
        line = (
            f"[adlb debug server] last {span:.1f}s: "
            f"events={int(w.get('events', 0))} "
            f"reserves={int(w.get('reserves', 0))} "
            f"immed={int(w.get('reserves_immed', 0))} "
            f"parked={int(w.get('reserves_parked', 0))} "
            f"rfr_failed={int(w.get('rfr_failed', 0))} "
            f"ss_msgs={int(w.get('ss_msgs', 0))} "
            f"avg_wq_targeted={w.get('wq_targeted', 0) / navg:.1f} "
            f"avg_wq={w.get('wq_count', 0) / navg:.1f} "
            f"avg_rq={w.get('rq_count', 0) / navg:.1f} "
            f"avg_backlog={w.get('backlog', 0) / navg:.1f} "
            f"avg_rss_kb={w.get('rss_kb', 0) / navg:.0f} "
            f"avg_nbytes={w.get('nbytes', 0) / navg:.0f}"
        )
        self.printed_lines.append(line)
        print(line, file=sys.stderr)
        self._window = {}
        self._window_n = 0

    def run(self) -> None:
        ended: set[int] = set()
        last_msg = time.monotonic()
        self._last_print = last_msg
        print_interval = self.cfg.debug_print_interval
        try:
            self._run(ended, last_msg, print_interval)
        finally:
            # flush the final partial window so short runs still get
            # their aggregate line
            if print_interval > 0:
                self._print_window(time.monotonic() - self._last_print)

    def _run(self, ended, last_msg, print_interval) -> None:
        while len(ended) < self.world.nservers:
            if self._abort_event is not None and self._abort_event.is_set():
                return
            m = self.ep.recv(timeout=min(self.cfg.debug_server_timeout / 4, 0.25))
            now = time.monotonic()
            if print_interval > 0 and now - self._last_print >= print_interval:
                self._print_window(now - self._last_print)
                self._last_print = now
            if m is None:
                if now - last_msg > self.cfg.debug_server_timeout:
                    self.timed_out = True
                    print(
                        f"[adlb debug server] no server heartbeat for "
                        f"{self.cfg.debug_server_timeout:.1f}s — aborting world",
                        file=sys.stderr,
                    )
                    # post-mortem artifact: the watchdog's last-known
                    # per-server aggregates (the servers dump their own
                    # flight records when the SS_ABORT below lands)
                    from adlb_tpu.obs.flight import write_artifact

                    write_artifact(
                        self.cfg.flight_dir,
                        "watchdog-timeout",
                        {
                            "role": "debug_server",
                            "reason": "watchdog timeout",
                            "timeout_s": self.cfg.debug_server_timeout,
                            "aggregates": {
                                str(r): dict(a)
                                for r, a in self.aggregates.items()
                            },
                            "recent_lines": self.printed_lines[-20:],
                        },
                    )
                    for s in self.world.server_ranks:
                        self.ep.send(s, msg(Tag.SS_ABORT, self.ep.rank, code=-2))
                    for a in self.world.app_ranks:
                        self.ep.send(a, msg(Tag.TA_ABORT, self.ep.rank, code=-2))
                    if self._abort_event is not None:
                        self._abort_event.set()
                    return
                continue
            last_msg = now
            if m.tag is Tag.DS_END:
                ended.add(m.src)
            elif m.tag is Tag.DS_LOG:
                agg = self.aggregates.setdefault(
                    m.src, {"wq_count": 0, "rq_count": 0, "nbytes": 0, "n": 0}
                )
                agg["wq_count"] = m.wq_count
                agg["rq_count"] = m.rq_count
                agg["nbytes"] = m.nbytes
                agg["n"] += 1
                for f in self._SUM_FIELDS + self._AVG_FIELDS:
                    v = m.data.get(f)
                    if v is not None:
                        self._window[f] = self._window.get(f, 0) + v
                self._window_n += 1
