"""Periodic cluster-wide statistics (the reference's periodic-stats ring).

The reference's master server assembles, every ``periodic_log_interval``
seconds, a per-type × per-target work-queue histogram plus the waiting-
requester vector and put/resolved-reserve counters, circulates it around the
server ring via ``SS_PERIODIC_STATS`` where each server adds its own share,
and prints the summed result in ≤500-byte ``STAT_APS:`` chunks parsed offline
by ``scripts/get_stats.py`` (reference ``src/adlb.c:447-477,712-753,
2391-2465``; decoder ``scripts/get_stats.py:1-117``).

This module is the rebuild's equivalent: per-server contributions are plain
dicts carried by the same ring token pass; the master emits the aggregate as
chunked ``STAT_APS:`` lines (JSON payload split at ``CHUNK`` bytes for parity
with the reference's aprintf limit) through a swappable sink, and
:func:`parse_stat_lines` reassembles them — shared by the offline decoder and
the tests.
"""

from __future__ import annotations

import json
from typing import Iterable

from adlb_tpu.runtime.sink import Sink

CHUNK = 500  # reference prints periodic stats in <=500-byte chunks

_SINK = Sink()
set_sink = _SINK.set
_emit = _SINK.emit


def contribution(server) -> dict:
    """One server's share of the periodic aggregate: wq histogram by
    (type, target bucket), rq length, cumulative put/resolved counters
    (reference assembles the same per-type × per-target table,
    ``src/adlb.c:447-477``)."""
    hist: dict[tuple[int, int], int] = {}
    for u in server.wq.units():
        key = (u.work_type, -1 if u.target_rank < 0 else u.target_rank)
        hist[key] = hist.get(key, 0) + 1
    return {
        "wq": [[t, tgt, n] for (t, tgt), n in sorted(hist.items())],
        "wq_count": server.wq.count,
        "rq": len(server.rq),
        "puts": int(server.metrics.value("puts")),
        "resolved": server.resolved_reserves,
        "nbytes": server.mem.curr,
    }


def aggregate(token: dict, now: float) -> dict:
    """Master-side sum of every server's contribution into the record the
    decoder consumes (reference sums around the ring then prints,
    ``src/adlb.c:2391-2465``)."""
    by_type: dict[int, dict[str, int]] = {}
    total = {"wq": 0, "rq": 0, "puts": 0, "resolved": 0, "nbytes": 0}
    for entry in token["entries"].values():
        for t, tgt, n in entry["wq"]:
            cell = by_type.setdefault(t, {"targeted": 0, "untargeted": 0})
            cell["targeted" if tgt >= 0 else "untargeted"] += n
        total["wq"] += entry["wq_count"]
        total["rq"] += entry["rq"]
        total["puts"] += entry["puts"]
        total["resolved"] += entry["resolved"]
        total["nbytes"] += entry["nbytes"]
    return {
        "seq": token["seq"],
        "t": round(now, 6),
        "trip_s": round(now - token["t0"], 6),
        "nservers": len(token["entries"]),
        "by_type": {str(t): c for t, c in sorted(by_type.items())},
        "total": total,
        "per_server": {
            str(r): {"wq": e["wq_count"], "rq": e["rq"], "nbytes": e["nbytes"]}
            for r, e in sorted(token["entries"].items())
        },
    }


def emit_stat_aps(record: dict) -> None:
    """Print one aggregate as chunked ``STAT_APS: seq=S part=I/N <chunk>``
    lines."""
    payload = json.dumps(record, separators=(",", ":"))
    parts = [payload[i : i + CHUNK] for i in range(0, len(payload), CHUNK)] or [""]
    for i, part in enumerate(parts):
        _emit(f"STAT_APS: seq={record['seq']} part={i + 1}/{len(parts)} {part}")


def parse_stat_lines(lines: Iterable[str]) -> list[dict]:
    """Reassemble chunked STAT_APS lines back into aggregate records —
    the in-library half of ``scripts/get_stats.py`` (reference decoder
    ``scripts/get_stats.py:1-117``)."""
    pending: dict[int, dict] = {}
    out: list[dict] = []
    for line in lines:
        idx = line.find("STAT_APS: ")
        if idx < 0:
            continue
        try:
            # "seq=S part=I/N <chunk>"
            fields = line[idx + len("STAT_APS: ") :].split(" ", 2)
            seq = int(fields[0].split("=", 1)[1])
            part_i, part_n = (int(x) for x in fields[1].split("=", 1)[1].split("/"))
            chunk = fields[2] if len(fields) > 2 else ""
        except (ValueError, IndexError):
            continue
        rec = pending.get(seq)
        if rec is None or rec["n"] != part_n or part_i in rec["parts"]:
            # a fresh record for a seq we were mid-assembly on (e.g. logs
            # from two runs concatenated): start over rather than mixing
            rec = pending[seq] = {"n": part_n, "parts": {}}
        rec["parts"][part_i] = chunk
        if len(rec["parts"]) == rec["n"]:
            payload = "".join(rec["parts"][i] for i in sorted(rec["parts"]))
            del pending[seq]
            try:
                out.append(json.loads(payload))
            except json.JSONDecodeError:
                continue
    out.sort(key=lambda r: r.get("seq", 0))
    return out


def summarize(records: list[dict]) -> list[dict]:
    """Per-period rates from consecutive cumulative counters — what the
    reference's offline decoder prints as its activity table."""
    rows: list[dict] = []
    prev = None
    for rec in records:
        row = {
            "seq": rec["seq"],
            "wq_total": rec["total"]["wq"],
            "rq_total": rec["total"]["rq"],
            "nbytes": rec["total"]["nbytes"],
            "by_type": rec["by_type"],
            "trip_s": rec["trip_s"],
        }
        if prev is not None:
            dt = rec["t"] - prev["t"]
            if dt > 0:
                row["puts_per_s"] = round(
                    (rec["total"]["puts"] - prev["total"]["puts"]) / dt, 2
                )
                row["resolved_per_s"] = round(
                    (rec["total"]["resolved"] - prev["total"]["resolved"]) / dt, 2
                )
        rows.append(row)
        prev = rec
    return rows
